"""Covering-index build pipeline (device data plane).

TPU-native re-design of ``CoveringIndex.createIndexData:140-192`` +
``write:56-71`` + ``CoveringIndexTrait`` refresh/optimize (:32-135):

    host scan (arrow, per source file)  →  SoA batches w/ lineage column
      →  murmur3 bucket hash                      [ops/hash, XLA]
      →  all-to-all over the mesh (>1 device)     [parallel/shuffle]
      →  lexsort by (bucket, keys)                [ops/sort, XLA]
      →  one parquet file per bucket under the new v__=N dir

Lineage (`_data_file_id`) is attached as a constant int64 column per source
file during the scan — the moral equivalent of the reference's
``input_file_name()`` ⋈ broadcast(fileId map) join
(CoveringIndex.scala:177-186) without needing a join at all, because our
scan is already per-file.

Single-host note: after the device exchange all shards live in this
process, so one host writes every bucket. On a multi-host mesh each host
writes only the buckets its local shards own; the layout (one file per
bucket, bucket id in the file name) is identical.

Datasets larger than the configured memory budget
(``hyperspace.index.build.memoryBudgetBytes``) never materialize whole:
``create_covering_index`` hands back a lazy :class:`SourceScan` and
``_write_bucketed_streaming`` runs the pipeline in waves with per-bucket
disk spill and a final per-bucket merge sort (peak memory = one wave +
one bucket). Incremental refresh streams BOTH sides the same way: the
appended source files and — via ``SourceScan.excluded_lineage_ids`` —
the previous index data minus deleted-lineage rows.
"""

from __future__ import annotations

import dataclasses
import threading as _threading
import time as _time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from hyperspace_tpu.constants import (
    DATA_FILE_NAME_ID,
    INDEX_FILE_PREFIX as C_INDEX_FILE_PREFIX,
    LINEAGE_PROPERTY,
)
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.indexes.base import UpdateMode
from hyperspace_tpu.io import parquet as pio
from hyperspace_tpu.obs import metrics as _obs_metrics
from hyperspace_tpu.obs import trace as _obs_trace
from hyperspace_tpu.io.columnar import Column, ColumnarBatch
from hyperspace_tpu.ops.hash import bucket_ids_np
from hyperspace_tpu.ops.sort import sort_permutation
from hyperspace_tpu.utils import resolver


# ---------------------------------------------------------------------------
# Scan side: build index data from source files
# ---------------------------------------------------------------------------


def _scan_with_lineage(
    files: Sequence[str],
    fmt: str,
    columns: List[str],
    file_ids: Optional[Dict[str, int]],
) -> ColumnarBatch:
    """Read the projection from each source file; attach `_data_file_id`
    when lineage is on (CoveringIndex.createIndexData:177-186)."""
    batches = []
    for f in files:
        t = pio.read_table([f], columns, fmt)
        b = ColumnarBatch.from_arrow(t)
        if file_ids is not None:
            fid = np.full(b.num_rows, file_ids[f], dtype=np.int64)
            b = b.with_column(
                DATA_FILE_NAME_ID, Column("numeric", pa.int64(), values=fid)
            )
        batches.append(b)
    if not batches:
        raise HyperspaceException("No source files to index")
    return ColumnarBatch.concat(batches)


@dataclasses.dataclass
class SourceScan:
    """Lazy build-side input: what to read, not the rows themselves.

    The carrier of the >HBM streaming build — when the estimated
    materialized size exceeds ``hyperspace.index.build.memoryBudgetBytes``
    the build keeps this descriptor and ``write_bucketed`` streams it in
    waves instead of materializing one giant batch (the role Spark's
    disk-backed shuffle plays for the reference,
    covering/CoveringIndex.scala:58-61).
    """

    files: Tuple[str, ...]
    fmt: str
    columns: Tuple[str, ...]  # projection to read
    file_ids: Optional[Dict[str, int]]  # lineage ids (None = lineage off)
    select_cols: Optional[Tuple[str, ...]] = None  # output column order
    # per-file estimated materialized bytes, computed once at create time
    # (footer parses are a round trip each on object stores)
    file_sizes: Optional[Tuple[int, ...]] = None
    # rows whose stored lineage id is listed are dropped at materialize
    # time — lets refresh's delete compensation stream previous index
    # data instead of materializing it whole
    excluded_lineage_ids: Optional[Tuple[int, ...]] = None

    def process_local(self) -> "SourceScan":
        """This process's file subset (``files[p::P]``) — the multi-host
        build feed (docs/MULTIHOST.md): each host scans, hashes and
        exchanges only its own rows (the exchange moves them straight to
        their owner host via ``make_array_from_process_local_data``, no
        round-trip through process 0). Global row order becomes
        process-major; identity on a single-process job."""
        import jax

        nproc = jax.process_count()
        if nproc <= 1:
            return self
        p = jax.process_index()
        return dataclasses.replace(
            self,
            files=self.files[p::nproc],
            file_sizes=(
                self.file_sizes[p::nproc]
                if self.file_sizes is not None
                else None
            ),
        )

    def empty_batch(self) -> ColumnarBatch:
        """Zero-row batch with this scan's exact output structure — the
        stripe a process contributes when a wave (or the whole job) has
        no files for it. Parquet-family sources read only the first
        file's footer schema (no row reads); anything else falls back to
        materializing one file and slicing it to zero rows."""
        if self.fmt in ("parquet", "delta", "iceberg"):
            try:
                import pyarrow.parquet as pq

                t = pq.read_schema(self.files[0]).empty_table()
                b = ColumnarBatch.from_arrow(t.select(list(self.columns)))
                if self.file_ids is not None:
                    b = b.with_column(
                        DATA_FILE_NAME_ID,
                        Column(
                            "numeric",
                            pa.int64(),
                            values=np.zeros(0, dtype=np.int64),
                        ),
                    )
                if self.select_cols is not None:
                    b = b.select(list(self.select_cols))
                return b
            except (
                OSError,
                KeyError,
                pa.ArrowInvalid,
                pa.ArrowNotImplementedError,
            ):  # nested/exotic schema or unreadable footer: pay the row read
                pass
        b = self.materialize(list(self.files[:1]))
        return b.filter(np.zeros(b.num_rows, dtype=bool))

    def materialize(self, files: Optional[Sequence[str]] = None) -> ColumnarBatch:
        batch = _scan_with_lineage(
            files if files is not None else self.files,
            self.fmt,
            list(self.columns),
            self.file_ids,
        )
        if self.excluded_lineage_ids:
            lineage = batch.column(DATA_FILE_NAME_ID).values
            keep = ~np.isin(
                lineage, np.array(self.excluded_lineage_ids, dtype=np.int64)
            )
            batch = batch.filter(keep)
        if self.select_cols is not None:
            batch = batch.select(list(self.select_cols))
        return batch

    def select(self, cols: Sequence[str]) -> "SourceScan":
        return dataclasses.replace(self, select_cols=tuple(cols))

    def stats_view(self, stat_cols: Sequence[str]) -> "SourceScan":
        """A projection of this scan reading only ``stat_cols`` (plus the
        lineage column when delete exclusion applies, so excluded rows do
        not contribute to encoding statistics)."""
        cols = tuple(stat_cols)
        read = cols
        if self.excluded_lineage_ids and DATA_FILE_NAME_ID not in read:
            read = read + (DATA_FILE_NAME_ID,)
        return dataclasses.replace(
            self, columns=read, file_ids=None, select_cols=cols
        )

    def estimated_bytes(self) -> int:
        if self.file_sizes is not None:
            return sum(self.file_sizes)
        return estimated_materialized_bytes(self.files, self.fmt)


@dataclasses.dataclass
class CompositeScan:
    """Several :class:`SourceScan` parts streamed as one input.

    Incremental refresh mixes heterogeneous inputs — appended SOURCE
    files (projection + lineage attach) and previous INDEX files
    (lineage-filtered for deletes). Each keeps its own read semantics;
    wave planning and materialization see one ordered file list. All
    parts must select the same output columns."""

    scans: Tuple[SourceScan, ...]

    @property
    def files(self) -> Tuple[str, ...]:
        return tuple(f for s in self.scans for f in s.files)

    @property
    def fmt(self) -> str:
        return self.scans[0].fmt

    @property
    def file_sizes(self) -> Tuple[int, ...]:
        out: List[int] = []
        for s in self.scans:
            out.extend(
                s.file_sizes
                if s.file_sizes is not None
                else per_file_materialized_bytes(s.files, s.fmt)
            )
        return tuple(out)

    def materialize(self, files: Optional[Sequence[str]] = None) -> ColumnarBatch:
        wanted = set(self.files if files is None else files)
        parts = []
        # scans are ordered and wave file lists are contiguous slices of
        # self.files, so per-scan grouping preserves global row order
        for s in self.scans:
            sub = [f for f in s.files if f in wanted]
            if sub:
                parts.append(s.materialize(sub))
        if not parts:
            raise HyperspaceException("No files to materialize")
        return ColumnarBatch.concat(parts)

    def process_local(self) -> "CompositeScan":
        return CompositeScan(tuple(s.process_local() for s in self.scans))

    def empty_batch(self) -> ColumnarBatch:
        # all parts select the same output columns (class contract)
        return self.scans[0].empty_batch()

    def select(self, cols: Sequence[str]) -> "CompositeScan":
        return CompositeScan(tuple(s.select(cols) for s in self.scans))

    def stats_view(self, stat_cols: Sequence[str]) -> "CompositeScan":
        return CompositeScan(
            tuple(s.stats_view(stat_cols) for s in self.scans)
        )

    def estimated_bytes(self) -> int:
        return sum(s.estimated_bytes() for s in self.scans)


def per_file_materialized_bytes(files: Sequence[str], fmt: str) -> List[int]:
    """Per-file rough in-memory size: parquet uncompressed data size from
    footers; other formats via on-disk size with an expansion factor."""
    import os

    if fmt in ("parquet", "delta", "iceberg"):
        import pyarrow.parquet as pq

        def uncompressed(p):
            md = pq.ParquetFile(p).metadata
            return sum(
                md.row_group(i).total_byte_size for i in range(md.num_row_groups)
            )

        return [uncompressed(f) for f in files]
    return [os.path.getsize(f) * 2 for f in files]


def estimated_materialized_bytes(files: Sequence[str], fmt: str) -> int:
    return sum(per_file_materialized_bytes(files, fmt))


def plan_waves(
    files: Sequence[str],
    fmt: str,
    budget: int,
    file_sizes: Optional[Sequence[int]] = None,
) -> List[List[str]]:
    """Greedy pack files into waves of estimated materialized size <=
    ``budget`` (always at least one file per wave — a single file larger
    than the budget still has to be read whole). ``file_sizes`` reuses
    estimates computed at create time instead of re-parsing footers."""
    if file_sizes is None:
        file_sizes = per_file_materialized_bytes(files, fmt)
    waves: List[List[str]] = []
    cur: List[str] = []
    cur_bytes = 0
    for f, sz in zip(files, file_sizes):
        if cur and cur_bytes + sz > budget:
            waves.append(cur)
            cur, cur_bytes = [], 0
        cur.append(f)
        cur_bytes += sz
    if cur:
        waves.append(cur)
    return waves


def resolve_index_schema(rel, config, properties: Dict[str, str]):
    """(indexed, included, lineage, schema_json) — shared by data-building
    ``create_covering_index`` and data-free ``describe_index`` so the
    begin-phase and final log entries can never diverge."""
    import json

    nested = resolver.nested_available_from(rel.column_names)
    indexed = [
        rc.normalized_name
        for rc in resolver.require_resolve(
            config.indexed_columns, rel.column_names, nested_available=nested
        )
    ]
    included = [
        rc.normalized_name
        for rc in resolver.require_resolve(
            config.included_columns, rel.column_names, nested_available=nested
        )
    ]
    lineage = str(properties.get(LINEAGE_PROPERTY, "false")).lower() == "true"
    schema = rel.schema
    schema_json = json.dumps(
        [[c, str(schema[c])] for c in indexed + included]
        + ([[DATA_FILE_NAME_ID, "int64"]] if lineage else [])
    )
    return indexed, included, lineage, schema_json


def describe_covering_index(ctx, source_df, config, properties: Dict[str, str]):
    """CoveringIndex object without scanning data (begin-phase log entry)."""
    from hyperspace_tpu.indexes.covering import CoveringIndex

    rel = _single_relation(source_df)
    indexed, included, _lineage, schema_json = resolve_index_schema(
        rel, config, properties
    )
    return CoveringIndex(
        indexed, included, schema_json, ctx.session.conf.num_buckets,
        dict(properties),
    )


def _single_relation(source_df):
    leaves = source_df.logical_plan.collect_leaves()
    if len(leaves) != 1:
        raise HyperspaceException(
            f"Index source must have exactly one relation; got {len(leaves)}"
        )
    return leaves[0].relation


def prepare_covering_index(ctx, source_df, config, properties: Dict[str, str]):
    """(CoveringIndex, lazy SourceScan) — the resolution + lineage-id
    registration half of index creation, with the data side still lazy
    (callers that stream — the z-order incremental refresh — compose the
    scan further before any row is read)."""
    from hyperspace_tpu.indexes.covering import CoveringIndex

    reset_build_breakdown()
    rel = _single_relation(source_df)
    indexed, included, lineage, schema_json = resolve_index_schema(
        rel, config, properties
    )
    file_ids = None
    if lineage:
        # Key file ids by the PROVIDER's (path,size,mtime) view — the same
        # keys create_metadata_relation records — or lineage ids and the
        # log entry's ids diverge for lake sources (Delta mtimes come from
        # the log, Iceberg pins mtime=0).
        file_ids = {}
        for path, size, mtime in source_file_infos(ctx.session, rel):
            file_ids[path] = ctx.file_id_tracker.add_file(path, size, mtime)
    index = CoveringIndex(
        indexed_columns=indexed,
        included_columns=included,
        schema_json=schema_json,
        num_buckets=ctx.session.conf.num_buckets,
        properties=dict(properties),
    )
    budget = ctx.session.conf.build_memory_budget
    sizes = per_file_materialized_bytes(rel.files, rel.fmt) if budget else None
    scan = SourceScan(
        files=tuple(rel.files),
        fmt=rel.fmt,
        columns=tuple(indexed + included),
        file_ids=file_ids,
        file_sizes=tuple(sizes) if sizes is not None else None,
    )
    return index, scan


# Per-stage wall times of the most recent build (scan/hash/sort/write),
# reset at each create/refresh data op — the bench publishes these so the
# throughput story names its bottleneck (SURVEY §7 hard part #4: measure
# before moving parquet decode on-device). Under the sharded tail the
# sort/write stages run per shard concurrently, so those values are BUSY
# time summed across shards (may exceed wall time — the excess over
# ``tail_wall`` is the sharding win); ``tail_shards`` records how many
# shard tails ran.
#
# Obs plane (docs/observability.md): this dict is the backing storage
# of a REGISTERED instrument — ``registry.stage_timer`` below adopts
# the exact dict + lock, so the registry's Prometheus snapshot and
# every legacy reader share one storage (SHARED_STATE unchanged) — and
# ``_stage_add`` also records a stage span on the current
# lifecycle-action trace.
last_build_breakdown: Dict[str, float] = {}
_build_bd_lock = _threading.Lock()
_obs_metrics.registry.stage_timer(
    "hs_build_stage_seconds",
    "build stage busy seconds (breakdown view)",
    data=last_build_breakdown,
    lock=_build_bd_lock,
)

# Non-timing telemetry of the most recent build: the exchange plane's
# snapshot (``parallel/shuffle.last_shuffle_stats`` — chosen strategy,
# pack/exchange/unpack seconds, capacity, per-(shard, peer) skew),
# folded in per exchange by ``_record_shuffle_telemetry`` (stage seconds
# summed across waves, skew carried as max/mean + wave count) so the
# bench and operators read one coherent snapshot.
last_build_telemetry: Dict[str, object] = {}


def _stage_add(name: str, t0: float) -> None:
    dt = _time.perf_counter() - t0
    with _build_bd_lock:
        last_build_breakdown[name] = last_build_breakdown.get(name, 0.0) + dt
    _obs_trace.stage(name, t0)


def reset_build_breakdown() -> None:
    """Called at the entry of every data op (create via
    prepare_covering_index; refresh/optimize call it directly) so the
    breakdown never mixes two ops' stage times. Takes the breakdown
    lock: a reset must never interleave with a sharded-tail worker's
    ``_stage_add`` read-modify-write (HS602, SHARED_STATE). Also rearms
    the shuffle's once-per-build skew warning (the streaming build runs
    one exchange per wave; the warning fires at most once per op while
    telemetry records every wave)."""
    from hyperspace_tpu.parallel import shuffle as _shuffle

    _shuffle.reset_skew_warning()
    with _build_bd_lock:
        last_build_breakdown.clear()
        last_build_telemetry.clear()


def lazy_or_materialized(ctx, scan):
    """THE build memory-budget rule, in one place: keep the scan lazy
    (streamed at write time through the wave loop) when its estimated
    materialized size exceeds ``hyperspace.index.build.memoryBudgetBytes``,
    else materialize now. Accepts SourceScan or CompositeScan. On a
    multi-process job each process materializes only its own file subset
    (``process_local``) — the exchange routes rows to their owner host."""
    budget = ctx.session.conf.build_memory_budget
    if budget and scan.estimated_bytes() > budget:
        return scan
    t0 = _time.perf_counter()
    local = scan.process_local()
    if local.files:
        out = local.materialize()
    else:
        # more hosts than files: this process contributes zero rows but
        # must still know the schema (and later join every exchange
        # collective) — a zero-row batch from the footer schema
        out = scan.empty_batch()
    _stage_add("scan", t0)
    return out


def previous_index_scan(
    ctx, previous_content, schema_cols, deleted_source_file_ids
):
    """Lazy scan of a previous index version's data files minus
    deleted-lineage rows (the refresh delete-compensation input). File
    sizes are computed once here when a budget is set — footer parses
    are a round trip each on object stores."""
    files = tuple(previous_content.files)
    sizes = (
        tuple(per_file_materialized_bytes(files, "parquet"))
        if ctx.session.conf.build_memory_budget
        else None
    )
    return SourceScan(
        files=files,
        fmt="parquet",
        columns=tuple(schema_cols),
        file_ids=None,
        select_cols=tuple(schema_cols),
        file_sizes=sizes,
        excluded_lineage_ids=tuple(deleted_source_file_ids),
    )


def create_covering_index(ctx, source_df, config, properties: Dict[str, str]):
    """(CoveringIndex, index_data batch) — the reference's
    ``CoveringIndexConfig.createIndex:43-61``."""
    index, scan = prepare_covering_index(ctx, source_df, config, properties)
    return index, lazy_or_materialized(ctx, scan)


def source_file_infos(session, plan_relation) -> List[Tuple[str, int, int]]:
    """(path, size, mtime) via the source provider SPI — restricted to the
    plan relation's current file subset (refresh passes appended-only
    relations)."""
    provider_rel = session.source_manager.get_relation(plan_relation)
    subset = set(plan_relation.files)
    return [
        (p, size, mtime)
        for p, size, mtime in provider_rel.all_file_infos()
        if p in subset
    ]


# ---------------------------------------------------------------------------
# Shuffle + sort + bucketed write
# ---------------------------------------------------------------------------


def _decompose(batch: ColumnarBatch):
    """Flatten a batch into device-movable arrays + reassembly spec."""
    arrays: List[np.ndarray] = []
    spec = []
    for name, col in batch.columns.items():
        if col.kind == "string":
            arrays.append(col.codes)
            spec.append(("string", name, col.arrow_type, col.dictionary, False))
        else:
            arrays.append(col.values)
            has_validity = col.validity is not None
            if has_validity:
                arrays.append(col.validity)
            spec.append(("numeric", name, col.arrow_type, None, has_validity))
    return arrays, spec


def _reassemble(spec, arrays: List[np.ndarray]) -> ColumnarBatch:
    cols = {}
    it = iter(arrays)
    for kind, name, atype, dictionary, has_validity in spec:
        if kind == "string":
            cols[name] = Column(
                "string", atype, codes=next(it).astype(np.int32),
                dictionary=dictionary,
            )
        else:
            values = next(it)
            validity = next(it) if has_validity else None
            cols[name] = Column("numeric", atype, values=values, validity=validity)
    return ColumnarBatch(cols)


def _hash_shuffle(
    ctx, batch: ColumnarBatch, indexed_cols: List[str], num_buckets: int
):
    """Bucket-id half of the pipeline: murmur3 bucket ids over the key
    reps (+ mesh all-to-all when >1 device). Returns ``(buckets, reps,
    batch, shard_offsets)`` in post-exchange row order; ``shard_offsets``
    is the ``[D+1]`` per-shard row extent of the exchanged batch (rows
    ``offsets[s]:offsets[s+1]`` hold exactly the buckets shard ``s``
    owns), or None when no exchange ran (single device / tiny batch)."""
    import jax

    t0 = _time.perf_counter()
    reps = batch.key_reps(indexed_cols)
    mesh = ctx.mesh
    shard_offs = None
    # multi-process: ALWAYS exchange, even a zero/tiny local batch — the
    # exchange is a collective and every process must take the same
    # number of steps (a peer may be feeding this wave real rows)
    if mesh.devices.size > 1 and (
        batch.num_rows >= mesh.devices.size or jax.process_count() > 1
    ):
        from hyperspace_tpu.parallel import shuffle as _shuffle

        arrays, spec = _decompose(batch)
        k = reps.shape[0]
        conf = ctx.session.conf
        buckets, moved, shard_offs = _shuffle.bucket_shuffle(
            mesh, reps, list(reps) + arrays, num_buckets,
            with_shard_offsets=True,
            strategy=conf.build_exchange_strategy,
            twostage_hosts=conf.build_exchange_twostage_hosts,
        )
        reps = np.stack(moved[:k]) if k else np.zeros((0, len(buckets)))
        batch = _reassemble(spec, moved[k:])
        _record_shuffle_telemetry(_shuffle.last_shuffle_stats)
    else:
        buckets = bucket_ids_np(reps, num_buckets)
    _stage_add("hash_shuffle", t0)
    return buckets, reps, batch, shard_offs


def _record_shuffle_telemetry(stats: Dict) -> None:
    """Fold one exchange's snapshot into the build telemetry: latest
    value for every ``shuffle_<key>``, pack/exchange/unpack seconds
    SUMMED across waves, and the per-wave skew carried as a max/mean
    pair plus the wave count (a streaming build runs one exchange per
    wave; a single hot wave must stay visible in the max while the mean
    says whether it was the rule or the exception)."""
    with _build_bd_lock:
        t = last_build_telemetry
        waves = t.get("shuffle_waves", 0.0) + 1.0
        for k, v in stats.items():
            key = "shuffle_" + k
            if k in ("pack_s", "exchange_s", "unpack_s"):
                t[key] = round(t.get(key, 0.0) + float(v), 4)
            else:
                t[key] = v
        skew = float(stats.get("skew_ratio", 1.0))
        prev_mean = t.get("shuffle_skew_ratio_mean", 0.0)
        t["shuffle_waves"] = waves
        t["shuffle_skew_ratio_max"] = max(
            t.get("shuffle_skew_ratio_max", 0.0), skew
        )
        t["shuffle_skew_ratio_mean"] = round(
            prev_mean + (skew - prev_mean) / waves, 3
        )


def _partition_first(ctx) -> bool:
    return ctx.session.conf.build_partition_first


def _sharded_tail_offsets(ctx, shard_offs):
    """The shard offsets when the device-local tail applies, else None:
    flag on (``hyperspace.build.shardedTail.enabled``), an exchange
    actually ran, and more than one shard holds rows."""
    if shard_offs is None or not ctx.session.conf.build_sharded_tail:
        return None
    occupied = int(np.count_nonzero(np.diff(shard_offs)))
    return shard_offs if occupied > 1 else None


def bucketize(ctx, batch: ColumnarBatch, indexed_cols: List[str], num_buckets: int):
    """Route rows to buckets -> (bucket_ids, batch) in bucket-grouped,
    key-sorted order. Uses the mesh all-to-all when >1 device.

    The sort half runs partition-first by default (stable counting
    scatter into per-bucket runs, then per-bucket key sorts on a thread
    pool — working set ≈ rows/num_buckets per sort) and produces a
    permutation bit-identical to the legacy global lexsort by
    (bucket, keys...) it replaces (``hyperspace.index.build.partitionFirst``
    = false restores the old path). On a >1-device mesh with the sharded
    tail on, each shard's slice sorts CONCURRENTLY
    (``ops/sort.sharded_sort_permutation``): row order is then
    shard-major rather than globally bucket-ascending, but each bucket's
    rows and their key-sorted order are identical — the bucketed writers
    (``pio.bucket_runs`` / per-bucket spill) only ever observe per-bucket
    runs."""
    from hyperspace_tpu.ops.sort import (
        partitioned_sort_permutation,
        sharded_sort_permutation,
    )

    buckets, reps, batch, shard_offs = _hash_shuffle(
        ctx, batch, indexed_cols, num_buckets
    )
    t0 = _time.perf_counter()
    if _partition_first(ctx):
        shard_offs = _sharded_tail_offsets(ctx, shard_offs)
        if shard_offs is not None:
            perm = sharded_sort_permutation(
                reps, buckets, num_buckets, shard_offs
            )
        else:
            perm = partitioned_sort_permutation(reps, buckets, num_buckets)
    else:
        perm = sort_permutation(reps, buckets)
    out = buckets[perm], batch.take(perm)
    _stage_add("sort", t0)
    return out


def write_bucketed(
    ctx,
    data,
    indexed_cols: List[str],
    num_buckets: int,
    file_idx_offset: int = 0,
) -> List[str]:
    """The full build pipeline tail: shuffle, sort-within-bucket, write one
    parquet per bucket (CoveringIndex.write:56-71 + saveWithBuckets).

    ``data`` is a ColumnarBatch, a :class:`SourceScan` (streamed in waves),
    or a list mixing both (incremental refresh: appended scan + rewritten
    old data).

    The parquet dictionary-encoding decision is computed ONCE here, on
    the pre-sort input, and passed to whichever writer runs — the legacy
    and partition-first layouts must stay byte-identical, so they cannot
    each sample a differently-ordered table.
    """
    import os

    sources = data if isinstance(data, list) else [data]
    if any(isinstance(s, SourceScan) for s in sources):
        return _global_written(
            ctx,
            _write_bucketed_streaming(
                ctx, sources, indexed_cols, num_buckets, file_idx_offset
            ),
        )
    batch = sources[0] if len(sources) == 1 else ColumnarBatch.concat(sources)
    if batch.num_rows == 0 and _single_process():
        # multi-process never takes this shortcut: a zero-row LOCAL
        # batch still owes its peers the exchange collectives and the
        # _global_written barrier (its devices may RECEIVE rows)
        os.makedirs(ctx.index_data_path, exist_ok=True)
        return []
    use_dict = pio.dictionary_columns_for_batch(batch)
    if _partition_first(ctx):
        return _global_written(
            ctx,
            _write_bucketed_pipelined(
                ctx, batch, indexed_cols, num_buckets, file_idx_offset,
                use_dict,
            ),
        )
    buckets, batch = bucketize(ctx, batch, indexed_cols, num_buckets)
    t0 = _time.perf_counter()
    out = pio.write_bucket_files(
        ctx.index_data_path,
        buckets,
        batch,
        num_buckets,
        file_idx_offset,
        use_dictionary=use_dict,
    )
    _stage_add("write", t0)
    return _global_written(ctx, out)


def _single_process() -> bool:
    import jax

    return jax.process_count() <= 1


def _global_written(ctx, written: List[str]) -> List[str]:
    """The written-file list a build hands to the metadata plane. On a
    single-process job this is the writer's own list; on a multi-process
    job every host wrote only the buckets its shards own, so after a
    cross-host barrier the (deterministically named, bucket-id-ordered)
    union is listed from the data dir — every process returns the same
    global list for the coordinator's log entry.

    Registered in ``COLLECTIVE_SITES`` (``parallel/collectives.py``,
    contract ``per-host-lane``): every ``write_bucketed`` exit path must
    reach this barrier on every process — zero-row stripes included —
    or the peers hang (hslint HS8xx enforces the shape)."""
    import jax

    if jax.process_count() <= 1:
        return written
    import os

    from jax.experimental import multihost_utils as mhu

    mhu.sync_global_devices("hs_build_bucketed_write")
    d = ctx.index_data_path
    return [
        os.path.join(d, f)
        for f in sorted(os.listdir(d))
        if f.startswith(C_INDEX_FILE_PREFIX) and f.endswith(".parquet")
    ]


def _write_bucketed_pipelined(
    ctx,
    batch: ColumnarBatch,
    indexed_cols: List[str],
    num_buckets: int,
    file_idx_offset: int,
    use_dict,
) -> List[str]:
    """Partition-first, pipelined tail for in-memory builds.

    1. counting-scatter rows into contiguous per-bucket runs (native
       ``hs_partition_by_bucket``; sequential histogram + scatter);
    2. per-bucket key lexsorts on a thread pool, bucket plane dropped
       (constant within a bucket) — each sort's working set is ~one
       bucket instead of the whole table, which is what collapsed the
       64M-row global lexsort (BASELINE.md: TLB-bound gathers over
       512MB);
    3. bucket *i*'s parquet write runs on a writer thread while bucket
       *i+1* is still sorting.

    Output is bit-identical to the legacy global-lexsort layout: the
    composed permutation equals the stable lexsort by (bucket, keys...)
    and each file is written from the same rows in the same order with
    the same encoding decision.

    Stage accounting: "sort" spans partition + all per-bucket sorts;
    "write" records only the drain after the last sort — the overlapped
    portion of the writes hides inside the sort stage, which is the
    point of the pipeline.

    Datasets beyond the memory budget never reach here; they stream
    through ``_write_bucketed_streaming``'s wave/spill loop, whose
    per-wave ``bucketize`` uses the same partition-first sort.
    """
    import os
    from concurrent.futures import ThreadPoolExecutor

    from hyperspace_tpu.ops.sort import (
        _order_words_np,
        bucket_key_sort_runs,
        partition_by_bucket,
    )

    buckets, reps, batch, shard_offs = _hash_shuffle(
        ctx, batch, indexed_cols, num_buckets
    )
    os.makedirs(ctx.index_data_path, exist_ok=True)
    shard_offs = _sharded_tail_offsets(ctx, shard_offs)
    if shard_offs is not None:
        return _write_bucketed_sharded(
            ctx, buckets, reps, batch, file_idx_offset, use_dict,
            num_buckets, shard_offs,
        )
    t0 = _time.perf_counter()
    order, offsets = partition_by_bucket(buckets, num_buckets)
    planes = _order_words_np(reps.astype(np.int64, copy=False))
    table = batch.to_arrow()
    written: List[str] = []
    with ThreadPoolExecutor(max_workers=1) as writer:
        futures = []
        for b, final_idx in bucket_key_sort_runs(planes, order, offsets):
            futures.append(
                writer.submit(
                    pio.write_bucket_file,
                    ctx.index_data_path,
                    b,
                    file_idx_offset,
                    table,
                    final_idx,
                    use_dict,
                )
            )
        _stage_add("sort", t0)
        t0 = _time.perf_counter()
        for f in futures:
            written.append(f.result())
    _stage_add("write", t0)
    return written


def _write_bucketed_sharded(
    ctx,
    buckets: np.ndarray,
    reps: np.ndarray,
    batch: ColumnarBatch,
    file_idx_offset: int,
    use_dict,
    num_buckets: int,
    shard_offs: np.ndarray,
) -> List[str]:
    """Device-local tail of the in-memory sharded build: each mesh
    shard's post-exchange slice (exactly the buckets it owns) runs the
    partition-first pipeline — counting scatter, per-bucket key sorts,
    per-bucket parquet writes with sort/write overlap — CONCURRENTLY
    with the other shards'. Sort working set and write bandwidth scale
    with the shard count; nothing serializes through one global
    permutation.

    Bit-identical files to the single-tail layout: a bucket lives wholly
    inside one shard slice, slices are contiguous in post-exchange row
    order, and the per-shard stable sort restricted to a bucket equals
    the global stable (bucket, keys...) sort restricted to that bucket.
    The encoding decision (``use_dict``) was computed once by the caller
    on the shared pre-sort input.

    Stage accounting: "sort"/"write" accumulate per-shard BUSY time
    (their sum can exceed wall time — the excess is the sharding win);
    "tail_wall" is the wall time of the whole sharded tail and
    "tail_shards" the number of concurrent shard tails.
    """
    from concurrent.futures import ThreadPoolExecutor

    from hyperspace_tpu.ops.sort import (
        _order_words_np,
        bucket_key_sort_runs,
        partition_by_bucket,
        shard_tail_plan,
    )

    t_tail = _time.perf_counter()
    planes = _order_words_np(reps.astype(np.int64, copy=False))
    table = batch.to_arrow()
    shards, threads = shard_tail_plan(shard_offs)

    def run_shard(s: int) -> List[Tuple[int, str]]:
        lo, hi = int(shard_offs[s]), int(shard_offs[s + 1])
        t0 = _time.perf_counter()
        order, offsets = partition_by_bucket(buckets[lo:hi], num_buckets)
        order += lo  # global row coordinates into planes/table
        out: List[Tuple[int, str]] = []
        # one writer thread per shard: bucket i+1 sorts while bucket i
        # writes, exactly the single-tail pipeline, D of them in flight
        with ThreadPoolExecutor(max_workers=1) as writer:
            futures = []
            for b, final_idx in bucket_key_sort_runs(
                planes, order, offsets, workers=1, n_threads=threads
            ):
                futures.append(
                    (
                        b,
                        writer.submit(
                            pio.write_bucket_file,
                            ctx.index_data_path,
                            b,
                            file_idx_offset,
                            table,
                            final_idx,
                            use_dict,
                        ),
                    )
                )
            _stage_add("sort", t0)
            t0 = _time.perf_counter()
            out = [(b, f.result()) for b, f in futures]
        _stage_add("write", t0)
        return out

    if len(shards) == 1:
        results = [run_shard(shards[0])]
    else:
        with ThreadPoolExecutor(
            max_workers=len(shards), thread_name_prefix="hs-shardtail"
        ) as pool:
            results = list(pool.map(run_shard, shards))
    with _build_bd_lock:
        last_build_breakdown["tail_wall"] = (
            last_build_breakdown.get("tail_wall", 0.0)
            + _time.perf_counter()
            - t_tail
        )
        last_build_breakdown["tail_shards"] = float(len(shards))
    # ascending bucket id, matching the single-tail writers' output order
    return [path for _b, path in sorted(p for r in results for p in r)]


def _write_bucketed_streaming(
    ctx,
    sources,
    indexed_cols: List[str],
    num_buckets: int,
    file_idx_offset: int = 0,
) -> List[str]:
    """The >HBM wave loop (SURVEY §7 hard part #1).

    Bounded peak memory: the build never materializes more than one wave
    (<= the configured budget) plus, at merge time, one bucket. Phases:

    1. **Waves**: chunk each source's files into waves within the memory
       budget; per wave, run the normal device pipeline (hash -> all-to-all
       -> bucket-grouped order) and spill each bucket's run to
       ``_spill_/b<b>-w<i>.parquet`` (flat, no ``=`` in any path component
       — Arrow's dataset reader hive-infers partition columns from
       ``key=value`` directories, which would graft phantom columns onto
       the merge read).
    2. **Merge**: per bucket, read that bucket's spilled parts (~1/num_buckets
       of the data), key-sort on device, write the final bucket file.

    The reference leans on Spark's disk-backed ``repartition`` shuffle for
    exactly this (covering/CoveringIndex.scala:58-61).
    """
    import os
    import shutil

    budget = ctx.session.conf.build_memory_budget or (1 << 62)
    import jax

    nproc = jax.process_count()
    # outside the v__=N data dir (also a key=value name) but inside the
    # index dir; the leading underscore keeps it out of data listings and
    # the sanitized name keeps "=" out of every spill path component.
    # Multi-process: the index dir is a SHARED filesystem and each
    # process spills + merges only its own owned buckets, so the spill
    # dir is per-process — a peer finishing early must never rmtree
    # parts another process is still merging
    suffix = f"-p{jax.process_index()}" if nproc > 1 else ""
    spill_root = os.path.join(
        os.path.dirname(ctx.index_data_path),
        "_spill_"
        + os.path.basename(ctx.index_data_path).replace("=", "_")
        + suffix,
    )
    os.makedirs(spill_root, exist_ok=True)
    wave_idx = 0
    bucket_parts: Dict[int, List[str]] = {}
    try:
        for src in sources:
            if isinstance(src, SourceScan):
                # waves are planned over the GLOBAL file list on every
                # process (the SPMD requirement: identical wave count =
                # identical number of per-wave exchange collectives);
                # multi-process, each host materializes only its stripe
                # of a wave — an empty stripe still joins the wave's
                # exchange with a zero-row, schema-correct slice
                waves = plan_waves(
                    src.files, src.fmt, budget, src.file_sizes
                )
                if nproc > 1:
                    index_of = {f: i for i, f in enumerate(src.files)}
                    pid = jax.process_index()

                    def stripes(src=src, waves=waves, index_of=index_of):
                        for w in waves:
                            mine = [
                                f for f in w if index_of[f] % nproc == pid
                            ]
                            if mine:
                                yield src.materialize(mine)
                            else:
                                yield src.empty_batch()

                    wave_batches = stripes()
                else:
                    wave_batches = (src.materialize(w) for w in waves)
            else:
                wave_batches = iter([src])
            for batch in wave_batches:
                if batch.num_rows == 0 and nproc == 1:
                    continue
                buckets, batch = bucketize(
                    ctx, batch, indexed_cols, num_buckets
                )
                table = batch.to_arrow()
                for b, idx in pio.bucket_runs(buckets):
                    path = os.path.join(
                        spill_root, f"b{b:05d}-w{wave_idx:05d}.parquet"
                    )
                    pio.write_table(path, table.take(pa.array(idx)))
                    bucket_parts.setdefault(b, []).append(path)
                wave_idx += 1
        # merge: per bucket, read parts, key-sort, write the final file.
        # On a >1-device mesh with the sharded tail on, each shard's
        # bucket range (bucket % D) merges on its own worker — the
        # streaming build's waves already sorted per shard (bucketize),
        # and this keeps the merge tail device-local too.
        def merge_bucket(b: int) -> List[str]:
            merged = ColumnarBatch.from_arrow(
                pio.read_table(bucket_parts[b], None)
            )
            perm = sort_permutation(merged.key_reps(indexed_cols))
            merged = merged.take(perm)
            return pio.write_bucket_files(
                ctx.index_data_path,
                np.full(merged.num_rows, b, dtype=np.int32),
                merged,
                num_buckets,
                file_idx_offset,
            )

        ordered = sorted(bucket_parts)
        D = ctx.mesh.devices.size
        written: List[str] = []
        merge_workers = 1
        if D > 1 and ctx.session.conf.build_sharded_tail and len(ordered) > 1:
            # The streaming build's contract is bounded peak memory (one
            # wave + one bucket); concurrent per-shard merges may only
            # widen that to k buckets when k of the LARGEST fit the wave
            # budget — estimated from the spilled parts' own footers.
            biggest = max(
                sum(per_file_materialized_bytes(bucket_parts[b], "parquet"))
                for b in ordered
            )
            fit = int(budget // max(biggest, 1))
            merge_workers = max(1, min(D, fit))
        if merge_workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            from hyperspace_tpu.parallel.mesh import bucket_owner_groups

            groups = bucket_owner_groups(ordered, D)

            def merge_shard(g: List[int]) -> Dict[int, List[str]]:
                return {ordered[i]: merge_bucket(ordered[i]) for i in g}

            with ThreadPoolExecutor(
                max_workers=merge_workers, thread_name_prefix="hs-shardmerge"
            ) as pool:
                merged_maps = list(pool.map(merge_shard, groups))
            by_bucket = {b: fs for m in merged_maps for b, fs in m.items()}
            for b in ordered:
                written.extend(by_bucket[b])
        else:
            for b in ordered:
                written.extend(merge_bucket(b))
        return written
    finally:
        shutil.rmtree(spill_root, ignore_errors=True)


# ---------------------------------------------------------------------------
# Optimize / refresh data plane
# ---------------------------------------------------------------------------


def rewrite_files(
    ctx, files_to_optimize: List[str], indexed_cols: List[str], num_buckets: int
) -> List[str]:
    """Optimize: read the listed index files and rewrite them compacted
    (CoveringIndexTrait.optimize:130-134 — 'read files → write'). On a
    multi-process job each host reads a disjoint subset; the exchange
    routes rows back to their owner host before the write."""
    import jax

    # a data op like create/refresh: fresh stage breakdown, telemetry
    # accumulators and skew-warn latch (the exchange stats now SUM
    # across waves — without the reset they would mix two ops)
    reset_build_breakdown()
    nproc = jax.process_count()
    subset = files_to_optimize
    if nproc > 1:
        subset = files_to_optimize[jax.process_index()::nproc]
    if subset:
        batch = ColumnarBatch.from_arrow(pio.read_table(subset, None))
    else:
        # more hosts than files: still owe peers the exchange
        # collectives + write barrier — a zero-row batch from the first
        # index file's footer schema (index files are always parquet)
        import pyarrow.parquet as pq

        batch = ColumnarBatch.from_arrow(
            pq.read_schema(files_to_optimize[0]).empty_table()
        )
    return write_bucketed(ctx, batch, indexed_cols, num_buckets)


def refresh_incremental(
    ctx,
    index,
    appended_df,
    deleted_source_file_ids: List[int],
    previous_content,
):
    """CoveringIndexTrait.refreshIncremental:57-106.

    * appended source files -> index their rows into the new version dir;
    * deleted source files  -> previous index data rewritten minus rows
      whose lineage id is among the deleted (anti-filter), also into the
      new version dir.
    Returns (index, UpdateMode.MERGE | OVERWRITE).
    """
    reset_build_breakdown()
    schema_cols = list(index.indexed_columns) + list(index.included_columns)
    if index.lineage_enabled:
        schema_cols.append(DATA_FILE_NAME_ID)
    # parts: ColumnarBatch or SourceScan (large appends stream in waves)
    parts: List = []
    if appended_df is not None:
        _index2, appended_data = create_covering_index(
            ctx,
            appended_df,
            _config_of(index),
            dict(index.properties),
        )
        parts.append(appended_data.select(schema_cols))
    if deleted_source_file_ids:
        if not index.lineage_enabled:
            raise HyperspaceException(
                "Cannot handle deleted source files without lineage"
            )
        # previous index data minus deleted-lineage rows, as a LAZY scan:
        # beyond the memory budget it streams through the wave loop like
        # the appended side instead of materializing whole
        old_scan = previous_index_scan(
            ctx, previous_content, schema_cols, deleted_source_file_ids
        )
        parts.append(lazy_or_materialized(ctx, old_scan))
        mode = UpdateMode.OVERWRITE
    else:
        mode = UpdateMode.MERGE
    if parts:
        write_bucketed(ctx, parts, index.indexed_columns, index.num_buckets)
    return index, mode


def refresh_full(ctx, index, df):
    """Rebuild the whole index from the current source
    (CoveringIndexTrait.refreshFull:108-126). Returns the REBUILT index —
    its schema_json reflects the current source types, which may have
    changed since the original build."""
    new_index, batch = create_covering_index(
        ctx, df, _config_of(index), dict(index.properties)
    )
    write_bucketed(ctx, batch, new_index.indexed_columns, new_index.num_buckets)
    return new_index


def _config_of(index):
    from hyperspace_tpu.indexes.covering import CoveringIndexConfig

    return CoveringIndexConfig(
        "__refresh__", index.indexed_columns, index.included_columns
    )
