"""Covering-index build pipeline (device data plane).

TPU-native re-design of ``CoveringIndex.createIndexData:140-192`` +
``write:56-71`` + ``CoveringIndexTrait`` refresh/optimize (:32-135):

    host scan (arrow, per source file)  →  SoA batches w/ lineage column
      →  murmur3 bucket hash                      [ops/hash, XLA]
      →  all-to-all over the mesh (>1 device)     [parallel/shuffle]
      →  lexsort by (bucket, keys)                [ops/sort, XLA]
      →  one parquet file per bucket under the new v__=N dir

Lineage (`_data_file_id`) is attached as a constant int64 column per source
file during the scan — the moral equivalent of the reference's
``input_file_name()`` ⋈ broadcast(fileId map) join
(CoveringIndex.scala:177-186) without needing a join at all, because our
scan is already per-file.

Single-host note: after the device exchange all shards live in this
process, so one host writes every bucket. On a multi-host mesh each host
writes only the buckets its local shards own; the layout (one file per
bucket, bucket id in the file name) is identical.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from hyperspace_tpu.constants import DATA_FILE_NAME_ID, LINEAGE_PROPERTY
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.indexes.base import UpdateMode
from hyperspace_tpu.io import parquet as pio
from hyperspace_tpu.io.columnar import Column, ColumnarBatch
from hyperspace_tpu.ops.hash import bucket_ids_np
from hyperspace_tpu.ops.sort import sort_permutation
from hyperspace_tpu.utils import resolver


# ---------------------------------------------------------------------------
# Scan side: build index data from source files
# ---------------------------------------------------------------------------


def _scan_with_lineage(
    files: Sequence[str],
    fmt: str,
    columns: List[str],
    file_ids: Optional[Dict[str, int]],
) -> ColumnarBatch:
    """Read the projection from each source file; attach `_data_file_id`
    when lineage is on (CoveringIndex.createIndexData:177-186)."""
    batches = []
    for f in files:
        t = pio.read_table([f], columns, fmt)
        b = ColumnarBatch.from_arrow(t)
        if file_ids is not None:
            fid = np.full(b.num_rows, file_ids[f], dtype=np.int64)
            b = b.with_column(
                DATA_FILE_NAME_ID, Column("numeric", pa.int64(), values=fid)
            )
        batches.append(b)
    if not batches:
        raise HyperspaceException("No source files to index")
    return ColumnarBatch.concat(batches)


def resolve_index_schema(rel, config, properties: Dict[str, str]):
    """(indexed, included, lineage, schema_json) — shared by data-building
    ``create_covering_index`` and data-free ``describe_index`` so the
    begin-phase and final log entries can never diverge."""
    import json

    indexed = [
        rc.name
        for rc in resolver.require_resolve(config.indexed_columns, rel.column_names)
    ]
    included = [
        rc.name
        for rc in resolver.require_resolve(config.included_columns, rel.column_names)
    ]
    lineage = str(properties.get(LINEAGE_PROPERTY, "false")).lower() == "true"
    schema = rel.schema
    schema_json = json.dumps(
        [[c, str(schema[c])] for c in indexed + included]
        + ([[DATA_FILE_NAME_ID, "int64"]] if lineage else [])
    )
    return indexed, included, lineage, schema_json


def describe_covering_index(ctx, source_df, config, properties: Dict[str, str]):
    """CoveringIndex object without scanning data (begin-phase log entry)."""
    from hyperspace_tpu.indexes.covering import CoveringIndex

    rel = _single_relation(source_df)
    indexed, included, _lineage, schema_json = resolve_index_schema(
        rel, config, properties
    )
    return CoveringIndex(
        indexed, included, schema_json, ctx.session.conf.num_buckets,
        dict(properties),
    )


def _single_relation(source_df):
    leaves = source_df.logical_plan.collect_leaves()
    if len(leaves) != 1:
        raise HyperspaceException(
            f"Index source must have exactly one relation; got {len(leaves)}"
        )
    return leaves[0].relation


def create_covering_index(ctx, source_df, config, properties: Dict[str, str]):
    """(CoveringIndex, index_data batch) — the reference's
    ``CoveringIndexConfig.createIndex:43-61``."""
    from hyperspace_tpu.indexes.covering import CoveringIndex

    rel = _single_relation(source_df)
    indexed, included, lineage, schema_json = resolve_index_schema(
        rel, config, properties
    )
    file_ids = None
    if lineage:
        # Key file ids by the PROVIDER's (path,size,mtime) view — the same
        # keys create_metadata_relation records — or lineage ids and the
        # log entry's ids diverge for lake sources (Delta mtimes come from
        # the log, Iceberg pins mtime=0).
        file_ids = {}
        for path, size, mtime in source_file_infos(ctx.session, rel):
            file_ids[path] = ctx.file_id_tracker.add_file(path, size, mtime)
    batch = _scan_with_lineage(rel.files, rel.fmt, indexed + included, file_ids)
    index = CoveringIndex(
        indexed_columns=indexed,
        included_columns=included,
        schema_json=schema_json,
        num_buckets=ctx.session.conf.num_buckets,
        properties=dict(properties),
    )
    return index, batch


def source_file_infos(session, plan_relation) -> List[Tuple[str, int, int]]:
    """(path, size, mtime) via the source provider SPI — restricted to the
    plan relation's current file subset (refresh passes appended-only
    relations)."""
    provider_rel = session.source_manager.get_relation(plan_relation)
    subset = set(plan_relation.files)
    return [
        (p, size, mtime)
        for p, size, mtime in provider_rel.all_file_infos()
        if p in subset
    ]


# ---------------------------------------------------------------------------
# Shuffle + sort + bucketed write
# ---------------------------------------------------------------------------


def _decompose(batch: ColumnarBatch):
    """Flatten a batch into device-movable arrays + reassembly spec."""
    arrays: List[np.ndarray] = []
    spec = []
    for name, col in batch.columns.items():
        if col.kind == "string":
            arrays.append(col.codes)
            spec.append(("string", name, col.arrow_type, col.dictionary, False))
        else:
            arrays.append(col.values)
            has_validity = col.validity is not None
            if has_validity:
                arrays.append(col.validity)
            spec.append(("numeric", name, col.arrow_type, None, has_validity))
    return arrays, spec


def _reassemble(spec, arrays: List[np.ndarray]) -> ColumnarBatch:
    cols = {}
    it = iter(arrays)
    for kind, name, atype, dictionary, has_validity in spec:
        if kind == "string":
            cols[name] = Column(
                "string", atype, codes=next(it).astype(np.int32),
                dictionary=dictionary,
            )
        else:
            values = next(it)
            validity = next(it) if has_validity else None
            cols[name] = Column("numeric", atype, values=values, validity=validity)
    return ColumnarBatch(cols)


def bucketize(ctx, batch: ColumnarBatch, indexed_cols: List[str], num_buckets: int):
    """Route rows to buckets -> (bucket_ids, batch) in bucket-grouped,
    key-sorted order. Uses the mesh all-to-all when >1 device."""
    reps = batch.key_reps(indexed_cols)
    mesh = ctx.mesh
    if mesh.devices.size > 1 and batch.num_rows >= mesh.devices.size:
        from hyperspace_tpu.parallel.shuffle import bucket_shuffle

        arrays, spec = _decompose(batch)
        k = reps.shape[0]
        buckets, moved = bucket_shuffle(
            mesh, reps, list(reps) + arrays, num_buckets
        )
        reps = np.stack(moved[:k]) if k else np.zeros((0, len(buckets)))
        batch = _reassemble(spec, moved[k:])
    else:
        buckets = bucket_ids_np(reps, num_buckets)
    perm = sort_permutation(reps, buckets)
    return buckets[perm], batch.take(perm)


def write_bucketed(
    ctx,
    batch: ColumnarBatch,
    indexed_cols: List[str],
    num_buckets: int,
    file_idx_offset: int = 0,
) -> List[str]:
    """The full build pipeline tail: shuffle, sort-within-bucket, write one
    parquet per bucket (CoveringIndex.write:56-71 + saveWithBuckets)."""
    if batch.num_rows == 0:
        import os

        os.makedirs(ctx.index_data_path, exist_ok=True)
        return []
    buckets, batch = bucketize(ctx, batch, indexed_cols, num_buckets)
    return pio.write_bucket_files(
        ctx.index_data_path, buckets, batch, num_buckets, file_idx_offset
    )


# ---------------------------------------------------------------------------
# Optimize / refresh data plane
# ---------------------------------------------------------------------------


def rewrite_files(
    ctx, files_to_optimize: List[str], indexed_cols: List[str], num_buckets: int
) -> List[str]:
    """Optimize: read the listed index files and rewrite them compacted
    (CoveringIndexTrait.optimize:130-134 — 'read files → write')."""
    batch = ColumnarBatch.from_arrow(pio.read_table(files_to_optimize, None))
    return write_bucketed(ctx, batch, indexed_cols, num_buckets)


def refresh_incremental(
    ctx,
    index,
    appended_df,
    deleted_source_file_ids: List[int],
    previous_content,
):
    """CoveringIndexTrait.refreshIncremental:57-106.

    * appended source files -> index their rows into the new version dir;
    * deleted source files  -> previous index data rewritten minus rows
      whose lineage id is among the deleted (anti-filter), also into the
      new version dir.
    Returns (index, UpdateMode.MERGE | OVERWRITE).
    """
    schema_cols = list(index.indexed_columns) + list(index.included_columns)
    if index.lineage_enabled:
        schema_cols.append(DATA_FILE_NAME_ID)
    parts: List[ColumnarBatch] = []
    if appended_df is not None:
        _index2, appended_batch = create_covering_index(
            ctx,
            appended_df,
            _config_of(index),
            dict(index.properties),
        )
        parts.append(appended_batch.select(schema_cols))
    if deleted_source_file_ids:
        if not index.lineage_enabled:
            raise HyperspaceException(
                "Cannot handle deleted source files without lineage"
            )
        old = ColumnarBatch.from_arrow(
            pio.read_table(list(previous_content.files), None)
        )
        lineage = old.column(DATA_FILE_NAME_ID).values
        keep = ~np.isin(
            lineage, np.array(deleted_source_file_ids, dtype=np.int64)
        )
        parts.append(old.filter(keep).select(schema_cols))
        mode = UpdateMode.OVERWRITE
    else:
        mode = UpdateMode.MERGE
    if parts:
        batch = ColumnarBatch.concat(parts)
        write_bucketed(ctx, batch, index.indexed_columns, index.num_buckets)
    return index, mode


def refresh_full(ctx, index, df):
    """Rebuild the whole index from the current source
    (CoveringIndexTrait.refreshFull:108-126). Returns the REBUILT index —
    its schema_json reflects the current source types, which may have
    changed since the original build."""
    new_index, batch = create_covering_index(
        ctx, df, _config_of(index), dict(index.properties)
    )
    write_bucketed(ctx, batch, new_index.indexed_columns, new_index.num_buckets)
    return new_index


def _config_of(index):
    from hyperspace_tpu.indexes.covering import CoveringIndexConfig

    return CoveringIndexConfig(
        "__refresh__", index.indexed_columns, index.included_columns
    )
