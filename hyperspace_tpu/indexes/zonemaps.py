"""Zone maps: per-file and per-row-group min/max (+ null counts) for
index data files, and the serve-side pruning pass built on them.

The reference gets its z-order/covering range payoff for free from
Spark's parquet min/max row-group pruning; our index files have carried
64k-row-group statistics since the first build (``io/parquet.py``
``INDEX_ROW_GROUP_SIZE``) that nothing ever read. This module closes the
loop (docs/range-serve.md):

* **capture** — at build/refresh/optimize time the actions write a
  ``_zonemaps.json`` sidecar into the version directory (underscore
  prefix: invisible to ``Content.from_directory_scan`` and the data-path
  filter) holding per-file/per-row-group min/max + null counts and, for
  z-order indexes, the per-row-group **z-address spans** plus the frozen
  encoder spec that produced them — the one thing parquet footers cannot
  provide;
* **lazy backfill** — pre-existing indexes (and files whose sidecar
  entry is stale) read the same statistics straight from parquet
  footers, memoized per file identity (path, size, mtime_ns), so a
  rewritten file can never serve stale zone maps;
* **pruning** — ``prune_scan_relation`` intersects per-column intervals
  extracted from the predicate's range/Eq/In conjuncts with the zone
  maps in one vectorized pass, drops dead files, and narrows kept files
  to matching row groups (``Relation.file_row_groups``; read by
  ``io/parquet.read_table_row_groups``). Z-order relations additionally
  prune in z-space via ``ops/zorder.z_box_ranges``.

Soundness contract: every decision is SUPERSET-safe — a file/row group
is dropped only when no row in it can satisfy the conjunction (nulls and
NaN rows never satisfy a comparison conjunct in this engine, so all-null
groups prune and NaN-poisoned statistics abstain). Statistics bounds are
converted to a float64 comparable domain with OUTWARD directed rounding
(file bounds widen, literal bounds widen), so rounding can only
over-keep, never over-prune. The executor re-applies the full mask on
whatever survives, exactly as before.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import functools
import json
import logging
import math
import os
import threading
from bisect import bisect_left
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.plan import expressions as E

_log = logging.getLogger("hyperspace_tpu.zonemaps")

SIDECAR_NAME = "_zonemaps.json"
_SIDECAR_VERSION = 1

# ---------------------------------------------------------------------------
# Predicate → per-column intervals (shared with indexes/sketches.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ColInterval:
    """One column's interval under the conjunction, in ENGINE domain
    (temporal literals lowered to int64 ticks with the same op-aware
    snapping the mask uses; strings as python str). ``None`` bound =
    unbounded; ``empty`` = the conjuncts contradict (or a literal can
    never match), so no row anywhere satisfies them."""

    lo: Any = None
    hi: Any = None
    lo_strict: bool = False
    hi_strict: bool = False
    empty: bool = False


def _is_string_type(t: pa.DataType) -> bool:
    if pa.types.is_dictionary(t):
        t = t.value_type
    return pa.types.is_string(t) or pa.types.is_large_string(t)


def _plain_number(lit):
    """Literal as a plain int/float comparable against numeric statistics,
    or None to abstain (the mask path may still match it; never prune)."""
    if isinstance(lit, (np.integer, np.floating)):
        lit = lit.item()
    if isinstance(lit, bool):
        return int(lit)
    if isinstance(lit, float) and math.isnan(lit):
        # NaN comparisons are never true — but "=" against NaN is handled
        # by the empty interval below only for floats; abstaining is
        # always sound and keeps this helper single-purpose
        return None
    if isinstance(lit, (int, float)):
        return lit
    return None


def interval_for(op: str, lit, t: pa.DataType) -> Optional[ColInterval]:
    """Interval of one ``col <op> lit`` conjunct, or None to abstain.
    Public: the MinMaxSketch probe translates its conjuncts through this
    same lowering so sketch and zone-map pruning cannot disagree."""
    if _is_string_type(t):
        # the engine str-casts literals for string columns
        # (plan/expressions._cmp), so mirror it
        val: Any = str(lit)
    elif pa.types.is_temporal(t):
        val = E.lower_literal(lit, t, op)
        if val is None:
            # op-aware lowering says the comparison can never hold (e.g.
            # equality against a between-tick instant, or an
            # unparseable literal) — exactly the engine's all-False mask
            return ColInterval(empty=True)
    else:
        val = _plain_number(lit)
        if val is None:
            return None
    if op == "=":
        return ColInterval(lo=val, hi=val)
    if op == "<":
        return ColInterval(hi=val, hi_strict=True)
    if op == "<=":
        return ColInterval(hi=val)
    if op == ">":
        return ColInterval(lo=val, lo_strict=True)
    if op == ">=":
        return ColInterval(lo=val)
    return None


def _in_interval(values, t: pa.DataType) -> Optional[ColInterval]:
    """[min, max] hull of an IN list's matchable literals (a superset of
    the point set, which is all pruning needs); empty when no literal can
    match — mirroring the engine's all-False IN mask."""
    if _is_string_type(t):
        vs = [v for v in values if isinstance(v, str)]
        if not vs:
            return ColInterval(empty=True)
        return ColInterval(lo=min(vs), hi=max(vs))
    lits = E.lower_in_literals([v for v in values if v is not None], t)
    lits = [int(v) if isinstance(v, bool) else v for v in lits]
    if not lits:
        return ColInterval(empty=True)
    return ColInterval(lo=min(lits), hi=max(lits))


def _merge(a: ColInterval, b: ColInterval) -> ColInterval:
    if a.empty or b.empty:
        return ColInterval(empty=True)
    lo, los = a.lo, a.lo_strict
    if b.lo is not None and (
        lo is None or b.lo > lo or (b.lo == lo and b.lo_strict)
    ):
        lo, los = b.lo, b.lo_strict
    hi, his = a.hi, a.hi_strict
    if b.hi is not None and (
        hi is None or b.hi < hi or (b.hi == hi and b.hi_strict)
    ):
        hi, his = b.hi, b.hi_strict
    out = ColInterval(lo=lo, hi=hi, lo_strict=los, hi_strict=his)
    if lo is not None and hi is not None:
        if lo > hi or (lo == hi and (los or his)):
            out.empty = True
    return out


def predicate_intervals(
    cond: E.Expr, schema: Dict[str, pa.DataType]
) -> Dict[str, ColInterval]:
    """Per-column intervals from the predicate's top-level range/Eq/In
    conjuncts (``!=``, OR trees, IS NULL and anything non-lowerable
    abstain). Keys are the ACTUAL schema column names. Shared by zone-map
    pruning and the MinMaxSketch probe so the two can never disagree on
    literal lowering."""
    cols = {c.lower(): c for c in schema}
    out: Dict[str, ColInterval] = {}
    for cj in E.split_conjuncts(cond):
        norm = E.normalize_comparison(cj)
        col = None
        iv = None
        if norm is not None:
            op, name, lit = norm
            if op == "!=":
                continue
            col = cols.get(name.lower())
            if col is None:
                continue
            iv = interval_for(op, lit, schema[col])
        elif isinstance(cj, E.In) and isinstance(cj.child, E.Col):
            col = cols.get(cj.child.name.lower())
            if col is None:
                continue
            iv = _in_interval(cj.values, schema[col])
        if iv is None or col is None:
            continue
        out[col] = _merge(out[col], iv) if col in out else iv
    return out


def predicate_intervals_complete(
    cond: E.Expr, schema: Dict[str, pa.DataType]
) -> Optional[Dict[str, ColInterval]]:
    """:func:`predicate_intervals`, but None unless EVERY top-level
    conjunct lowered into an interval on a known column — for consumers
    whose soundness needs the intervals to BE the predicate, not merely
    bound it (the aggregate plane's full-coverage classification,
    ``indexes/aggindex.py``: a row group may be answered from persisted
    partials only when *all* of its rows provably satisfy the whole
    conjunction).

    Deliberately stricter than the pruning lowering: ``IN`` lists abstain
    here even though pruning accepts their [min, max] hull — the hull is
    a superset of the point set, sound for keep/drop decisions but NOT
    for "every row matches". Same for ``!=``, OR trees, IS NULL and any
    non-lowerable conjunct."""
    cols = {c.lower(): c for c in schema}
    out: Dict[str, ColInterval] = {}
    for cj in E.split_conjuncts(cond):
        norm = E.normalize_comparison(cj)
        if norm is None:
            return None
        op, name, lit = norm
        if op == "!=":
            return None
        col = cols.get(name.lower())
        if col is None:
            return None
        iv = interval_for(op, lit, schema[col])
        if iv is None:
            return None
        out[col] = _merge(out[col], iv) if col in out else iv
    return out


# ---------------------------------------------------------------------------
# Comparable-domain conversion (directed rounding — see module docstring)
# ---------------------------------------------------------------------------


def f64_down(v) -> float:
    """Largest float64 <= v (np.float64 subclasses python float, so the
    int-vs-float comparison below is exact at arbitrary precision)."""
    f = np.float64(v)
    if f > v:
        f = np.nextafter(f, -np.inf)
    return float(f)


def f64_up(v) -> float:
    f = np.float64(v)
    if f < v:
        f = np.nextafter(f, np.inf)
    return float(f)


def _stat_engine_value(v, t: pa.DataType):
    """A statistics cell (python value out of a parquet footer or sidecar)
    in the engine's comparable domain for arrow type ``t``: str for
    string columns, int ticks for temporals, int/float otherwise. None =
    unusable (abstain; the group stays unpruned)."""
    if v is None:
        return None
    if isinstance(v, np.generic):
        v = v.item()
    if _is_string_type(t):
        return v if isinstance(v, str) else None
    if pa.types.is_temporal(t):
        from hyperspace_tpu.io.columnar import Column

        try:
            arr = pa.array([v], type=t)
        except (pa.ArrowInvalid, pa.ArrowTypeError, TypeError, OverflowError):
            return None
        col = Column.from_arrow(arr)
        if col.null_mask is not None:
            return None
        return int(col.values[0])
    if pa.types.is_boolean(t):
        return int(bool(v)) if isinstance(v, bool) else None
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, float):
        return None if math.isnan(v) else v
    if isinstance(v, int):
        return v
    return None


# ---------------------------------------------------------------------------
# Per-file statistics: parquet footers (lazy backfill) + sidecar capture
# ---------------------------------------------------------------------------


def _read_footer_zones(path: str) -> dict:
    """Raw per-row-group statistics of one parquet file, every flat
    column: {"rg_rows": [...], "cols": {name: [(min, max, nulls)|None per
    rg]}}. Values are pyarrow's logical-type conversions (date →
    datetime.date etc.); a row group whose chunk carries no usable
    min/max gets (None, None, nulls) so all-null detection still works."""
    md = pq.ParquetFile(path).metadata
    idx_of: Dict[str, int] = {}
    for j in range(md.num_columns):
        idx_of.setdefault(md.schema.column(j).path, j)
    rg_rows: List[int] = []
    cols: Dict[str, list] = {name: [] for name in idx_of}
    for i in range(md.num_row_groups):
        rg = md.row_group(i)
        rg_rows.append(rg.num_rows)
        for name, j in idx_of.items():
            cc = rg.column(j)
            st = cc.statistics
            if st is None:
                cols[name].append(None)
                continue
            nulls = st.null_count if st.has_null_count else None
            if st.has_min_max:
                cols[name].append((st.min, st.max, nulls))
            else:
                cols[name].append((None, None, nulls))
    return {"rg_rows": rg_rows, "cols": cols}


@functools.lru_cache(maxsize=4096)
def _footer_zones_cached(path: str, _size: int, _mtime_ns: int) -> dict:
    return _read_footer_zones(path)


def footer_zones(path: str) -> Optional[dict]:
    """Memoized footer statistics keyed by file identity — a rewritten
    file gets a fresh read (stale-eviction by construction). None when
    the file or its footer is unreadable (caller keeps the whole file)."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    try:
        return _footer_zones_cached(path, st.st_size, st.st_mtime_ns)
    except (OSError, ValueError, KeyError, pa.ArrowInvalid):
        return None


# -- sidecar value (de)serialization ----------------------------------------


def _enc_stat(v):
    if isinstance(v, np.generic):
        v = v.item()
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else {"t": "f", "v": repr(v)}
    if isinstance(v, _dt.datetime):
        return {"t": "dt", "v": v.isoformat()}
    if isinstance(v, _dt.date):
        return {"t": "d", "v": v.isoformat()}
    if isinstance(v, _dt.time):
        return {"t": "tm", "v": v.isoformat()}
    if isinstance(v, _dt.timedelta):
        return {"t": "td", "v": [v.days, v.seconds, v.microseconds]}
    return {"t": "x"}  # unencodable: decodes to None (abstain)


def _dec_stat(v):
    if not isinstance(v, dict):
        return v
    t = v.get("t")
    try:
        if t == "f":
            return float(v["v"])
        if t == "dt":
            return _dt.datetime.fromisoformat(v["v"])
        if t == "d":
            return _dt.date.fromisoformat(v["v"])
        if t == "tm":
            return _dt.time.fromisoformat(v["v"])
        if t == "td":
            d, s, us = v["v"]
            return _dt.timedelta(days=d, seconds=s, microseconds=us)
    except (ValueError, KeyError, TypeError):
        return None
    return None


@functools.lru_cache(maxsize=256)
def _sidecar_cached(path: str, _size: int, _mtime_ns: int) -> Optional[dict]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if data.get("version") != _SIDECAR_VERSION:
        return None
    return data


def _sidecar_for_dir(dirpath: str) -> Optional[dict]:
    path = os.path.join(dirpath, SIDECAR_NAME)
    try:
        st = os.stat(path)
    except OSError:
        return None
    return _sidecar_cached(path, st.st_size, st.st_mtime_ns)


# ---------------------------------------------------------------------------
# Capture (build/refresh/optimize time)
# ---------------------------------------------------------------------------


def capture_index_dir(dir_path: str, index) -> bool:
    """Write the ``_zonemaps.json`` sidecar for one freshly-written index
    version directory. Covering-family indexes only (a data-skipping
    sketch table is itself metadata). Z-order indexes additionally get
    per-row-group z-address spans under a frozen encoder spec fit on the
    directory's own data (one extra read of the indexed columns, paid at
    build time so the serve path never has to). Returns True when a
    sidecar was written; failures only cost the lazy-backfill path."""
    kind = getattr(index, "kind", "")
    if kind not in ("CoveringIndex", "ZOrderCoveringIndex"):
        return False
    from hyperspace_tpu.io import parquet as pio

    try:
        files = pio.list_format_files(dir_path, "parquet")
    except (OSError, KeyError):
        return False
    if not files:
        return False
    footers = {}
    for f in files:
        fz = footer_zones(f)
        if fz is not None:
            footers[f] = fz
    doc: dict = {"version": _SIDECAR_VERSION, "files": {}}
    for f, fz in footers.items():
        st = os.stat(f)
        doc["files"][os.path.basename(f)] = {
            "size": st.st_size,
            "mtime_ns": st.st_mtime_ns,
            "rg_rows": list(fz["rg_rows"]),
            "cols": {
                name: [
                    None
                    if e is None
                    else [_enc_stat(e[0]), _enc_stat(e[1]), e[2]]
                    for e in entries
                ]
                for name, entries in fz["cols"].items()
            },
        }
    if kind == "ZOrderCoveringIndex":
        try:
            _capture_zspans(doc, files, footers, list(index.indexed_columns))
        # z capture is best-effort extra sharpness: any failure (exotic
        # dtype, memory pressure) must leave the min/max sidecar usable
        except Exception as exc:  # hslint: disable=HS402
            _log.warning("z-span capture failed for %s: %s", dir_path, exc)
    tmp = os.path.join(dir_path, f".{SIDECAR_NAME}.tmp.{os.getpid()}")
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(dir_path, SIDECAR_NAME))
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    return True


def capture_safely(dir_path: str, index) -> None:
    """The actions' capture entry: a zone-map sidecar is a precomputed
    optimization (the serve path backfills from footers without it), so
    no capture failure may ever fail a build/refresh/optimize."""
    try:
        capture_index_dir(dir_path, index)
    except Exception as exc:  # hslint: disable=HS402
        _log.warning("zone-map capture failed for %s: %s", dir_path, exc)


_Z_BITS = 16


def _capture_zspans(doc, files, footers, zcols: List[str]) -> None:
    """Per-row-group z-address spans for a z-order version dir, two
    passes bounded by the largest file: (1) fit a frozen range/dict
    encoder spec over the directory's data, (2) per file, compute planes
    and record each row group's packed (z_lo, z_hi)."""
    from hyperspace_tpu.io import parquet as pio
    from hyperspace_tpu.io.columnar import ColumnarBatch
    from hyperspace_tpu.ops.zorder import (
        ZOrderEncoder,
        order_u64_np,
        planes_z_minmax,
    )

    k = len(zcols)
    mins: List[Optional[int]] = [None] * k
    maxs: List[Optional[int]] = [None] * k
    dicts: List[Optional[set]] = [None] * k
    # pass 1 (spec fit) reads per file and discards, pass 2 re-reads per
    # file: peak memory stays bounded by the largest file's indexed
    # columns, not the whole index
    for f in files:
        batch = ColumnarBatch.from_arrow(pio.read_table([f], zcols))
        for j, c in enumerate(zcols):
            col = batch.column(c)
            if col.kind == "string":
                if dicts[j] is None:
                    dicts[j] = set()
                dicts[j].update(col.dictionary)
                continue
            e = order_u64_np(col)
            if not len(e):
                continue
            lo, hi = int(e.min()), int(e.max())
            mins[j] = lo if mins[j] is None else min(mins[j], lo)
            maxs[j] = hi if maxs[j] is None else max(maxs[j], hi)
    specs = []
    for j in range(k):
        if dicts[j] is not None:
            specs.append(("dict", sorted(dicts[j])))
        else:
            specs.append(
                (
                    "range",
                    np.uint64(mins[j] or 0),
                    np.uint64(maxs[j] or 0),
                )
            )
    encoder = ZOrderEncoder(_Z_BITS, specs)
    nplanes = None
    for f in files:
        fz = footers.get(f)
        entry = doc["files"].get(os.path.basename(f))
        if fz is None or entry is None:
            continue
        batch = ColumnarBatch.from_arrow(pio.read_table([f], zcols))
        planes = encoder.planes([batch.column(c) for c in zcols])
        nplanes = planes.shape[0]
        spans = []
        pos = 0
        for rows in fz["rg_rows"]:
            mm = planes_z_minmax(planes, pos, pos + rows)
            spans.append(
                None if mm is None else [format(mm[0], "x"), format(mm[1], "x")]
            )
            pos += rows
        entry["rg_zspans"] = spans
    doc["zorder"] = {
        "columns": list(zcols),
        "bits": _Z_BITS,
        "nplanes": int(nplanes or 1),
        "specs": [
            ["dict", s[1]]
            if s[0] == "dict"
            else ["range", str(int(s[1])), str(int(s[2]))]
            for s in specs
        ],
    }


# ---------------------------------------------------------------------------
# Assembled zone data for one relation (serve side)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ColZones:
    domain: str  # "num" | "str"
    lo: np.ndarray  # float64 (down-rounded) or object, per row group
    hi: np.ndarray  # float64 (up-rounded) or object
    has: np.ndarray  # bool: bounds usable
    allnull: np.ndarray  # bool: the group holds only nulls


@dataclasses.dataclass
class ZoneData:
    """Query-independent zone maps for one file set, assembled once and
    cached (ServeCache kind "zonemap" when serve-server mode is on, else
    a small module LRU). Row groups are flattened across files."""

    files: Tuple[str, ...]
    rg_file: np.ndarray  # row group -> file index
    rg_index: np.ndarray  # row group ordinal within its file
    opaque: np.ndarray  # per FILE: stats unreadable, never narrow it
    cols: Dict[str, ColZones]
    zspans: list  # per row group: (z_lo, z_hi) python ints or None
    zspecs: Dict[str, dict]  # dir path -> zorder spec doc
    rg_spec: list  # per row group: dir path (zspec key) or None
    sidecar_files: int
    footer_files: int

    @property
    def nbytes(self) -> int:
        n = len(self.rg_file)
        return 64 * n * max(len(self.cols), 1) + 128 * len(self.files)


def _file_stats_from_sidecar(path: str, side: Optional[dict]):
    """This file's decoded sidecar stats when present AND stat-fresh
    (size + mtime_ns match the file on disk), else None — a refreshed or
    rewritten file silently falls back to its own footer."""
    if side is None:
        return None
    entry = side.get("files", {}).get(os.path.basename(path))
    if entry is None:
        return None
    try:
        st = os.stat(path)
    except OSError:
        return None
    if entry.get("size") != st.st_size or entry.get("mtime_ns") != st.st_mtime_ns:
        return None
    cols = {
        name: [
            None if e is None else (_dec_stat(e[0]), _dec_stat(e[1]), e[2])
            for e in entries
        ]
        for name, entries in entry.get("cols", {}).items()
    }
    out = {"rg_rows": list(entry.get("rg_rows", [])), "cols": cols}
    if entry.get("rg_zspans") is not None:
        spans = []
        for s in entry["rg_zspans"]:
            spans.append(None if s is None else (int(s[0], 16), int(s[1], 16)))
        out["rg_zspans"] = spans
    return out


def column_zones(cells, t: pa.DataType) -> "ColZones":
    """One column's :class:`ColZones` from per-group statistics cells —
    the SINGLE assembly point shared by :func:`assemble_zone_data` (cells
    = row groups) and ``MinMaxSketch`` (cells = sketch-table rows), so
    the comparable-domain conversion and placeholder rules cannot
    diverge. Each cell is the string ``"allnull"`` (the group holds only
    nulls), a ``(vmin, vmax)`` pair of raw statistics values, or None
    (no usable statistics: abstain, the group is always kept)."""
    n = len(cells)
    domain = "str" if _is_string_type(t) else "num"
    # string placeholders must be COMPARABLE (None would raise in the
    # object-array compares); ~has masks them out of every decision
    lo = (
        np.full(n, "", dtype=object)
        if domain == "str"
        else np.zeros(n, dtype=np.float64)
    )
    hi = (
        np.full(n, "", dtype=object)
        if domain == "str"
        else np.zeros(n, dtype=np.float64)
    )
    has = np.zeros(n, dtype=bool)
    allnull = np.zeros(n, dtype=bool)
    for gi, cell in enumerate(cells):
        if cell is None:
            continue
        if cell == "allnull":
            allnull[gi] = True
            continue
        ev_min = _stat_engine_value(cell[0], t)
        ev_max = _stat_engine_value(cell[1], t)
        if ev_min is None or ev_max is None:
            continue  # unusable cell: abstain for this group
        if domain == "str":
            lo[gi], hi[gi] = ev_min, ev_max
        else:
            lo[gi], hi[gi] = f64_down(ev_min), f64_up(ev_max)
        has[gi] = True
    return ColZones(domain, lo, hi, has, allnull)


#: lazy-backfill chunking: per-file statistics (sidecar entries, decoded
#: parquet footers) are folded into the per-column cell lists this many
#: files at a time, and the per-directory sidecar dicts are dropped at
#: every chunk boundary — so assembling a huge relation never holds the
#: whole backfill's decoded statistics at once, only one chunk of them
#: plus the O(row-group) cells (ALLOC_SITES: chunk-bounded). A directory
#: spanning chunks re-reads its sidecar at most once per chunk.
_ASSEMBLE_CHUNK_FILES = 64


def assemble_zone_data(
    files: Tuple[str, ...], schema: Dict[str, pa.DataType]
) -> ZoneData:
    rg_file: List[int] = []
    rg_index: List[int] = []
    opaque = np.zeros(len(files), dtype=bool)
    zspans: list = []
    rg_spec: list = []
    zspecs: Dict[str, dict] = {}
    sidecar_n = footer_n = 0
    side_by_dir: Dict[str, Optional[dict]] = {}
    # per-column cell lists — the ONLY per-row-group state that survives
    # a chunk; each cell is None / "allnull" / a (vmin, vmax) pair
    cells_by_col: Dict[str, List] = {name: [] for name in schema}
    col_seen: Dict[str, bool] = {name: False for name in schema}

    def _fold(rows: Optional[int], rg_cols: Optional[dict]) -> None:
        # derive one row group's cell per schema column; the full stats
        # dict it came from dies with the chunk
        for name in schema:
            entry = rg_cols.get(name) if rg_cols is not None else None
            if entry is None:
                cells_by_col[name].append(None)
                continue
            col_seen[name] = True
            vmin, vmax, nulls = entry
            if vmin is None and vmax is None:
                if nulls is not None and rows and nulls == rows:
                    cells_by_col[name].append("allnull")
                else:
                    cells_by_col[name].append(None)
                continue
            cells_by_col[name].append((vmin, vmax))

    for c0 in range(0, len(files), _ASSEMBLE_CHUNK_FILES):
        side_by_dir.clear()  # chunk boundary: drop the decoded sidecars
        for off, path in enumerate(files[c0 : c0 + _ASSEMBLE_CHUNK_FILES]):
            fi = c0 + off
            d = os.path.dirname(path)
            if d not in side_by_dir:
                side_by_dir[d] = _sidecar_for_dir(d)
            side = side_by_dir[d]
            stats = _file_stats_from_sidecar(path, side)
            if stats is not None:
                sidecar_n += 1
            else:
                stats = footer_zones(path)
                if stats is not None:
                    footer_n += 1
            if stats is None:
                opaque[fi] = True
                rg_file.append(fi)
                rg_index.append(0)
                _fold(None, None)
                zspans.append(None)
                rg_spec.append(None)
                continue
            spans = stats.get("rg_zspans")
            spec = side.get("zorder") if side else None
            if spec is not None and spans is not None:
                zspecs.setdefault(d, spec)
            n_rg = len(stats["rg_rows"])
            for gi in range(n_rg):
                rg_file.append(fi)
                rg_index.append(gi)
                _fold(
                    stats["rg_rows"][gi],
                    {
                        name: entries[gi]
                        for name, entries in stats["cols"].items()
                        if gi < len(entries)
                    },
                )
                if spans is not None and spec is not None and gi < len(spans):
                    zspans.append(spans[gi])
                    rg_spec.append(d)
                else:
                    zspans.append(None)
                    rg_spec.append(None)
    cols: Dict[str, ColZones] = {}
    for name, t in schema.items():
        if col_seen[name]:
            cols[name] = column_zones(cells_by_col[name], t)
    return ZoneData(
        files=tuple(files),
        rg_file=np.asarray(rg_file, dtype=np.int64),
        rg_index=np.asarray(rg_index, dtype=np.int64),
        opaque=opaque,
        cols=cols,
        zspans=zspans,
        zspecs=zspecs,
        rg_spec=rg_spec,
        sidecar_files=sidecar_n,
        footer_files=footer_n,
    )


# Module-level bounded LRU for assembled zone data, so pruning works at
# full speed with serve-server mode OFF (the default). Keyed by the file
# fingerprint, same staleness story as the ServeCache entries. Bounded
# in BYTES as well as entries (entries carry their zd.nbytes in the
# value; _local_bytes is the ledger) — 64 wide-relation zone maps can be
# gigabytes, and an entry cap alone is not a residency bound
# (ALLOC_SITES doctrine, memory.py).
# SHARED_STATE-registered ("guarded": every access under _local_lock);
# the runtime lock witness wraps _local_lock during the stress suites.
_local_lock = threading.Lock()
_local_cache: "OrderedDict[tuple, Tuple[ZoneData, int]]" = OrderedDict()
_local_bytes = 0
_LOCAL_CACHE_ENTRIES = 64
_LOCAL_CACHE_MAX_BYTES = 256 << 20


def _local_put(key, zd: ZoneData, nbytes: int) -> None:
    """Insert into the module LRU, evicting oldest-first until both the
    entry cap and the byte cap hold. Caller must NOT hold _local_lock."""
    global _local_bytes
    if nbytes > _LOCAL_CACHE_MAX_BYTES:
        return  # larger than the whole fallback cache: not cacheable
    with _local_lock:
        old = _local_cache.pop(key, None)
        if old is not None:
            _local_bytes -= old[1]
        while _local_cache and (
            len(_local_cache) >= _LOCAL_CACHE_ENTRIES
            or _local_bytes + nbytes > _LOCAL_CACHE_MAX_BYTES
        ):
            _, (_zd, freed) = _local_cache.popitem(last=False)
            _local_bytes -= freed
        _local_cache[key] = (zd, nbytes)
        _local_bytes += nbytes


def zone_data_for(rel, cache=None) -> Optional[Tuple[ZoneData, bool]]:
    """(assembled zone data, was_cache_hit) for a relation's file set, or
    None when the files cannot be fingerprinted (caller skips pruning)."""
    from hyperspace_tpu.execution.serve_cache import file_fingerprint

    fp = file_fingerprint(rel.files)
    if fp is None:
        return None
    key = ("zonemap", fp)
    if cache is not None:
        hit = cache.get(key)
        if hit is not None:
            return hit, True
    with _local_lock:
        hit = _local_cache.get(key)
        if hit is not None:
            _local_cache.move_to_end(key)
            return hit[0], True
    zd = assemble_zone_data(tuple(rel.files), rel.schema)
    nbytes = zd.nbytes
    if cache is not None:
        cache.put(key, zd, nbytes)
    _local_put(key, zd, nbytes)
    return zd, False


def invalidate_local_cache() -> None:
    """Tests / operational tooling: drop the module-level assembled-map
    cache (the lru_cached footer/sidecar reads are keyed by file identity
    and never serve stale)."""
    global _local_bytes
    with _local_lock:
        _local_cache.clear()
        _local_bytes = 0


def invalidate_paths_under(root: str) -> int:
    """Drop only the LRU entries whose fingerprint names a file under
    ``root`` — the fleet fanout's scoped invalidation
    (``serve/bus.py``): a refresh of index A must not cost index B its
    warm assembled state. Entries are fingerprint-keyed so this is pure
    memory reclamation, never a staleness fix."""
    prefix = root.replace("\\", "/").rstrip("/") + "/"

    def _mentions(obj) -> bool:
        if isinstance(obj, str):
            return obj.replace("\\", "/").startswith(prefix)
        if isinstance(obj, tuple):
            return any(_mentions(x) for x in obj)
        return False

    global _local_bytes
    with _local_lock:
        victims = [k for k in _local_cache if _mentions(k)]
        for k in victims:
            _zd, freed = _local_cache.pop(k)
            _local_bytes -= freed
        return len(victims)


# ---------------------------------------------------------------------------
# The pruning pass
# ---------------------------------------------------------------------------

last_prune_stats: Dict[str, Any] = {}


def zone_keep_mask(cz: ColZones, iv: ColInterval) -> np.ndarray:
    """Keep-mask over row groups for one column's interval: a group
    survives when its bounds are unusable (abstain) or overlap the
    interval; all-null groups never satisfy a comparison conjunct."""
    n = len(cz.has)
    if iv.empty:
        return np.zeros(n, dtype=bool)
    overlap = np.ones(n, dtype=bool)
    if iv.lo is not None:
        if cz.domain == "str":
            if isinstance(iv.lo, str):
                overlap &= (cz.hi > iv.lo) if iv.lo_strict else (cz.hi >= iv.lo)
        else:
            lof = f64_down(iv.lo)
            overlap &= (cz.hi > lof) if iv.lo_strict else (cz.hi >= lof)
    if iv.hi is not None:
        if cz.domain == "str":
            if isinstance(iv.hi, str):
                overlap &= (cz.lo < iv.hi) if iv.hi_strict else (cz.lo <= iv.hi)
        else:
            hif = f64_up(iv.hi)
            overlap &= (cz.lo < hif) if iv.hi_strict else (cz.lo <= hif)
    return (~cz.allnull) & (overlap | ~cz.has)


def _encode_box_bound(iv: ColInterval, kind: str, sorted_dict):
    """(enc_lo, enc_hi) uint64 box bounds of one column's interval for
    z-space pruning, rounded OUTWARD; None abstains (full range),
    "empty" prunes the whole spec group."""
    from hyperspace_tpu.ops.zorder import order_u64_scalar

    if iv.empty:
        return "empty"

    def enc(v, up: bool):
        if sorted_dict is not None:
            if not isinstance(v, str):
                return None
            return bisect_left(sorted_dict, v) + 1
        if kind != "f" and isinstance(v, float):
            if math.isinf(v):
                return ("inf_pos" if v > 0 else "inf_neg")
            v = math.ceil(v) if up is False else math.floor(v)
            # NOTE: lo bounds round UP to the next representable int, hi
            # bounds round DOWN — that TIGHTENS toward the true point
            # set, which stays sound because integer columns hold no
            # between-integer values
        try:
            return order_u64_scalar(v, kind)
        except (OverflowError, ValueError, TypeError):
            return None

    enc_lo = 0 if iv.lo is None else enc(iv.lo, up=False)
    enc_hi = (1 << 64) - 1 if iv.hi is None else enc(iv.hi, up=True)
    if enc_lo == "inf_neg":
        enc_lo = 0
    if enc_hi == "inf_pos":
        enc_hi = (1 << 64) - 1
    if enc_lo == "inf_pos" or enc_hi == "inf_neg":
        return "empty"  # e.g. col >= +inf on an integer column
    if enc_lo is None or enc_hi is None:
        return None
    enc_hi = max(int(enc_hi), 1)  # null slot 0: data encodings clamp to >= 1
    return int(enc_lo), int(enc_hi)


def _z_keep_mask(zd: ZoneData, intervals, schema) -> Optional[np.ndarray]:
    """Z-space keep-mask over row groups (None = no z metadata). Only
    groups with captured spans narrow; everything else stays kept."""
    from hyperspace_tpu.ops.zorder import (
        pack_box_ranges,
        spec_word_bounds,
        z_box_ranges,
    )

    if not zd.zspecs:
        return None
    n = len(zd.rg_file)
    keep = np.ones(n, dtype=bool)
    lower_schema = {c.lower(): c for c in schema}
    ranges_by_spec: Dict[str, Optional[list]] = {}
    for spec_key, spec in zd.zspecs.items():
        bits = int(spec.get("bits", _Z_BITS))
        zcols = spec.get("columns", [])
        specs = spec.get("specs", [])
        k = len(zcols)
        if k == 0 or len(specs) != k:
            ranges_by_spec[spec_key] = None
            continue
        word_lo, word_hi = [], []
        empty = False
        abstain = False
        top = (1 << bits) - 1
        for j, cname in enumerate(zcols):
            sname = lower_schema.get(cname.lower())
            iv = intervals.get(sname) if sname else None
            if iv is None:
                word_lo.append(0)
                word_hi.append(top)
                continue
            t = schema[sname]
            if _is_string_type(t):
                kind = "s"
                sorted_dict = specs[j][1] if specs[j][0] == "dict" else None
                if sorted_dict is None:
                    abstain = True
                    break
            else:
                sorted_dict = None
                if pa.types.is_floating(t):
                    kind = "f"
                elif pa.types.is_boolean(t):
                    kind = "b"
                elif pa.types.is_unsigned_integer(t):
                    kind = "u"
                else:
                    kind = "i"
            eb = _encode_box_bound(iv, kind, sorted_dict)
            if eb == "empty":
                empty = True
                break
            if eb is None:
                abstain = True
                break
            sp = specs[j]
            sp_t = (
                ("dict", sp[1])
                if sp[0] == "dict"
                else ("range", int(sp[1]), int(sp[2]))
            )
            wb = spec_word_bounds(sp_t, eb[0], eb[1], bits)
            if wb is None:
                abstain = True
                break
            word_lo.append(wb[0])
            word_hi.append(wb[1])
        if empty:
            ranges_by_spec[spec_key] = []
            continue
        if abstain:
            ranges_by_spec[spec_key] = None
            continue
        ranges = z_box_ranges(word_lo, word_hi, bits)
        ranges_by_spec[spec_key] = pack_box_ranges(
            ranges, bits, k, int(spec.get("nplanes", 1))
        )
    for gi in range(n):
        spec_key = zd.rg_spec[gi]
        span = zd.zspans[gi]
        if spec_key is None or span is None:
            continue
        ranges = ranges_by_spec.get(spec_key)
        if ranges is None:
            continue
        a, b = span
        if not any(a <= rhi and b >= rlo for rlo, rhi in ranges):
            keep[gi] = False
    return keep


def prune_scan_relation(scan, cond: E.Expr, cache=None):
    """The range-pruning pass over one index Scan: returns a Scan over
    the surviving files with ``file_row_groups`` narrowing (the same
    node when nothing prunes). Superset-safe by construction — see the
    module docstring; the executor re-applies the full mask."""
    import dataclasses as _dc

    from hyperspace_tpu.plan.nodes import Scan

    rel = scan.relation
    stats = {
        "files_total": len(rel.files),
        "files_kept": len(rel.files),
        "row_groups_total": 0,
        "row_groups_kept": 0,
        "zonemap_files_sidecar": 0,
        "zonemap_files_footer": 0,
        "zonemap_cache_hit": False,
        "z_pruned": False,
    }
    global last_prune_stats
    if (
        rel.index_info is None
        or rel.fmt not in ("parquet", "delta", "iceberg")
        or not rel.files
        or rel.file_row_groups is not None
    ):
        return scan
    intervals = predicate_intervals(cond, rel.schema)
    if not intervals:
        return scan
    # from here on the pass EVALUATED this scan, so telemetry must
    # reflect it even on abstain — a consumer (bench, smoke assert) must
    # never read a previous query's stats as this one's
    last_prune_stats = stats
    got = zone_data_for(rel, cache)
    if got is None:
        return scan
    zd, was_hit = got
    stats["zonemap_cache_hit"] = was_hit
    stats["zonemap_files_sidecar"] = zd.sidecar_files
    stats["zonemap_files_footer"] = zd.footer_files
    n = len(zd.rg_file)
    stats["row_groups_total"] = n
    keep = np.ones(n, dtype=bool)
    for cname, iv in intervals.items():
        cz = zd.cols.get(cname)
        if cz is None:
            if iv.empty:
                # a contradictory conjunction matches nothing anywhere,
                # stats or not
                keep[:] = False
            continue
        keep &= zone_keep_mask(cz, iv)
    if rel.index_info[2] == "ZOCI":
        before = int(keep.sum())
        zk = _z_keep_mask(zd, intervals, rel.schema)
        if zk is not None:
            keep &= zk
            stats["z_pruned"] = int(keep.sum()) < before
    # opaque files (unreadable stats) are never narrowed
    keep |= zd.opaque[zd.rg_file]
    stats["row_groups_kept"] = int(keep.sum())
    # per-execution attribution: the calling query's root span gets
    # exactly this evaluation's delta, so concurrent queries never read
    # each other's pruning out of the module-global last_prune_stats
    obs_trace.accumulate("rows_pruned", n - stats["row_groups_kept"])
    if bool(keep.all()):
        stats["files_kept"] = len(rel.files)
        stats["row_groups_kept"] = n
        return scan
    kept_files: List[str] = []
    kept_groups: List[Optional[Tuple[int, ...]]] = []
    for fi, path in enumerate(rel.files):
        sel = keep[zd.rg_file == fi]
        if not sel.any():
            continue
        kept_files.append(path)
        if bool(sel.all()) or zd.opaque[fi]:
            kept_groups.append(None)
        else:
            idx = zd.rg_index[(zd.rg_file == fi) & keep]
            kept_groups.append(tuple(int(i) for i in idx))
    stats["files_kept"] = len(kept_files)
    row_groups = (
        tuple(kept_groups)
        if any(g is not None for g in kept_groups)
        else None
    )
    return Scan(
        _dc.replace(
            rel, files=tuple(kept_files), file_row_groups=row_groups
        )
    )
