"""Covering index — the core index kind.

Reference: ``index/covering/CoveringIndex.scala:33-193``,
``CoveringIndexTrait.scala:32-135``, ``CoveringIndexConfig.scala:37-151``.

A covering index is a vertical slice of the source (indexed + included
columns), **hash-bucketed by the indexed columns and sorted within each
bucket**, so that at query time it can substitute (a) the scan in a filter
query with bucket pruning, and (b) the whole shuffle+sort in a sort-merge
join (both sides co-bucketed ⇒ no exchange).

TPU-native build pipeline (replaces ``indexData.repartition(numBuckets,
cols) + saveWithBuckets``, CoveringIndex.scala:56-71):

    host scan (arrow) → device columnar batches
      → murmur3 hash of indexed cols (ops.hash, XLA)
      → shard_map all-to-all over the mesh: row i goes to the device owning
        bucket h(i) % num_buckets            (parallel.shuffle)
      → per-device sort by (bucket, key)     (XLA sort on packed keys)
      → host write: one parquet file per bucket under v__=N/
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.constants import DATA_FILE_NAME_ID, LINEAGE_PROPERTY
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.indexes.base import Index, IndexConfigTrait, UpdateMode
from hyperspace_tpu.indexes.registry import register_index


@register_index
class CoveringIndex(Index):
    kind = "CoveringIndex"
    kind_abbr = "CI"

    def __init__(
        self,
        indexed_columns: List[str],
        included_columns: List[str],
        schema_json: str,
        num_buckets: int,
        properties: Optional[Dict[str, str]] = None,
    ):
        self._indexed_columns = list(indexed_columns)
        self._included_columns = list(included_columns)
        self.schema_json = schema_json
        self.num_buckets = int(num_buckets)
        self.properties: Dict[str, str] = dict(properties or {})

    # -- identity -----------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, CoveringIndex)
            and self._indexed_columns == other._indexed_columns
            and self._included_columns == other._included_columns
            and self.num_buckets == other.num_buckets
            and self.schema_json == other.schema_json
        )

    def __hash__(self):
        return hash((tuple(self._indexed_columns), self.num_buckets))

    # -- schema -------------------------------------------------------------
    @property
    def indexed_columns(self) -> List[str]:
        return list(self._indexed_columns)

    @property
    def included_columns(self) -> List[str]:
        return list(self._included_columns)

    @property
    def lineage_enabled(self) -> bool:
        return str(self.properties.get(LINEAGE_PROPERTY, "false")).lower() == "true"

    @property
    def can_handle_deleted_files(self) -> bool:
        # Deletes are compensated via the lineage column (CoveringIndexTrait)
        return self.lineage_enabled

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "type": self.kind,
            "kindAbbr": self.kind_abbr,
            "indexedColumns": self._indexed_columns,
            "includedColumns": self._included_columns,
            "schemaJson": self.schema_json,
            "numBuckets": self.num_buckets,
            "properties": dict(self.properties),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CoveringIndex":
        return cls(
            d["indexedColumns"],
            d.get("includedColumns", []),
            d.get("schemaJson", ""),
            d["numBuckets"],
            d.get("properties", {}),
        )

    # -- data plane ---------------------------------------------------------
    def write(self, ctx, index_data) -> None:
        """Bucketed + sorted write (CoveringIndex.write:56-71)."""
        from hyperspace_tpu.indexes import covering_build

        covering_build.write_bucketed(
            ctx, index_data, self._indexed_columns, self.num_buckets
        )

    def optimize(self, ctx, files_to_optimize: List[str]) -> None:
        """Read the listed index files and rewrite them bucketed
        (CoveringIndexTrait.optimize:130-134)."""
        from hyperspace_tpu.indexes import covering_build

        covering_build.rewrite_files(
            ctx, files_to_optimize, self._indexed_columns, self.num_buckets
        )

    def refresh_incremental(
        self, ctx, appended_df, deleted_source_file_ids, previous_content
    ) -> Tuple["CoveringIndex", UpdateMode]:
        """Incremental refresh (CoveringIndexTrait.refreshIncremental:57-106):

        * appended source files → index only those rows into the new version
          dir (same bucketing ⇒ merge keeps co-bucketing);
        * deleted source files → rewrite previous index data minus rows whose
          lineage id is in ``deleted_source_file_ids``.
        Returns (index, UpdateMode.MERGE | OVERWRITE).
        """
        from hyperspace_tpu.indexes import covering_build

        return covering_build.refresh_incremental(
            ctx,
            self,
            appended_df,
            deleted_source_file_ids,
            previous_content,
        )

    def refresh_full(self, ctx, df) -> "CoveringIndex":
        """Full rebuild from the current source state
        (CoveringIndexTrait.refreshFull:108-126)."""
        from hyperspace_tpu.indexes import covering_build

        return covering_build.refresh_full(ctx, self, df)

    def statistics(self, extended: bool = False) -> Dict[str, str]:
        return {
            "indexedColumns": ",".join(self._indexed_columns),
            "includedColumns": ",".join(self._included_columns),
            "numBuckets": str(self.num_buckets),
            "schema": self.schema_json if extended else "",
        }


class CoveringIndexConfig(IndexConfigTrait):
    """name + indexedColumns + includedColumns
    (CoveringIndexConfig.scala:37-151)."""

    def __init__(
        self,
        index_name: str,
        indexed_columns: List[str],
        included_columns: Optional[List[str]] = None,
    ):
        if not index_name:
            raise HyperspaceException("Index name cannot be empty")
        if not indexed_columns:
            raise HyperspaceException("indexed_columns cannot be empty")
        lowered = [c.lower() for c in indexed_columns]
        if len(set(lowered)) != len(lowered):
            raise HyperspaceException("Duplicate indexed column names")
        inc = list(included_columns or [])
        if set(c.lower() for c in inc) & set(lowered):
            raise HyperspaceException(
                "Duplicate column names in indexed/included columns"
            )
        self._name = index_name
        self._indexed = list(indexed_columns)
        self._included = inc

    def __repr__(self):
        return (
            f"CoveringIndexConfig(indexName={self._name!r}, "
            f"indexedColumns={self._indexed}, includedColumns={self._included})"
        )

    @property
    def index_name(self) -> str:
        return self._name

    @property
    def indexed_columns(self) -> List[str]:
        return list(self._indexed)

    @property
    def included_columns(self) -> List[str]:
        return list(self._included)

    @property
    def referenced_columns(self) -> List[str]:
        return self._indexed + self._included

    def create_index(self, ctx, source_data, properties: Dict[str, str]):
        """(CoveringIndex, index_data) — projection + optional lineage column
        (CoveringIndexConfig.createIndex:43-61 →
        CoveringIndex.createIndexData:140-192)."""
        from hyperspace_tpu.indexes import covering_build

        return covering_build.create_covering_index(
            ctx, source_data, self, properties
        )

    def describe_index(self, ctx, source_data, properties: Dict[str, str]):
        """CoveringIndex object without scanning data (begin-phase entry)."""
        from hyperspace_tpu.indexes import covering_build

        return covering_build.describe_covering_index(
            ctx, source_data, self, properties
        )
