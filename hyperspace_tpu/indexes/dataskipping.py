"""Data-skipping index — per-source-file sketch table.

Reference: ``dataskipping/DataSkippingIndex.scala:44-336``: build
(`createIndexData:291-317`) groups rows by source file and aggregates each
sketch; query time (`translateFilterCondition:143-185`) converts the filter
predicate into a predicate over the sketch table and prunes source files.
Unlike the covering kinds, the rewritten plan still scans the SOURCE —
just fewer files (``DataSkippingFileIndex``,
``dataskipping/execution/DataSkippingFileIndex.scala:32-74``).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from hyperspace_tpu.constants import DATA_FILE_NAME_ID
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.indexes.base import Index, IndexConfigTrait, UpdateMode
from hyperspace_tpu.indexes.registry import register_index
from hyperspace_tpu.indexes.sketches import Sketch, sketch_from_dict
from hyperspace_tpu.io import parquet as pio
from hyperspace_tpu.io.columnar import ColumnarBatch
from hyperspace_tpu.plan import expressions as E


@register_index
class DataSkippingIndex(Index):
    kind = "DataSkippingIndex"
    kind_abbr = "DS"

    def __init__(
        self,
        sketches: List[Sketch],
        schema_json: str = "",
        properties: Optional[Dict[str, str]] = None,
    ):
        self.sketches = list(sketches)
        self.schema_json = schema_json
        self.properties: Dict[str, str] = dict(properties or {})

    def __eq__(self, other):
        return (
            isinstance(other, DataSkippingIndex)
            and [s.to_dict() for s in self.sketches]
            == [s.to_dict() for s in other.sketches]
        )

    def __hash__(self):
        return hash(tuple(s.kind + s.column for s in self.sketches))

    # -- schema surface -----------------------------------------------------
    @property
    def indexed_columns(self) -> List[str]:
        seen = []
        for s in self.sketches:
            for c in s.referenced_columns():
                if c not in seen:
                    seen.append(c)
        return seen

    @property
    def included_columns(self) -> List[str]:
        return []

    @property
    def can_handle_deleted_files(self) -> bool:
        # one sketch row per file: deletion = drop rows (no lineage needed)
        return True

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "type": self.kind,
            "kindAbbr": self.kind_abbr,
            "sketches": [s.to_dict() for s in self.sketches],
            "schemaJson": self.schema_json,
            "properties": dict(self.properties),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DataSkippingIndex":
        return cls(
            [sketch_from_dict(s) for s in d["sketches"]],
            d.get("schemaJson", ""),
            d.get("properties", {}),
        )

    # -- build --------------------------------------------------------------
    def build_sketch_rows(self, ctx, plan_relation) -> pa.Table:
        """One sketch row per source file (createIndexData:291-317). File
        ids are keyed by the provider's (path,size,mtime) view so they
        match the ids recorded in the log entry's source content."""
        from hyperspace_tpu.indexes.covering_build import source_file_infos

        fmt = plan_relation.fmt
        cols = self.indexed_columns
        fields: List[Tuple[str, pa.DataType]] = [(DATA_FILE_NAME_ID, pa.int64())]
        rows: List[Dict] = []
        out_fields = None
        for f, size, mtime in sorted(
            source_file_infos(ctx.session, plan_relation)
        ):
            fid = ctx.file_id_tracker.add_file(f, size, mtime)
            batch = ColumnarBatch.from_arrow(pio.read_table([f], cols, fmt))
            row = {DATA_FILE_NAME_ID: fid}
            if out_fields is None:
                out_fields = list(fields)
                for s in self.sketches:
                    src_t = batch.column(s.referenced_columns()[0]).arrow_type
                    out_fields.extend(s.output_fields(src_t))
            for s in self.sketches:
                row.update(s.aggregate(batch))
            rows.append(row)
        if out_fields is None:
            raise HyperspaceException("No source files to sketch")
        return pa.table(
            {
                name: pa.array([r.get(name) for r in rows], type=t)
                for name, t in out_fields
            }
        )

    def write(self, ctx, index_data: pa.Table) -> None:
        import os

        os.makedirs(ctx.index_data_path, exist_ok=True)
        pio.write_table(
            os.path.join(ctx.index_data_path, "part-00000-sketch.parquet"),
            index_data,
        )

    def optimize(self, ctx, files_to_optimize: List[str]) -> None:
        table = pio.read_table(files_to_optimize, None)
        self.write(ctx, table)

    def refresh_incremental(
        self, ctx, appended_df, deleted_source_file_ids, previous_content
    ) -> Tuple["DataSkippingIndex", UpdateMode]:
        parts = []
        if appended_df is not None:
            rel = appended_df.logical_plan.collect_leaves()[0].relation
            parts.append(self.build_sketch_rows(ctx, rel))
        if deleted_source_file_ids:
            old = pio.read_table(list(previous_content.files), None)
            ids = np.asarray(old.column(DATA_FILE_NAME_ID))
            keep = ~np.isin(ids, np.array(deleted_source_file_ids, dtype=np.int64))
            parts.append(old.filter(pa.array(keep)))
            mode = UpdateMode.OVERWRITE
        else:
            mode = UpdateMode.MERGE
        if parts:
            self.write(ctx, pa.concat_tables(parts, promote_options="permissive"))
        return self, mode

    def refresh_full(self, ctx, df) -> "DataSkippingIndex":
        rel = df.logical_plan.collect_leaves()[0].relation
        table = self.build_sketch_rows(ctx, rel)
        self.write(ctx, table)
        return self

    # -- query-time translation (translateFilterCondition:143-185) ----------
    def translate_filter(
        self, condition: E.Expr, sketch_table: pa.Table
    ) -> Optional[np.ndarray]:
        """Keep-mask over sketch rows, or None when nothing translates."""

        def walk(expr) -> Optional[np.ndarray]:
            if isinstance(expr, E.And):
                l, r = walk(expr.left), walk(expr.right)
                if l is not None and r is not None:
                    return l & r
                return l if l is not None else r
            if isinstance(expr, E.Or):
                l, r = walk(expr.left), walk(expr.right)
                if l is not None and r is not None:
                    return l | r
                return None  # OR prunes only if BOTH sides translate
            for s in self.sketches:
                m = s.convert_predicate(expr, sketch_table)
                if m is not None:
                    return m
            return None

        return walk(condition)

    def statistics(self, extended: bool = False) -> Dict[str, str]:
        return {
            "sketches": ";".join(repr(s) for s in self.sketches),
            "indexedColumns": ",".join(self.indexed_columns),
            "schema": self.schema_json if extended else "",
        }


class DataSkippingIndexConfig(IndexConfigTrait):
    """name + sketches (DataSkippingIndexConfig.scala:39-95); a
    PartitionSketch is implicit in our build since constancy is detected
    per file (`:72-84` auto-adds it for partitioned sources)."""

    def __init__(self, index_name: str, *sketches: Sketch):
        if not index_name:
            raise HyperspaceException("Index name cannot be empty")
        if not sketches:
            raise HyperspaceException("At least one sketch is required")
        cols = [s.referenced_columns()[0].lower() + s.kind for s in sketches]
        if len(set(cols)) != len(cols):
            raise HyperspaceException("Duplicate sketches")
        self._name = index_name
        self._sketches = list(sketches)

    @property
    def index_name(self) -> str:
        return self._name

    @property
    def referenced_columns(self) -> List[str]:
        out = []
        for s in self._sketches:
            for c in s.referenced_columns():
                if c not in out:
                    out.append(c)
        return out

    def _mk_index(self, ctx, source_data, properties) -> DataSkippingIndex:
        from hyperspace_tpu.utils import resolver

        rel = source_data.logical_plan.collect_leaves()[0].relation
        schema = rel.schema
        resolved_sketches = []
        for s in self._sketches:
            rc = resolver.require_resolve(
                s.referenced_columns(), rel.column_names
            )[0]
            d = s.to_dict()
            d["column"] = rc.name
            d["sourceType"] = str(schema[rc.name])
            resolved_sketches.append(sketch_from_dict(d))
        schema_json = json.dumps(
            [[c, str(schema[c])] for c in self.referenced_columns]
        )
        return DataSkippingIndex(resolved_sketches, schema_json, dict(properties))

    def create_index(self, ctx, source_data, properties: Dict[str, str]):
        index = self._mk_index(ctx, source_data, properties)
        rel = source_data.logical_plan.collect_leaves()[0].relation
        data = index.build_sketch_rows(ctx, rel)
        return index, data

    def describe_index(self, ctx, source_data, properties: Dict[str, str]):
        return self._mk_index(ctx, source_data, properties)
