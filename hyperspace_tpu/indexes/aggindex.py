"""Aggregate index plane: persisted partial-aggregate state + samples.

ROADMAP item 2 (Partial Partial Aggregates, PAPERS.md): every serve of a
``Filter→Aggregate`` already computes per-chunk partial COUNT/SUM/MIN/
MAX state (``hs_fused_filter_agg``) and throws it away. This module
persists that state at build time so point aggregates become *metadata
reads* (docs/agg-serve.md):

* **capture** — at create/refresh/optimize the actions write an
  ``_aggstate.json`` sidecar into the version directory (underscore
  prefix: invisible to content scans and the data-path filter, the
  zone-map sidecar pattern) holding, per file / per row group, the
  partial-aggregate state of every column — valid counts, wrapped int64
  sums, float sums, replace-on-equal min/max with clean/NaN side
  counts — plus single-key GROUPED partials for every fusable column
  whose per-row-group distinct count stays under
  ``hyperspace.index.agg.maxGroupsPerRowGroup``. A stratified per-row-
  group row sample lands next to it in ``_aggsample.parquet`` for the
  approximate plane (``execution/approx_exec.py``). Partials are
  computed through the SAME public hook the serve sweep snapshots
  (``pipeline_compiler.partials_from_batch`` / ``AggPartials``), so the
  build-time capture and the serve-time pass share one state layout by
  construction.
* **lazy backfill** — pre-existing indexes (and files whose sidecar
  entry is stale by (size, mtime_ns)) compute the same per-file doc by
  reading the file once, memoized per file identity; a rewritten file
  can never serve stale partials.
* **serve assembly** — ``agg_data_for`` assembles one file set's
  decoded state, cached in the ServeCache under ``("aggstate", fp)``
  (``evict_kind`` support) with a module LRU for cache-off serves;
  ``classify_row_groups`` splits a strictly-lowered conjunction
  (``zonemaps.predicate_intervals_complete``) into FULL / EMPTY /
  PARTIAL row groups, and ``rg_partials`` turns a FULL row group's
  stored state back into :class:`~hyperspace_tpu.execution.
  pipeline_compiler.AggPartials` for the order-preserving fold.

Soundness contract: a row group is FULL only when EVERY row provably
satisfies the whole conjunction — exact per-column min/max computed from
the data itself (never parquet footer statistics, whose NaN handling
diverges from the engine), zero nulls and zero NaNs in every conjunct
column, interval bounds compared in float64 with INWARD directed
rounding (can only demote full → partial, never promote). EMPTY requires
provable non-overlap (outward rounding, the zone-map rule). Everything
else is PARTIAL and gets scanned.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import logging
import os
import threading
import time as _time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pa_compute
import pyarrow.parquet as pq

from hyperspace_tpu import constants as C
from hyperspace_tpu.obs import trace as _obs_trace
from hyperspace_tpu.testing import faults

_log = logging.getLogger("hyperspace_tpu.aggindex")

SIDECAR_NAME = "_aggstate.json"
SAMPLE_NAME = "_aggsample.parquet"
_SIDECAR_VERSION = 1

_I64_MIN = -(1 << 63)
_I64_MAX = (1 << 63) - 1


# ---------------------------------------------------------------------------
# Scalar codec: every stored scalar is an int (int64 value, or the int64
# BIT VIEW of a float64 — exact for -0.0 / NaN payloads / infinities,
# which repr/hex round-trips are not) or None ("no valid value here").
# ---------------------------------------------------------------------------


def _enc_f64(v: float) -> int:
    return int(np.float64(v).view(np.int64))


def _dec_f64_arr(vals: List[Optional[int]], identity: float) -> np.ndarray:
    bits = np.array(
        [(_enc_f64(identity) if v is None else v) for v in vals],
        dtype=np.int64,
    )
    return bits.view(np.float64)


def _dec_i64_arr(vals: List[Optional[int]], identity: int) -> np.ndarray:
    return np.array(
        [identity if v is None else v for v in vals], dtype=np.int64
    )


# ---------------------------------------------------------------------------
# Per-file doc computation (shared by capture and lazy backfill)
# ---------------------------------------------------------------------------


def _capture_spec(schema: pa.Schema):
    """(count-only cols, numeric cols with f64 flag, key candidates) for
    one index file's schema, using the fused pipeline's own type lowering
    so capture and serve can never disagree on what is fusable."""
    from hyperspace_tpu.execution.pipeline_compiler import _fusable_f64

    count_only: List[str] = []
    numeric: List[Tuple[str, bool]] = []
    for name in schema.names:
        f64 = _fusable_f64(schema.field(name).type)
        if f64 is None:
            count_only.append(name)
        else:
            numeric.append((name, f64))
    return count_only, numeric


class _CaptureSpec:
    """A minimal plan-shaped object for ``partials_from_batch``: just
    ``group_by`` + ``agg_ops`` (the capture has no AggSpecs)."""

    def __init__(self, group_by, agg_ops):
        self.group_by = tuple(group_by)
        self.agg_ops = tuple(agg_ops)


def _capture_ops(count_only, numeric):
    """The agg-op list capturing every column's full partial state, and
    per-column slot maps back into it."""
    from hyperspace_tpu.execution import pipeline_compiler as PC

    ops: List[Tuple[int, Optional[str]]] = [(PC._OP_COUNT_STAR, None)]
    slots: Dict[str, Dict[str, int]] = {}
    for c in count_only:
        slots[c] = {"cnt": len(ops)}
        ops.append((PC._OP_COUNT_COL, c))
    for c, f64 in numeric:
        if f64:
            slots[c] = {
                "sum": len(ops),
                "min": len(ops) + 1,
                "max": len(ops) + 2,
                "f64": 1,
            }
            ops.extend(
                [(PC._OP_SUM_F64, c), (PC._OP_MIN_F64, c), (PC._OP_MAX_F64, c)]
            )
        else:
            slots[c] = {
                "sum": len(ops),
                "min": len(ops) + 1,
                "max": len(ops) + 2,
                "f64": 0,
            }
            ops.extend(
                [(PC._OP_SUM_I64, c), (PC._OP_MIN_I64, c), (PC._OP_MAX_I64, c)]
            )
    return ops, slots


def _partials_to_cols(pt, slots) -> Dict[str, Dict[str, list]]:
    """Per-column stored arrays (one cell per group) from one partials
    snapshot — the inverse of :func:`rg_partials`' accumulator mapping."""
    G = pt.n_groups
    cols: Dict[str, Dict[str, list]] = {}
    for name, sl in slots.items():
        if "sum" not in sl:  # count-only column
            a = sl["cnt"]
            cols[name] = {"cnt": [int(pt.acc_cnt[a, g]) for g in range(G)]}
            continue
        a_sum, a_min, a_max = sl["sum"], sl["min"], sl["max"]
        cnt = [int(pt.acc_cnt[a_sum, g]) for g in range(G)]
        if sl["f64"]:
            clean = [int(pt.acc_aux[a_min, g]) for g in range(G)]
            nan = [int(pt.acc_aux[a_max, g]) for g in range(G)]
            cols[name] = {
                "cnt": cnt,
                "f64": 1,
                "sum": [_enc_f64(pt.acc_f[a_sum, g]) for g in range(G)],
                "min": [
                    _enc_f64(pt.acc_f[a_min, g]) if clean[g] else None
                    for g in range(G)
                ],
                "max": [
                    _enc_f64(pt.acc_f[a_max, g]) if clean[g] else None
                    for g in range(G)
                ],
                "clean": clean,
                "nan": nan,
            }
        else:
            cols[name] = {
                "cnt": cnt,
                "f64": 0,
                "sum": [int(pt.acc_i[a_sum, g]) for g in range(G)],
                "min": [
                    int(pt.acc_i[a_min, g]) if cnt[g] else None
                    for g in range(G)
                ],
                "max": [
                    int(pt.acc_i[a_max, g]) if cnt[g] else None
                    for g in range(G)
                ],
            }
    return cols


def _sample_rng(basename: str, rg: int):
    """Deterministic per-(file, row group) generator so capture and lazy
    backfill produce the SAME sample rows."""
    from hyperspace_tpu.utils.hashing import murmur3_64_bytes

    seed = murmur3_64_bytes(f"hs-aggsample:{basename}:{rg}".encode("utf-8"))
    return np.random.default_rng(np.uint64(np.int64(seed)))


def file_agg_doc(
    path: str,
    max_groups: int = C.INDEX_AGG_MAX_GROUPS_DEFAULT,
    sample_rows: int = C.INDEX_AGG_SAMPLE_ROWS_DEFAULT,
    group_keys: Optional[Tuple[str, ...]] = None,
) -> Tuple[dict, Optional[pa.Table]]:
    """(sidecar entry, stratified sample table) for ONE index data file,
    computed from the file itself — the single definition shared by
    build-time capture and the serve path's lazy backfill. Partials run
    through ``pipeline_compiler.partials_from_batch`` (the fused sweep's
    numpy twin), so the stored state is bit-identical to what the serve
    kernel would have produced over the same rows.

    ``group_keys`` restricts grouped-partial capture to those columns
    (lowercase match): the serve-path backfill passes the ONE key the
    query groups by, so a first serve over an unsidecar'd index pays one
    grouped sweep instead of one per numeric column; build-time capture
    leaves it None (every fusable candidate)."""
    from hyperspace_tpu.execution import pipeline_compiler as PC
    from hyperspace_tpu.io.columnar import ColumnarBatch

    pf = pq.ParquetFile(path)
    schema = pf.schema_arrow
    count_only, numeric = _capture_spec(schema)
    ops, slots = _capture_ops(count_only, numeric)
    base = os.path.basename(path)
    entry: dict = {
        "rg_rows": [],
        "cols": {c: {k: [] for k in ("cnt",)} for c in count_only},
        "groups": {},
    }
    for c, f64 in numeric:
        entry["cols"][c] = {
            k: []
            for k in (
                ("cnt", "f64", "sum", "min", "max", "clean", "nan")
                if f64
                else ("cnt", "f64", "sum", "min", "max")
            )
        }
    key_candidates = [c for c, _f in numeric]
    if group_keys is not None:
        wanted = {k.lower() for k in group_keys}
        key_candidates = [c for c in key_candidates if c.lower() in wanted]
    for c in key_candidates:
        entry["groups"][c] = []
    samples: List[pa.Table] = []
    for gi in range(pf.metadata.num_row_groups):
        table = pf.read_row_group(gi)
        batch = ColumnarBatch.from_arrow(table)
        n = batch.num_rows
        entry["rg_rows"].append(n)
        pt = PC.partials_from_batch(_CaptureSpec((), ops), batch)
        if pt is None:  # a column decoded outside the expected set
            raise ValueError(f"uncapturable column set in {path}")
        cols = _partials_to_cols(pt, slots)
        for c, cell in cols.items():
            dst = entry["cols"][c]
            for k, vals in cell.items():
                if k == "f64":
                    dst["f64"] = vals
                    continue
                dst[k].append(vals[0] if vals else None)
        # single-key grouped partials per candidate column under the cap.
        # A 4·cap-row PREFIX probe (canonical key_rep over a prefix
        # slice, O(cap) not O(rows)) rejects high-cardinality columns
        # cheaply — a prefix can only UNDER-count distincts, so it never
        # rejects an eligible column; the full pass's own factorize then
        # decides exactly (probe-passing over-cap columns are discarded
        # by the n_groups check below).
        for kc in key_candidates:
            if n == 0 or max_groups <= 0:
                entry["groups"][kc].append(None)
                continue
            col = batch.column(kc)
            m = min(n, 4 * max_groups)
            probe = col.take(np.arange(m)).key_rep()
            if len(np.unique(probe)) > max_groups:
                entry["groups"][kc].append(None)
                continue
            gpt = PC.partials_from_batch(_CaptureSpec((kc,), ops), batch)
            if gpt is None or gpt.n_groups > max_groups:
                entry["groups"][kc].append(None)
                continue
            gcols = _partials_to_cols(gpt, slots)
            gentry: dict = {
                "kv": [int(v) for v in gpt.g_kvals[0]],
                "n": [int(v) for v in gpt.acc_cnt[0]],
                "cols": gcols,
            }
            if gpt.key_has_validity[0]:
                gentry["kn"] = [int(v) for v in gpt.g_kvalid[0]]
            entry["groups"][kc].append(gentry)
        if sample_rows > 0 and n > 0:
            k = min(sample_rows, n)
            idx = np.sort(_sample_rng(base, gi).choice(n, size=k, replace=False))
            sampled = table.take(idx)
            sampled = sampled.add_column(
                0, "__rg", pa.array(np.full(k, gi, dtype=np.int32))
            )
            sampled = sampled.add_column(
                0, "__file", pa.array([base] * k, type=pa.string())
            )
            samples.append(sampled)
    # prune all-None grouped candidates (over-cap everywhere)
    entry["groups"] = {
        k: v for k, v in entry["groups"].items() if any(e is not None for e in v)
    }
    sample_table = (
        pa.concat_tables(samples, promote_options="permissive")
        if samples
        else None
    )
    return entry, sample_table


# ---------------------------------------------------------------------------
# Capture (build/refresh/optimize time)
# ---------------------------------------------------------------------------


def capture_index_dir(dir_path: str, index, conf=None) -> bool:
    """Write the ``_aggstate.json`` + ``_aggsample.parquet`` sidecars for
    one freshly-written index version directory (covering-family indexes
    only, like zone maps). Atomic publish with the crash seam
    ``mid_sidecar_publish`` armed before each replace — a crash here
    fails the surrounding action op(), which recovery rolls back; the
    sidecar is either absent (lazy backfill covers it) or complete."""
    kind = getattr(index, "kind", "")
    if kind not in ("CoveringIndex", "ZOrderCoveringIndex"):
        return False
    if conf is not None and not conf.index_agg_enabled:
        return False
    max_groups = (
        conf.index_agg_max_groups
        if conf is not None
        else C.INDEX_AGG_MAX_GROUPS_DEFAULT
    )
    sample_rows = (
        conf.index_agg_sample_rows
        if conf is not None
        else C.INDEX_AGG_SAMPLE_ROWS_DEFAULT
    )
    from hyperspace_tpu.io import parquet as pio

    _t0 = _time.perf_counter()
    try:
        files = pio.list_format_files(dir_path, "parquet")
    except (OSError, KeyError):
        return False
    if not files:
        return False
    doc: dict = {"version": _SIDECAR_VERSION, "files": {}}
    sample_tables: List[pa.Table] = []
    for f in files:
        entry, sample = file_agg_doc(f, max_groups, sample_rows)
        st = os.stat(f)
        entry["size"] = st.st_size
        entry["mtime_ns"] = st.st_mtime_ns
        doc["files"][os.path.basename(f)] = entry
        if sample is not None:
            sample_tables.append(sample)
    side_path = os.path.join(dir_path, SIDECAR_NAME)
    tmp = os.path.join(dir_path, f".{SIDECAR_NAME}.tmp.{os.getpid()}")
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
            fh.flush()
            os.fsync(fh.fileno())
        faults.crash("mid_sidecar_publish", side_path)
        os.replace(tmp, side_path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    if sample_tables:
        sample_path = os.path.join(dir_path, SAMPLE_NAME)
        stmp = os.path.join(dir_path, f".{SAMPLE_NAME}.tmp.{os.getpid()}")
        try:
            pq.write_table(
                pa.concat_tables(sample_tables, promote_options="permissive"),
                stmp,
            )
            faults.crash("mid_sidecar_publish", sample_path)
            os.replace(stmp, sample_path)
        except OSError:
            try:
                os.unlink(stmp)
            except OSError:
                pass
    from hyperspace_tpu.utils.files import fsync_dir

    fsync_dir(dir_path)
    # build-tail I/O outside every breakdown stage — span it so action
    # traces have no unexplained tail (OBS_SITES-registered)
    _obs_trace.stage("sidecar_capture", _t0)
    return True


def capture_safely(dir_path: str, index, conf=None) -> None:
    """The actions' capture entry: the sidecar is a precomputed
    optimization (the serve path lazily backfills without it), so no
    capture failure may ever fail a build/refresh/optimize."""
    try:
        capture_index_dir(dir_path, index, conf)
    except Exception as exc:  # hslint: disable=HS402
        _log.warning("aggstate capture failed for %s: %s", dir_path, exc)


def prune_missing(dir_path: str) -> None:
    """Vacuum support: rewrite the sidecars of a RETAINED version dir to
    drop entries/rows describing files that no longer exist (the sidecar
    travels with the files it describes; the whole dir's sidecars die
    with the dir). Best-effort — stale entries are also defused by the
    per-file (size, mtime_ns) freshness check at assembly."""
    side_path = os.path.join(dir_path, SIDECAR_NAME)
    try:
        with open(side_path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        kept = {
            base: entry
            for base, entry in doc.get("files", {}).items()
            if os.path.exists(os.path.join(dir_path, base))
        }
        if len(kept) != len(doc.get("files", {})):
            if kept:
                doc["files"] = kept
                # the shared fsync-before-replace publish (the
                # calibrate._store_cache pattern, testing/artifacts.py):
                # a crash right after vacuum must not tear the rewrite
                from hyperspace_tpu.testing.artifacts import atomic_write_json

                atomic_write_json(side_path, doc)
            else:
                os.unlink(side_path)
    except (OSError, ValueError):
        pass
    sample_path = os.path.join(dir_path, SAMPLE_NAME)
    try:
        if os.path.exists(sample_path):
            table = pq.read_table(sample_path)
            bases = table.column("__file").to_pylist()
            keep = np.array(
                [os.path.exists(os.path.join(dir_path, b)) for b in bases]
            )
            if not keep.all():
                if keep.any():
                    tmp = sample_path + f".tmp.{os.getpid()}"
                    pq.write_table(table.filter(pa.array(keep)), tmp)
                    os.replace(tmp, sample_path)
                else:
                    os.unlink(sample_path)
    except (OSError, ValueError, KeyError, pa.ArrowInvalid):
        pass


# ---------------------------------------------------------------------------
# Sidecar read + lazy backfill (memoized per file identity)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def _sidecar_cached(path: str, _size: int, _mtime_ns: int) -> Optional[dict]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    if data.get("version") != _SIDECAR_VERSION:
        return None
    return data


def _sidecar_for_dir(dirpath: str) -> Optional[dict]:
    path = os.path.join(dirpath, SIDECAR_NAME)
    try:
        st = os.stat(path)
    except OSError:
        return None
    return _sidecar_cached(path, st.st_size, st.st_mtime_ns)


@functools.lru_cache(maxsize=16)
def _backfill_cached(
    path: str,
    _size: int,
    _mtime_ns: int,
    keys: Optional[Tuple[str, ...]] = None,
    max_groups: int = C.INDEX_AGG_MAX_GROUPS_DEFAULT,
    sample_rows: int = C.INDEX_AGG_SAMPLE_ROWS_DEFAULT,
):
    """Lazy backfill for a file without a fresh sidecar entry: compute
    the same doc (and sample) by reading the file once. Keyed by file
    identity — a rewritten file gets a fresh computation — plus the
    grouped-key restriction and the session's capture knobs, so a
    differently-configured serve never reads stale-shaped state."""
    return file_agg_doc(path, max_groups, sample_rows, keys)


def _entry_for_file(
    path: str,
    side: Optional[dict],
    keys: Optional[Tuple[str, ...]],
    max_groups: int,
    sample_rows: int,
):
    """(entry, from_sidecar) — this file's sidecar entry when present
    AND stat-fresh, else the lazily-backfilled computation; (None, False)
    when the file is unreadable (caller scans it as PARTIAL)."""
    try:
        st = os.stat(path)
    except OSError:
        return None, False
    if side is not None:
        entry = side.get("files", {}).get(os.path.basename(path))
        if (
            entry is not None
            and entry.get("size") == st.st_size
            and entry.get("mtime_ns") == st.st_mtime_ns
        ):
            return entry, True
    try:
        entry, _sample = _backfill_cached(
            path, st.st_size, st.st_mtime_ns, keys, max_groups, sample_rows
        )
        return entry, False
    except Exception as exc:  # hslint: disable=HS402
        # backfill is best-effort extra coverage: any failure (exotic
        # dtype, I/O error) must only cost the metadata answer, never
        # the query
        _log.warning("aggstate backfill failed for %s: %s", path, exc)
        return None, False


# ---------------------------------------------------------------------------
# Serve-side assembly
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AggData:
    """Decoded aggregate-state of one file set, assembled once and
    cached (ServeCache kind ``("aggstate", fp)`` when serve-server mode
    is on, else the module LRU). ``backfill_keys`` records which grouped
    keys any backfilled portion was restricted to (lowercase; None =
    unrestricted) — a cache hit only serves a query whose key that
    covers, so a key-restricted first backfill can never starve a later
    query on a different key."""

    files: Tuple[str, ...]
    per_file: list  # decoded per-file dict, or None (unreadable)
    sidecar_files: int
    backfill_files: int
    nbytes: int
    backfill_keys: Optional[frozenset] = None
    # per file: True when the entry came from a STAT-FRESH sidecar (the
    # sample plane trusts _aggsample.parquet rows only for these — a
    # rewritten file's samples must come from backfill, not the old dir
    # sidecar)
    per_file_sidecar: Tuple[bool, ...] = ()

    def covers_key(self, group_key: Optional[str]) -> bool:
        if self.backfill_files == 0 or group_key is None:
            return True
        if self.backfill_keys is None:
            return True  # unrestricted backfill: all candidates captured
        return group_key.lower() in self.backfill_keys


def _decode_entry(entry: dict) -> Tuple[dict, int]:
    """Runtime (numpy) form of one stored file entry + a byte estimate."""
    rg_rows = [int(r) for r in entry.get("rg_rows", [])]
    cols: Dict[str, dict] = {}
    scalars = 0
    for name, st in entry.get("cols", {}).items():
        cnt = _dec_i64_arr(st.get("cnt", []), 0)
        scalars += len(cnt)
        if "sum" not in st:
            cols[name] = {"cnt": cnt}
            continue
        f64 = bool(st.get("f64"))
        d: dict = {"cnt": cnt, "is_f64": f64}
        if f64:
            d["sum"] = _dec_f64_arr(st["sum"], 0.0)
            d["min"] = _dec_f64_arr(st["min"], np.inf)
            d["max"] = _dec_f64_arr(st["max"], -np.inf)
            d["clean"] = _dec_i64_arr(st.get("clean", []), 0)
            d["nan"] = _dec_i64_arr(st.get("nan", []), 0)
        else:
            d["sum"] = _dec_i64_arr(st["sum"], 0)
            d["min"] = _dec_i64_arr(st["min"], _I64_MAX)
            d["max"] = _dec_i64_arr(st["max"], _I64_MIN)
        scalars += 5 * len(cnt)
        cols[name] = d
    groups: Dict[str, list] = {}
    for kc, per_rg in entry.get("groups", {}).items():
        decoded = []
        for g in per_rg:
            if g is None:
                decoded.append(None)
                continue
            gcols: Dict[str, dict] = {}
            for name, st in g.get("cols", {}).items():
                cnt = _dec_i64_arr(st.get("cnt", []), 0)
                if "sum" not in st:
                    gcols[name] = {"cnt": cnt}
                elif st.get("f64"):
                    gcols[name] = {
                        "cnt": cnt,
                        "is_f64": True,
                        "sum": _dec_f64_arr(st["sum"], 0.0),
                        "min": _dec_f64_arr(st["min"], np.inf),
                        "max": _dec_f64_arr(st["max"], -np.inf),
                        "clean": _dec_i64_arr(st.get("clean", []), 0),
                        "nan": _dec_i64_arr(st.get("nan", []), 0),
                    }
                else:
                    gcols[name] = {
                        "cnt": cnt,
                        "is_f64": False,
                        "sum": _dec_i64_arr(st["sum"], 0),
                        "min": _dec_i64_arr(st["min"], _I64_MAX),
                        "max": _dec_i64_arr(st["max"], _I64_MIN),
                    }
                scalars += 6 * len(cnt)
            decoded.append(
                {
                    "kv": np.array(g["kv"], dtype=np.int64),
                    "kvalid": (
                        np.array(g["kn"], dtype=np.uint8)
                        if "kn" in g
                        else None
                    ),
                    "n": np.array(g["n"], dtype=np.int64),
                    "cols": gcols,
                }
            )
            scalars += 2 * len(g.get("kv", []))
        groups[kc.lower()] = decoded
    return (
        {"rg_rows": rg_rows, "cols": cols, "groups": groups},
        64 + 8 * scalars,
    )


# Module-level bounded LRU for assembled agg data, so the metadata plane
# works at full speed with serve-server mode OFF (the default). Keyed by
# the file fingerprint, same staleness story as the ServeCache entries.
# Bounded in BYTES as well as entries — AggData carries its own decoded
# size (data.nbytes) and grouped partials over wide relations are not
# small, so an entry cap alone is not a residency bound (ALLOC_SITES
# doctrine, memory.py); _local_bytes is the ledger.
# SHARED_STATE-registered ("guarded": every access under _local_lock).
_local_lock = threading.Lock()
_local_cache: "OrderedDict[tuple, AggData]" = OrderedDict()
_local_bytes = 0
_LOCAL_CACHE_ENTRIES = 32
_LOCAL_CACHE_MAX_BYTES = 128 << 20


def _local_put(key, data: "AggData") -> None:
    """Insert into the module LRU, evicting oldest-first until both the
    entry cap and the byte cap hold. Caller must NOT hold _local_lock."""
    global _local_bytes
    nbytes = int(data.nbytes)
    if nbytes > _LOCAL_CACHE_MAX_BYTES:
        return  # larger than the whole fallback cache: not cacheable
    with _local_lock:
        old = _local_cache.pop(key, None)
        if old is not None:
            _local_bytes -= int(old.nbytes)
        while _local_cache and (
            len(_local_cache) >= _LOCAL_CACHE_ENTRIES
            or _local_bytes + nbytes > _LOCAL_CACHE_MAX_BYTES
        ):
            _, victim = _local_cache.popitem(last=False)
            _local_bytes -= int(victim.nbytes)
        _local_cache[key] = data
        _local_bytes += nbytes


def agg_data_for(
    rel, cache=None, conf=None, group_key: Optional[str] = None
) -> Optional[AggData]:
    """Assembled aggregate-state for a relation's file set, from the
    serve cache / module LRU, sidecars, or lazy backfill. ``conf``
    supplies the capture knobs for backfill (defaults otherwise);
    ``group_key`` restricts any backfill's grouped sweep to the one key
    this query needs (a first serve over an unsidecar'd index pays one
    grouped pass, not one per numeric column). None when the files
    cannot be fingerprinted (caller skips the plane)."""
    from hyperspace_tpu.execution.serve_cache import file_fingerprint

    fp = file_fingerprint(rel.files)
    if fp is None:
        return None
    key = ("aggstate", fp)
    if cache is not None:
        hit = cache.get(key)
        if hit is not None and hit.covers_key(group_key):
            return hit
    with _local_lock:
        hit = _local_cache.get(key)
        if hit is not None and hit.covers_key(group_key):
            _local_cache.move_to_end(key)
            return hit
    max_groups = (
        conf.index_agg_max_groups
        if conf is not None
        else C.INDEX_AGG_MAX_GROUPS_DEFAULT
    )
    sample_rows = (
        conf.index_agg_sample_rows
        if conf is not None
        else C.INDEX_AGG_SAMPLE_ROWS_DEFAULT
    )
    bf_keys: Tuple[str, ...] = () if group_key is None else (group_key.lower(),)
    if not bf_keys:
        # no grouped capture wanted: normalize the cap so ungrouped
        # backfills (exact plane and sample assembly) share one memo
        max_groups = 0
    side_by_dir: Dict[str, Optional[dict]] = {}
    per_file: list = []
    provenance: list = []
    nbytes = 256
    sidecar_n = backfill_n = 0
    for path in rel.files:
        d = os.path.dirname(path)
        if d not in side_by_dir:
            side_by_dir[d] = _sidecar_for_dir(d)
        entry, from_sidecar = _entry_for_file(
            path, side_by_dir[d], bf_keys, max_groups, sample_rows
        )
        provenance.append(bool(from_sidecar))
        if entry is None:
            per_file.append(None)
            continue
        decoded, nb = _decode_entry(entry)
        per_file.append(decoded)
        nbytes += nb
        if from_sidecar:
            sidecar_n += 1
        else:
            backfill_n += 1
    data = AggData(
        files=tuple(rel.files),
        per_file=per_file,
        sidecar_files=sidecar_n,
        backfill_files=backfill_n,
        nbytes=nbytes,
        backfill_keys=frozenset(bf_keys) if backfill_n else None,
        per_file_sidecar=tuple(provenance),
    )
    if cache is not None:
        cache.put(key, data, data.nbytes)
    _local_put(key, data)
    return data


def invalidate_local_cache() -> None:
    """Tests / operational tooling: drop the module-level assembled
    cache (sidecar/backfill memos are keyed by file identity and never
    serve stale)."""
    global _local_bytes
    with _local_lock:
        _local_cache.clear()
        _local_bytes = 0


def invalidate_paths_under(root: str) -> int:
    """Drop only the LRU entries whose fingerprint names a file under
    ``root`` — the fleet fanout's scoped invalidation (``serve/bus.py``;
    same contract as ``zonemaps.invalidate_paths_under``): reclaim the
    changed index's dead-version memory without costing other indexes
    their warm assembled state."""
    prefix = root.replace("\\", "/").rstrip("/") + "/"

    def _mentions(obj) -> bool:
        if isinstance(obj, str):
            return obj.replace("\\", "/").startswith(prefix)
        if isinstance(obj, tuple):
            return any(_mentions(x) for x in obj)
        return False

    global _local_bytes
    with _local_lock:
        victims = [k for k in _local_cache if _mentions(k)]
        for k in victims:
            victim = _local_cache.pop(k)
            _local_bytes -= int(victim.nbytes)
        return len(victims)


# ---------------------------------------------------------------------------
# Fleet fanout (docs/fleet-serve.md): metadata answers are tiny and
# version-addressed, so a refresh/optimize PUSHES the new version's
# aggregate state to peer frontends instead of invalidating it — the
# peers' first point aggregate over the new snapshot folds straight from
# RAM without even the sidecar read.
# ---------------------------------------------------------------------------


def fanout_payload(files) -> Optional[dict]:
    """JSON-safe push payload for one committed file set: the raw
    per-file sidecar entries plus the file fingerprint the receivers key
    by. None unless EVERY file has a stat-fresh sidecar entry — a
    partial push would make the receiver's assembly lie about coverage,
    and the lazy re-read path covers the gap anyway."""
    from hyperspace_tpu.execution.serve_cache import file_fingerprint

    files = tuple(files)
    if not files:
        return None
    fp = file_fingerprint(files)
    if fp is None:
        return None
    side_by_dir: Dict[str, Optional[dict]] = {}
    entries: Dict[str, dict] = {}
    for path in files:
        d = os.path.dirname(path)
        if d not in side_by_dir:
            side_by_dir[d] = _sidecar_for_dir(d)
        side = side_by_dir[d]
        if side is None:
            return None
        entry = side.get("files", {}).get(os.path.basename(path))
        try:
            st = os.stat(path)
        except OSError:
            return None
        if (
            entry is None
            or entry.get("size") != st.st_size
            or entry.get("mtime_ns") != st.st_mtime_ns
        ):
            return None
        entries[path] = entry
    return {
        "files": list(files),
        "fp": [[p, s, m] for p, s, m in fp],
        "entries": entries,
    }


def install_fanout_payload(payload: dict, cache=None) -> bool:
    """Install a pushed payload into this process's caches under
    ``("aggstate", fp)``. Validates the fingerprint against the CURRENT
    on-disk stats first — a stale push (the files changed again before
    this frontend polled) would be cached under an unreachable key, so
    it is dropped instead. Returns whether the install happened."""
    from hyperspace_tpu.execution.serve_cache import file_fingerprint

    try:
        files = tuple(str(f) for f in payload["files"])
        fp = tuple((str(p), int(s), int(m)) for p, s, m in payload["fp"])
        raw_entries = payload["entries"]
    except (KeyError, TypeError, ValueError):
        return False
    if not files or file_fingerprint(files) != fp:
        return False
    per_file: list = []
    nbytes = 256
    try:
        for path in files:
            decoded, nb = _decode_entry(raw_entries[path])
            per_file.append(decoded)
            nbytes += nb
    except (KeyError, TypeError, ValueError):
        return False
    data = AggData(
        files=files,
        per_file=per_file,
        sidecar_files=len(files),
        backfill_files=0,
        nbytes=nbytes,
        backfill_keys=None,
        per_file_sidecar=(True,) * len(files),
    )
    key = ("aggstate", fp)
    if cache is not None:
        cache.put(key, data, data.nbytes)
    _local_put(key, data)
    return True


# ---------------------------------------------------------------------------
# Classification: FULL / EMPTY / PARTIAL per selected row group
# ---------------------------------------------------------------------------


def _zone_verdict(st: Optional[dict], gi: int, iv, rows: int) -> str:
    """One conjunct column's verdict for one row group: "empty" (no row
    can satisfy it), "full" (every row provably satisfies it) or
    "partial" (undecidable at this granularity). Directed rounding:
    OUTWARD for the empty test (the zone-map keep rule), INWARD for the
    full test — rounding can only demote toward "partial"."""
    from hyperspace_tpu.indexes.zonemaps import f64_down, f64_up

    if iv.empty:
        return "empty"
    if st is None or "sum" not in st and "min" not in st:
        return "partial"  # count-only column (string/bool/narrow): abstain
    cnt = int(st["cnt"][gi]) if gi < len(st["cnt"]) else None
    if cnt is None:
        return "partial"
    if cnt == 0:
        return "empty"  # all-null group: no row satisfies a comparison
    is_f64 = bool(st.get("is_f64"))
    if is_f64:
        clean = int(st["clean"][gi])
        if clean == 0:
            return "empty"  # every valid value is NaN: all rows fail
    lo_v = st["min"][gi]
    hi_v = st["max"][gi]
    lo_r = f64_down(lo_v.item() if isinstance(lo_v, np.generic) else lo_v)
    hi_r = f64_up(hi_v.item() if isinstance(hi_v, np.generic) else hi_v)
    if iv.lo is not None:
        b = f64_down(iv.lo)
        keep = hi_r > b if iv.lo_strict else hi_r >= b
        if not keep:
            return "empty"
    if iv.hi is not None:
        b = f64_up(iv.hi)
        keep = lo_r < b if iv.hi_strict else lo_r <= b
        if not keep:
            return "empty"
    full = cnt == rows and (not is_f64 or int(st["nan"][gi]) == 0)
    if full and iv.lo is not None:
        b = f64_up(iv.lo)
        full = lo_r > b if iv.lo_strict else lo_r >= b
    if full and iv.hi is not None:
        b = f64_down(iv.hi)
        full = hi_r < b if iv.hi_strict else hi_r <= b
    return "full" if full else "partial"


def _op_available(op: int, cname: Optional[str], cols: Dict[str, dict]) -> bool:
    from hyperspace_tpu.execution import pipeline_compiler as PC

    if op == PC._OP_COUNT_STAR:
        return True
    st = cols.get(cname)
    if st is None or "cnt" not in st:
        return False
    if op == PC._OP_COUNT_COL:
        return True
    return "sum" in st


def classify_row_groups(
    data: AggData, rel, ivs: Dict[str, Any], key: Optional[str], fplan
) -> Optional[List[Tuple[int, Optional[int], str]]]:
    """Per selected (file, row group): "full" | "empty" | "partial", in
    the interpreted chain's read order. A FULL verdict additionally
    requires the stored partials the lowering needs (grouped entry for
    ``key``, per-column state for every agg input) — missing state
    demotes to "partial" (scan), never to a wrong answer. Files without
    usable state classify as one whole-file "partial" cell."""
    key_lower = key.lower() if key is not None else None
    cells: List[Tuple[int, Optional[int], str]] = []
    groups_sel = rel.file_row_groups or (None,) * len(rel.files)
    for fi, path in enumerate(rel.files):
        pf = data.per_file[fi]
        if pf is None:
            cells.append((fi, None, "partial"))
            continue
        n_rg = len(pf["rg_rows"])
        sel = groups_sel[fi]
        rgs = sel if sel is not None else range(n_rg)
        for gi in rgs:
            if gi >= n_rg:
                cells.append((fi, gi, "partial"))
                continue
            rows = pf["rg_rows"][gi]
            if rows == 0:
                cells.append((fi, gi, "empty"))
                continue
            kind = "full"
            for col, iv in ivs.items():
                v = _zone_verdict(pf["cols"].get(col), gi, iv, rows)
                if v == "empty":
                    kind = "empty"
                    break
                if v == "partial":
                    kind = "partial"
            if kind == "full":
                if key_lower is not None:
                    glist = pf["groups"].get(key_lower)
                    g = (
                        glist[gi]
                        if glist is not None and gi < len(glist)
                        else None
                    )
                    if g is None or not all(
                        _op_available(op, c, g["cols"])
                        for op, c in fplan.agg_ops
                    ):
                        kind = "partial"
                elif not all(
                    _op_available(op, c, pf["cols"])
                    for op, c in fplan.agg_ops
                ):
                    kind = "partial"
            cells.append((fi, gi, kind))
    return cells


# ---------------------------------------------------------------------------
# Stored state -> AggPartials (the fold input for FULL row groups)
# ---------------------------------------------------------------------------


def rg_partials(data: AggData, fi: int, gi: int, fplan, key: Optional[str]):
    """One FULL row group's stored partials as
    :class:`~hyperspace_tpu.execution.pipeline_compiler.AggPartials` —
    every row passes, so the stored unfiltered state IS the chunk state
    the sweep would have produced."""
    from hyperspace_tpu.execution import pipeline_compiler as PC
    from hyperspace_tpu.io.columnar import Column

    pf = data.per_file[fi]
    rows = pf["rg_rows"][gi]
    na = len(fplan.agg_ops)
    if key is None:
        G = 1
        g_reps = np.zeros((0, G), dtype=np.int64)
        g_nulls = np.zeros((0, G), dtype=np.uint8)
        g_kvals = np.zeros((0, G), dtype=np.int64)
        g_kvalid = np.ones((0, G), dtype=np.uint8)
        khv: Tuple[bool, ...] = ()

        def cell(col, field):
            return pf["cols"][col][field][gi : gi + 1]

        count_star = np.array([rows], dtype=np.int64)
    else:
        g = pf["groups"][key.lower()][gi]
        G = len(g["n"])
        kvals = g["kv"]
        kvalid = g["kvalid"]
        col = Column(
            "numeric",
            fplan.key_types[0],
            values=kvals.view(np.float64) if fplan.key_f64[0] else kvals,
            validity=None if kvalid is None else kvalid.astype(bool),
        )
        reps = col.key_rep()
        nm = col.null_mask
        g_reps = reps.reshape(1, G)
        g_nulls = (
            nm.astype(np.uint8) if nm is not None else np.zeros(G, np.uint8)
        ).reshape(1, G)
        g_kvals = kvals.reshape(1, G)
        g_kvalid = (
            kvalid if kvalid is not None else np.ones(G, dtype=np.uint8)
        ).reshape(1, G)
        khv = (kvalid is not None,)

        def cell(colname, field):
            return g["cols"][colname][field]

        count_star = g["n"]
    acc_i = np.zeros((na, G), dtype=np.int64)
    acc_f = np.zeros((na, G), dtype=np.float64)
    acc_cnt = np.zeros((na, G), dtype=np.int64)
    acc_aux = np.zeros((na, G), dtype=np.int64)
    for a, (op, c) in enumerate(fplan.agg_ops):
        if op == PC._OP_COUNT_STAR:
            acc_cnt[a] = count_star
            continue
        acc_cnt[a] = cell(c, "cnt")
        if op == PC._OP_COUNT_COL:
            continue
        if op == PC._OP_SUM_I64:
            acc_i[a] = cell(c, "sum")
        elif op == PC._OP_MIN_I64:
            acc_i[a] = cell(c, "min")
        elif op == PC._OP_MAX_I64:
            acc_i[a] = cell(c, "max")
        elif op == PC._OP_MIN_F64:
            acc_f[a] = cell(c, "min")
            acc_aux[a] = cell(c, "clean")
        elif op == PC._OP_MAX_F64:
            acc_f[a] = cell(c, "max")
            acc_aux[a] = cell(c, "nan")
        else:  # pragma: no cover — the lowering filtered ops already
            return None
    return PC.AggPartials(
        n_groups=G,
        rows_scanned=0,
        rows_passed=int(rows),
        g_reps=g_reps,
        g_nulls=g_nulls,
        g_kvals=g_kvals,
        g_kvalid=g_kvalid,
        key_has_validity=khv,
        acc_i=acc_i,
        acc_f=acc_f,
        acc_cnt=acc_cnt,
        acc_aux=acc_aux,
    )


# ---------------------------------------------------------------------------
# Stratified samples for the approximate plane
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=16)
def _sample_table_cached(path: str, _size: int, _mtime_ns: int) -> Optional[pa.Table]:
    try:
        return pq.read_table(path)
    except (OSError, pa.ArrowInvalid):
        return None


def _sample_table_for_dir(dirpath: str) -> Optional[pa.Table]:
    path = os.path.join(dirpath, SAMPLE_NAME)
    try:
        st = os.stat(path)
    except OSError:
        return None
    return _sample_table_cached(path, st.st_size, st.st_mtime_ns)


def sample_data_for(rel, conf=None) -> Optional[dict]:
    """Stratified sample over a relation's file set for the approximate
    plane: ``{"table": pa.Table (sample rows, file order), "stratum":
    int array per sample row, "N": rows per stratum, "n": sampled rows
    per stratum}``. Strata are (file, row group). None when any file has
    neither a sample sidecar nor a computable backfill."""
    data = agg_data_for(rel, None, conf, None)
    if data is None:
        return None
    sample_rows = (
        conf.index_agg_sample_rows
        if conf is not None
        else C.INDEX_AGG_SAMPLE_ROWS_DEFAULT
    )
    tables: List[pa.Table] = []
    stratum_ids: List[np.ndarray] = []
    N: List[int] = []
    n: List[int] = []
    sample_by_dir: Dict[str, Optional[pa.Table]] = {}
    for fi, path in enumerate(rel.files):
        pf = data.per_file[fi]
        if pf is None:
            return None
        d = os.path.dirname(path)
        base = os.path.basename(path)
        if d not in sample_by_dir:
            sample_by_dir[d] = _sample_table_for_dir(d)
        stable = sample_by_dir[d]
        ftable = None
        # trust the dir's sample sidecar only for files whose AGGSTATE
        # entry was stat-fresh: a rewritten file must sample from the
        # backfill read, never from the old dir's rows
        fresh = (
            fi < len(data.per_file_sidecar) and data.per_file_sidecar[fi]
        )
        if fresh and stable is not None and "__file" in stable.column_names:
            mask = pa_compute.equal(stable.column("__file"), base)
            ftable = stable.filter(mask)
        if ftable is None or ftable.num_rows == 0:
            try:
                st = os.stat(path)
                _entry, ftable = _backfill_cached(
                    path, st.st_size, st.st_mtime_ns, (), 0, sample_rows
                )
            except Exception:  # hslint: disable=HS402
                ftable = None
        rg_rows = pf["rg_rows"]
        if ftable is None:
            if sum(rg_rows) == 0:
                continue  # empty file contributes no strata
            return None
        rgs = np.asarray(ftable.column("__rg"))
        for gi, rows in enumerate(rg_rows):
            if rows == 0:
                continue
            sel = np.nonzero(rgs == gi)[0]
            sid = len(N)
            N.append(int(rows))
            n.append(int(len(sel)))
            if len(sel):
                tables.append(
                    ftable.take(sel).drop_columns(["__file", "__rg"])
                )
                stratum_ids.append(np.full(len(sel), sid, dtype=np.int64))
    if not N:
        return None
    if any(v == 0 for v in n):
        return None  # a stratum with rows but no sample: not estimable
    table = pa.concat_tables(tables, promote_options="permissive")
    return {
        "table": table,
        "stratum": np.concatenate(stratum_ids),
        "N": np.asarray(N, dtype=np.int64),
        "n": np.asarray(n, dtype=np.int64),
    }
