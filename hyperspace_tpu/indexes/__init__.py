"""Index implementations ("derived datasets", L3).

Reference: ``index/covering/``, ``index/zordercovering/``,
``index/dataskipping/``; the polymorphic ``Index`` trait is
``index/Index.scala:31-168``.
"""

from hyperspace_tpu.indexes.base import Index, IndexConfigTrait, UpdateMode

__all__ = ["Index", "IndexConfigTrait", "UpdateMode"]
