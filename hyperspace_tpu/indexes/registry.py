"""Polymorphic index (de)serialization registry.

Plays the role of Jackson's ``@JsonTypeInfo(use=Id.CLASS)`` on the
reference's ``Index`` trait (``index/Index.scala:25-30``): the JSON carries
a ``"type"`` discriminator; this registry maps it back to the class.
"""

from __future__ import annotations

from typing import Dict, Type

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.indexes.base import Index

_REGISTRY: Dict[str, Type[Index]] = {}


def register_index(cls: Type[Index]) -> Type[Index]:
    _REGISTRY[cls.kind] = cls
    return cls


def _ensure_builtin_kinds_loaded() -> None:
    # Importing the modules runs their @register_index decorators. Only a
    # module genuinely not existing yet is tolerated; transitive import
    # failures inside an existing module must propagate.
    import importlib

    for mod in (
        "hyperspace_tpu.indexes.covering",
        "hyperspace_tpu.indexes.zorder",
        "hyperspace_tpu.indexes.dataskipping",
    ):
        try:
            importlib.import_module(mod)
        except ModuleNotFoundError as e:
            if e.name != mod:
                raise


def index_from_dict(d: dict) -> Index:
    _ensure_builtin_kinds_loaded()
    kind = d.get("type")
    cls = _REGISTRY.get(kind)
    if cls is None:
        raise HyperspaceException(f"Unknown index kind: {kind!r}")
    return cls.from_dict(d)
