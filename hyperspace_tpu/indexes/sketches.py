"""Sketches for the data-skipping index.

Reference: ``dataskipping/sketches/`` — ``Sketch.scala:36-119`` (the
expressions/aggregate/convertPredicate contract), ``MinMaxSketch.scala``
(range pruning for =,<,≤,>,≥,In), ``BloomFilterSketch.scala`` (equality/In
membership pruning), ``PartitionSketch.scala`` (constant-per-file
columns). A sketch aggregates one source file into a few cells of the
sketch table and converts query conjuncts into keep-masks over its rows.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple, Type

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import Column, ColumnarBatch, column_value_range
from hyperspace_tpu.ops.bloom import _bit_indices
from hyperspace_tpu.ops.hash import split_words_np
from hyperspace_tpu.plan import expressions as E
from hyperspace_tpu.utils.hashing import murmur3_64_bytes

_SKETCH_REGISTRY: Dict[str, Type["Sketch"]] = {}


def register_sketch(cls):
    _SKETCH_REGISTRY[cls.kind] = cls
    return cls


def sketch_from_dict(d: dict) -> "Sketch":
    cls = _SKETCH_REGISTRY.get(d.get("type"))
    if cls is None:
        raise HyperspaceException(f"Unknown sketch kind: {d.get('type')!r}")
    return cls.from_dict(d)


# Shared NaN/null-aware range helper (io/columnar.column_value_range):
# previously a plain v.min() here let one NaN poison a file's min to NaN,
# making `min <= lit` False and wrongly skipping a file with matching rows.
_column_min_max = column_value_range


# Col-vs-Lit normalization lives in plan/expressions (shared with the
# executor's bucket pruning); keep the historical local name.
_normalize_conjunct = E.normalize_comparison


class Sketch:
    kind = "Sketch"

    def __init__(self, column: str):
        self.column = column
        # arrow type string of the source column, resolved at index
        # creation; literals are coerced against it at probe time
        self.source_type: Optional[str] = None

    # -- identity / serialization ------------------------------------------
    def __eq__(self, other):
        return type(self) is type(other) and self.to_dict() == other.to_dict()

    def __repr__(self):
        return f"{self.kind}({self.column})"

    def to_dict(self) -> dict:
        d = {"type": self.kind, "column": self.column}
        if self.source_type is not None:
            d["sourceType"] = self.source_type
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Sketch":
        s = cls(d["column"])
        s.source_type = d.get("sourceType")
        return s

    # -- contract -----------------------------------------------------------
    def referenced_columns(self) -> List[str]:
        return [self.column]

    def output_fields(self, source_type: pa.DataType) -> List[Tuple[str, pa.DataType]]:
        raise NotImplementedError

    def aggregate(self, batch: ColumnarBatch) -> Dict[str, Any]:
        """One source file's batch -> sketch cell values."""
        raise NotImplementedError

    def convert_predicate(
        self, expr: E.Expr, table: pa.Table
    ) -> Optional[np.ndarray]:
        """Keep-mask over sketch rows for one conjunct, or None if this
        sketch cannot decide it (Sketch.convertPredicate contract)."""
        return None


@register_sketch
class MinMaxSketch(Sketch):
    kind = "MinMaxSketch"

    def output_fields(self, source_type):
        return [
            (f"MinMax_{self.column}__min", source_type),
            (f"MinMax_{self.column}__max", source_type),
        ]

    def aggregate(self, batch):
        lo, hi = _column_min_max(batch.column(self.column))
        return {
            f"MinMax_{self.column}__min": lo,
            f"MinMax_{self.column}__max": hi,
        }

    def _arrow_type(self):
        if self.source_type is None:
            return None
        from hyperspace_tpu.rules.rule_utils import parse_arrow_type

        try:
            return parse_arrow_type(self.source_type)
        except (ValueError, HyperspaceException):
            return None

    def _cell_zones(self, table: pa.Table, t):
        """The sketch table's min/max cells as a zone-map column
        (``indexes/zonemaps.ColZones``) through the SHARED assembly
        helper, memoized per table identity — ``translate_filter`` probes
        once per conjunct against the same table, and the cell conversion
        is the dominant per-call cost (one pyarrow round trip per
        temporal cell)."""
        cached = getattr(self, "_zone_cache", None)
        if cached is not None and cached[0] is table:
            return cached[1]
        from hyperspace_tpu.indexes import zonemaps as zm

        lo_cells = table.column(f"MinMax_{self.column}__min").to_pylist()
        hi_cells = table.column(f"MinMax_{self.column}__max").to_pylist()
        cells = [
            "allnull" if lo is None and hi is None else (lo, hi)
            for lo, hi in zip(lo_cells, hi_cells)
        ]
        cz = zm.column_zones(cells, t)
        self._zone_cache = (table, cz)
        return cz

    def convert_predicate(self, expr, table):
        """Keep-mask over sketch rows, evaluated for ALL files in one
        vectorized pass through the zone-map overlap test
        (``indexes/zonemaps``) — the interval extraction and literal
        lowering are SHARED with the executor's ``_range_pruned_scan``,
        so sketch pruning and zone-map pruning can never disagree on
        what a literal means."""
        from hyperspace_tpu.indexes import zonemaps as zm

        if f"MinMax_{self.column}__min" not in table.column_names:
            return None
        t = self._arrow_type()
        if t is None:
            return None  # no recorded type: abstain (sound, and real
            # indexes always record one at creation)
        if isinstance(expr, E.In):
            if not (
                isinstance(expr.child, E.Col)
                and expr.child.name.lower() == self.column.lower()
            ):
                return None
            cz = self._cell_zones(table, t)
            masks = []
            for v in expr.values:
                if v is None:
                    continue
                iv = zm.interval_for("=", v, t)
                if iv is None:
                    return None  # incomparable literal type: abstain
                masks.append(zm.zone_keep_mask(cz, iv))
            if not masks:
                return np.zeros(len(cz.has), dtype=bool)
            return np.logical_or.reduce(masks)
        norm = _normalize_conjunct(expr)
        if norm is None:
            return None
        op, col, lit = norm
        if col.lower() != self.column.lower() or op == "!=":
            return None
        iv = zm.interval_for(op, lit, t)
        if iv is None:
            return None  # incomparable literal type: abstain
        return zm.zone_keep_mask(self._cell_zones(table, t), iv)


@register_sketch
class BloomFilterSketch(Sketch):
    kind = "BloomFilterSketch"

    def __init__(self, column: str, fpp: float = 0.01, expected_items: int = 10000):
        super().__init__(column)
        self.fpp = float(fpp)
        self.expected_items = int(expected_items)
        from hyperspace_tpu.ops.bloom import optimal_params

        self.m, self.k = optimal_params(self.expected_items, self.fpp)

    def to_dict(self):
        d = {
            "type": self.kind,
            "column": self.column,
            "fpp": self.fpp,
            "expectedItems": self.expected_items,
        }
        if self.source_type is not None:
            d["sourceType"] = self.source_type
        return d

    @classmethod
    def from_dict(cls, d):
        s = cls(d["column"], d.get("fpp", 0.01), d.get("expectedItems", 10000))
        s.source_type = d.get("sourceType")
        return s

    def output_fields(self, source_type):
        return [(f"BloomFilter_{self.column}__bits", pa.binary())]

    def aggregate(self, batch):
        from hyperspace_tpu.ops.bloom import build_bloom

        col = batch.column(self.column)
        reps = col.key_rep()
        nulls = col.null_mask
        if nulls is not None:
            reps = reps[~nulls]
        words = build_bloom(reps, self.m, self.k)
        return {f"BloomFilter_{self.column}__bits": words.tobytes()}

    def _probe(self, table: pa.Table, values) -> Optional[np.ndarray]:
        name = f"BloomFilter_{self.column}__bits"
        if name not in table.column_names:
            return None
        reps = []
        for v in values:
            rep = _value_rep(v, self.source_type)
            if rep is _ABSTAIN:
                return None  # un-coercible literal: this sketch can't decide
            if rep is not _NO_MATCH:
                reps.append(rep)
        blobs = table.column(name).to_pylist()
        if not reps:  # every literal is outside the column's value domain
            return np.zeros(len(blobs), dtype=bool)
        blooms = np.stack(
            [
                np.frombuffer(b, dtype=np.uint64)
                if b
                else np.zeros(self.m // 64, dtype=np.uint64)
                for b in blobs
            ]
        )
        idx = np.asarray(
            _bit_indices(
                jnp.asarray(split_words_np(np.array(reps, dtype=np.int64)[None, :])),
                self.m,
                self.k,
            )
        )  # [k, n_values]
        widx, bit = idx >> 6, (idx & 63).astype(np.uint64)
        # hits[f, j] = all k bits of value j set in bloom f
        hits = (
            (blooms[:, widx] >> bit[None, :, :]) & np.uint64(1)
        ).all(axis=1)
        return hits.any(axis=1)

    def convert_predicate(self, expr, table):
        if isinstance(expr, E.In):
            if (
                isinstance(expr.child, E.Col)
                and expr.child.name.lower() == self.column.lower()
            ):
                vals = [v for v in expr.values if v is not None]
                return self._probe(table, vals)
            return None
        norm = _normalize_conjunct(expr)
        if norm is None:
            return None
        op, col, lit = norm
        if col.lower() != self.column.lower() or op != "=":
            return None
        return self._probe(table, [lit])


_ABSTAIN = object()  # literal un-coercible -> sketch cannot decide
_NO_MATCH = object()  # literal outside the column's domain -> matches nothing


def _value_rep(v, source_type: Optional[str]):
    """Literal -> the int64 key rep io/columnar assigns to the COLUMN's
    values, coercing the literal to the column's type first (an int column
    probed with 2050.0 must hash the integer 2050; a probe the executor
    would match must never be pruned away)."""
    if source_type is None:
        return _ABSTAIN
    t = source_type
    if t in ("string", "large_string"):
        if not isinstance(v, str):
            return _ABSTAIN
        return murmur3_64_bytes(v.encode("utf-8"))
    if t == "bool":
        return int(bool(v))
    if t.startswith("int") or t.startswith("uint"):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return _ABSTAIN
        if isinstance(v, float):
            if not v.is_integer():
                return _NO_MATCH
            v = int(v)
        if t.startswith("uint"):
            # Column key_rep for uint64 is the int64 bit-view (values >= 2^63
            # appear negative); the probe must match bit-for-bit.
            if v < 0 or v >= 1 << 64:
                return _NO_MATCH
            return int(np.uint64(v).view(np.int64))
        if v < -(1 << 63) or v >= 1 << 63:
            return _NO_MATCH
        return int(v)
    if t in ("float", "double", "halffloat"):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return _ABSTAIN
        f = np.float64(v)
        if f == 0.0:
            return 0
        return int(f.view(np.int64))
    return _ABSTAIN


@register_sketch
class PartitionSketch(Sketch):
    """Constant-per-file column values (the reference auto-adds this for
    hive-partitioned sources, PartitionSketch.scala:38-74; ours detects
    constancy per file at build time, which also covers partition dirs)."""

    kind = "PartitionSketch"

    def output_fields(self, source_type):
        return [
            (f"Partition_{self.column}__val", source_type),
            (f"Partition_{self.column}__const", pa.bool_()),
        ]

    def aggregate(self, batch):
        col = batch.column(self.column)
        val, const = None, False
        if batch.num_rows:
            if col.kind == "string":
                codes = np.unique(col.codes)
                const = len(codes) == 1
                if const and codes[0] >= 0:
                    val = col.dictionary[codes[0]]
            else:
                v = col.values
                if col.validity is None or col.validity.all():
                    const = bool((v == v[0]).all()) if len(v) else False
                    if const:
                        val = v[0].item()
        return {
            f"Partition_{self.column}__val": val,
            f"Partition_{self.column}__const": const,
        }

    def convert_predicate(self, expr, table):
        name = f"Partition_{self.column}__val"
        if name not in table.column_names:
            return None
        vals = table.column(name).to_pylist()
        const = np.asarray(table.column(f"Partition_{self.column}__const"))

        def eq_mask(lit):
            return np.array(
                [
                    (not c) or (v is not None and v == lit)
                    for v, c in zip(vals, const)
                ]
            )

        if isinstance(expr, E.In):
            if (
                isinstance(expr.child, E.Col)
                and expr.child.name.lower() == self.column.lower()
            ):
                masks = [eq_mask(v) for v in expr.values if v is not None]
                if not masks:
                    return np.zeros(len(vals), dtype=bool)
                return np.logical_or.reduce(masks)
            return None
        norm = _normalize_conjunct(expr)
        if norm is None:
            return None
        op, col, lit = norm
        if col.lower() != self.column.lower() or op != "=":
            return None
        return eq_mask(lit)
