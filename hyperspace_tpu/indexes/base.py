"""Index trait + config trait.

Reference: ``index/Index.scala:31-168`` (the contract every index kind
implements; Jackson-polymorphic on a ``type`` property) and
``index/IndexConfigTrait.scala:32-59`` (user config whose ``createIndex``
returns the index object plus its data).
"""

from __future__ import annotations

import abc
import enum
from typing import Dict, List, Optional, Tuple


class UpdateMode(enum.Enum):
    """How refreshed index data combines with the previous version
    (Index.scala:162-168)."""

    MERGE = "merge"          # new version dir adds to previous content
    OVERWRITE = "overwrite"  # new version dir replaces previous content


class Index(abc.ABC):
    """A derived dataset. Subclasses must set ``kind`` and register in
    :mod:`hyperspace_tpu.indexes.registry`."""

    kind: str = "Index"
    # Reference kindAbbr shown in plan strings, e.g. "CI" / "ZOCI" / "DS".
    kind_abbr: str = "IX"

    # -- serialization (polymorphic via "type") -----------------------------
    @abc.abstractmethod
    def to_dict(self) -> dict:
        ...

    @classmethod
    @abc.abstractmethod
    def from_dict(cls, d: dict) -> "Index":
        ...

    # -- schema surface -----------------------------------------------------
    @property
    @abc.abstractmethod
    def indexed_columns(self) -> List[str]:
        ...

    @property
    def included_columns(self) -> List[str]:
        return []

    def referenced_columns(self) -> List[str]:
        return list(self.indexed_columns) + list(self.included_columns)

    # -- data-plane operations (Index.scala write/optimize/refresh*) --------
    @abc.abstractmethod
    def write(self, ctx, index_data) -> None:
        """Write ``index_data`` into ``ctx.index_data_path``."""

    def optimize(self, ctx, files_to_optimize: List[str]) -> None:
        raise NotImplementedError(f"{self.kind} does not support optimize")

    def refresh_incremental(
        self, ctx, appended_df, deleted_source_files, previous_content
    ):
        raise NotImplementedError(
            f"{self.kind} does not support incremental refresh"
        )

    def refresh_full(self, ctx, df) -> "Index":
        """Rebuild from the current source; returns the rebuilt Index (its
        schema may differ if source types changed)."""
        raise NotImplementedError(f"{self.kind} does not support full refresh")

    @property
    def can_handle_deleted_files(self) -> bool:
        return False

    def statistics(self, extended: bool = False) -> Dict[str, str]:
        return {}


class IndexConfigTrait(abc.ABC):
    """User-supplied index definition (IndexConfigTrait.scala:32-59)."""

    @property
    @abc.abstractmethod
    def index_name(self) -> str:
        ...

    @property
    @abc.abstractmethod
    def referenced_columns(self) -> List[str]:
        ...

    @abc.abstractmethod
    def create_index(self, ctx, source_data, properties: Dict[str, str]):
        """Return ``(Index, index_data)`` — the index object and the data to
        write (IndexConfigTrait.createIndex)."""

    def describe_index(self, ctx, source_data, properties: Dict[str, str]):
        """The Index object alone, WITHOUT building index data — used for
        the begin-phase (transient-state) log entry, which is written
        before any data exists."""
        raise NotImplementedError
