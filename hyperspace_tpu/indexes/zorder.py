"""Z-order covering index.

Reference: ``zordercovering/ZOrderCoveringIndex.scala:32-189`` — a covering
index whose rows are globally sorted by interleaved-bit **z-address**
instead of hash-bucketed: multi-column range queries touch few files.
Build = z-address kernel (``ops/zorder.py``) + global device sort + write
split into ~targetSourceBytesPerPartition files (the reference's
``repartitionByRange`` on ``_zaddr``, `:139-153`).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from hyperspace_tpu.constants import DATA_FILE_NAME_ID, LINEAGE_PROPERTY
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.indexes.base import Index, IndexConfigTrait, UpdateMode
from hyperspace_tpu.indexes.registry import register_index
from hyperspace_tpu.io import parquet as pio
from hyperspace_tpu.io.columnar import ColumnarBatch


@register_index
class ZOrderCoveringIndex(Index):
    kind = "ZOrderCoveringIndex"
    kind_abbr = "ZOCI"

    def __init__(
        self,
        indexed_columns: List[str],
        included_columns: List[str],
        schema_json: str,
        target_bytes_per_partition: int,
        properties: Optional[Dict[str, str]] = None,
    ):
        self._indexed_columns = list(indexed_columns)
        self._included_columns = list(included_columns)
        self.schema_json = schema_json
        self.target_bytes_per_partition = int(target_bytes_per_partition)
        self.properties: Dict[str, str] = dict(properties or {})

    def __eq__(self, other):
        return (
            isinstance(other, ZOrderCoveringIndex)
            and self._indexed_columns == other._indexed_columns
            and self._included_columns == other._included_columns
            and self.schema_json == other.schema_json
        )

    def __hash__(self):
        return hash(tuple(self._indexed_columns))

    @property
    def indexed_columns(self) -> List[str]:
        return list(self._indexed_columns)

    @property
    def included_columns(self) -> List[str]:
        return list(self._included_columns)

    @property
    def lineage_enabled(self) -> bool:
        return str(self.properties.get(LINEAGE_PROPERTY, "false")).lower() == "true"

    @property
    def can_handle_deleted_files(self) -> bool:
        return self.lineage_enabled

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "type": self.kind,
            "kindAbbr": self.kind_abbr,
            "indexedColumns": self._indexed_columns,
            "includedColumns": self._included_columns,
            "schemaJson": self.schema_json,
            "targetBytesPerPartition": self.target_bytes_per_partition,
            "properties": dict(self.properties),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ZOrderCoveringIndex":
        return cls(
            d["indexedColumns"],
            d.get("includedColumns", []),
            d.get("schemaJson", ""),
            d.get("targetBytesPerPartition", 1 << 30),
            d.get("properties", {}),
        )

    # -- data plane ---------------------------------------------------------
    def write(self, ctx, index_data: ColumnarBatch) -> None:
        """Z-sort + size-targeted split write
        (ZOrderCoveringIndex.write:97-154)."""
        _write_zordered(
            ctx, index_data, self._indexed_columns, self.target_bytes_per_partition
        )

    def optimize(self, ctx, files_to_optimize: List[str]) -> None:
        batch = ColumnarBatch.from_arrow(pio.read_table(files_to_optimize, None))
        _write_zordered(
            ctx, batch, self._indexed_columns, self.target_bytes_per_partition
        )

    def refresh_incremental(
        self, ctx, appended_df, deleted_source_file_ids, previous_content
    ) -> Tuple["ZOrderCoveringIndex", UpdateMode]:
        """Like the covering index, but the new data is z-sorted on its own
        (a merged global re-sort would be a full rebuild; the reference
        likewise z-sorts only the delta)."""
        from hyperspace_tpu.indexes import covering_build

        schema_cols = self._indexed_columns + self._included_columns
        if self.lineage_enabled:
            schema_cols = schema_cols + [DATA_FILE_NAME_ID]
        parts = []
        if appended_df is not None:
            _idx, data = covering_build.create_covering_index(
                ctx, appended_df, self._config(), dict(self.properties)
            )
            # z-order needs the whole delta in memory (global min/max +
            # total z-sort); the streaming wave loop is covering-index only
            parts.append(
                covering_build.materialize_if_scan(data).select(schema_cols)
            )
        if deleted_source_file_ids:
            if not self.lineage_enabled:
                raise HyperspaceException(
                    "Cannot handle deleted source files without lineage"
                )
            old = ColumnarBatch.from_arrow(
                pio.read_table(list(previous_content.files), None)
            )
            lineage = old.column(DATA_FILE_NAME_ID).values
            keep = ~np.isin(
                lineage, np.array(deleted_source_file_ids, dtype=np.int64)
            )
            parts.append(old.filter(keep).select(schema_cols))
            mode = UpdateMode.OVERWRITE
        else:
            mode = UpdateMode.MERGE
        if parts:
            batch = ColumnarBatch.concat(parts)
            _write_zordered(
                ctx, batch, self._indexed_columns, self.target_bytes_per_partition
            )
        return self, mode

    def refresh_full(self, ctx, df) -> "ZOrderCoveringIndex":
        from hyperspace_tpu.indexes import covering_build

        new_index, batch = covering_build.create_covering_index(
            ctx, df, self._config(), dict(self.properties)
        )
        batch = covering_build.materialize_if_scan(batch)
        # create_covering_index builds a CoveringIndex; re-wrap with our kind
        rebuilt = ZOrderCoveringIndex(
            new_index.indexed_columns,
            new_index.included_columns,
            new_index.schema_json,
            self.target_bytes_per_partition,
            dict(self.properties),
        )
        rebuilt.write(ctx, batch)
        return rebuilt

    def _config(self) -> "ZOrderCoveringIndexConfig":
        return ZOrderCoveringIndexConfig(
            "__refresh__", self._indexed_columns, self._included_columns
        )

    def statistics(self, extended: bool = False) -> Dict[str, str]:
        return {
            "indexedColumns": ",".join(self._indexed_columns),
            "includedColumns": ",".join(self._included_columns),
            "targetBytesPerPartition": str(self.target_bytes_per_partition),
            "schema": self.schema_json if extended else "",
        }


def _write_zordered(
    ctx, batch: ColumnarBatch, indexed_cols: List[str], target_bytes: int
) -> List[str]:
    """Global z-sort then split into ~equal files sized to hit the target
    partition bytes."""
    import os

    from hyperspace_tpu.ops.zorder import z_order_permutation

    os.makedirs(ctx.index_data_path, exist_ok=True)
    if batch.num_rows == 0:
        return []
    conf = ctx.session.conf
    perm = z_order_permutation(
        [batch.column(c) for c in indexed_cols],
        quantile=conf.zorder_quantile_enabled,
        relative_error=conf.zorder_quantile_relative_error,
    )
    table = batch.take(perm).to_arrow()
    nbytes = max(table.nbytes, 1)
    num_parts = max(1, math.ceil(nbytes / target_bytes))
    rows_per_part = math.ceil(table.num_rows / num_parts)
    written = []
    for i in range(num_parts):
        chunk = table.slice(i * rows_per_part, rows_per_part)
        if chunk.num_rows == 0:
            continue
        path = os.path.join(ctx.index_data_path, f"part-{i:05d}-zorder.parquet")
        pio.write_table(path, chunk)
        written.append(path)
    return written


class ZOrderCoveringIndexConfig(IndexConfigTrait):
    """name + indexedColumns + includedColumns
    (ZOrderCoveringIndexConfig.scala)."""

    def __init__(
        self,
        index_name: str,
        indexed_columns: List[str],
        included_columns: Optional[List[str]] = None,
    ):
        if not index_name:
            raise HyperspaceException("Index name cannot be empty")
        if not indexed_columns:
            raise HyperspaceException("indexed_columns cannot be empty")
        self._name = index_name
        self._indexed = list(indexed_columns)
        self._included = list(included_columns or [])

    @property
    def index_name(self) -> str:
        return self._name

    @property
    def indexed_columns(self) -> List[str]:
        return list(self._indexed)

    @property
    def included_columns(self) -> List[str]:
        return list(self._included)

    @property
    def referenced_columns(self) -> List[str]:
        return self._indexed + self._included

    def _target_bytes(self, ctx) -> int:
        return ctx.session.conf.zorder_target_source_bytes_per_partition

    def create_index(self, ctx, source_data, properties: Dict[str, str]):
        from hyperspace_tpu.indexes import covering_build

        covering, batch = covering_build.create_covering_index(
            ctx, source_data, self, properties
        )
        # z-order's global normalization + total sort are not streamed;
        # materialize even when the covering build would have waved it
        batch = covering_build.materialize_if_scan(batch)
        index = ZOrderCoveringIndex(
            covering.indexed_columns,
            covering.included_columns,
            covering.schema_json,
            self._target_bytes(ctx),
            dict(properties),
        )
        return index, batch

    def describe_index(self, ctx, source_data, properties: Dict[str, str]):
        from hyperspace_tpu.indexes import covering_build

        covering = covering_build.describe_covering_index(
            ctx, source_data, self, properties
        )
        return ZOrderCoveringIndex(
            covering.indexed_columns,
            covering.included_columns,
            covering.schema_json,
            self._target_bytes(ctx),
            dict(properties),
        )
