"""Z-order covering index.

Reference: ``zordercovering/ZOrderCoveringIndex.scala:32-189`` — a covering
index whose rows are globally sorted by interleaved-bit **z-address**
instead of hash-bucketed: multi-column range queries touch few files.
Build = z-address kernel (``ops/zorder.py``) + global device sort + write
split into ~targetSourceBytesPerPartition files (the reference's
``repartitionByRange`` on ``_zaddr``, `:139-153`).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from hyperspace_tpu.constants import DATA_FILE_NAME_ID, LINEAGE_PROPERTY
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.indexes.base import Index, IndexConfigTrait, UpdateMode
from hyperspace_tpu.indexes.registry import register_index
from hyperspace_tpu.io import parquet as pio
from hyperspace_tpu.io.columnar import ColumnarBatch


@register_index
class ZOrderCoveringIndex(Index):
    kind = "ZOrderCoveringIndex"
    kind_abbr = "ZOCI"

    def __init__(
        self,
        indexed_columns: List[str],
        included_columns: List[str],
        schema_json: str,
        target_bytes_per_partition: int,
        properties: Optional[Dict[str, str]] = None,
    ):
        self._indexed_columns = list(indexed_columns)
        self._included_columns = list(included_columns)
        self.schema_json = schema_json
        self.target_bytes_per_partition = int(target_bytes_per_partition)
        self.properties: Dict[str, str] = dict(properties or {})

    def __eq__(self, other):
        return (
            isinstance(other, ZOrderCoveringIndex)
            and self._indexed_columns == other._indexed_columns
            and self._included_columns == other._included_columns
            and self.schema_json == other.schema_json
        )

    def __hash__(self):
        return hash(tuple(self._indexed_columns))

    @property
    def indexed_columns(self) -> List[str]:
        return list(self._indexed_columns)

    @property
    def included_columns(self) -> List[str]:
        return list(self._included_columns)

    @property
    def lineage_enabled(self) -> bool:
        return str(self.properties.get(LINEAGE_PROPERTY, "false")).lower() == "true"

    @property
    def can_handle_deleted_files(self) -> bool:
        return self.lineage_enabled

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "type": self.kind,
            "kindAbbr": self.kind_abbr,
            "indexedColumns": self._indexed_columns,
            "includedColumns": self._included_columns,
            "schemaJson": self.schema_json,
            "targetBytesPerPartition": self.target_bytes_per_partition,
            "properties": dict(self.properties),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ZOrderCoveringIndex":
        return cls(
            d["indexedColumns"],
            d.get("includedColumns", []),
            d.get("schemaJson", ""),
            d.get("targetBytesPerPartition", 1 << 30),
            d.get("properties", {}),
        )

    # -- data plane ---------------------------------------------------------
    def write(self, ctx, index_data: ColumnarBatch) -> None:
        """Z-sort + size-targeted split write
        (ZOrderCoveringIndex.write:97-154)."""
        _write_zordered(
            ctx, index_data, self._indexed_columns, self.target_bytes_per_partition
        )

    def optimize(self, ctx, files_to_optimize: List[str]) -> None:
        batch = ColumnarBatch.from_arrow(pio.read_table(files_to_optimize, None))
        _write_zordered(
            ctx, batch, self._indexed_columns, self.target_bytes_per_partition
        )

    def refresh_incremental(
        self, ctx, appended_df, deleted_source_file_ids, previous_content
    ) -> Tuple["ZOrderCoveringIndex", UpdateMode]:
        """Like the covering index, but the new data is z-sorted on its own
        (a merged global re-sort would be a full rebuild; the reference
        likewise z-sorts only the delta).

        The refresh input — appended source files and, for deletes, the
        lineage-filtered previous index data — is assembled LAZILY
        (SourceScan/CompositeScan) and only materialized when it fits the
        build memory budget; otherwise it streams through the same
        two-pass wave loop as create/full refresh."""
        from hyperspace_tpu.indexes.covering_build import (
            CompositeScan,
            lazy_or_materialized,
            prepare_covering_index,
            previous_index_scan,
            reset_build_breakdown,
        )

        reset_build_breakdown()
        schema_cols = self._indexed_columns + self._included_columns
        if self.lineage_enabled:
            schema_cols = schema_cols + [DATA_FILE_NAME_ID]
        scans = []
        if appended_df is not None:
            _idx, scan = prepare_covering_index(
                ctx, appended_df, self._config(), dict(self.properties)
            )
            scans.append(scan.select(schema_cols))
        if deleted_source_file_ids:
            if not self.lineage_enabled:
                raise HyperspaceException(
                    "Cannot handle deleted source files without lineage"
                )
            scans.append(
                previous_index_scan(
                    ctx, previous_content, schema_cols, deleted_source_file_ids
                )
            )
            mode = UpdateMode.OVERWRITE
        else:
            mode = UpdateMode.MERGE
        if scans:
            combined = scans[0] if len(scans) == 1 else CompositeScan(tuple(scans))
            _write_zordered(
                ctx,
                lazy_or_materialized(ctx, combined),
                self._indexed_columns,
                self.target_bytes_per_partition,
            )
        return self, mode

    def refresh_full(self, ctx, df) -> "ZOrderCoveringIndex":
        from hyperspace_tpu.indexes import covering_build

        new_index, batch = covering_build.create_covering_index(
            ctx, df, self._config(), dict(self.properties)
        )
        # a SourceScan flows straight into write (streamed two-pass build)
        # create_covering_index builds a CoveringIndex; re-wrap with our kind
        rebuilt = ZOrderCoveringIndex(
            new_index.indexed_columns,
            new_index.included_columns,
            new_index.schema_json,
            self.target_bytes_per_partition,
            dict(self.properties),
        )
        rebuilt.write(ctx, batch)
        return rebuilt

    def _config(self) -> "ZOrderCoveringIndexConfig":
        return ZOrderCoveringIndexConfig(
            "__refresh__", self._indexed_columns, self._included_columns
        )

    def statistics(self, extended: bool = False) -> Dict[str, str]:
        return {
            "indexedColumns": ",".join(self._indexed_columns),
            "includedColumns": ",".join(self._included_columns),
            "targetBytesPerPartition": str(self.target_bytes_per_partition),
            "schema": self.schema_json if extended else "",
        }


def _write_zordered(
    ctx, data, indexed_cols: List[str], target_bytes: int
) -> List[str]:
    """Global z-sort then split into ~equal files sized to hit the target
    partition bytes. ``data`` is a ColumnarBatch or (for datasets beyond
    the build memory budget) a lazy SourceScan streamed in two passes."""
    import os

    from hyperspace_tpu.indexes.covering_build import CompositeScan, SourceScan
    from hyperspace_tpu.ops.zorder import z_order_permutation

    os.makedirs(ctx.index_data_path, exist_ok=True)
    if isinstance(data, (SourceScan, CompositeScan)):
        return _write_zordered_streaming(
            ctx, data, indexed_cols, target_bytes
        )
    batch = data
    if batch.num_rows == 0:
        return []
    conf = ctx.session.conf
    perm = z_order_permutation(
        [batch.column(c) for c in indexed_cols],
        quantile=conf.zorder_quantile_enabled,
        relative_error=conf.zorder_quantile_relative_error,
    )
    table = batch.take(perm).to_arrow()
    nbytes = max(table.nbytes, 1)
    num_parts = max(1, math.ceil(nbytes / target_bytes))
    rows_per_part = math.ceil(table.num_rows / num_parts)
    written = []
    for i in range(num_parts):
        chunk = table.slice(i * rows_per_part, rows_per_part)
        if chunk.num_rows == 0:
            continue
        path = os.path.join(ctx.index_data_path, f"part-{i:05d}-zorder.parquet")
        pio.write_table(path, chunk)
        written.append(path)
    return written


# range-partition count for the streamed z-order spill: top bits of the
# most-significant z-address plane (64 contiguous z-ranges; peak merge
# memory ~= total/64 for a balanced address space)
_ZORDER_SPILL_BITS = 6


def _write_zordered_streaming(
    ctx, scan, indexed_cols: List[str], target_bytes: int
) -> List[str]:
    """The >memory-budget z-order build (two passes over the waves):

    1. **Stats pass** (indexed columns only): accumulate each column's
       order-encodings — global min/max, plus a bounded stride sample
       when quantile encoding is on — and FREEZE the encoding spec
       (``ZOrderEncoder``). A fixed spec makes z-addresses identical in
       every later step, so local order == global order.
    2. **Spill pass**: per wave, compute z-address planes under the
       frozen spec and spill rows into 2^_ZORDER_SPILL_BITS contiguous
       z-RANGES (top bits of the most significant plane) — the streamed
       equivalent of the reference's ``repartitionByRange`` on ``_zaddr``
       (ZOrderCoveringIndex.scala:139-153).
    3. **Merge**: per range in ascending order, re-encode + lexsort (a
       range holds ~1/64 of the data) and write size-targeted files.
    """
    import os
    import shutil

    from hyperspace_tpu.indexes.covering_build import plan_waves
    from hyperspace_tpu.io.columnar import ColumnarBatch
    from hyperspace_tpu.ops.sort import lexsort_perm
    from hyperspace_tpu.ops.zorder import ZOrderEncoder, order_u64_np

    conf = ctx.session.conf
    budget = conf.build_memory_budget or (1 << 62)
    quantile = conf.zorder_quantile_enabled
    rel_err = conf.zorder_quantile_relative_error
    waves = plan_waves(scan.files, scan.fmt, budget, scan.file_sizes)

    # pass 1: frozen encoding spec from a stats-only scan
    stats_scan = scan.stats_view(indexed_cols)
    k = len(indexed_cols)
    mins = [None] * k
    maxs = [None] * k
    samples: List[List] = [[] for _ in range(k)]
    dicts: List = [None] * k  # string columns: global dictionary union
    max_sample = max(int(1.0 / max(rel_err, 1e-4) ** 2), 1024)
    per_wave = max(max_sample // max(len(waves), 1), 64)
    for w in waves:
        b = stats_scan.materialize(w)
        for j, c in enumerate(indexed_cols):
            col = b.column(c)
            if col.kind == "string":
                # batch-local dictionary ranks are NOT stable across
                # waves; freeze a GLOBAL dictionary instead
                if dicts[j] is None:
                    dicts[j] = set()
                dicts[j].update(col.dictionary)
                continue
            e = order_u64_np(col)
            if not len(e):
                continue
            lo, hi = e.min(), e.max()
            mins[j] = lo if mins[j] is None else min(mins[j], lo)
            maxs[j] = hi if maxs[j] is None else max(maxs[j], hi)
            if quantile:
                samples[j].append(e[:: max(1, len(e) // per_wave)])
    specs = []
    for j in range(k):
        if dicts[j] is not None:
            specs.append(("dict", sorted(dicts[j])))
        elif quantile:
            s = (
                np.sort(np.concatenate(samples[j]))
                if samples[j]
                else np.zeros(1, dtype=np.uint64)
            )
            specs.append(("quantile", s))
        else:
            specs.append(
                (
                    "range",
                    mins[j] if mins[j] is not None else np.uint64(0),
                    maxs[j] if maxs[j] is not None else np.uint64(0),
                )
            )
    encoder = ZOrderEncoder(16, specs)

    # pass 2: spill into contiguous z-ranges
    spill_root = os.path.join(
        os.path.dirname(ctx.index_data_path),
        "_spill_z_" + os.path.basename(ctx.index_data_path).replace("=", "_"),
    )
    os.makedirs(spill_root, exist_ok=True)
    range_parts: dict = {}
    try:
        import pyarrow as pa

        for wi, w in enumerate(waves):
            batch = scan.materialize(w)
            if batch.num_rows == 0:
                continue
            planes = encoder.planes(
                [batch.column(c) for c in indexed_cols]
            )
            pid = (planes[0] >> np.uint32(32 - _ZORDER_SPILL_BITS)).astype(
                np.int32
            )
            table = batch.to_arrow()
            for p, idx in pio.bucket_runs(pid):
                path = os.path.join(spill_root, f"r{p:03d}-w{wi:05d}.parquet")
                pio.write_table(path, table.take(pa.array(idx)))
                range_parts.setdefault(p, []).append(path)

        # merge: per z-range ascending, local sort == global order.
        # A skewed/constant key can funnel most rows into ONE range;
        # oversized ranges split recursively on deeper z-address bits —
        # through the remaining windows of plane 0, then every deeper
        # plane — and only when EVERY bit of every plane is exhausted
        # (all rows share one complete z-address, whose relative order is
        # semantically arbitrary) is each part sorted and written
        # individually. Peak memory stays bounded either way.
        from hyperspace_tpu.indexes.covering_build import (
            estimated_materialized_bytes,
        )

        total_bits = len(indexed_cols) * encoder.bits
        n_planes = max(1, (total_bits + 31) // 32)

        def plane_floor(plane_idx):
            """Lowest MEANINGFUL bit of a plane: the last plane's tail
            below 32 - (total_bits mod 32) is zero padding — descending
            into it would read and rewrite oversized groups without
            discriminating anything."""
            if plane_idx == n_planes - 1:
                rem = total_bits - 32 * (n_planes - 1)
                return 32 - rem
            return 0

        written: List[str] = []
        state = {"file_idx": 0}

        def write_sorted(table):
            nbytes = max(table.nbytes, 1)
            num_parts = max(1, math.ceil(nbytes / target_bytes))
            rows_per_part = math.ceil(table.num_rows / num_parts)
            for i in range(num_parts):
                chunk = table.slice(i * rows_per_part, rows_per_part)
                if chunk.num_rows == 0:
                    continue
                path = os.path.join(
                    ctx.index_data_path,
                    f"part-{state['file_idx']:05d}-zorder.parquet",
                )
                pio.write_table(path, chunk)
                written.append(path)
                state["file_idx"] += 1

        def sort_batch(batch):
            perm = lexsort_perm(
                encoder.planes([batch.column(c) for c in indexed_cols])
            )
            return batch.take(perm).to_arrow()

        def next_window(plane_idx, shift):
            """The split window after (plane_idx, shift): slide down the
            current plane (clamping the last window to the plane's floor
            so the lowest meaningful bits still discriminate), then
            advance to the next plane."""
            floor = plane_floor(plane_idx)
            if shift > floor:
                return plane_idx, max(shift - _ZORDER_SPILL_BITS, floor)
            nxt = plane_idx + 1
            return nxt, max(
                32 - _ZORDER_SPILL_BITS,
                plane_floor(nxt) if nxt < n_planes else 0,
            )

        def merge_parts(parts, plane_idx, shift):
            est = estimated_materialized_bytes(parts, "parquet")
            if est <= budget or plane_idx >= n_planes:
                if plane_idx >= n_planes and est > budget:
                    # every z-address bit is exhausted: rows share one
                    # complete z-address, whose relative order is
                    # arbitrary — sort parts independently
                    for part in parts:
                        write_sorted(
                            sort_batch(
                                ColumnarBatch.from_arrow(
                                    pio.read_table([part], None)
                                )
                            )
                        )
                    return
                write_sorted(
                    sort_batch(
                        ColumnarBatch.from_arrow(pio.read_table(parts, None))
                    )
                )
                return
            # split on the window's _ZORDER_SPILL_BITS bits of this plane
            sub_parts: dict = {}
            nxt = next_window(plane_idx, shift)
            for part in parts:
                b = ColumnarBatch.from_arrow(pio.read_table([part], None))
                plane = encoder.planes(
                    [b.column(c) for c in indexed_cols]
                )[plane_idx]
                sub = ((plane >> np.uint32(shift))
                       & np.uint32((1 << _ZORDER_SPILL_BITS) - 1)).astype(
                    np.int32
                )
                table = b.to_arrow()
                for sp, idx in pio.bucket_runs(sub):
                    path = part + f".s{sp:03d}"
                    pio.write_table(path, table.take(pa.array(idx)))
                    sub_parts.setdefault(sp, []).append(path)
            for sp in sorted(sub_parts):
                merge_parts(sub_parts[sp], *nxt)

        for p in sorted(range_parts):
            merge_parts(
                range_parts[p], 0, 32 - 2 * _ZORDER_SPILL_BITS
            )
        return written
    finally:
        shutil.rmtree(spill_root, ignore_errors=True)


class ZOrderCoveringIndexConfig(IndexConfigTrait):
    """name + indexedColumns + includedColumns
    (ZOrderCoveringIndexConfig.scala)."""

    def __init__(
        self,
        index_name: str,
        indexed_columns: List[str],
        included_columns: Optional[List[str]] = None,
    ):
        if not index_name:
            raise HyperspaceException("Index name cannot be empty")
        if not indexed_columns:
            raise HyperspaceException("indexed_columns cannot be empty")
        self._name = index_name
        self._indexed = list(indexed_columns)
        self._included = list(included_columns or [])

    @property
    def index_name(self) -> str:
        return self._name

    @property
    def indexed_columns(self) -> List[str]:
        return list(self._indexed)

    @property
    def included_columns(self) -> List[str]:
        return list(self._included)

    @property
    def referenced_columns(self) -> List[str]:
        return self._indexed + self._included

    def _target_bytes(self, ctx) -> int:
        return ctx.session.conf.zorder_target_source_bytes_per_partition

    def create_index(self, ctx, source_data, properties: Dict[str, str]):
        from hyperspace_tpu.indexes import covering_build

        covering, batch = covering_build.create_covering_index(
            ctx, source_data, self, properties
        )
        # a SourceScan (dataset beyond the memory budget) flows straight
        # into write(): the streamed two-pass z-order build handles it
        index = ZOrderCoveringIndex(
            covering.indexed_columns,
            covering.included_columns,
            covering.schema_json,
            self._target_bytes(ctx),
            dict(properties),
        )
        return index, batch

    def describe_index(self, ctx, source_data, properties: Dict[str, str]):
        from hyperspace_tpu.indexes import covering_build

        covering = covering_build.describe_covering_index(
            ctx, source_data, self, properties
        )
        return ZOrderCoveringIndex(
            covering.indexed_columns,
            covering.included_columns,
            covering.schema_json,
            self._target_bytes(ctx),
            dict(properties),
        )
