"""IndexerContext — everything an index build step needs.

Reference: ``index/IndexerContext.scala:25-43`` (spark session, shared
FileIdTracker, index data path). Ours adds the device mesh (the session's
runtime) since the build pipeline runs on it.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from hyperspace_tpu.metadata.entry import FileIdTracker


@dataclasses.dataclass
class IndexerContext:
    session: object
    file_id_tracker: FileIdTracker
    index_data_path: str

    @property
    def mesh(self):
        return self.session.runtime.mesh
