"""IndexerContext — everything an index build step needs.

Reference: ``index/IndexerContext.scala:25-43`` (spark session, shared
FileIdTracker, index data path). Ours adds the device mesh (the session's
runtime) since the build pipeline runs on it.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from hyperspace_tpu.metadata.entry import FileIdTracker


@dataclasses.dataclass
class IndexerContext:
    session: object
    file_id_tracker: FileIdTracker
    index_data_path: str
    _build_mesh: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    @property
    def mesh(self):
        """The build-plane mesh: the session mesh, capped to the first
        ``hyperspace.build.numShards`` devices when that conf is set
        (0 = all). Memoized per context so one action's pipeline stages
        all see the same mesh object."""
        if self._build_mesh is None:
            mesh = self.session.runtime.mesh
            n = self.session.conf.build_num_shards
            if 0 < n < mesh.devices.size:
                from hyperspace_tpu.parallel.mesh import default_mesh

                mesh = default_mesh(list(mesh.devices.flat)[:n])
            self._build_mesh = mesh
        return self._build_mesh
