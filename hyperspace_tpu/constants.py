"""Config keys, index states and reserved property names.

Reference: ``index/IndexConstants.scala:21-170`` and
``actions/Constants.scala:20-34``. Keys drop the ``spark.`` prefix — this
framework owns its own config system (see :mod:`hyperspace_tpu.config`).

Contract (machine-checked by hslint HS7xx, ``analysis/contracts.py``):
every ``hyperspace.*`` key the package reads has its ``<NAME>_DEFAULT``
sibling here and a row in ``docs/CONFIG.md``; keys nothing reads are
flagged as dead.
"""

import os

# ---------------------------------------------------------------------------
# Index lifecycle states (actions/Constants.scala:20-34)
# ---------------------------------------------------------------------------


class States:
    DOESNOTEXIST = "DOESNOTEXIST"
    CREATING = "CREATING"
    ACTIVE = "ACTIVE"
    REFRESHING = "REFRESHING"
    OPTIMIZING = "OPTIMIZING"
    DELETING = "DELETING"
    DELETED = "DELETED"
    RESTORING = "RESTORING"
    VACUUMING = "VACUUMING"
    VACUUMINGOUTDATED = "VACUUMINGOUTDATED"

    STABLE_STATES = frozenset({ACTIVE, DELETED, DOESNOTEXIST})

    # transient state -> stable state it rolls back to on cancel()
    ROLLBACK = {
        CREATING: DOESNOTEXIST,
        REFRESHING: ACTIVE,
        OPTIMIZING: ACTIVE,
        VACUUMINGOUTDATED: ACTIVE,
        DELETING: ACTIVE,
        RESTORING: DELETED,
        VACUUMING: DELETED,
    }


# ---------------------------------------------------------------------------
# Config keys (index/IndexConstants.scala) — flat string keys
# ---------------------------------------------------------------------------

HYPERSPACE_APPLY_ENABLED = "hyperspace.apply.enabled"
HYPERSPACE_APPLY_ENABLED_DEFAULT = True

INDEX_SYSTEM_PATH = "hyperspace.system.path"
# PathResolver.scala's <warehouse>/indexes, anchored at the user's home
# (no Spark warehouse here); metadata/path_resolver.py reads through this
INDEX_SYSTEM_PATH_DEFAULT = os.path.join(
    os.path.expanduser("~"), "hyperspace", "indexes"
)

INDEX_NUM_BUCKETS = "hyperspace.index.num_buckets"
INDEX_NUM_BUCKETS_DEFAULT = 200  # IndexConstants.scala:33-36 (= shuffle partitions)

INDEX_LINEAGE_ENABLED = "hyperspace.index.lineage.enabled"
INDEX_LINEAGE_ENABLED_DEFAULT = False  # IndexConstants.scala:105-106

INDEX_HYBRID_SCAN_ENABLED = "hyperspace.index.hybridscan.enabled"
INDEX_HYBRID_SCAN_ENABLED_DEFAULT = False
INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO = "hyperspace.index.hybridscan.maxAppendedRatio"
INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO_DEFAULT = 0.3  # IndexConstants.scala:44-52
INDEX_HYBRID_SCAN_MAX_DELETED_RATIO = "hyperspace.index.hybridscan.maxDeletedRatio"
INDEX_HYBRID_SCAN_MAX_DELETED_RATIO_DEFAULT = 0.2

INDEX_FILTER_RULE_USE_BUCKET_SPEC = "hyperspace.index.filterRule.useBucketSpec"
INDEX_FILTER_RULE_USE_BUCKET_SPEC_DEFAULT = False  # IndexConstants.scala:56-57

OPTIMIZE_FILE_SIZE_THRESHOLD = "hyperspace.index.optimize.fileSizeThreshold"
OPTIMIZE_FILE_SIZE_THRESHOLD_DEFAULT = 256 * 1024 * 1024  # 256MB, :116-117
OPTIMIZE_MODE_QUICK = "quick"
OPTIMIZE_MODE_FULL = "full"
OPTIMIZE_MODES = (OPTIMIZE_MODE_QUICK, OPTIMIZE_MODE_FULL)

REFRESH_MODE_FULL = "full"
REFRESH_MODE_INCREMENTAL = "incremental"
REFRESH_MODE_QUICK = "quick"
REFRESH_MODES = (REFRESH_MODE_FULL, REFRESH_MODE_INCREMENTAL, REFRESH_MODE_QUICK)

INDEX_CACHE_EXPIRY_SECONDS = "hyperspace.index.cache.expiryDurationInSeconds"
INDEX_CACHE_EXPIRY_SECONDS_DEFAULT = 300  # CachingIndexCollectionManager.scala

INDEX_SOURCES_PROVIDERS = "hyperspace.index.sources.fileBasedBuilders"
INDEX_SOURCES_PROVIDERS_DEFAULT = (
    "hyperspace_tpu.sources.default.DefaultFileBasedSourceBuilder,"
    "hyperspace_tpu.sources.delta.DeltaLakeSourceBuilder,"
    "hyperspace_tpu.sources.iceberg.IcebergSourceBuilder"
)

DEFAULT_SUPPORTED_FORMATS = "hyperspace.index.sources.defaultSupportedFormats"
# reference default: DefaultFileBasedSource.scala:76-85
DEFAULT_SUPPORTED_FORMATS_DEFAULT = "avro,csv,json,orc,parquet,text"

# Observability: when set, every session.execute runs under an XLA
# profiler trace written to this directory (TensorBoard/Perfetto format).
# SURVEY §5 calls for profiler integration on top of the typed event bus.
PROFILE_TRACE_DIR = "hyperspace.profile.traceDir"
PROFILE_TRACE_DIR_DEFAULT = ""

# Explain rendering (DisplayMode.scala: plaintext / console / html)
EXPLAIN_DISPLAY_MODE = "hyperspace.explain.displayMode"
EXPLAIN_DISPLAY_MODE_DEFAULT = "plaintext"

# Streaming build: cap the bytes materialized per wave of the covering
# index build (0 = unbounded, one in-memory pass). The reference gets
# disk-backed spill for free from Spark's shuffle
# (covering/CoveringIndex.scala:58-61 repartition); our wave loop lives in
# indexes/covering_build.py.
INDEX_BUILD_MEMORY_BUDGET = "hyperspace.index.build.memoryBudgetBytes"
INDEX_BUILD_MEMORY_BUDGET_DEFAULT = 0

# Partition-first build sort: counting-scatter rows into per-bucket runs
# first, then key-sort each bucket independently (working set ≈
# rows/num_buckets) instead of one global lexsort by (bucket, keys) —
# bit-identical output, fixes the 64M-row sort collapse (BASELINE.md:
# permutation gathers walking a 512MB working set, TLB-bound). Off =
# the legacy global lexsort, kept as a differential-test reference and
# escape hatch.
INDEX_BUILD_PARTITION_FIRST = "hyperspace.index.build.partitionFirst"
INDEX_BUILD_PARTITION_FIRST_DEFAULT = True

# Sharded build/serve tail (docs/MULTIHOST.md): on a >1-device mesh,
# bucket ownership stays device-local past the exchange — each shard's
# bucket range runs its own partition-first sort + bucketed parquet
# write (build) and its own prepare + merge-join (serve) concurrently,
# with a cheap per-bucket union at the edge, instead of serializing the
# post-exchange tail through one global permutation on the host. Every
# bucket file and every join row is bit-identical either way (a bucket
# lives wholly on one shard); the flag restores the old single-tail
# path for A/B timing and as an escape hatch. No effect on a 1-device
# mesh.
BUILD_SHARDED_TAIL_ENABLED = "hyperspace.build.shardedTail.enabled"
BUILD_SHARDED_TAIL_ENABLED_DEFAULT = True

# Exchange-strategy plane (parallel/shuffle.py, docs/MULTIHOST.md): the
# build's bucket shuffle is a library of pluggable strategies behind one
# interface — "auto" resolves per topology (multi-process job ->
# "twostage" DCN/ICI decomposition; CPU mesh -> "host" pure-RAM reorder,
# the simulation must never pay ICI-emulation costs; single-host
# accelerator -> "compact" when the calibration probe measured it
# beating "flat" at the build size, else "flat", the padded-[D, cap]
# all_to_all baseline). Every strategy is differential-tested
# bit-identical to "flat".
BUILD_EXCHANGE_STRATEGY = "hyperspace.build.exchange.strategy"
BUILD_EXCHANGE_STRATEGY_DEFAULT = "auto"

# Simulated host count for the twostage strategy on a SINGLE-process
# mesh (tests / A-B runs carve the flat mesh into this many groups of
# contiguous devices); 0 = derive from jax.process_count(). A real
# multi-process job always uses the process count.
BUILD_EXCHANGE_TWOSTAGE_HOSTS = "hyperspace.build.exchange.twostageHosts"
BUILD_EXCHANGE_TWOSTAGE_HOSTS_DEFAULT = 0

# Warn when the bucket shuffle's per-(shard, peer) send-count skew
# (max/mean) exceeds this: the exchange pads every slot to the max
# count, so one hot bucket silently inflates exchange memory by ~skew×.
# Tiny builds skip the warning (below the row floor the padded buffers
# are KBs — the ratio is always noisy there); telemetry records the
# ratio regardless.
BUILD_SHUFFLE_SKEW_WARN_RATIO = 4.0
BUILD_SHUFFLE_SKEW_WARN_MIN_ROWS = 1 << 12

# Z-order (IndexConstants.scala:59-74)
ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION = (
    "hyperspace.index.zorder.targetSourceBytesPerPartition"
)
ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION_DEFAULT = 1024 * 1024 * 1024
ZORDER_QUANTILE_ENABLED = "hyperspace.index.zorder.quantile.enabled"
ZORDER_QUANTILE_ENABLED_DEFAULT = False
ZORDER_QUANTILE_RELATIVE_ERROR = "hyperspace.index.zorder.quantile.relativeError"
ZORDER_QUANTILE_RELATIVE_ERROR_DEFAULT = 0.01

# Data-skipping (IndexConstants.scala:149-169)
DATASKIPPING_TARGET_INDEX_DATA_FILE_SIZE = (
    "hyperspace.index.dataskipping.targetIndexDataFileSize"
)
DATASKIPPING_TARGET_INDEX_DATA_FILE_SIZE_DEFAULT = 256 * 1024 * 1024
DATASKIPPING_AUTO_PARTITION_SKETCH = (
    "hyperspace.index.dataskipping.autoPartitionSketch"
)
DATASKIPPING_AUTO_PARTITION_SKETCH_DEFAULT = True

EVENT_LOGGER_CLASS = "hyperspace.eventLoggerClass"
EVENT_LOGGER_CLASS_DEFAULT = ""  # empty = the no-op EventLogger

# Number of device shards used for the build plane; 0 = all devices in
# the session mesh. A positive value caps the build mesh to the first N
# devices (A/B scaling runs; pinning a build off busy serve chips).
BUILD_NUM_SHARDS = "hyperspace.build.numShards"
BUILD_NUM_SHARDS_DEFAULT = 0

# ---------------------------------------------------------------------------
# Reserved column / property names
# ---------------------------------------------------------------------------

# Lineage column (IndexConstants: DATA_FILE_NAME_ID = "_data_file_id")
DATA_FILE_NAME_ID = "_data_file_id"

# Index log directory + data-version prefix (IndexDataManager.scala:24-37)
HYPERSPACE_LOG_DIR = "_hyperspace_log"
INDEX_VERSION_DIR_PREFIX = "v__"
LATEST_STABLE_LOG_NAME = "latestStable"

# IndexLogEntry property keys
LINEAGE_PROPERTY = "lineage"
HAS_PARQUET_AS_SOURCE_FORMAT_PROPERTY = "hasParquetAsSourceFormat"
DELTA_VERSION_HISTORY_PROPERTY = "deltaVersions"

# Nested-column prefix (util/ResolverUtils.scala `__hs_nested.`)
NESTED_FIELD_PREFIX = "__hs_nested."

# Nested (struct) field indexing is opt-in, as in the reference
# (conf.supportNestedFields gate, actions/CreateAction.scala:69-71;
# flattened-name machinery in util/ResolverUtils.scala:130-234).
INDEX_SUPPORT_NESTED_FIELDS = "hyperspace.index.supportNestedFields"
INDEX_SUPPORT_NESTED_FIELDS_DEFAULT = False

# Filenames written by the index data plane.
INDEX_FILE_PREFIX = "part"

# -- execution tuning --------------------------------------------------------
# Predicate evaluation dispatches to the XLA kernel only at/above this
# row count. Serve-path batches come out of host parquet reads, so the
# mask pays host->device transfer + readback before any compute —
# measured ~100ms for a 500k-row bucket through the tunnel vs ~2ms of
# host numpy. Data already resident in HBM (mesh-sharded serve) is a
# different regime; lower this to force the device kernel.
EXECUTION_DEVICE_FILTER_MIN_ROWS = "hyperspace.execution.deviceFilterMinRows"
EXECUTION_DEVICE_FILTER_MIN_ROWS_DEFAULT = 8_000_000

# Single-device join matching runs on host by default (measured ~10x
# faster than the device sort+transfer round trip on one chip; a >1-device
# mesh always uses the sharded device program). Set a positive row count
# to force the device program on a single device once total rows reach it.
EXECUTION_DEVICE_JOIN_MIN_ROWS = "hyperspace.execution.deviceJoinMinRows"
EXECUTION_DEVICE_JOIN_MIN_ROWS_DEFAULT = 0  # 0 = never on single device

# -- aggregate index plane (indexes/aggindex.py, docs/agg-serve.md) ----------
# Master switch for the aggregate/approximate index plane: build-time
# capture of per-row-group partial-aggregate state into an
# ``_aggstate.json`` sidecar (+ the ``_aggsample.parquet`` stratified
# row sample), the serve-side metadata lowering that answers fully-
# covered Filter(→Project)→Aggregate plans from those partials without
# opening a single parquet file, and the AggregateIndexRule rewrite of
# bare Aggregate∘Scan plans onto a covering index. Off = the pre-plane
# behavior everywhere (no capture, no metadata lowering, no rewrite).
INDEX_AGG_ENABLED = "hyperspace.index.agg.enabled"
INDEX_AGG_ENABLED_DEFAULT = True

# Grouped-partial capture cap: per row group, single-column grouped
# partials are captured only for (fusable) columns whose distinct-value
# count in that row group stays at/below this. Row groups over the cap
# simply have no grouped entry for that key and fall back to the fused
# scan at serve time — a cap, never a correctness knob.
INDEX_AGG_MAX_GROUPS = "hyperspace.index.agg.maxGroupsPerRowGroup"
INDEX_AGG_MAX_GROUPS_DEFAULT = 256

# Stratified-sample size: rows sampled per row group (without
# replacement, seeded by (file, row group) so capture and lazy backfill
# produce the same sample) into the ``_aggsample.parquet`` sidecar that
# serves the approximate plane. 0 disables sampling at capture.
INDEX_AGG_SAMPLE_ROWS = "hyperspace.index.agg.sampleRowsPerGroup"
INDEX_AGG_SAMPLE_ROWS_DEFAULT = 128

# Approximate serving (execution/approx_exec.py): explicit opt-in for
# sample-based COUNT/SUM estimates with 95% confidence intervals via
# ``DataFrame.collect_approx()``. NEVER substituted for exact answers —
# the exact serve path ignores samples entirely; with the flag off,
# ``collect_approx`` raises instead of estimating.
SERVE_APPROX_ENABLED = "hyperspace.serve.approx.enabled"
SERVE_APPROX_ENABLED_DEFAULT = False

# Per-query error budget: the widest acceptable 95%-CI half-width
# relative to the estimate's magnitude. Estimates whose interval blows
# the budget raise ApproximationError (run exact instead) rather than
# returning a number the caller would over-trust. Overridable per query
# via ``collect_approx(max_rel_error=...)``.
SERVE_APPROX_MAX_REL_ERROR = "hyperspace.serve.approx.maxRelativeError"
SERVE_APPROX_MAX_REL_ERROR_DEFAULT = 0.05

# -- serve-server mode (execution/serve_cache.py) ----------------------------
# Opt-in cache of decoded index data (batches, prepared join sides) in
# host RAM, keyed by the immutable index file set — the data-plane
# extension of the reference's metadata TTL cache
# (CachingIndexCollectionManager.scala:38-108). First touch decodes and
# retains; later queries skip parquet entirely. LRU-evicted by bytes.
SERVE_CACHE_ENABLED = "hyperspace.serve.cache.enabled"
SERVE_CACHE_ENABLED_DEFAULT = False
SERVE_CACHE_MAX_BYTES = "hyperspace.serve.cache.maxBytes"
SERVE_CACHE_MAX_BYTES_DEFAULT = 4 << 30  # 4 GiB

# -- out-of-core serve (docs/out-of-core.md) ---------------------------------
# Streaming per-bucket join serve: prepared join sides are produced,
# matched, expanded and released wave-by-wave instead of materializing
# both whole prepared sides, so peak residency is one wave's buckets
# (<= stream.maxBytes estimated) rather than the relation. Bit-identical
# to the materializing path (differential-tested); the flag exists for
# A/B timing and as an escape hatch.
SERVE_STREAM_ENABLED = "hyperspace.serve.stream.enabled"
SERVE_STREAM_ENABLED_DEFAULT = False

# Wave budget for the streaming join path: the estimated decoded bytes
# of prepared buckets held in flight at once. Estimates come from
# parquet footer row counts x projected columns; waves are planned so
# their estimate stays under this cap (a single oversized bucket still
# runs alone — the bucket is the atom of residency).
SERVE_STREAM_MAX_BYTES = "hyperspace.serve.stream.maxBytes"
SERVE_STREAM_MAX_BYTES_DEFAULT = 256 << 20  # 256 MiB

# Spill tier for the ServeCache (execution/serve_cache.py): evicted
# prepared sides / decoded scans are demoted to fsync'd files under
# <system.path>/_hyperspace_spill/ (atomic publish per utils/files.py)
# and restored zero-copy (mmap + pickle5 out-of-band buffers) on the
# next miss, instead of being re-derived from parquet. 0 = off (evict
# to oblivion, the pre-spill behavior). The byte cap bounds the on-disk
# tier; oldest spill files are deleted when it overflows.
SERVE_SPILL_MAX_BYTES = "hyperspace.serve.spill.maxBytes"
SERVE_SPILL_MAX_BYTES_DEFAULT = 0

# Lease age for orphaned spill files: recovery's spill reaper
# (metadata/recovery.py reap_spill_orphans) deletes spill files and
# torn .tmp_spool_ temps whose mtime is older than this and that no
# live ServeCache in this process claims. Crashed serve processes leak
# spill files; the reaper is what makes the tier derived state, not
# durable state.
SERVE_SPILL_ORPHAN_TTL_MS = "hyperspace.serve.spill.orphanTtlMs"
SERVE_SPILL_ORPHAN_TTL_MS_DEFAULT = 10 * 60 * 1000  # 10 minutes

# Memory-mapped Arrow/parquet reads (io/parquet.py): pass
# memory_map=True into pyarrow readers so file bytes enter as kernel
# page-cache mappings. Parquet decode still copies (decompression), so
# this mainly helps uncompressed/IPC payloads; the honest-accounting
# half lives in serve_cache.estimate_nbytes, which charges mmap-backed
# buffers as file-backed (near-zero resident).
IO_MMAP_ENABLED = "hyperspace.io.mmap.enabled"
IO_MMAP_ENABLED_DEFAULT = False

# Range serve plane (executor._range_pruned_scan + indexes/zonemaps.py,
# see docs/range-serve.md): zone-map pruning of index files and row
# groups under range/Eq/In conjuncts, z-address range decomposition for
# z-order relations, and the fused hs_range_mask residual kernel.
# Superset-safe by construction (pruned-scan ≡ full-scan+mask,
# differential-tested); the flag restores the unpruned path bit-
# identically for A/B timing and as an escape hatch.
SERVE_RANGEPRUNE_ENABLED = "hyperspace.serve.rangeprune.enabled"
SERVE_RANGEPRUNE_ENABLED_DEFAULT = True

# Pipelined serve path (execution/executor.py + join_exec.py, see
# docs/serve-pipeline.md): on a co-bucketed join over clean index-scan
# shapes, the two sides prepare concurrently, per-bucket parquet reads
# overlap per-bucket prepare (reps/combine/sortedness), and the
# hybrid-scan appended-files delta is prepared off the critical path.
# Results are bit-identical to the sequential path (differential-tested);
# the flag exists for A/B timing and as an escape hatch.
SERVE_PIPELINE_ENABLED = "hyperspace.serve.pipeline.enabled"
SERVE_PIPELINE_ENABLED_DEFAULT = True

# Fused serve-pipeline compiler (execution/pipeline_compiler.py, see
# docs/serve-compiler.md): a Filter→Project→Aggregate (or plain
# Filter→Project) subtree over a pruned index scan is lowered into one
# fused native pass per surviving row-group chunk — predicate, projection
# and partial COUNT/SUM/MIN/MAX (grouped or not) in a single sweep, no
# materialized mask/gather/filtered-batch intermediates, partials merged
# at the edge. Bit-identical to the interpreted chain (differential-
# tested); the flag restores the old op-at-a-time path for A/B timing
# and as an escape hatch.
SERVE_FUSEDPIPELINE_ENABLED = "hyperspace.serve.fusedpipeline.enabled"
SERVE_FUSEDPIPELINE_ENABLED_DEFAULT = True

# FALLBACK default for the fused-pipeline dispatch crossover: at/above
# this many scanned rows the fused native pass runs; below it the
# interpreted chain (numpy twins) wins on kernel-call overhead. The
# effective value comes from the per-machine calibration probe
# (native/calibrate.py, native_fused_pipeline_min_rows); this constant
# is the probe-failure fallback, like every other dispatch threshold.
NATIVE_FUSED_PIPELINE_MIN_ROWS_DEFAULT = 1 << 15

# -- concurrent serve frontend (hyperspace_tpu/serve/) -----------------------
# Worker threads answering queries concurrently. 0 = auto: min(32,
# 4 x cores) — serve work is read-dominated (parquet/Arrow release the
# GIL), so oversubscribing cores keeps the scan pool fed while masks/
# merges run.
SERVE_MAX_CONCURRENCY = "hyperspace.serve.maxConcurrency"
SERVE_MAX_CONCURRENCY_DEFAULT = 0

# Admission control: queries queued (admitted but not yet running)
# beyond this bound are shed with a typed ServeOverloadedError instead
# of growing an unbounded backlog whose tail latency is unbounded too.
# 0 = unbounded (benchmark/batch use).
SERVE_MAX_QUEUE_DEPTH = "hyperspace.serve.maxQueueDepth"
SERVE_MAX_QUEUE_DEPTH_DEFAULT = 128

# Retry-with-backoff for TRANSIENT failures at the serve operation
# boundary (Exoshuffle doctrine: fault handling lives in the
# application-level dataflow, not under it): maxAttempts total tries,
# exponential backoff starting at backoffMs. Each retry re-pins the
# index snapshot, so a vacuum that removed the pinned version's files
# mid-query recovers onto the current version.
SERVE_RETRY_MAX_ATTEMPTS = "hyperspace.serve.retry.maxAttempts"
SERVE_RETRY_MAX_ATTEMPTS_DEFAULT = 3
SERVE_RETRY_BACKOFF_MS = "hyperspace.serve.retry.backoffMs"
SERVE_RETRY_BACKOFF_MS_DEFAULT = 10

# Fault injection (hyperspace_tpu/testing/faults.py): config keys
# ``hyperspace.faults.<point>`` name an injection point with a spec like
# "transient", "transient:3", "persistent", or "persistent;match=v__="
# (match = only paths containing the substring fault). Points:
# parquet_read, kernel_dispatch, log_read, cache_insert. The keys are
# READ only by an explicit ``faults.configure(session.conf)`` call (an
# operator/test act — production never arms itself); the serve plane's
# retry/degrade behavior under armed faults is the tested contract
# (docs/serve-server.md fault matrix).
FAULTS_KEY_PREFIX = "hyperspace.faults."

# Crash injection (same module): ``hyperspace.faults.crash.<point>``
# with a spec "raise[;at=N][;match=substr]" (in-process SimulatedCrash)
# or "exit[...]" (os._exit mid-protocol — true torn state). Points:
# after_begin_log, mid_data_write, after_data_write, after_end_log,
# mid_vacuum_delete. The crash × action recovery matrix is the tested
# contract (docs/recovery.md, tests/test_crash_recovery.py).
CRASH_KEY_PREFIX = "hyperspace.faults.crash."

# -- crash-safe lifecycle recovery (metadata/recovery.py) --------------------
# Master switch for the recovery plane: writer leases stamped into
# transient log entries, stranded-entry rollback at action start /
# session attach, stale latestStable healing, and the OCC retry loop in
# Action.run. Off = the pre-recovery behavior (a crashed writer strands
# the index until a manual cancel()).
RECOVERY_ENABLED = "hyperspace.recovery.enabled"
RECOVERY_ENABLED_DEFAULT = True

# Writer lease duration. A live action's heartbeat re-stamps its
# transient entry every leaseMs/3; an entry whose lease expired belongs
# to a DEAD writer (crash) and may be rolled back — this is what makes a
# slow writer distinguishable from a dead one. Entries written before
# the lease era (no lease properties) fall back to entry.timestamp +
# leaseMs.
RECOVERY_LEASE_MS = "hyperspace.recovery.leaseMs"
RECOVERY_LEASE_MS_DEFAULT = 60_000

# Orphan GC quarantine TTL: index data files referenced by no stable log
# entry are first MOVED into <index>/_hyperspace_quarantine/<stamp>/ and
# only deleted once the stamp is older than this grace period — so a
# serve that pinned its snapshot before the files went unreferenced
# finishes from the quarantine-free window (in-process pins are excluded
# from quarantine outright; the TTL covers other processes).
RECOVERY_ORPHAN_GRACE_MS = "hyperspace.recovery.orphanGraceMs"
RECOVERY_ORPHAN_GRACE_MS_DEFAULT = 10 * 60_000

# Lifecycle retry: an action losing the write_log OCC race re-snapshots
# the log tip and retries with exponential backoff (the PR 8 serve-retry
# shape at the write boundary) instead of surfacing
# ConcurrentWriteException to the user on the first collision.
RECOVERY_RETRY_MAX_ATTEMPTS = "hyperspace.recovery.retry.maxAttempts"
RECOVERY_RETRY_MAX_ATTEMPTS_DEFAULT = 3
RECOVERY_RETRY_BACKOFF_MS = "hyperspace.recovery.retry.backoffMs"
RECOVERY_RETRY_BACKOFF_MS_DEFAULT = 10

# Quarantine directory name (underscore-prefixed: invisible to data
# scans, like HYPERSPACE_LOG_DIR).
HYPERSPACE_QUARANTINE_DIR = "_hyperspace_quarantine"

# -- observability plane (hyperspace_tpu/obs/, docs/observability.md) --------
# Master switch for structured tracing + the durable query log: every
# query through the serve frontend and every lifecycle action gets ONE
# root span with child stage spans mirroring the legacy breakdown keys,
# and each served query appends one JSONL record to the _hyperspace_obs/
# sidecar next to the lake. Off (the default) = the zero-cost path:
# every obs call site degrades to a single module-bool check and the
# serve/build behavior is bit-identical to the pre-obs tree.
OBS_ENABLED = "hyperspace.obs.enabled"
OBS_ENABLED_DEFAULT = False

# Durable query log (obs/querylog.py): one JSONL record per served
# query (fingerprint, predicate shape, stage timings, retry/degrade
# events, trace id), written to per-process files under
# <system.path>/_hyperspace_obs/ — the machine-readable workload
# profile the advisor loop (ROADMAP item 5) mines. Requires obs.enabled.
OBS_QUERYLOG_ENABLED = "hyperspace.obs.querylog.enabled"
OBS_QUERYLOG_ENABLED_DEFAULT = True

# Rotation bounds: the active per-process file rotates (fsync-before-
# rename, crash-safe — see the mid_querylog_rotate crash point) once it
# exceeds maxBytes, and at most maxFiles rotated segments are retained
# per process (oldest pruned first). Readers union every segment of
# every process, so rotation never loses in-flight records.
OBS_QUERYLOG_MAX_BYTES = "hyperspace.obs.querylog.maxBytes"
OBS_QUERYLOG_MAX_BYTES_DEFAULT = 4 << 20  # 4 MiB per segment
OBS_QUERYLOG_MAX_FILES = "hyperspace.obs.querylog.maxFiles"
OBS_QUERYLOG_MAX_FILES_DEFAULT = 8

# Trace plane bounds (obs/trace.py): maxSpans caps the child spans
# recorded per trace (excess children are dropped and counted in the
# root's ``spans_dropped`` attr — a runaway per-bucket fan-out must not
# hold the whole serve's span set in RAM); retain caps the in-memory
# ring of finished traces kept for bench/test introspection.
OBS_TRACE_MAX_SPANS = "hyperspace.obs.trace.maxSpans"
OBS_TRACE_MAX_SPANS_DEFAULT = 512
OBS_TRACE_RETAIN = "hyperspace.obs.trace.retain"
OBS_TRACE_RETAIN_DEFAULT = 256

# JSONL event sink path for telemetry events (obs/metrics.py JsonlSink
# + telemetry.JsonlEventLogger): empty = next to the lake under
# <system.path>/_hyperspace_obs/events.<pid>.jsonl when the Jsonl
# logger is selected via hyperspace.eventLoggerClass.
OBS_EVENTLOG_PATH = "hyperspace.obs.eventlog.path"
OBS_EVENTLOG_PATH_DEFAULT = ""

# Opt-in replayable plan specs in the query log (obs/planspec.py): each
# record additionally carries a re-executable "replay" plan spec.
# Specs retain predicate LITERALS (unlike the scrubbed predicate
# shape), so this stays off unless the operator wants the advisor's
# what-if scoring and the replay harness (testing/replay.py) to work
# straight from production logs.
OBS_QUERYLOG_RECORD_PLANS = "hyperspace.obs.querylog.recordPlans"
OBS_QUERYLOG_RECORD_PLANS_DEFAULT = False

# Observability sidecar directory under the lake root (underscore-
# prefixed: invisible to data scans, like the quarantine/pins dirs).
HYPERSPACE_OBS_DIR = "_hyperspace_obs"

# -- workload advisor (hyperspace_tpu/advisor/, docs/advisor.md) --------------
# Workload-profile bound: the query-log aggregator groups records by
# literal-scrubbed predicate shape and keeps at most this many shape
# groups resident (further shapes fold into an overflow counter) — the
# profile is O(maxShapes), never O(records), whatever the log size
# (ALLOC_SITES const-bounded contract).
ADVISOR_PROFILE_MAX_SHAPES = "hyperspace.advisor.profile.maxShapes"
ADVISOR_PROFILE_MAX_SHAPES_DEFAULT = 256

# What-if search bound: at most this many candidate indexes are
# enumerated from the hot shapes and scored against the recorded
# workload per advise() pass (hottest shapes first, overflow logged).
ADVISOR_MAX_CANDIDATES = "hyperspace.advisor.maxCandidates"
ADVISOR_MAX_CANDIDATES_DEFAULT = 32

# Opt-in budgeted apply: advisor.apply() executes top recommendations
# through the lifecycle actions (lease-stamped like any maintenance,
# so fleet serve traffic sees the PR 10 protections) until either
# budget is exhausted. Off = advise-only, nothing touches the lake.
ADVISOR_APPLY_ENABLED = "hyperspace.advisor.apply.enabled"
ADVISOR_APPLY_ENABLED_DEFAULT = False
ADVISOR_APPLY_MAX_BYTES = "hyperspace.advisor.apply.maxBytes"
ADVISOR_APPLY_MAX_BYTES_DEFAULT = 1 << 30
ADVISOR_APPLY_MAX_SECONDS = "hyperspace.advisor.apply.maxSeconds"
ADVISOR_APPLY_MAX_SECONDS_DEFAULT = 300.0

# -- replicated serve fleet (serve/fleet.py, serve/bus.py) -------------------
# Master switch for fleet mode: N ServeFrontend processes over ONE index
# lake. Turns on (a) DURABLE query pins — each pinned snapshot is also
# published as a lease-expiring file under <index>/_hyperspace_pins/ so
# an orphan GC or vacuum running in ANOTHER process never deletes files
# under a live query; (b) the index-version fanout bus — lifecycle
# actions publish change events under <system.path>/_hyperspace_fleet/
# that peers poll to invalidate (or, for aggregate-plane state, install)
# their ServeCache entries instead of serving stale pins; (c) cross-
# process single-flight — identical plans submitted to several frontends
# elect one executor through a fingerprint-keyed claim file and share
# the answer through a bounded result spool. Off = the single-process
# PR 8 behavior everywhere (in-memory pins, no bus, no spool).
FLEET_ENABLED = "hyperspace.fleet.enabled"
FLEET_ENABLED_DEFAULT = False

# Durable pin lease: a fleet frontend's pin files are renewed every
# leaseMs/3 by a heartbeat thread; a pin whose lease expired belongs to
# a DEAD frontend (kill -9, OOM) and is reaped by the next GC/vacuum —
# the recovery plane's writer-lease discriminator applied to readers.
FLEET_PIN_LEASE_MS = "hyperspace.fleet.pin.leaseMs"
FLEET_PIN_LEASE_MS_DEFAULT = 30_000

# Fanout bus poll cadence: how often each subscribed frontend lists the
# bus directory for events published by its peers.
FLEET_BUS_POLL_MS = "hyperspace.fleet.bus.pollMs"
FLEET_BUS_POLL_MS_DEFAULT = 100

# Bus event retention: event files older than this are pruned by the
# next publisher (every subscriber that was alive at publish time has
# long since polled them; a frontend attaching later starts from the
# current state anyway).
FLEET_BUS_RETAIN_MS = "hyperspace.fleet.bus.retainMs"
FLEET_BUS_RETAIN_MS_DEFAULT = 60_000

# Cross-process single-flight: identical plans arriving at N frontends
# elect ONE executor via an atomic claim file keyed by the plan + pinned
# snapshot fingerprint; the losers wait up to waitMs for the winner's
# spooled result before executing locally (correctness never depends on
# the election — a timeout just forfeits the dedup win). claimMs bounds
# how long a dead winner's claim blocks peers.
FLEET_SINGLEFLIGHT_ENABLED = "hyperspace.fleet.singleflight.enabled"
FLEET_SINGLEFLIGHT_ENABLED_DEFAULT = True
FLEET_SINGLEFLIGHT_WAIT_MS = "hyperspace.fleet.singleflight.waitMs"
FLEET_SINGLEFLIGHT_WAIT_MS_DEFAULT = 5_000
FLEET_SINGLEFLIGHT_CLAIM_MS = "hyperspace.fleet.singleflight.claimMs"
FLEET_SINGLEFLIGHT_CLAIM_MS_DEFAULT = 10_000

# Result spool byte budget: the winner of a single-flight election
# publishes its answer as an Arrow IPC file under
# <system.path>/_hyperspace_fleet/spool/; writers prune the oldest
# results past this budget (results are version-addressed — a refresh
# re-keys every plan, so stale entries are unreachable, only unread).
FLEET_SPOOL_MAX_BYTES = "hyperspace.fleet.spool.maxBytes"
FLEET_SPOOL_MAX_BYTES_DEFAULT = 256 << 20  # 256 MiB

# Per-tenant SLO classes (prefix family, like hyperspace.faults.):
# hyperspace.fleet.class.<name>.maxConcurrency caps how many queries of
# class <name> RUN at once on a frontend (0 = unlimited; excess admits
# queue without occupying worker threads), and
# hyperspace.fleet.class.<name>.maxQueueDepth sheds class-<name>
# admissions past that backlog with a typed ServeOverloadedError —
# layered UNDER the global hyperspace.serve.maxQueueDepth bound, so a
# batch tier with a tight class budget sheds before the interactive
# tier feels any pressure. Queries submitted without a class (or with
# an unconfigured class name) see only the global bounds.
FLEET_CLASS_KEY_PREFIX = "hyperspace.fleet.class."

# -- fleet fast data plane (serve/fastbus.py, serve/router.py) ---------------
# The durable planes above coordinate through files and polling — always
# correct, but the polling tax dominates at small fleets (ROADMAP item
# 3). The fast plane layers a per-host push bus (Unix sockets announced
# through lease-stamped member files under _hyperspace_fleet/members/)
# and owner routing (rendezvous-hash the plan digest to one member, ship
# the plan spec, stream the Arrow result back — no claim election, no
# fsync'd spool round-trip) on top. Every fast-path message is
# idempotently replayable from the durable planes: a dropped push costs
# a poll interval, a dead owner costs one failed connect and a fallback
# to the claim/spool path — never a wrong answer. Off = PR 14 behavior.
FLEET_FAST_ENABLED = "hyperspace.fleet.fast.enabled"
FLEET_FAST_ENABLED_DEFAULT = True

# Owner routing sub-switch: with it off the fast plane still pushes
# fanout events, result-ready wakeups and SLO gossip, but single-flight
# stays on the claim/spool election (useful to isolate a routing bug in
# production without losing push latency).
FLEET_FAST_ROUTING_ENABLED = "hyperspace.fleet.fast.routing.enabled"
FLEET_FAST_ROUTING_ENABLED_DEFAULT = True

# Round-trip budget for one owner-routed execution request. A timeout
# (or any send/receive failure, including an armed fastbus_send fault)
# falls back to the durable single-flight plane — the budget bounds the
# p99 blip when an owner dies, it never forfeits the answer.
FLEET_FAST_REQUEST_TIMEOUT_MS = "hyperspace.fleet.fast.requestTimeoutMs"
FLEET_FAST_REQUEST_TIMEOUT_MS_DEFAULT = 2_000

# Member lease: each frontend announces its socket in a lease-expiring
# member file renewed every leaseMs/3 by the router maintenance thread;
# a member whose lease expired is a dead process (kill -9, OOM) — peers
# reap its member file AND its socket file, and rendezvous routing stops
# offering it work. The same discriminator as the writer and pin leases.
FLEET_FAST_MEMBER_LEASE_MS = "hyperspace.fleet.fast.memberLeaseMs"
FLEET_FAST_MEMBER_LEASE_MS_DEFAULT = 10_000

# Byte budget for the in-memory digest->result cache each member keeps
# (LRU, measured by Arrow table nbytes). Results are snapshot-addressed
# like the spool, so a cached entry can be stale only in the sense of
# unreachable — a refresh re-keys every digest. 0 disables the cache.
FLEET_FAST_RESULT_CACHE_BYTES = "hyperspace.fleet.fast.resultCacheBytes"
FLEET_FAST_RESULT_CACHE_BYTES_DEFAULT = 64 << 20  # 64 MiB

# Queue-depth gossip cadence: each member pushes its per-class
# running+pending depths to every live peer this often, feeding the
# fleet-wide SLO admission check. Entries older than ~10 gossip periods
# are ignored (a dead peer must not pin its last-known depth forever).
FLEET_FAST_GOSSIP_MS = "hyperspace.fleet.fast.gossipMs"
FLEET_FAST_GOSSIP_MS_DEFAULT = 50

# Fleet-wide SLO enforcement: when on, the per-tenant class queue-depth
# bound counts the gossiped depths of live peers too, so a batch tier
# saturating ONE process sheds fleet-wide before the interactive tier
# feels pressure on ANY process. Off = per-process depths (PR 14).
FLEET_FAST_SLO_FLEET_WIDE = "hyperspace.fleet.fast.sloFleetWide"
FLEET_FAST_SLO_FLEET_WIDE_DEFAULT = True

# Durable pin directory name (underscore-prefixed, next to the log —
# invisible to data scans like the quarantine dir).
HYPERSPACE_PINS_DIR = "_hyperspace_pins"

# Fleet coordination directory under the lake root (hyperspace.system.
# path): <root>/_hyperspace_fleet/bus/ event files +
# <root>/_hyperspace_fleet/spool/ single-flight claims and results.
HYPERSPACE_FLEET_DIR = "_hyperspace_fleet"

# ServeCache spill tier directory under the lake root:
# <root>/_hyperspace_spill/<sha>.spill files. Derived state — fully
# rebuildable from the index parquet — so the recovery plane's spill
# reaper deletes orphans past hyperspace.serve.spill.orphanTtlMs and
# gc_orphans/vacuum never quarantine the live dir (underscore-prefixed,
# invisible to data and index scans like the other sidecar dirs).
HYPERSPACE_SPILL_DIR = "_hyperspace_spill"
