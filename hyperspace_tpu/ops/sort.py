"""Device sort — the sort-within-bucket step of the covering index build.

Reference: the bucketed *sorted* write in
``index/DataFrameWriterExtensions.scala:58-67`` (Spark sorts each bucket by
the indexed columns before writing). Here the whole shard is sorted by
``(bucket_id, key_0, key_1, …)`` in one XLA lexsort; the per-bucket runs
are then contiguous and each bucket's parquet file is written from a slice.

Sorting uses int64 key reps (``io/columnar.py``): an arbitrary-but-
consistent total order, which is exactly what bucketed sort-merge joins
need (both sides sort by the same function of the key values;
``JoinIndexRule.scala:619-634``). Like the hash kernel, comparisons run on
32-bit planes (TPU-native): each int64 key becomes (hi ^ signbit as uint32
major, lo uint32 minor), which orders identically to signed int64.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import hyperspace_tpu.ops  # noqa: F401  (enables x64)

_SIGN = np.uint32(0x80000000)

# Below this row count lexsort runs as numpy on host (identical stable
# semantics); the device sort pays transfer + readback that dwarfs the
# sort itself for HOST-RESIDENT batches. Measured on the bench chip
# (v5e via tunnel, round 5): 4M-row single-key build lexsort = 0.9s host
# numpy (radix) vs 3.7s device incl. transfer — the device kernel's home
# is HBM-resident data on a sharded mesh, not host-resident builds, so
# the host path covers every practical single-host size.
_HOST_SORT_MAX_ROWS = 1 << 26

# At or above this row count the host path prefers the native C++ radix
# lexsort (hyperspace_tpu/native): one adaptive LSD radix over all planes
# with constant-byte pass skipping, measured 3.3x over np.lexsort at the
# 4M-row bench shape (bit-identical stable output). Below it numpy's
# overhead is already microseconds and a first native call would pay the
# one-time g++ compile for nothing.
_NATIVE_SORT_MIN_ROWS = 1 << 15


def _order_words_np(key_reps: np.ndarray) -> np.ndarray:
    """[k, n] int64 -> [2k, n] uint32 planes whose lexicographic order
    (row 0 major) equals signed-int64 order of the keys."""
    u = np.ascontiguousarray(key_reps).view(np.uint64)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = ((u >> np.uint64(32)).astype(np.uint32)) ^ _SIGN  # flip sign bit
    return np.stack([w for pair in zip(hi, lo) for w in pair])


@jax.jit
def lexsort_indices(word_planes):
    """[m, n] uint32 -> [n] permutation; primary key = row 0.

    ``jnp.lexsort`` treats the *last* row as primary, so reverse.
    """
    return jnp.lexsort(word_planes[::-1])


def lexsort_perm(planes: np.ndarray, n_valid: int | None = None) -> np.ndarray:
    """Host dispatch of :func:`lexsort_indices` at a padded static shape.

    Pads the row dimension to ``pad_len`` with ``0xFFFFFFFF`` in every
    plane (the ops/__init__ shape policy: one compile per 2x size band).
    Pad slots sort after every real row: their key is the maximum in all
    planes and ``jnp.lexsort`` is stable, so a real row that ties still
    precedes them (its index is smaller). The first ``n_valid`` outputs
    are therefore exactly the sorted real rows.
    """
    from hyperspace_tpu.ops import pad_len

    n = planes.shape[1] if n_valid is None else n_valid
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    planes = planes.astype(np.uint32, copy=False)
    if planes.shape[1] <= _HOST_SORT_MAX_ROWS:
        # host lexsort: same stable semantics, no device round trip
        # (host-resident serve batches pay transfer + readback otherwise)
        if planes.shape[1] >= _NATIVE_SORT_MIN_ROWS:
            from hyperspace_tpu import native

            perm = native.lexsort_u32(planes)
            if perm is not None:
                return perm[:n]
        return np.lexsort(planes[::-1])[:n]
    n_pad = pad_len(planes.shape[1])
    if n_pad != planes.shape[1]:
        fill = np.full(
            (planes.shape[0], n_pad - planes.shape[1]),
            np.uint32(0xFFFFFFFF),
        )
        planes = np.concatenate([planes, fill], axis=1)
    perm = np.asarray(lexsort_indices(jnp.asarray(planes)))
    return perm[:n]


def sort_permutation(
    key_reps: np.ndarray, bucket: np.ndarray | None = None
) -> np.ndarray:
    """Host entry: permutation sorting rows by (bucket, key_reps...)."""
    planes = _order_words_np(key_reps.astype(np.int64, copy=False))
    if bucket is not None:
        planes = np.concatenate(
            [bucket.astype(np.uint32)[None, :], planes]
        )
    return lexsort_perm(planes)


# ---------------------------------------------------------------------------
# User-facing ORDER BY (value order, not key-rep order)
# ---------------------------------------------------------------------------


def order_rep(col) -> np.ndarray:
    """int64 rep whose signed order equals the column's VALUE order.

    Unlike ``Column.key_rep`` (arbitrary-but-consistent order, hash for
    strings), this is order-preserving: ints/temporal as-is, uints via
    sign-bit xor, floats via the IEEE-754 total-order trick (NaN sorts
    after +inf, matching numpy/pyarrow), strings via per-batch dictionary
    rank. Null placement is handled by the caller (``ordering_permutation``
    adds a null plane), so nulls here get an arbitrary in-band value.
    """
    if col.kind == "string":
        order = sorted(range(len(col.dictionary)), key=col.dictionary.__getitem__)
        rank = np.empty(max(len(col.dictionary), 1), dtype=np.int64)
        for r, i in enumerate(order):
            rank[i] = r
        return rank[np.maximum(col.codes, 0)].astype(np.int64)
    v = col.values
    if v.dtype.kind == "f":
        # IEEE-754 total order as SIGNED int64: positives keep their bit
        # pattern; negatives complement the magnitude bits (sign bit stays,
        # so they remain negative and larger magnitudes sort lower).
        u = v.astype(np.float64).view(np.uint64)
        rep = np.where(
            u >> np.uint64(63) == 1,
            u ^ np.uint64(0x7FFFFFFFFFFFFFFF),
            u,
        )
        return rep.view(np.int64)
    if v.dtype.kind == "u":
        return (
            v.astype(np.uint64) ^ np.uint64(0x8000000000000000)
        ).view(np.int64)
    if v.dtype.kind == "b":
        return v.astype(np.int64)
    return v.astype(np.int64)


def ordering_permutation(batch, keys) -> np.ndarray:
    """Stable permutation ordering ``batch`` by ``keys`` =
    ((column, ascending), ...). Nulls always sort last (pyarrow's
    ``null_placement="at_end"``), and NaN always sorts after every other
    value but before nulls — in BOTH directions, like pyarrow's sort_by.
    Descending flips values only, never the null/NaN placement."""
    planes = []
    for name, asc in keys:
        col = batch.column(name)
        rep = order_rep(col)
        if not asc:
            rep = ~rep  # bitwise complement reverses signed order
        null = col.null_mask
        null_plane = (
            np.zeros(len(col), dtype=np.uint32)
            if null is None
            else null.astype(np.uint32)
        )
        planes.append(null_plane)
        if col.kind == "numeric" and col.values.dtype.kind == "f":
            # direction-independent NaN plane (pyarrow: NaN after values)
            planes.append(np.isnan(col.values).astype(np.uint32))
        planes.extend(_order_words_np(rep[None, :]))
    return lexsort_perm(np.stack(planes))
