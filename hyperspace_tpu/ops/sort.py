"""Device sort — the sort-within-bucket step of the covering index build.

Reference: the bucketed *sorted* write in
``index/DataFrameWriterExtensions.scala:58-67`` (Spark sorts each bucket by
the indexed columns before writing). Here the whole shard is sorted by
``(bucket_id, key_0, key_1, …)`` in one XLA lexsort; the per-bucket runs
are then contiguous and each bucket's parquet file is written from a slice.

Sorting uses int64 key reps (``io/columnar.py``): an arbitrary-but-
consistent total order, which is exactly what bucketed sort-merge joins
need (both sides sort by the same function of the key values;
``JoinIndexRule.scala:619-634``). Like the hash kernel, comparisons run on
32-bit planes (TPU-native): each int64 key becomes (hi ^ signbit as uint32
major, lo uint32 minor), which orders identically to signed int64.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import hyperspace_tpu.ops  # noqa: F401  (enables x64)

_SIGN = np.uint32(0x80000000)


def _order_words_np(key_reps: np.ndarray) -> np.ndarray:
    """[k, n] int64 -> [2k, n] uint32 planes whose lexicographic order
    (row 0 major) equals signed-int64 order of the keys."""
    u = np.ascontiguousarray(key_reps).view(np.uint64)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = ((u >> np.uint64(32)).astype(np.uint32)) ^ _SIGN  # flip sign bit
    return np.stack([w for pair in zip(hi, lo) for w in pair])


@jax.jit
def lexsort_indices(word_planes):
    """[m, n] uint32 -> [n] permutation; primary key = row 0.

    ``jnp.lexsort`` treats the *last* row as primary, so reverse.
    """
    return jnp.lexsort(word_planes[::-1])


def sort_permutation(
    key_reps: np.ndarray, bucket: np.ndarray | None = None
) -> np.ndarray:
    """Host entry: permutation sorting rows by (bucket, key_reps...)."""
    planes = _order_words_np(key_reps.astype(np.int64, copy=False))
    if bucket is not None:
        planes = np.concatenate(
            [bucket.astype(np.uint32)[None, :], planes]
        )
    return np.asarray(lexsort_indices(jnp.asarray(planes)))
