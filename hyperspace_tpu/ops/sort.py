"""Device sort — the sort-within-bucket step of the covering index build.

Reference: the bucketed *sorted* write in
``index/DataFrameWriterExtensions.scala:58-67`` (Spark sorts each bucket by
the indexed columns before writing). Here the whole shard is sorted by
``(bucket_id, key_0, key_1, …)`` in one XLA lexsort; the per-bucket runs
are then contiguous and each bucket's parquet file is written from a slice.

Sorting uses int64 key reps (``io/columnar.py``): an arbitrary-but-
consistent total order, which is exactly what bucketed sort-merge joins
need (both sides sort by the same function of the key values;
``JoinIndexRule.scala:619-634``). Like the hash kernel, comparisons run on
32-bit planes (TPU-native): each int64 key becomes (hi ^ signbit as uint32
major, lo uint32 minor), which orders identically to signed int64.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import hyperspace_tpu.ops  # noqa: F401  (enables x64)

_SIGN = np.uint32(0x80000000)

# Below this row count lexsort runs as numpy on host (identical stable
# semantics); the device sort pays transfer + readback that dwarfs the
# sort itself for HOST-RESIDENT batches. Measured on the bench chip
# (v5e via tunnel, round 5): 4M-row single-key build lexsort = 0.9s host
# numpy (radix) vs 3.7s device incl. transfer — the device kernel's home
# is HBM-resident data on a sharded mesh, not host-resident builds, so
# the host path covers every practical single-host size.
#
# FALLBACK DEFAULT: the effective threshold comes from the per-machine
# calibration probe (hyperspace_tpu/native/calibrate.py) when available;
# this constant is used when calibration is disabled (HS_CALIBRATE=0),
# has not produced a measurement, or when a test overrides the module
# attribute directly (an override always wins — see _host_sort_max_rows).
_HOST_SORT_MAX_ROWS_DEFAULT = 1 << 26
_HOST_SORT_MAX_ROWS = _HOST_SORT_MAX_ROWS_DEFAULT

# At or above this row count the host path prefers the native C++ radix
# lexsort (hyperspace_tpu/native): one adaptive LSD radix over all planes
# with constant-byte pass skipping, measured 3.3x over np.lexsort at the
# 4M-row bench shape (bit-identical stable output). Below it numpy's
# overhead is already microseconds and a first native call would pay the
# one-time g++ compile for nothing. Fallback default; see above.
_NATIVE_SORT_MIN_ROWS_DEFAULT = 1 << 15
_NATIVE_SORT_MIN_ROWS = _NATIVE_SORT_MIN_ROWS_DEFAULT

# Same idea for the counting-scatter partition kernel. Its crossover is
# NOT the lexsort's: the kernel is O(n) with two sequential passes and
# near-zero per-row work, so ctypes/threading overhead amortizes much
# earlier than for the radix sort. Calibrated separately (see
# native/calibrate.py); fallback default below.
_NATIVE_PARTITION_MIN_ROWS_DEFAULT = 1 << 15
_NATIVE_PARTITION_MIN_ROWS = _NATIVE_PARTITION_MIN_ROWS_DEFAULT


def _host_sort_max_rows() -> int:
    if _HOST_SORT_MAX_ROWS != _HOST_SORT_MAX_ROWS_DEFAULT:
        return _HOST_SORT_MAX_ROWS  # explicit (test/ops) override wins
    from hyperspace_tpu.native import calibrate

    return calibrate.thresholds().host_sort_max_rows or _HOST_SORT_MAX_ROWS


def _native_sort_min_rows() -> int:
    if _NATIVE_SORT_MIN_ROWS != _NATIVE_SORT_MIN_ROWS_DEFAULT:
        return _NATIVE_SORT_MIN_ROWS
    from hyperspace_tpu.native import calibrate

    return (
        calibrate.thresholds().native_sort_min_rows or _NATIVE_SORT_MIN_ROWS
    )


def _native_partition_min_rows() -> int:
    if _NATIVE_PARTITION_MIN_ROWS != _NATIVE_PARTITION_MIN_ROWS_DEFAULT:
        return _NATIVE_PARTITION_MIN_ROWS
    from hyperspace_tpu.native import calibrate

    return (
        calibrate.thresholds().native_partition_min_rows
        or _NATIVE_PARTITION_MIN_ROWS
    )


def _order_words_np(key_reps: np.ndarray) -> np.ndarray:
    """[k, n] int64 -> [2k, n] uint32 planes whose lexicographic order
    (row 0 major) equals signed-int64 order of the keys."""
    u = np.ascontiguousarray(key_reps).view(np.uint64)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = ((u >> np.uint64(32)).astype(np.uint32)) ^ _SIGN  # flip sign bit
    return np.stack([w for pair in zip(hi, lo) for w in pair])


@jax.jit
def lexsort_indices(word_planes):
    """[m, n] uint32 -> [n] permutation; primary key = row 0.

    ``jnp.lexsort`` treats the *last* row as primary, so reverse.
    """
    return jnp.lexsort(word_planes[::-1])


def lexsort_perm(
    planes: np.ndarray,
    n_valid: int | None = None,
    n_threads: int | None = None,
) -> np.ndarray:
    """Host dispatch of :func:`lexsort_indices` at a padded static shape.

    Pads the row dimension to ``pad_len`` with ``0xFFFFFFFF`` in every
    plane (the ops/__init__ shape policy: one compile per 2x size band).
    Pad slots sort after every real row: their key is the maximum in all
    planes and ``jnp.lexsort`` is stable, so a real row that ties still
    precedes them (its index is smaller). The first ``n_valid`` outputs
    are therefore exactly the sorted real rows.

    ``n_threads`` caps the native kernel's thread count — the partitioned
    build runs many per-bucket sorts concurrently and hands each a slice
    of the core budget instead of letting every sort claim the machine.
    """
    from hyperspace_tpu.ops import pad_len

    n = planes.shape[1] if n_valid is None else n_valid
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    planes = planes.astype(np.uint32, copy=False)
    if planes.shape[1] <= _host_sort_max_rows():
        # host lexsort: same stable semantics, no device round trip
        # (host-resident serve batches pay transfer + readback otherwise)
        if planes.shape[1] >= _native_sort_min_rows():
            from hyperspace_tpu import native

            perm = native.lexsort_u32(planes, n_threads=n_threads)
            if perm is not None:
                return perm[:n]
        return np.lexsort(planes[::-1])[:n]
    n_pad = pad_len(planes.shape[1])
    if n_pad != planes.shape[1]:
        fill = np.full(
            (planes.shape[0], n_pad - planes.shape[1]),
            np.uint32(0xFFFFFFFF),
        )
        planes = np.concatenate([planes, fill], axis=1)
    perm = np.asarray(lexsort_indices(jnp.asarray(planes)))
    return perm[:n]


def sort_permutation(
    key_reps: np.ndarray, bucket: np.ndarray | None = None
) -> np.ndarray:
    """Host entry: permutation sorting rows by (bucket, key_reps...)."""
    planes = _order_words_np(key_reps.astype(np.int64, copy=False))
    if bucket is not None:
        planes = np.concatenate(
            [bucket.astype(np.uint32)[None, :], planes]
        )
    return lexsort_perm(planes)


# ---------------------------------------------------------------------------
# Partition-first build sort (locality-aware alternative to the global
# (bucket, keys) lexsort — the 64M-row sort collapse fix)
# ---------------------------------------------------------------------------


def partition_by_bucket(
    bucket_ids: np.ndarray, num_buckets: int
) -> tuple[np.ndarray, np.ndarray]:
    """Stable partition of row indices by bucket id: ``(order, offsets)``
    with bucket ``b``'s rows at ``order[offsets[b]:offsets[b+1]]`` in
    original order. Native counting-scatter kernel
    (``hs_partition_by_bucket``: sequential histogram + per-cursor
    sequential writes) above the native dispatch threshold, bit-exact
    numpy twin (stable argsort + bincount prefix sum) below or when the
    kernel is unavailable."""
    bucket_ids = np.ascontiguousarray(bucket_ids, dtype=np.int32)
    n = len(bucket_ids)
    if n >= _native_partition_min_rows():
        from hyperspace_tpu import native

        got = native.partition_by_bucket_i32(bucket_ids, num_buckets)
        if got is not None:
            return got
    return partition_by_bucket_numpy(bucket_ids, num_buckets)


def partition_by_bucket_numpy(
    bucket_ids: np.ndarray, num_buckets: int
) -> tuple[np.ndarray, np.ndarray]:
    """The pure-numpy leg of :func:`partition_by_bucket` (stable argsort
    + bincount prefix sum), never dispatching to the native kernel —
    also the reference the calibration probe times the native
    counting-scatter against."""
    counts = np.bincount(bucket_ids, minlength=num_buckets)
    offsets = np.concatenate(
        [np.zeros(1, dtype=np.int64), np.cumsum(counts, dtype=np.int64)]
    )
    return np.argsort(bucket_ids, kind="stable").astype(np.int64), offsets


def _sort_pool_plan(n_buckets: int) -> tuple[int, int]:
    """(pool workers, native threads per sort) splitting the core budget
    across concurrent per-bucket sorts."""
    from hyperspace_tpu import native

    budget = max(1, min(native._cores(), 16))
    workers = max(1, min(budget, n_buckets))
    return workers, max(1, budget // workers)


def bucket_key_sort_runs(
    planes: np.ndarray,
    order: np.ndarray,
    offsets: np.ndarray,
    workers: int | None = None,
    n_threads: int | None = None,
):
    """Per-bucket stable key sorts over a partitioned order — yields
    ``(bucket, final_indices)`` in ascending bucket id as each bucket's
    sort completes, running the sorts on a thread pool.

    ``planes`` are the key order-words in ORIGINAL row order; bucket
    ``b``'s rows are gathered (``planes[:, idx]``, a working set of ~one
    bucket instead of the whole table) and lexsorted WITHOUT the bucket
    plane (constant within a bucket). Ties keep ``idx`` order, and
    ``idx`` is ascending, so ``idx[perm]`` reproduces exactly the global
    stable lexsort by (bucket, keys...) restricted to bucket ``b``.

    ``workers``/``n_threads`` override the core-budget split — the
    sharded tail runs one of these loops PER SHARD concurrently
    (``workers=1``, the shard thread is the concurrency unit) and hands
    each shard a slice of the native-sort thread budget.
    """
    from concurrent.futures import ThreadPoolExecutor

    nonempty = [
        b for b in range(len(offsets) - 1) if offsets[b + 1] > offsets[b]
    ]
    if not nonempty:
        return
    if workers is None:
        workers, threads = _sort_pool_plan(len(nonempty))
    else:
        threads = max(1, n_threads or 1)

    def sort_one(b: int) -> np.ndarray:
        idx = order[offsets[b] : offsets[b + 1]]
        perm = lexsort_perm(
            np.ascontiguousarray(planes[:, idx]), n_threads=threads
        )
        return idx[perm]

    if workers == 1:
        for b in nonempty:
            yield b, sort_one(b)
        return
    with ThreadPoolExecutor(max_workers=workers) as pool:
        futures = [(b, pool.submit(sort_one, b)) for b in nonempty]
        for b, fut in futures:
            yield b, fut.result()


def partitioned_sort_permutation(
    key_reps: np.ndarray, bucket: np.ndarray, num_buckets: int
) -> np.ndarray:
    """Bit-identical to ``sort_permutation(key_reps, bucket)`` (stable
    lexsort by (bucket, keys...)) computed partition-first: one counting
    scatter groups rows by bucket, then each bucket is key-sorted
    independently on a thread pool with a working set of
    ~rows/num_buckets. The 64M-row global lexsort's permutation gathers
    walk the entire multi-hundred-MB working set per radix pass
    (TLB-bound — BASELINE.md); per-bucket sorts keep each pass resident.
    """
    order, offsets = partition_by_bucket(bucket, num_buckets)
    planes = _order_words_np(key_reps.astype(np.int64, copy=False))
    out = np.empty(len(order), dtype=np.int64)
    for b, final_idx in bucket_key_sort_runs(planes, order, offsets):
        out[offsets[b] : offsets[b + 1]] = final_idx
    return out


def shard_tail_plan(shard_offsets: np.ndarray) -> tuple[list, int]:
    """(non-empty shards, native threads per shard) for the sharded
    build tail: shards are the concurrency unit, each gets an equal
    slice of the core budget for its in-shard native sorts."""
    from hyperspace_tpu import native

    shards = [
        s
        for s in range(len(shard_offsets) - 1)
        if shard_offsets[s + 1] > shard_offsets[s]
    ]
    budget = max(1, min(native._cores(), 16))
    return shards, max(1, budget // max(len(shards), 1))


def sharded_sort_permutation(
    key_reps: np.ndarray,
    bucket: np.ndarray,
    num_buckets: int,
    shard_offsets: np.ndarray,
) -> np.ndarray:
    """The device-local twin of :func:`partitioned_sort_permutation`:
    each mesh shard's post-exchange slice (``shard_offsets[s] :
    shard_offsets[s+1]``, exactly the buckets that shard owns) runs its
    own counting scatter + per-bucket key sorts CONCURRENTLY with the
    other shards', so sort working set and thread occupancy scale with
    the shard count instead of serializing through one permutation over
    the full batch.

    Output row order is shard-major (shard 0's buckets ascending, then
    shard 1's, …), NOT the globally bucket-ascending order of the
    single-tail sort — but every bucket lives wholly inside one shard
    slice, so each bucket's rows and their stable key-sorted order are
    bit-identical to the global sort restricted to that bucket, which is
    the only order the bucketed writers observe (one file per bucket).
    """
    from concurrent.futures import ThreadPoolExecutor

    planes = _order_words_np(key_reps.astype(np.int64, copy=False))
    n = int(shard_offsets[-1])
    out = np.empty(n, dtype=np.int64)
    shards, threads = shard_tail_plan(shard_offsets)
    if not shards:
        return out

    def run_shard(s: int) -> None:
        lo, hi = int(shard_offsets[s]), int(shard_offsets[s + 1])
        order, offsets = partition_by_bucket(bucket[lo:hi], num_buckets)
        order += lo  # global row coordinates for the planes gather
        pos = lo
        for _b, final_idx in bucket_key_sort_runs(
            planes, order, offsets, workers=1, n_threads=threads
        ):
            out[pos : pos + len(final_idx)] = final_idx
            pos += len(final_idx)

    if len(shards) == 1:
        run_shard(shards[0])
        return out
    with ThreadPoolExecutor(
        max_workers=len(shards), thread_name_prefix="hs-shardsort"
    ) as pool:
        list(pool.map(run_shard, shards))
    return out


# ---------------------------------------------------------------------------
# User-facing ORDER BY (value order, not key-rep order)
# ---------------------------------------------------------------------------


def order_rep(col) -> np.ndarray:
    """int64 rep whose signed order equals the column's VALUE order.

    Unlike ``Column.key_rep`` (arbitrary-but-consistent order, hash for
    strings), this is order-preserving: ints/temporal as-is, uints via
    sign-bit xor, floats via the IEEE-754 total-order trick (NaN sorts
    after +inf, matching numpy/pyarrow), strings via per-batch dictionary
    rank. Null placement is handled by the caller (``ordering_permutation``
    adds a null plane), so nulls here get an arbitrary in-band value.
    """
    if col.kind == "string":
        order = sorted(range(len(col.dictionary)), key=col.dictionary.__getitem__)
        rank = np.empty(max(len(col.dictionary), 1), dtype=np.int64)
        for r, i in enumerate(order):
            rank[i] = r
        return rank[np.maximum(col.codes, 0)].astype(np.int64)
    v = col.values
    if v.dtype.kind == "f":
        # IEEE-754 total order as SIGNED int64: positives keep their bit
        # pattern; negatives complement the magnitude bits (sign bit stays,
        # so they remain negative and larger magnitudes sort lower).
        u = v.astype(np.float64).view(np.uint64)
        rep = np.where(
            u >> np.uint64(63) == 1,
            u ^ np.uint64(0x7FFFFFFFFFFFFFFF),
            u,
        )
        return rep.view(np.int64)
    if v.dtype.kind == "u":
        return (
            v.astype(np.uint64) ^ np.uint64(0x8000000000000000)
        ).view(np.int64)
    if v.dtype.kind == "b":
        return v.astype(np.int64)
    return v.astype(np.int64)


def ordering_permutation(batch, keys) -> np.ndarray:
    """Stable permutation ordering ``batch`` by ``keys`` =
    ((column, ascending), ...). Nulls always sort last (pyarrow's
    ``null_placement="at_end"``), and NaN always sorts after every other
    value but before nulls — in BOTH directions, like pyarrow's sort_by.
    Descending flips values only, never the null/NaN placement."""
    planes = []
    for name, asc in keys:
        col = batch.column(name)
        rep = order_rep(col)
        if not asc:
            rep = ~rep  # bitwise complement reverses signed order
        null = col.null_mask
        null_plane = (
            np.zeros(len(col), dtype=np.uint32)
            if null is None
            else null.astype(np.uint32)
        )
        planes.append(null_plane)
        if col.kind == "numeric" and col.values.dtype.kind == "f":
            # direction-independent NaN plane (pyarrow: NaN after values)
            planes.append(np.isnan(col.values).astype(np.uint32))
        planes.extend(_order_words_np(rep[None, :]))
    return lexsort_perm(np.stack(planes))
