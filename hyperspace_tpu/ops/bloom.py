"""Bloom filter build/probe kernels.

Reference: the data-skipping Catalyst expression toolkit —
``dataskipping/expressions/BloomFilterAgg.scala`` (per-file bloom
aggregation) and ``BloomFilterMightContain(Any).scala`` (probe). Here both
sides are double-hashing over the murmur3 word kernel (``ops/hash.py``):
bit index j = (h1 + j·h2) mod m, the standard Kirsch-Mitzenmacher scheme.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

import hyperspace_tpu.ops  # noqa: F401  (enables x64)
from hyperspace_tpu.ops.hash import hash_words, split_words_np


def optimal_params(expected_items: int, fpp: float) -> Tuple[int, int]:
    """(num_bits m, num_hashes k) for a target false-positive rate."""
    expected_items = max(1, expected_items)
    m = max(64, int(-expected_items * math.log(fpp) / (math.log(2) ** 2)))
    m = ((m + 63) // 64) * 64  # word-align
    k = max(1, round(m / expected_items * math.log(2)))
    return m, min(k, 16)


@functools.partial(jax.jit, static_argnames=("m", "k"))
def _bit_indices(words, m: int, k: int):
    """[2, n] uint32 key words -> [k, n] int32 bit indices."""
    h1 = hash_words(words, 0x9747B28C)
    h2 = hash_words(words, 0x85EBCA6B) | jnp.uint32(1)  # odd => full cycle
    idx = []
    for j in range(k):
        idx.append(((h1 + jnp.uint32(j) * h2) % jnp.uint32(m)).astype(jnp.int32))
    return jnp.stack(idx)


def build_bloom(key_reps: np.ndarray, m: int, k: int) -> np.ndarray:
    """int64 key reps [n] -> packed bit array as uint64 words [m/64]."""
    if len(key_reps) == 0:
        return np.zeros(m // 64, dtype=np.uint64)
    words = split_words_np(key_reps[None, :])
    idx = np.asarray(_bit_indices(jnp.asarray(words), m, k)).ravel()
    bits = np.zeros(m, dtype=bool)
    bits[idx] = True
    return np.packbits(bits, bitorder="little").view(np.uint64)


def might_contain(bloom_words: np.ndarray, key_reps: np.ndarray, m: int, k: int):
    """[n] reps against one bloom -> bool [n]."""
    if len(key_reps) == 0:
        return np.zeros(0, dtype=bool)
    bits = np.unpackbits(
        bloom_words.view(np.uint8), bitorder="little", count=m
    ).astype(bool)
    words = split_words_np(key_reps[None, :])
    idx = np.asarray(_bit_indices(jnp.asarray(words), m, k))  # [k, n]
    return bits[idx].all(axis=0)
