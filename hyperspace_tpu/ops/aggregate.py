"""Device grouped reductions — the engine's aggregate kernel.

The reference delegates group-by to Spark's hash aggregate; here the
engine is the serve path, so grouped reductions run as XLA segment ops
(``jax.ops.segment_sum``/``min``/``max``): group ids are computed on host
(O(rows) factorize over int64 key reps), the O(rows·aggs) reduction work
runs compiled on device. Null semantics match SQL/Spark: sum/min/max/avg
ignore nulls (an all-null group yields null), count(col) counts non-null
rows, count(*) counts rows.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import hyperspace_tpu.ops  # noqa: F401  (enables x64)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def _seg_sum_count(gid, vals, valid, num_segments):
    """(per-group sum over valid rows, per-group count of valid rows)."""
    v = jnp.where(valid, vals, jnp.zeros((), dtype=vals.dtype))
    sums = jax.ops.segment_sum(v, gid, num_segments=num_segments)
    counts = jax.ops.segment_sum(
        valid.astype(jnp.int64), gid, num_segments=num_segments
    )
    return sums, counts


@functools.partial(jax.jit, static_argnames=("num_segments",))
def _seg_min(gid, vals, valid, num_segments):
    if jnp.issubdtype(vals.dtype, jnp.floating):
        # Spark float ordering: NaN > +inf, so min is NaN only when the
        # group has no non-NaN valid values (matches ops/sort.order_rep).
        isn = jnp.isnan(vals)
        clean = jnp.where(valid & ~isn, vals, jnp.inf)
        m = jax.ops.segment_min(clean, gid, num_segments=num_segments)
        has_clean = (
            jax.ops.segment_sum(
                (valid & ~isn).astype(jnp.int32), gid, num_segments=num_segments
            )
            > 0
        )
        return jnp.where(has_clean, m, jnp.asarray(jnp.nan, vals.dtype))
    v = jnp.where(valid, vals, jnp.iinfo(vals.dtype).max)
    return jax.ops.segment_min(v, gid, num_segments=num_segments)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def _seg_max(gid, vals, valid, num_segments):
    if jnp.issubdtype(vals.dtype, jnp.floating):
        # Spark float ordering: any valid NaN wins the max.
        isn = jnp.isnan(vals)
        clean = jnp.where(valid & ~isn, vals, -jnp.inf)
        m = jax.ops.segment_max(clean, gid, num_segments=num_segments)
        has_nan = (
            jax.ops.segment_sum(
                (valid & isn).astype(jnp.int32), gid, num_segments=num_segments
            )
            > 0
        )
        return jnp.where(has_nan, jnp.asarray(jnp.nan, vals.dtype), m)
    v = jnp.where(valid, vals, jnp.iinfo(vals.dtype).min)
    return jax.ops.segment_max(v, gid, num_segments=num_segments)


def _as_device(vals: np.ndarray) -> jnp.ndarray:
    if vals.dtype.kind == "b":
        return jnp.asarray(vals.astype(np.int64))
    if vals.dtype.kind == "u":
        # keep unsigned (x64 enabled): min/max order and modular sums stay
        # correct; the executor casts back to the output type
        return jnp.asarray(vals.astype(np.uint64))
    return jnp.asarray(vals)


# Below this row count the reductions run as plain numpy: the device
# segment ops pay a host->device->host round trip AND recompile per
# (row count, group count) pair, while the numpy twins (same null/NaN
# semantics, exact int64 sums via ufunc.at) finish in milliseconds on
# host-resident serve batches.
_HOST_AGG_MAX_ROWS = 1 << 20


def _host_sum_count(gid, vals, valid, num_segments):
    # accumulate in the same widened dtype the device path uses
    # (_as_device): unsigned -> uint64, bool/ints -> int64, floats as-is —
    # narrow-dtype accumulation would wrap (uint8 sums mod 256)
    if vals.dtype.kind == "u":
        acc = np.uint64
    elif vals.dtype.kind in "bi":
        acc = np.int64
    else:
        acc = vals.dtype
    v = np.where(valid, vals, np.zeros((), dtype=vals.dtype)).astype(
        acc, copy=False
    )
    sums = np.zeros(num_segments, dtype=acc)
    np.add.at(sums, gid, v)
    counts = np.bincount(gid[valid], minlength=num_segments)
    return sums, counts.astype(np.int64)


def _host_minmax(gid, vals, valid, num_segments, mode):
    if np.issubdtype(vals.dtype, np.floating):
        isn = np.isnan(vals)
        clean_mask = valid & ~isn
        fill = np.inf if mode == "min" else -np.inf
        clean = np.where(clean_mask, vals, fill)
        out = np.full(num_segments, fill, dtype=vals.dtype)
        (np.minimum if mode == "min" else np.maximum).at(out, gid, clean)
        has_clean = np.bincount(gid[clean_mask], minlength=num_segments) > 0
        if mode == "min":
            # NaN wins only when the group has no non-NaN valid values
            return np.where(has_clean, out, np.asarray(np.nan, vals.dtype))
        has_nan = np.bincount(gid[valid & isn], minlength=num_segments) > 0
        return np.where(has_nan, np.asarray(np.nan, vals.dtype), out)
    fill = (
        np.iinfo(vals.dtype).max if mode == "min" else np.iinfo(vals.dtype).min
    ) if vals.dtype.kind in "iu" else (True if mode == "min" else False)
    v = np.where(valid, vals, np.asarray(fill, dtype=vals.dtype))
    out = np.full(num_segments, fill, dtype=vals.dtype)
    (np.minimum if mode == "min" else np.maximum).at(out, gid, v)
    return out


def segment_sum_count(
    gid: np.ndarray,
    vals: np.ndarray,
    valid: Optional[np.ndarray],
    num_segments: int,
) -> Tuple[np.ndarray, np.ndarray]:
    valid = (
        np.ones(len(vals), dtype=bool) if valid is None else valid
    )
    if len(vals) <= _HOST_AGG_MAX_ROWS:
        return _host_sum_count(gid, vals, valid, num_segments)
    s, c = _seg_sum_count(
        jnp.asarray(gid), _as_device(vals), jnp.asarray(valid), num_segments
    )
    return np.asarray(s), np.asarray(c)


def segment_minmax(
    gid: np.ndarray,
    vals: np.ndarray,
    valid: Optional[np.ndarray],
    num_segments: int,
    mode: str,
) -> np.ndarray:
    valid = np.ones(len(vals), dtype=bool) if valid is None else valid
    if len(vals) <= _HOST_AGG_MAX_ROWS:
        return _host_minmax(gid, vals, valid, num_segments, mode)
    fn = _seg_min if mode == "min" else _seg_max
    out = fn(jnp.asarray(gid), _as_device(vals), jnp.asarray(valid), num_segments)
    return np.asarray(out)


def segment_count(
    gid: np.ndarray, valid: Optional[np.ndarray], n: int, num_segments: int
) -> np.ndarray:
    valid = np.ones(n, dtype=bool) if valid is None else valid
    if n <= _HOST_AGG_MAX_ROWS:
        return np.bincount(
            gid[valid], minlength=num_segments
        ).astype(np.int64)
    counts = jax.ops.segment_sum(
        jnp.asarray(valid).astype(jnp.int64),
        jnp.asarray(gid),
        num_segments=num_segments,
    )
    return np.asarray(counts)
