"""Device (XLA) kernels for the index data plane.

Everything in this package is jit-compilable JAX: bucket hashing
(:mod:`hyperspace_tpu.ops.hash`), packed-key sorting
(:mod:`hyperspace_tpu.ops.sort`), z-address bit interleaving
(:mod:`hyperspace_tpu.ops.zorder`) and bloom-filter build/probe
(:mod:`hyperspace_tpu.ops.bloom`). These replace the row-pipeline work that
the reference leaves to Spark executors (hash partitioning, sort-within-
bucket, sketch aggregation).

Dtype policy: hot kernels (hash, sort keys, z-address) run on 32-bit words
— TPU VPUs are 32-bit and int64 is emulated — so int64 key reps are split
into (lo, hi) uint32 planes at the host boundary. x64 is still enabled
globally because payload columns (int64 values, file ids) must round-trip
through device exchanges losslessly.

Shape policy: every host kernel entry point pads its row dimension up to
the next power of two before dispatch (:func:`pad_len`): bucket hashing
(``hash.bucket_ids_np``), all sort paths (``sort.lexsort_perm``, used by
``sort_permutation``/``ordering_permutation``/``zorder``), predicate
evaluation (``filter.device_filter_mask``), the per-bucket join width
(``execution/join_exec.side_arrays``) and the shuffle row dimension
(``parallel/shuffle.bucket_shuffle``). Under jit each distinct input shape
is a fresh XLA compile — on TPU a large sort alone costs tens of seconds
of compile — so row counts must never leak into compiled shapes. Padding
buys an O(log n)-sized shape universe: any two datasets within a 2x size
band share every kernel binary. Combined with the persistent compilation
cache (below), steady-state builds and queries never recompile.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

def _machine_cache_tag() -> str:
    """Short fingerprint of THIS machine's CPU feature set (plus arch).

    The persistent cache stores XLA:CPU AOT results compiled against the
    build machine's exact feature flags; loading an entry on a host with
    a different feature set makes ``cpu_aot_loader`` emit a wall of
    machine-feature-mismatch warnings per entry (and risks SIGILL).
    Shared cache dirs (home on NFS, baked images, heterogeneous fleets)
    hit this constantly — scoping the cache per machine fingerprint
    makes every entry loadable by construction. Same-hardware hosts
    still share (same flags -> same tag)."""
    import hashlib
    import platform

    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        feats = platform.processor() or platform.machine()

    return hashlib.sha256(
        (platform.machine() + "|" + feats).encode()
    ).hexdigest()[:12]


# Persistent XLA compilation cache. TPU sort kernels take 40-80s to
# compile while executing in milliseconds; caching them on disk makes every
# process after the first pay only dispatch cost. Opt out (or relocate)
# via HYPERSPACE_JAX_CACHE_DIR; the exact value "off" disables (a
# directory literally named off/OFF still works as a path). The cache is
# scoped per machine fingerprint (see _machine_cache_tag) so entries are
# always feature-compatible with the loading host.
_cache_dir = os.environ.get(
    "HYPERSPACE_JAX_CACHE_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "hyperspace_tpu", "jax"),
)
if _cache_dir != "off":
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(_cache_dir, "m-" + _machine_cache_tag()),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    # older jax without the knobs (exception type varies by version):
    # in-memory cache only
    except Exception:  # hslint: disable=HS402
        pass


def pad_len(n: int, minimum: int = 8) -> int:
    """Next power of two >= max(n, minimum) — the padded row count every
    kernel dispatches at (see module docstring)."""
    n = max(n, minimum)
    return 1 << (n - 1).bit_length()
