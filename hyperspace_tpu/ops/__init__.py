"""Device (XLA/Pallas) kernels for the index data plane.

Everything in this package is jit-compilable JAX: bucket hashing
(:mod:`hyperspace_tpu.ops.hash`), packed-key sorting
(:mod:`hyperspace_tpu.ops.sort`), z-address bit interleaving
(:mod:`hyperspace_tpu.ops.zorder`) and bloom-filter build/probe
(:mod:`hyperspace_tpu.ops.bloom`). These replace the row-pipeline work that
the reference leaves to Spark executors (hash partitioning, sort-within-
bucket, sketch aggregation).

Dtype policy: hot kernels (hash, sort keys, z-address) run on 32-bit words
— TPU VPUs are 32-bit and int64 is emulated — so int64 key reps are split
into (lo, hi) uint32 planes at the host boundary. x64 is still enabled
globally because payload columns (int64 values, file ids) must round-trip
through device exchanges losslessly.
"""

import jax

jax.config.update("jax_enable_x64", True)
