"""Device bucket hashing — MurmurHash3 as an XLA kernel.

TPU-native replacement for Spark's hash-partitioning shuffle key
(``HashPartitioning``/``Murmur3Hash``) used by the covering-index build
(reference: ``index/covering/CoveringIndex.scala:58-61`` —
``repartition(numBuckets, indexedCols)``). Bucket assignment must be a pure
function of the key *values* so that build, incremental refresh and
query-time Hybrid Scan shuffles all agree on the layout
(``CoveringIndexRuleUtils.scala:357-417`` re-shuffles appended data with the
same partitioning).

The kernel is pure 32-bit arithmetic (TPU VPU-native): each int64 key rep
(see ``io/columnar.py``) is split into lo/hi uint32 words and hashed as the
corresponding 8 little-endian bytes; multiple key columns extend the block
stream. The result equals host ``murmur3_32_bytes(b"".join(rep_i 8-byte
LE))`` — tested against the scalar reference implementation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

import hyperspace_tpu.ops  # noqa: F401  (enables x64)
from hyperspace_tpu.ops import pad_len

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    k1 = k1 * _C1
    k1 = _rotl32(k1, 15)
    return k1 * _C2


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return h1 * np.uint32(5) + np.uint32(0xE6546B64)


def _fmix(h1, length):
    h1 = h1 ^ length
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * np.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * np.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def split_words_np(key_reps: np.ndarray) -> np.ndarray:
    """Host split: [k, n] int64 -> [2k, n] uint32 (lo, hi interleaved)."""
    u = np.ascontiguousarray(key_reps).view(np.uint64)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (u >> np.uint64(32)).astype(np.uint32)
    return np.stack([w for lohi in zip(lo, hi) for w in lohi])


def split_words(key_reps):
    """Device split: [k, n] int64 -> [2k, n] uint32 (lo, hi interleaved)."""
    u = key_reps.astype(jnp.uint64)
    lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
    return jnp.concatenate(
        [jnp.stack([lo[i], hi[i]]) for i in range(key_reps.shape[0])]
    )


def hash_words(words, seed):
    """murmur3-32 over [2k, n] uint32 word blocks -> uint32 [n]."""
    h = jnp.broadcast_to(jnp.uint32(seed), words.shape[1:]).astype(jnp.uint32)
    for i in range(words.shape[0]):
        h = _mix_h1(h, _mix_k1(words[i]))
    return _fmix(h, jnp.uint32(4 * words.shape[0]))


def hash_columns(key_reps, seed: int = 42):
    """[num_keys, n] int64 key reps -> uint32 [n] (splits to words first)."""
    return hash_words(split_words(key_reps), seed)


@functools.partial(jax.jit, static_argnames=("num_buckets", "seed"))
def _bucket_ids_words(words, num_buckets: int, seed: int):
    return (hash_words(words, seed) % jnp.uint32(num_buckets)).astype(jnp.int32)


# Below this row count the hash runs as plain numpy: the mix functions
# are dtype-generic (np.uint32 arithmetic works identically on numpy and
# jnp arrays — bit-exact by construction), and a device dispatch costs a
# host->device->host round trip that dwarfs the arithmetic for
# HOST-RESIDENT inputs (measured ~64ms to hash ONE bucket-pruning
# literal, and — bench chip via tunnel, round 5 — 3.4s device vs 0.15s
# host at 4M rows: transfer dominates at every practical size). The
# device kernel's home is HBM-resident data on a sharded mesh
# (parallel/shuffle.py), not host-resident builds.
#
# FALLBACK DEFAULT: the effective threshold comes from the per-machine
# calibration probe (hyperspace_tpu/native/calibrate.py) when available;
# this constant applies when calibration is disabled (HS_CALIBRATE=0) or
# when a test overrides the module attribute (an override always wins).
_HOST_HASH_MAX_ROWS_DEFAULT = 1 << 26
_HOST_HASH_MAX_ROWS = _HOST_HASH_MAX_ROWS_DEFAULT

# At or above this row count the host hash uses the native single-pass
# murmur3 kernel (hyperspace_tpu/native); below it numpy's vectorized
# mixes are already microseconds. Fallback default; see above.
_NATIVE_HASH_MIN_ROWS_DEFAULT = 1 << 15
_NATIVE_HASH_MIN_ROWS = _NATIVE_HASH_MIN_ROWS_DEFAULT


def _host_hash_max_rows() -> int:
    if _HOST_HASH_MAX_ROWS != _HOST_HASH_MAX_ROWS_DEFAULT:
        return _HOST_HASH_MAX_ROWS  # explicit (test/ops) override wins
    from hyperspace_tpu.native import calibrate

    return calibrate.thresholds().host_hash_max_rows or _HOST_HASH_MAX_ROWS


def _native_hash_min_rows() -> int:
    if _NATIVE_HASH_MIN_ROWS != _NATIVE_HASH_MIN_ROWS_DEFAULT:
        return _NATIVE_HASH_MIN_ROWS
    from hyperspace_tpu.native import calibrate

    return (
        calibrate.thresholds().native_hash_min_rows or _NATIVE_HASH_MIN_ROWS
    )


def bucket_ids_host(
    key_reps: np.ndarray, num_buckets: int, seed: int = 42
) -> np.ndarray:
    """Pure-numpy bucket ids — the bit-exact host twin of the device
    kernel (same mix functions on np.uint32). Used for small inputs and
    for host-side pre-passes that must never touch the device."""
    n = key_reps.shape[1]
    if n == 0:
        return np.zeros(0, dtype=np.int32)
    if n >= _native_hash_min_rows():
        from hyperspace_tpu import native

        # one pass per row vs ~10 vectorized passes; bit-exact twin
        ids = native.bucket_ids_i64(
            key_reps.astype(np.int64, copy=False), num_buckets, seed
        )
        if ids is not None:
            return ids
    return bucket_ids_numpy(key_reps, num_buckets, seed)


def bucket_ids_numpy(
    key_reps: np.ndarray, num_buckets: int, seed: int = 42
) -> np.ndarray:
    """The pure-numpy murmur leg of :func:`bucket_ids_host`, never
    dispatching to the native kernel — also the reference the
    calibration probe (native/calibrate.py) times the native kernel
    against, so the probe always measures exactly the code that runs
    when the native kernel loses or is unavailable."""
    n = key_reps.shape[1]
    if n == 0:
        return np.zeros(0, dtype=np.int32)
    words = split_words_np(key_reps)
    with np.errstate(over="ignore"):
        h = np.full(n, np.uint32(seed))
        for i in range(words.shape[0]):
            h = _mix_h1(h, _mix_k1(words[i]))
        h = _fmix(h, np.uint32(4 * words.shape[0]))
    return (h % np.uint32(num_buckets)).astype(np.int32)


def bucket_ids_np(key_reps: np.ndarray, num_buckets: int, seed: int = 42) -> np.ndarray:
    """Host entry: [k, n] int64 key reps -> int32 bucket ids. Large inputs
    hash on device (padded to a power of two, ops/__init__ shape policy);
    small ones use the same arithmetic directly in numpy."""
    n = key_reps.shape[1]
    if n == 0:
        return np.zeros(0, dtype=np.int32)
    if n <= _host_hash_max_rows():
        return bucket_ids_host(key_reps, num_buckets, seed)
    words = split_words_np(key_reps)
    n_pad = pad_len(n)
    if n_pad != n:
        words = np.concatenate(
            [words, np.zeros((words.shape[0], n_pad - n), dtype=np.uint32)],
            axis=1,
        )
    out = np.asarray(_bucket_ids_words(jnp.asarray(words), num_buckets, seed))
    return out[:n]


# ---------------------------------------------------------------------------
# Pallas TPU kernel (HBM-resident regime)
# ---------------------------------------------------------------------------

# VPU tile for the Pallas grid: each grid step hashes a block of
# (_PALLAS_BLOCK_ROWS x 128 lanes) elements per word plane.
_PALLAS_BLOCK_ROWS = 8 * 64  # x 128 lanes = 64Ki elements per grid step
_PALLAS_BLOCK_N = _PALLAS_BLOCK_ROWS * 128


def bucket_ids_pallas(words, num_buckets: int, seed: int = 42):
    """Pallas twin of ``_bucket_ids_words`` for HBM-RESIDENT word planes.

    Same arithmetic as the XLA kernel (the ``_mix_*``/``_fmix`` helpers
    are dtype-generic), hand-tiled over the VPU in (sublane, lane) blocks:
    each grid step hashes a (2k, _PALLAS_BLOCK_ROWS, 128) block of the
    interleaved uint32 word planes. Input ``words`` is a device array
    [2k, n] with n a multiple of ``_PALLAS_BLOCK_N`` (callers pad; pad
    lanes produce garbage buckets that are sliced off). Measured A/B vs
    the XLA kernel in BASELINE.md — on host-resident data neither
    matters (transfer dominates; the numpy twin wins), so this kernel's
    home is mesh-sharded HBM-resident data. Falls back to interpreter
    mode off-TPU (tests run on CPU).
    """
    import jax.experimental.pallas as pl

    m, n = words.shape
    assert n % _PALLAS_BLOCK_N == 0, (n, _PALLAS_BLOCK_N)
    rows = n // 128
    w3 = words.reshape(m, rows, 128)

    def kernel(words_ref, out_ref):
        h = jnp.full(out_ref.shape, jnp.uint32(seed))
        for i in range(m):
            h = _mix_h1(h, _mix_k1(words_ref[i]))
        h = _fmix(h, jnp.uint32(4 * m))
        out_ref[...] = (h % jnp.uint32(num_buckets)).astype(jnp.int32)

    grid = (rows // _PALLAS_BLOCK_ROWS,)
    # trace under x64 DISABLED: the package-wide jax_enable_x64 makes the
    # BlockSpec index maps produce i64 grid indices, which this Mosaic
    # rejects ("failed to legalize 'func.return'" on (i64, i32)); the
    # kernel itself is pure uint32/int32
    try:
        x64_off = jax.enable_x64(False)
    except AttributeError:  # older jax: the experimental spelling
        from jax.experimental import enable_x64 as _enable_x64

        x64_off = _enable_x64(False)
    with x64_off:
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((m, _PALLAS_BLOCK_ROWS, 128), lambda i: (0, i, 0))
            ],
            out_specs=pl.BlockSpec(
                (_PALLAS_BLOCK_ROWS, 128), lambda i: (i, 0)
            ),
            out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.int32),
            interpret=jax.devices()[0].platform != "tpu",
        )(w3)
    return out.reshape(n)
