"""XLA-compiled columnar predicate evaluation (the device twin of
``plan/expressions.evaluate``).

This is the scan-side filter kernel of the serve path (SURVEY §7 Phase 2:
"XLA-compiled columnar filter kernel over index files"). The host prepares
device-friendly inputs per batch:

* numeric columns → their value arrays (+ validity);
* string columns → per-row dictionary *rank* arrays (order-preserving
  integers, host-computed O(unique) — see ``plan/expressions._StringRef``),
  with string literals lowered to ``(bisect_left, bisect_right)`` rank
  bounds. Every string predicate (=, <, IN, …) is thereby pure integer
  arithmetic on device.

The expression tree is lowered to a hashable *spec* (nested tuples) used as
the jit static argument, so each predicate shape compiles once; literals
and column arrays flow in as dynamic args (changing a literal or reading a
different file does not recompile).
"""

from __future__ import annotations

import functools
import math
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import hyperspace_tpu.ops  # noqa: F401  (enables x64)
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan import expressions as E


class Unsupported(HyperspaceException):
    """Expression not device-compilable; caller falls back to host eval."""


class _Prep:
    """Lowers an Expr over a given batch into (spec, args)."""

    def __init__(self, batch):
        self.batch = batch
        self.args: List[Any] = []
        self.row_slots: set = set()  # arg indices holding per-row arrays
        self._col_slots = {}

    def _arg(self, v, per_row: bool = False) -> int:
        self.args.append(v)
        if per_row:
            self.row_slots.add(len(self.args) - 1)
        return len(self.args) - 1

    def _col(self, name: str):
        """-> ("col", values_slot, valid_slot|-1, kind)"""
        if name in self._col_slots:
            return self._col_slots[name]
        col = self.batch.column(name)
        if col.kind == "string":
            ref = E._StringRef(col.codes, col.dictionary)
            vals = self._arg(ref.rank_values().astype(np.int64), per_row=True)
            valid = self._arg(ref.valid, per_row=True)
            spec = ("col", vals, valid, "string", name)
            self._col_slots[name] = (spec, ref)
            return self._col_slots[name]
        vals = self._arg(col.values, per_row=True)
        valid = (
            -1 if col.validity is None else self._arg(col.validity, per_row=True)
        )
        spec = ("col", vals, valid, "numeric", name)
        self._col_slots[name] = (spec, None)
        return self._col_slots[name]

    def lower(self, e: E.Expr):
        if isinstance(e, E.Lit):
            if e.value is None:
                return ("null",)
            if not isinstance(e.value, (bool, np.bool_)):
                raise Unsupported(f"Bare non-bool literal: {e!r}")
            return ("const", bool(e.value))
        if isinstance(e, (E.Eq, E.Ne, E.Lt, E.Le, E.Gt, E.Ge)):
            op = e.op
            left, right = e.left, e.right
            flipped = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}
            if isinstance(left, E.Lit) and not isinstance(right, E.Lit):
                left, right = right, left
                op = flipped[op]
            if isinstance(left, E.Col) and isinstance(right, E.Lit):
                if right.value is None:
                    return ("null",)
                cspec, ref = self._col(left.name)
                if ref is not None:  # string: literal -> rank bounds
                    lo, hi = ref.rank_bounds(str(right.value))
                    return (
                        "cmp_str",
                        op,
                        cspec,
                        self._arg(np.int64(lo)),
                        self._arg(np.int64(hi)),
                    )
                lit = E.lower_literal(
                    right.value, self.batch.column(left.name).arrow_type, op
                )
                if lit is None:
                    # unrepresentable literal: constant truth value but
                    # UNKNOWN on null rows — mirrors the host path's
                    # (vals, column-validity) exactly so NOT composes the
                    # same on both paths
                    return ("unrep", op == "!=", cspec)
                return ("cmp_lit", op, cspec, self._arg(np.asarray(lit)))
            if isinstance(left, E.Col) and isinstance(right, E.Col):
                lspec, lref = self._col(left.name)
                rspec, rref = self._col(right.name)
                if (lref is None) != (rref is None):
                    raise Unsupported(f"Mixed-type column comparison: {e!r}")
                if lref is not None:
                    # ranks are per-column orders; cross-column string
                    # comparison needs the host path
                    raise Unsupported(f"String col-col comparison: {e!r}")
                return ("cmp_col", op, lspec, rspec)
            raise Unsupported(f"Comparison operands: {e!r}")
        if isinstance(e, E.And):
            return ("and", self.lower(e.left), self.lower(e.right))
        if isinstance(e, E.Or):
            return ("or", self.lower(e.left), self.lower(e.right))
        if isinstance(e, E.Not):
            return ("not", self.lower(e.child))
        if isinstance(e, E.IsNull):
            if not isinstance(e.child, E.Col):
                raise Unsupported(f"IS NULL on non-column: {e!r}")
            cspec, _ref = self._col(e.child.name)
            return ("isnull", cspec)
        if isinstance(e, E.In):
            if not isinstance(e.child, E.Col):
                raise Unsupported(f"IN on non-column: {e!r}")
            cspec, ref = self._col(e.child.name)
            # a NULL in the list makes non-matching rows UNKNOWN (host twin)
            has_null = any(v is None for v in e.values)
            vals = [v for v in e.values if v is not None]
            if not vals:
                if has_null:
                    # x IN (NULL): unknown on every row (host: vals=0,
                    # known=0) — exactly the null-literal spec
                    return ("null",)
                # x IN () is never true (matches the host path's all-False)
                return ("const", False)
            if ref is not None:
                ranks = []
                for v in vals:
                    if not isinstance(v, str):
                        continue  # non-string literal never matches
                    lo, hi = ref.rank_bounds(v)
                    if hi > lo:
                        ranks.append(lo)
                arr = np.array(sorted(ranks) or [-1], dtype=np.int64)
            else:
                # shared lowering with the host path (E.lower_in_literals)
                # so device and host IN agree on temporal/typed literals
                lits = E.lower_in_literals(
                    vals, self.batch.column(e.child.name).arrow_type
                )
                if not lits:
                    # NULL marker survives even when every non-null
                    # literal lowered away (host twin: unknown rows)
                    return ("null",) if has_null else ("const", False)
                arr = np.sort(np.array(lits))
                if arr.dtype.kind not in "iuf":
                    raise Unsupported(f"IN literal set: {e!r}")
            return ("in", cspec, self._arg(arr), has_null)
        raise Unsupported(f"Expression not device-compilable: {e!r}")


def _eval_spec(spec, args, n):
    """Recursive jnp evaluation -> (values[bool n], valid[bool n])."""
    kind = spec[0]
    t = lambda: jnp.ones(n, dtype=bool)
    if kind == "null":
        return jnp.zeros(n, bool), jnp.zeros(n, bool)
    if kind == "const":
        return jnp.full(n, spec[1]), t()
    if kind in ("cmp_lit", "cmp_col", "cmp_str"):
        op = spec[1]
        _c, vslot, valslot, _k, _name = spec[2]
        v = args[vslot]
        valid = t() if valslot == -1 else args[valslot]
        if kind == "cmp_lit":
            lit = args[spec[3]]
            vals = _apply_cmp(op, v, lit)
        elif kind == "cmp_str":
            lo, hi = args[spec[3]], args[spec[4]]
            vals = {
                "=": (v >= lo) & (v < hi),
                "!=": ~((v >= lo) & (v < hi)),
                "<": v < lo,
                "<=": v < hi,
                ">": v >= hi,
                ">=": v >= lo,
            }[op]
        else:
            _c2, vslot2, valslot2, _k2, _n2 = spec[3]
            v2 = args[vslot2]
            valid = valid & (t() if valslot2 == -1 else args[valslot2])
            vals = _apply_cmp(op, v, v2)
        return vals, valid
    if kind == "and":
        lv, lk = _eval_spec(spec[1], args, n)
        rv, rk = _eval_spec(spec[2], args, n)
        vals = lv & rv & lk & rk
        known = (lk & rk) | (lk & ~lv) | (rk & ~rv)
        return vals, known
    if kind == "or":
        lv, lk = _eval_spec(spec[1], args, n)
        rv, rk = _eval_spec(spec[2], args, n)
        vals = (lv & lk) | (rv & rk)
        known = (lk & rk) | (lk & lv) | (rk & rv)
        return vals, known
    if kind == "not":
        v, k = _eval_spec(spec[1], args, n)
        return ~v, k
    if kind == "isnull":
        _c, vslot, valslot, _k, _name = spec[1]
        valid = t() if valslot == -1 else args[valslot]
        return ~valid, t()
    if kind == "unrep":
        # constant truth value, unknown on null rows (host-path twin of
        # the unrepresentable-literal comparison)
        _c, vslot, valslot, _k, _name = spec[2]
        valid = t() if valslot == -1 else args[valslot]
        return jnp.full(n, spec[1]), valid
    if kind == "in":
        _c, vslot, valslot, _k, _name = spec[1]
        v = args[vslot]
        valid = t() if valslot == -1 else args[valslot]
        lits = args[spec[2]]
        # binary-search membership (SortedArrayLowerBound-style,
        # dataskipping/expressions/SortedArrayLowerBound.scala)
        pos = jnp.searchsorted(lits, v)
        pos = jnp.clip(pos, 0, lits.shape[0] - 1)
        vals = lits[pos] == v
        if len(spec) > 3 and spec[3]:  # NULL in the list: non-matches unknown
            valid = valid & vals
        return vals, valid
    raise HyperspaceException(f"Bad spec node: {spec!r}")


def _apply_cmp(op, a, b):
    return {
        "=": a == b,
        "!=": a != b,
        "<": a < b,
        "<=": a <= b,
        ">": a > b,
        ">=": a >= b,
    }[op]


@functools.partial(jax.jit, static_argnames=("spec", "n"))
def _run(spec, n, args: Tuple):
    vals, valid = _eval_spec(spec, list(args), n)
    return vals & valid


# ---------------------------------------------------------------------------
# Fused range mask (hs_range_mask; docs/range-serve.md)
# ---------------------------------------------------------------------------
#
# A conjunction of numeric col-vs-lit range/Eq conjuncts — the residual
# mask of the range serve plane after zone-map pruning — evaluates on the
# host as one fused compare-AND pass instead of ~2 numpy passes per
# conjunct plus the Kleene bookkeeping of the expression interpreter.
# Final-mask equivalence is exact: for a conjunction, the filter's final
# mask equals the AND of each conjunct's (values & valid) mask, and each
# supported conjunct's mask is a pair of bound comparisons ANDed with the
# column's validity. Anything outside that shape (strings, IN, OR, NOT,
# IS NULL, !=, unloggable literals) falls back to the interpreter
# unchanged.

# At or above this ROW count the fused mask dispatches to the native
# kernel; below it the numpy twin's vectorized passes win. FALLBACK
# DEFAULT: the effective threshold comes from the per-machine calibration
# probe (native/calibrate.py); an explicit module-attribute override wins.
_NATIVE_RANGE_MASK_MIN_ROWS_DEFAULT = 1 << 15
_NATIVE_RANGE_MASK_MIN_ROWS = _NATIVE_RANGE_MASK_MIN_ROWS_DEFAULT


def _native_range_mask_min_rows() -> int:
    if _NATIVE_RANGE_MASK_MIN_ROWS != _NATIVE_RANGE_MASK_MIN_ROWS_DEFAULT:
        return _NATIVE_RANGE_MASK_MIN_ROWS  # explicit (test/ops) override
    from hyperspace_tpu.native import calibrate

    return (
        calibrate.thresholds().native_range_mask_min_rows
        or _NATIVE_RANGE_MASK_MIN_ROWS
    )


class _BatchColTypes:
    """Lazy ``name -> (dtype_kind, arrow_type)`` view of a batch for
    :func:`lower_range_terms_typed` — only the columns the condition
    actually references are inspected (a wide covering index would
    otherwise pay an all-columns dict per filter serve)."""

    def __init__(self, batch):
        self._batch = batch

    def __contains__(self, name) -> bool:
        return name in self._batch.columns

    def __getitem__(self, name):
        col = self._batch.columns[name]
        return (
            "S" if col.kind == "string" else col.values.dtype.kind,
            col.arrow_type,
        )


def lower_range_terms(expr: E.Expr, batch):
    """[(name, lo, lo_strict, hi, hi_strict, empty)] when EVERY conjunct
    is a numeric col-vs-lit comparison in =,<,<=,>,>= with a literal the
    engine can compare (temporal literals lowered with the same op-aware
    snapping the interpreter uses), else None. ``empty`` marks a conjunct
    whose lowered literal can never match (all-False mask)."""
    return lower_range_terms_typed(expr, _BatchColTypes(batch))


def lower_range_terms_typed(expr: E.Expr, cols):
    """:func:`lower_range_terms` against a ``{name: (dtype_kind,
    arrow_type)}`` mapping instead of a materialized batch — the
    pre-read half the serve-pipeline compiler needs (the decoded numpy
    dtype kind is derivable from the arrow type before any file is
    opened; see ``pipeline_compiler._np_kind``). The batch-based wrapper
    above feeds it the actual decoded kinds, so the two can never
    disagree on a column the batch carries."""
    terms = []
    for cj in E.split_conjuncts(expr):
        norm = E.normalize_comparison(cj)
        if norm is None:
            return None
        op, name, lit = norm
        if op == "!=":
            return None
        if name not in cols:
            return None
        kind, arrow_type = cols[name]
        if kind == "S":
            return None
        if kind not in "if":
            return None  # uint/bool columns keep the interpreter path
        lv = E.lower_literal(lit, arrow_type, op)
        if lv is None:
            terms.append((name, None, False, None, False, True))
            continue
        if isinstance(lv, (np.integer, np.floating)):
            pass  # engine-lowered scalar, compares exactly
        elif isinstance(lv, bool):
            lv = int(lv)
        elif isinstance(lv, int):
            if kind == "i" and not (-(2**63) <= lv < 2**63):
                return None  # out-of-range python int: interpreter decides
        elif not isinstance(lv, float):
            return None  # non-numeric literal on a numeric column
        if op == "=":
            terms.append((name, lv, False, lv, False, False))
        elif op == "<":
            terms.append((name, None, False, lv, True, False))
        elif op == "<=":
            terms.append((name, None, False, lv, False, False))
        elif op == ">":
            terms.append((name, lv, True, None, False, False))
        else:  # >=
            terms.append((name, lv, False, None, False, False))
    if not terms or len(terms) > 16:
        return None
    return terms


def range_mask_numpy(batch, terms) -> np.ndarray:
    """The numpy twin of ``hs_range_mask``: per term the SAME comparison
    expressions the host interpreter runs (so dtype promotion, NaN and
    uint semantics can never diverge), ANDed into one mask."""
    n = batch.num_rows
    out = np.ones(n, dtype=bool)
    with np.errstate(invalid="ignore"):
        for name, lo, lo_strict, hi, hi_strict, empty in terms:
            col = batch.columns[name]
            if empty:
                vals = np.zeros(n, dtype=bool)
            else:
                v = col.values
                vals = np.ones(n, dtype=bool)
                if lo is not None:
                    vals &= (v > lo) if lo_strict else (v >= lo)
                if hi is not None:
                    vals &= (v < hi) if hi_strict else (v <= hi)
            if col.validity is not None:
                vals = vals & col.validity
            out &= vals
    return out


NEVER_MATCH = "never"


def native_range_bounds(terms, f64_flags):
    """Lower range-term bounds into the exact int64/float64 form the
    native kernels compare with — shared by ``hs_range_mask``,
    ``hs_fused_filter_select`` and ``hs_fused_filter_agg`` so the three
    can never disagree with the numpy twin on a bound.

    ``f64_flags``: per-term bool, True when the column is float64 (else
    an int64-view column). Returns ``(lo_i, hi_i, lo_f, hi_f, flags)``
    lists aligned with ``terms``, :data:`NEVER_MATCH` when some bound can
    never hold (all-False mask), or None when a bound is not exactly
    representable natively (the numpy twin must decide). Integer bounds
    given as floats tighten to the enclosing integers (exact on integer
    domains)."""
    lo_i, hi_i, lo_f, hi_f, flags = [], [], [], [], []
    for (name, lo, lo_strict, hi, hi_strict, empty), f64 in zip(
        terms, f64_flags
    ):
        if empty:
            return NEVER_MATCH

        def int_bound(b, is_lo):
            """(bound, strict) in exact int64, or None to bail."""
            nonlocal_strict = lo_strict if is_lo else hi_strict
            if isinstance(b, (np.integer,)):
                b = int(b)
            if isinstance(b, float) or isinstance(b, np.floating):
                fb = float(b)
                if math.isnan(fb):
                    return "never"
                if math.isinf(fb):
                    # -inf lo / +inf hi: unbounded; +inf lo / -inf hi:
                    # nothing can pass
                    if (fb > 0) == is_lo:
                        return "never"
                    return "unbounded"
                if abs(fb) >= 2.0**53:
                    # the interpreter/twin compare int64 values against a
                    # FLOAT bound by promoting the column to float64; an
                    # exact int64 compare diverges for values beyond
                    # 2^53, so the numpy twin must decide these
                    return None
                if fb != int(fb):
                    # v > 2.5 == v >= 3; v < 2.5 == v <= 2 on integers
                    return (
                        (math.ceil(fb), False)
                        if is_lo
                        else (math.floor(fb), False)
                    )
                b = int(fb)
            if not isinstance(b, int):
                return None
            if not (-(2**63) <= b < 2**63):
                return None
            return (b, nonlocal_strict)

        if f64:
            def f_bound(b):
                if isinstance(b, (int, np.integer)) and not isinstance(b, bool):
                    fb = np.float64(b)
                    if int(fb) != int(b):
                        return None  # not exactly representable: bail
                    return float(fb)
                return float(b)

            flo = f_bound(lo) if lo is not None else None
            fhi = f_bound(hi) if hi is not None else None
            if (lo is not None and flo is None) or (
                hi is not None and fhi is None
            ):
                return None
            lo_f.append(flo if flo is not None else 0.0)
            hi_f.append(fhi if fhi is not None else 0.0)
            lo_i.append(0)
            hi_i.append(0)
            flags.append(
                (lo is not None, hi is not None, lo_strict, hi_strict)
            )
        else:
            ilo = int_bound(lo, True) if lo is not None else "unbounded"
            ihi = int_bound(hi, False) if hi is not None else "unbounded"
            if ilo is None or ihi is None:
                return None
            if ilo == "never" or ihi == "never":
                return NEVER_MATCH
            has_lo = ilo != "unbounded"
            has_hi = ihi != "unbounded"
            lo_i.append(ilo[0] if has_lo else 0)
            hi_i.append(ihi[0] if has_hi else 0)
            lo_f.append(0.0)
            hi_f.append(0.0)
            flags.append(
                (
                    has_lo,
                    has_hi,
                    ilo[1] if has_lo else False,
                    ihi[1] if has_hi else False,
                )
            )
    return lo_i, hi_i, lo_f, hi_f, flags


def native_terms_for_batch(batch, terms):
    """The full native argument set for ``terms`` over ``batch``:
    ``(cols, valids, is_f64, lo_i, hi_i, lo_f, hi_f, flags)`` ready for
    ``native.range_mask_u8`` / ``native.fused_filter_select``, or
    :data:`NEVER_MATCH` (all-False), or None (numpy twin decides —
    non-8-byte/non-contiguous columns or unrepresentable bounds)."""
    cols = []
    valids = []
    is_f64 = []
    for name, _lo, _ls, _hi, _hs, _empty in terms:
        col = batch.columns[name]
        v = col.values
        if v.ndim != 1 or v.dtype.itemsize != 8 or not v.flags.c_contiguous:
            return None
        f64 = v.dtype.kind == "f"
        if f64 and v.dtype != np.float64:
            return None
        if not f64 and v.dtype.kind not in "iMm":
            return None
        is_f64.append(f64)
        cols.append(v if f64 else v.view(np.int64))
        valids.append(col.validity)
    bounds = native_range_bounds(terms, is_f64)
    if bounds is None or bounds == NEVER_MATCH:
        return bounds
    lo_i, hi_i, lo_f, hi_f, flags = bounds
    return cols, valids, is_f64, lo_i, hi_i, lo_f, hi_f, flags


def _native_range_mask(batch, terms) -> Optional[np.ndarray]:
    """Native dispatch of the fused mask: contiguous 8-byte numeric
    columns with exactly-representable bounds only — anything else
    returns None and the numpy twin runs."""
    n = batch.num_rows
    prep = native_terms_for_batch(batch, terms)
    if prep is None:
        return None
    if prep == NEVER_MATCH:
        return np.zeros(n, dtype=bool)
    from hyperspace_tpu import native

    return native.range_mask_u8(*prep, n)


def range_mask(batch, terms) -> np.ndarray:
    """Host dispatch of the fused range mask: the native single-pass
    kernel at or above the calibrated row crossover, else the numpy twin
    — identical output either way."""
    if batch.num_rows >= _native_range_mask_min_rows():
        out = _native_range_mask(batch, terms)
        if out is not None:
            return out
    return range_mask_numpy(batch, terms)


def fused_range_mask(expr: E.Expr, batch) -> Optional[np.ndarray]:
    """The executor's entry: the fused mask when the whole predicate
    lowers to numeric range terms, else None (interpreter path)."""
    if batch.num_rows == 0:
        return None
    terms = lower_range_terms(expr, batch)
    if terms is None:
        return None
    return range_mask(batch, terms)


def device_filter_mask(expr: E.Expr, batch) -> np.ndarray:
    """Evaluate a predicate on device; raises :class:`Unsupported` when the
    expression needs the host path (``plan/expressions.filter_mask``).

    Per-row args are padded to ``pad_len`` (ops/__init__ shape policy) so
    the kernel compiles once per (predicate shape, 2x size band); pad rows
    are sliced off the mask. Validity pads are False, so even spec nodes
    that read validity alone (isnull) can't leak pad rows into downstream
    consumers that might ignore the slice.
    """
    from hyperspace_tpu.ops import pad_len

    n = batch.num_rows
    if n == 0:
        return np.zeros(0, dtype=bool)
    p = _Prep(batch)
    spec = p.lower(expr)
    n_pad = pad_len(n)
    args = []
    for i, a in enumerate(p.args):
        a = np.asarray(a)
        if i in p.row_slots and n_pad != n:
            fill = np.zeros((n_pad - n,) + a.shape[1:], dtype=a.dtype)
            a = np.concatenate([a, fill])
        args.append(jnp.asarray(a))
    return np.asarray(_run(spec, n_pad, tuple(args)))[:n]
