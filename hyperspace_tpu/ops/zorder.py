"""Z-address computation — bit interleaving as an XLA kernel.

Reference: ``zordercovering/ZOrderField.scala:26-569`` (per-type bit
encoding of values into z-address bits) and ``ZOrderUDF.scala:32-100``
(row → z-address via a precomputed bit-index map). The reference computes
z-addresses row-wise in a Spark UDF; here the whole column pipeline is
vectorized 32-bit device arithmetic:

1. per column, an order-preserving uint64 encoding (sign-flip for ints,
   IEEE total-order trick for floats, dictionary ranks for strings);
2. min/max normalization onto ``bits_per_column`` bits (the reference's
   min/max-based ZOrderField encoding; percentile variant = quantile
   normalization, same shape);
3. bit interleaving across columns into a multi-word z-address, ordered
   lexicographically word-major.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import hyperspace_tpu.ops  # noqa: F401  (enables x64)


def order_u64_np(col) -> np.ndarray:
    """Order-preserving uint64 of a Column's values (host prep; O(n) but
    trivially vectorized; nulls sort first)."""
    if col.kind == "string":
        order = sorted(range(len(col.dictionary)), key=lambda i: col.dictionary[i])
        rank = np.empty(max(len(col.dictionary), 1), dtype=np.uint64)
        for r, i in enumerate(order):
            rank[i] = r + 1  # 0 reserved for null
        return np.where(
            col.codes < 0, np.uint64(0), rank[np.maximum(col.codes, 0)]
        )
    v = col.values
    if v.dtype.kind == "f":
        bits = v.astype(np.float64).view(np.uint64)
        sign = bits >> np.uint64(63)
        enc = np.where(
            sign == 1, ~bits, bits | np.uint64(1) << np.uint64(63)
        )
    elif v.dtype.kind == "b":
        enc = v.astype(np.uint64) + np.uint64(1)
    elif v.dtype.kind == "u":
        enc = v.astype(np.uint64)
    else:
        enc = (v.astype(np.int64) ^ np.int64(-(2**63))).view(np.uint64)
    if col.validity is not None:
        enc = np.where(col.validity, np.maximum(enc, np.uint64(1)), np.uint64(0))
    return enc


@functools.partial(jax.jit, static_argnames=("bits",))
def _normalize(enc_hi, enc_lo, mins_hi, mins_lo, ranges_f, bits: int):
    """Scale (hi,lo) 32-bit planes of order-encodings onto [0, 2^bits)."""
    # relative offset as float64 (exact enough: bits<=21 keeps us inside
    # the 52-bit mantissa)
    off = (enc_hi - mins_hi).astype(jnp.float64) * (2.0**32) + (
        enc_lo.astype(jnp.float64) - mins_lo.astype(jnp.float64)
    )
    scale = jnp.where(ranges_f > 0, ((2.0**bits) - 1) / ranges_f, 0.0)
    w = jnp.clip(off * scale, 0, (2.0**bits) - 1)
    return w.astype(jnp.uint32)


@functools.partial(jax.jit, static_argnames=("bits",))
def _interleave(words, bits: int):
    """[k, n] uint32 (each < 2^bits) -> [ceil(k*bits/32), n] uint32 planes,
    most-significant plane first; lexsort over planes == z-order."""
    k, n = words.shape
    total = k * bits
    nplanes = (total + 31) // 32
    planes = jnp.zeros((nplanes, n), dtype=jnp.uint32)
    # z-bit t (from most significant) = bit (bits-1 - t//k) of column t%k
    for t in range(total):
        src_col = t % k
        src_bit = bits - 1 - (t // k)
        bit = (words[src_col] >> np.uint32(src_bit)) & jnp.uint32(1)
        dst_plane = t // 32
        dst_bit = 31 - (t % 32)
        planes = planes.at[dst_plane].add(bit << np.uint32(dst_bit))
    return planes


def _quantile_words_np(
    enc: np.ndarray, bits: int, relative_error: float
) -> np.ndarray:
    """Rank-normalized words: each value maps to its (approximate)
    quantile bucket on ``bits`` bits — the skew-resistant alternative to
    min/max scaling (reference: the percentile-based ZOrderField variant,
    ZOrderField.scala:83+). A deterministic stride sample of size
    ~1/relative_error² bounds the rank estimation error; equal values
    always land in the same bucket (searchsorted is value-determined)."""
    n = len(enc)
    if n == 0:
        return np.zeros(0, dtype=np.uint32)
    top = np.float64((1 << bits) - 1)
    max_sample = max(int(1.0 / max(relative_error, 1e-4) ** 2), 1024)
    sample = enc if n <= max_sample else enc[:: max(1, n // max_sample)]
    s = np.sort(sample)
    pos = np.searchsorted(s, enc, side="right").astype(np.float64)
    return ((pos / max(len(s), 1)) * top).astype(np.uint32)


def z_order_permutation(
    columns: List,
    bits: int = 16,
    quantile: bool = False,
    relative_error: float = 0.01,
) -> np.ndarray:
    """Sort permutation by z-address over the given Columns
    (the build-side replacement for repartitionByRange on ``_zaddr``,
    ZOrderCoveringIndex.scala:97-154). ``quantile=True`` switches from
    min/max scaling to quantile-bucket encoding (skewed columns keep
    using all address bits instead of collapsing onto a few)."""
    from hyperspace_tpu.ops import pad_len

    encs = [order_u64_np(c) for c in columns]
    n = len(encs[0]) if encs else 0
    n_pad = pad_len(max(n, 1))
    if quantile:
        word_rows = [_quantile_words_np(e, bits, relative_error) for e in encs]
        if n_pad != n:
            # pad rows take the max word so they sort last (shape policy)
            fill = np.full(n_pad - n, np.uint32((1 << bits) - 1))
            word_rows = [np.concatenate([w, fill]) for w in word_rows]
        words = jnp.asarray(np.stack(word_rows))
    else:
        mins = [e.min() if len(e) else np.uint64(0) for e in encs]
        maxs = [e.max() if len(e) else np.uint64(0) for e in encs]
        if n_pad != n:
            # pad rows encode as the max z-address and sort last (shape
            # policy; lexsort_perm slices them off)
            encs = [
                np.concatenate(
                    [e, np.full(n_pad - n, np.uint64(0xFFFFFFFFFFFFFFFF))]
                )
                for e in encs
            ]
        enc_hi = np.stack([(e >> np.uint64(32)).astype(np.uint32) for e in encs])
        enc_lo = np.stack(
            [(e & np.uint64(0xFFFFFFFF)).astype(np.uint32) for e in encs]
        )
        mins_hi = np.array(
            [(m >> np.uint64(32)) for m in mins], dtype=np.uint32
        )[:, None]
        mins_lo = np.array(
            [(m & np.uint64(0xFFFFFFFF)) for m in mins], dtype=np.uint32
        )[:, None]
        ranges = np.array(
            [float(int(mx) - int(mn)) for mn, mx in zip(mins, maxs)],
            dtype=np.float64,
        )[:, None]
        words = _normalize(
            jnp.asarray(enc_hi),
            jnp.asarray(enc_lo),
            jnp.asarray(mins_hi),
            jnp.asarray(mins_lo),
            jnp.asarray(ranges),
            bits,
        )
    planes = _interleave(words, bits)
    from hyperspace_tpu.ops.sort import lexsort_perm

    return lexsort_perm(np.asarray(planes), n_valid=n)
