"""Z-address computation — bit interleaving as an XLA kernel.

Reference: ``zordercovering/ZOrderField.scala:26-569`` (per-type bit
encoding of values into z-address bits) and ``ZOrderUDF.scala:32-100``
(row → z-address via a precomputed bit-index map). The reference computes
z-addresses row-wise in a Spark UDF; here the whole column pipeline is
vectorized 32-bit device arithmetic:

1. per column, an order-preserving uint64 encoding (sign-flip for ints,
   IEEE total-order trick for floats, dictionary ranks for strings);
2. min/max normalization onto ``bits_per_column`` bits (the reference's
   min/max-based ZOrderField encoding; percentile variant = quantile
   normalization, same shape);
3. bit interleaving across columns into a multi-word z-address, ordered
   lexicographically word-major.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import hyperspace_tpu.ops  # noqa: F401  (enables x64)


def order_u64_np(col) -> np.ndarray:
    """Order-preserving uint64 of a Column's values (host prep; O(n) but
    trivially vectorized; nulls sort first)."""
    if col.kind == "string":
        order = sorted(range(len(col.dictionary)), key=lambda i: col.dictionary[i])
        rank = np.empty(max(len(col.dictionary), 1), dtype=np.uint64)
        for r, i in enumerate(order):
            rank[i] = r + 1  # 0 reserved for null
        return np.where(
            col.codes < 0, np.uint64(0), rank[np.maximum(col.codes, 0)]
        )
    v = col.values
    if v.dtype.kind == "f":
        bits = v.astype(np.float64).view(np.uint64)
        sign = bits >> np.uint64(63)
        enc = np.where(
            sign == 1, ~bits, bits | np.uint64(1) << np.uint64(63)
        )
    elif v.dtype.kind == "b":
        enc = v.astype(np.uint64) + np.uint64(1)
    elif v.dtype.kind == "u":
        enc = v.astype(np.uint64)
    else:
        enc = (v.astype(np.int64) ^ np.int64(-(2**63))).view(np.uint64)
    if col.validity is not None:
        enc = np.where(col.validity, np.maximum(enc, np.uint64(1)), np.uint64(0))
    return enc


@functools.partial(jax.jit, static_argnames=("bits",))
def _interleave(words, bits: int):
    """[k, n] uint32 (each < 2^bits) -> [ceil(k*bits/32), n] uint32 planes,
    most-significant plane first; lexsort over planes == z-order."""
    k, n = words.shape
    total = k * bits
    nplanes = (total + 31) // 32
    planes = jnp.zeros((nplanes, n), dtype=jnp.uint32)
    # z-bit t (from most significant) = bit (bits-1 - t//k) of column t%k
    for t in range(total):
        src_col = t % k
        src_bit = bits - 1 - (t // k)
        bit = (words[src_col] >> np.uint32(src_bit)) & jnp.uint32(1)
        dst_plane = t // 32
        dst_bit = 31 - (t % 32)
        planes = planes.at[dst_plane].add(bit << np.uint32(dst_bit))
    return planes


class ZOrderEncoder:
    """FIXED per-column encoding spec -> z-address planes.

    Freezing the spec and making plane computation a pure function of it
    is what lets the streamed z-order build work: every wave, the spill
    partitioner and the per-partition merge sort all encode IDENTICALLY,
    so local sorted order equals global order. Spec kinds per column:

    * ``("range", min_u64, max_u64)`` — min/max scaling of the numeric
      order encoding;
    * ``("quantile", sorted_bounds)`` — rank via binary search over
      sampled boundaries (skew-resistant);
    * ``("dict", sorted_strings)`` — GLOBAL lexicographic rank for string
      columns. Batch-local dictionary ranks are NOT stable across waves,
      so string encoding must always go through a frozen global
      dictionary (rank normalization doubles as quantile normalization).
    """

    def __init__(self, bits: int, specs: List):
        self.bits = bits
        self.specs = specs

    # -- construction -------------------------------------------------------
    @staticmethod
    def fit(
        columns: List, bits: int, quantile: bool, relative_error: float
    ):
        """(encoder, per-column encodings) from in-memory Columns — the
        encodings are returned so the caller never encodes twice."""
        specs = []
        encs = []
        for col in columns:
            if col.kind == "string":
                spec = ("dict", sorted(set(col.dictionary)))
                specs.append(spec)
                encs.append(_dict_encode(col, spec[1]))
                continue
            e = order_u64_np(col)
            encs.append(e)
            if quantile:
                max_sample = max(
                    int(1.0 / max(relative_error, 1e-4) ** 2), 1024
                )
                sample = (
                    e if len(e) <= max_sample else e[:: max(1, len(e) // max_sample)]
                )
                specs.append(("quantile", np.sort(sample)))
            else:
                specs.append(
                    (
                        "range",
                        e.min() if len(e) else np.uint64(0),
                        e.max() if len(e) else np.uint64(0),
                    )
                )
        return ZOrderEncoder(bits, specs), encs

    # -- encoding -----------------------------------------------------------
    def encode(self, col, j: int) -> np.ndarray:
        """Per-row uint64 order encoding of a Column under spec j."""
        spec = self.specs[j]
        if spec[0] == "dict":
            return _dict_encode(col, spec[1])
        return order_u64_np(col)

    def _words(self, enc: np.ndarray, spec) -> np.ndarray:
        bits = self.bits
        top = (1 << bits) - 1
        if spec[0] == "quantile":
            bounds = spec[1]
            pos = np.searchsorted(bounds, enc, side="right").astype(np.float64)
            return ((pos / max(len(bounds), 1)) * np.float64(top)).astype(
                np.uint32
            )
        if spec[0] == "dict":
            # global ranks in [0, len]: plain range scaling over the rank
            # space (rank IS the quantile of the unique-value distribution)
            mn, mx = np.uint64(0), np.uint64(len(spec[1]))
        else:
            _tag, mn, mx = spec
        # min/max scaling on host (per-wave word computation is O(n)
        # elementwise; device dispatch pays transfers)
        off = (enc - mn).astype(np.float64)
        rng = float(int(mx) - int(mn))
        scale = ((2.0**bits) - 1) / rng if rng > 0 else 0.0
        return np.clip(off * scale, 0, top).astype(np.uint32)

    def planes_from_encodings(self, encs: List[np.ndarray]) -> np.ndarray:
        """[nplanes, n] uint32 planes (most-significant first) from
        per-column encodings produced by :meth:`encode`."""
        from hyperspace_tpu.ops import pad_len

        n = len(encs[0]) if encs else 0
        words = np.stack(
            [self._words(e, s) for e, s in zip(encs, self.specs)]
        ) if encs else np.zeros((0, 0), dtype=np.uint32)
        n_pad = pad_len(max(n, 1))
        if n_pad != n:
            fill = np.full(
                (words.shape[0], n_pad - n), np.uint32((1 << self.bits) - 1)
            )
            words = np.concatenate([words, fill], axis=1)
        planes = np.asarray(_interleave(jnp.asarray(words), self.bits))
        return planes[:, :n]

    def planes(self, columns: List) -> np.ndarray:
        return self.planes_from_encodings(
            [self.encode(c, j) for j, c in enumerate(columns)]
        )


def _dict_encode(col, sorted_global: List[str]) -> np.ndarray:
    """uint64 global lexicographic rank (+1; 0 = null) of a string
    Column's values under a frozen sorted dictionary."""
    local = col.dictionary
    rank_of = np.searchsorted(np.array(sorted_global, dtype=object), local)
    lut = np.asarray(rank_of, dtype=np.uint64) + np.uint64(1)
    if len(lut) == 0:
        lut = np.zeros(1, dtype=np.uint64)
    enc = lut[np.maximum(col.codes, 0)]
    return np.where(col.codes < 0, np.uint64(0), enc)


def z_order_permutation(
    columns: List,
    bits: int = 16,
    quantile: bool = False,
    relative_error: float = 0.01,
) -> np.ndarray:
    """Sort permutation by z-address over the given Columns
    (the build-side replacement for repartitionByRange on ``_zaddr``,
    ZOrderCoveringIndex.scala:97-154). ``quantile=True`` switches from
    min/max scaling to quantile-bucket encoding (skewed columns keep
    using all address bits instead of collapsing onto a few)."""
    from hyperspace_tpu.ops.sort import lexsort_perm

    enc, encs = ZOrderEncoder.fit(columns, bits, quantile, relative_error)
    return lexsort_perm(enc.planes_from_encodings(encs))
