"""Z-address computation — bit interleaving as an XLA kernel.

Reference: ``zordercovering/ZOrderField.scala:26-569`` (per-type bit
encoding of values into z-address bits) and ``ZOrderUDF.scala:32-100``
(row → z-address via a precomputed bit-index map). The reference computes
z-addresses row-wise in a Spark UDF; here the whole column pipeline is
vectorized 32-bit device arithmetic:

1. per column, an order-preserving uint64 encoding (sign-flip for ints,
   IEEE total-order trick for floats, dictionary ranks for strings);
2. min/max normalization onto ``bits_per_column`` bits (the reference's
   min/max-based ZOrderField encoding; percentile variant = quantile
   normalization, same shape);
3. bit interleaving across columns into a multi-word z-address, ordered
   lexicographically word-major.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import hyperspace_tpu.ops  # noqa: F401  (enables x64)


def order_u64_np(col) -> np.ndarray:
    """Order-preserving uint64 of a Column's values (host prep; O(n) but
    trivially vectorized; nulls sort first)."""
    if col.kind == "string":
        order = sorted(range(len(col.dictionary)), key=lambda i: col.dictionary[i])
        rank = np.empty(max(len(col.dictionary), 1), dtype=np.uint64)
        for r, i in enumerate(order):
            rank[i] = r + 1  # 0 reserved for null
        return np.where(
            col.codes < 0, np.uint64(0), rank[np.maximum(col.codes, 0)]
        )
    v = col.values
    if v.dtype.kind == "f":
        bits = v.astype(np.float64).view(np.uint64)
        sign = bits >> np.uint64(63)
        enc = np.where(
            sign == 1, ~bits, bits | np.uint64(1) << np.uint64(63)
        )
    elif v.dtype.kind == "b":
        enc = v.astype(np.uint64) + np.uint64(1)
    elif v.dtype.kind == "u":
        enc = v.astype(np.uint64)
    else:
        enc = (v.astype(np.int64) ^ np.int64(-(2**63))).view(np.uint64)
    if col.validity is not None:
        enc = np.where(col.validity, np.maximum(enc, np.uint64(1)), np.uint64(0))
    return enc


@functools.partial(jax.jit, static_argnames=("bits",))
def _interleave(words, bits: int):
    """[k, n] uint32 (each < 2^bits) -> [ceil(k*bits/32), n] uint32 planes,
    most-significant plane first; lexsort over planes == z-order."""
    k, n = words.shape
    total = k * bits
    nplanes = (total + 31) // 32
    planes = jnp.zeros((nplanes, n), dtype=jnp.uint32)
    # z-bit t (from most significant) = bit (bits-1 - t//k) of column t%k
    for t in range(total):
        src_col = t % k
        src_bit = bits - 1 - (t // k)
        bit = (words[src_col] >> np.uint32(src_bit)) & jnp.uint32(1)
        dst_plane = t // 32
        dst_bit = 31 - (t % 32)
        planes = planes.at[dst_plane].add(bit << np.uint32(dst_bit))
    return planes


class ZOrderEncoder:
    """FIXED per-column encoding spec -> z-address planes.

    Freezing the spec and making plane computation a pure function of it
    is what lets the streamed z-order build work: every wave, the spill
    partitioner and the per-partition merge sort all encode IDENTICALLY,
    so local sorted order equals global order. Spec kinds per column:

    * ``("range", min_u64, max_u64)`` — min/max scaling of the numeric
      order encoding;
    * ``("quantile", sorted_bounds)`` — rank via binary search over
      sampled boundaries (skew-resistant);
    * ``("dict", sorted_strings)`` — GLOBAL lexicographic rank for string
      columns. Batch-local dictionary ranks are NOT stable across waves,
      so string encoding must always go through a frozen global
      dictionary (rank normalization doubles as quantile normalization).
    """

    def __init__(self, bits: int, specs: List):
        self.bits = bits
        self.specs = specs

    # -- construction -------------------------------------------------------
    @staticmethod
    def fit(
        columns: List, bits: int, quantile: bool, relative_error: float
    ):
        """(encoder, per-column encodings) from in-memory Columns — the
        encodings are returned so the caller never encodes twice."""
        specs = []
        encs = []
        for col in columns:
            if col.kind == "string":
                spec = ("dict", sorted(set(col.dictionary)))
                specs.append(spec)
                encs.append(_dict_encode(col, spec[1]))
                continue
            e = order_u64_np(col)
            encs.append(e)
            if quantile:
                max_sample = max(
                    int(1.0 / max(relative_error, 1e-4) ** 2), 1024
                )
                sample = (
                    e if len(e) <= max_sample else e[:: max(1, len(e) // max_sample)]
                )
                specs.append(("quantile", np.sort(sample)))
            else:
                specs.append(
                    (
                        "range",
                        e.min() if len(e) else np.uint64(0),
                        e.max() if len(e) else np.uint64(0),
                    )
                )
        return ZOrderEncoder(bits, specs), encs

    # -- encoding -----------------------------------------------------------
    def encode(self, col, j: int) -> np.ndarray:
        """Per-row uint64 order encoding of a Column under spec j."""
        spec = self.specs[j]
        if spec[0] == "dict":
            return _dict_encode(col, spec[1])
        return order_u64_np(col)

    def _words(self, enc: np.ndarray, spec) -> np.ndarray:
        bits = self.bits
        top = (1 << bits) - 1
        if spec[0] == "quantile":
            bounds = spec[1]
            pos = np.searchsorted(bounds, enc, side="right").astype(np.float64)
            return ((pos / max(len(bounds), 1)) * np.float64(top)).astype(
                np.uint32
            )
        if spec[0] == "dict":
            # global ranks in [0, len]: plain range scaling over the rank
            # space (rank IS the quantile of the unique-value distribution)
            mn, mx = np.uint64(0), np.uint64(len(spec[1]))
        else:
            _tag, mn, mx = spec
        # min/max scaling on host (per-wave word computation is O(n)
        # elementwise; device dispatch pays transfers)
        off = (enc - mn).astype(np.float64)
        rng = float(int(mx) - int(mn))
        scale = ((2.0**bits) - 1) / rng if rng > 0 else 0.0
        return np.clip(off * scale, 0, top).astype(np.uint32)

    def planes_from_encodings(self, encs: List[np.ndarray]) -> np.ndarray:
        """[nplanes, n] uint32 planes (most-significant first) from
        per-column encodings produced by :meth:`encode`."""
        from hyperspace_tpu.ops import pad_len

        n = len(encs[0]) if encs else 0
        words = np.stack(
            [self._words(e, s) for e, s in zip(encs, self.specs)]
        ) if encs else np.zeros((0, 0), dtype=np.uint32)
        n_pad = pad_len(max(n, 1))
        if n_pad != n:
            fill = np.full(
                (words.shape[0], n_pad - n), np.uint32((1 << self.bits) - 1)
            )
            words = np.concatenate([words, fill], axis=1)
        planes = np.asarray(_interleave(jnp.asarray(words), self.bits))
        return planes[:, :n]

    def planes(self, columns: List) -> np.ndarray:
        return self.planes_from_encodings(
            [self.encode(c, j) for j, c in enumerate(columns)]
        )


def _dict_encode(col, sorted_global: List[str]) -> np.ndarray:
    """uint64 global lexicographic rank (+1; 0 = null) of a string
    Column's values under a frozen sorted dictionary."""
    local = col.dictionary
    rank_of = np.searchsorted(np.array(sorted_global, dtype=object), local)
    lut = np.asarray(rank_of, dtype=np.uint64) + np.uint64(1)
    if len(lut) == 0:
        lut = np.zeros(1, dtype=np.uint64)
    enc = lut[np.maximum(col.codes, 0)]
    return np.where(col.codes < 0, np.uint64(0), enc)


# ---------------------------------------------------------------------------
# Z-address range decomposition (serve-side pruning; docs/range-serve.md)
# ---------------------------------------------------------------------------
#
# A z-laid-out index file is a contiguous run of the z-sorted order, so its
# rows span a narrow interval of z-addresses even when each COLUMN's
# per-file min/max is wide. Pruning therefore works in z-space: the query
# box (per-column word intervals under the file set's frozen encoder spec)
# decomposes into a small set of z-address keep-ranges, and a file/row
# group whose captured z-span misses every range cannot hold a matching
# row. Per-column min/max alone cannot reconstruct the spans (the interval
# [z(mins), z(maxs)] always intersects the box whenever every column
# overlaps it), which is why capture (indexes/zonemaps.py) records the
# actual spans at build time and the serve path falls back to per-column
# pruning when they are absent.


def order_u64_scalar(value, kind: str) -> int:
    """Order-preserving uint64 of ONE engine-domain value — the scalar
    twin of :func:`order_u64_np` (same branches, same bit tricks) for
    encoding query-box bounds. ``kind`` is the numpy dtype kind of the
    column's storage ("f"/"b"/"u"/else-int). ``value`` must already be
    in the column's storage domain — callers convert non-integral or
    out-of-range bounds outward (floor/ceil, ±inf → unbounded side)
    before encoding."""
    if kind == "f":
        bits = int(np.float64(value).view(np.uint64))
        if bits >> 63:
            return (~bits) & 0xFFFFFFFFFFFFFFFF
        return bits | (1 << 63)
    if kind == "b":
        return int(bool(value)) + 1
    v = int(value)
    if kind == "u":
        return v & 0xFFFFFFFFFFFFFFFF
    return (v ^ -(1 << 63)) & 0xFFFFFFFFFFFFFFFF


def spec_word_bounds(spec, enc_lo: int, enc_hi: int, bits: int):
    """[word_lo, word_hi] of an encoded-value interval under one frozen
    spec — the scalar twin of :meth:`ZOrderEncoder._words`, rounded
    OUTWARD (floor the low end, ceil the high end) so the word box is a
    superset of the value box. Only "range" and "dict" specs appear in
    captured zone-map metadata; quantile specs abstain (None)."""
    top = (1 << bits) - 1
    if spec[0] == "dict":
        mn, mx = 0, len(spec[1])
    elif spec[0] == "range":
        mn, mx = int(spec[1]), int(spec[2])
    else:
        return None
    rng = mx - mn
    if rng <= 0:
        return 0, top
    scale = ((2.0**bits) - 1) / float(rng)

    def word(enc, up):
        off = float(max(min(enc, mx), mn) - mn) * scale
        w = int(np.ceil(off)) if up else int(np.floor(off))
        return max(0, min(top, w))

    return word(enc_lo, False), word(enc_hi, True)


def z_box_ranges(word_lo, word_hi, bits: int, max_ranges: int = 64):
    """Decompose a per-column word box into z-address keep-ranges.

    Returns a sorted list of inclusive ``(z_lo, z_hi)`` python-int ranges
    (in k*bits-bit z-space, MSB = column 0's top bit — the
    :func:`_interleave` layout) whose union COVERS every z-address inside
    the box; a bounded recursion emits partially-covered cells whole when
    the budget runs out, so the union may over-cover (superset-safe) but
    never under-covers. Standard prefix-tree (BIGMIN-family) walk: a cell
    disjoint from the box in any column is dropped, a fully-contained
    cell emits its whole z-interval, anything else splits on the next
    z-bit."""
    k = len(word_lo)
    total = k * bits
    out = []
    budget = [max(4, int(max_ranges)) * 4]

    def rec(depth, zpref, col_pref):
        nfixed = [depth // k + (1 if j < depth % k else 0) for j in range(k)]
        for j in range(k):
            free = bits - nfixed[j]
            clo = col_pref[j] << free
            chi = clo + (1 << free) - 1
            if chi < word_lo[j] or clo > word_hi[j]:
                return
        inside = True
        for j in range(k):
            free = bits - nfixed[j]
            clo = col_pref[j] << free
            chi = clo + (1 << free) - 1
            if clo < word_lo[j] or chi > word_hi[j]:
                inside = False
                break
        span = total - depth
        if inside or depth == total or budget[0] <= 0:
            lo = zpref << span
            out.append((lo, lo + (1 << span) - 1))
            return
        budget[0] -= 1
        j = depth % k
        for b in (0, 1):
            child = list(col_pref)
            child[j] = (col_pref[j] << 1) | b
            rec(depth + 1, (zpref << 1) | b, child)

    rec(0, 0, [0] * k)
    out.sort()
    merged = []
    for lo, hi in out:
        if merged and lo <= merged[-1][1] + 1:
            merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
        else:
            merged.append((lo, hi))
    return merged


def planes_z_minmax(planes: np.ndarray, start: int, end: int):
    """(z_lo, z_hi) python ints of rows [start, end) of ``planes``
    ([nplanes, n] uint32, most-significant plane first), in PACKED
    (32*nplanes-bit) z-space — the capture-side reader of per-row-group
    z-spans. None for an empty slice. Single-plane layouts (k*bits ≤ 32,
    the common 1-2 column case) reduce to a vectorized min/max; wider
    addresses pay one lexsort of the slice."""
    sub = planes[:, start:end]
    n = sub.shape[1]
    if n == 0:
        return None

    def pack(col) -> int:
        z = 0
        for w in col:
            z = (z << 32) | int(w)
        return z

    if sub.shape[0] == 1:
        return int(sub[0].min()), int(sub[0].max())
    order = np.lexsort(sub[::-1])
    return pack(sub[:, order[0]]), pack(sub[:, order[-1]])


def pack_box_ranges(ranges, bits: int, k: int, nplanes: int):
    """Shift keep-ranges from k*bits-bit z-space into the PACKED
    32*nplanes-bit space :func:`planes_z_minmax` reports spans in (the
    last plane's low bits are zero padding)."""
    pad = 32 * nplanes - k * bits
    if pad <= 0:
        return list(ranges)
    return [
        ((lo << pad), ((hi << pad) | ((1 << pad) - 1))) for lo, hi in ranges
    ]


def z_order_permutation(
    columns: List,
    bits: int = 16,
    quantile: bool = False,
    relative_error: float = 0.01,
) -> np.ndarray:
    """Sort permutation by z-address over the given Columns
    (the build-side replacement for repartitionByRange on ``_zaddr``,
    ZOrderCoveringIndex.scala:97-154). ``quantile=True`` switches from
    min/max scaling to quantile-bucket encoding (skewed columns keep
    using all address bits instead of collapsing onto a few)."""
    from hyperspace_tpu.ops.sort import lexsort_perm

    enc, encs = ZOrderEncoder.fit(columns, bits, quantile, relative_error)
    return lexsort_perm(enc.planes_from_encodings(encs))
