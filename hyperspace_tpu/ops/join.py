"""Device merge-join kernel for co-bucketed index scans.

The payoff of the JoinIndexRule (reference
``covering/JoinIndexRule.scala:619-634``): both sides are bucketed by the
join keys, so the join runs per bucket pair with NO shuffle. Here the
per-bucket matching — combine-rep, argsort, binary-search match ranges —
is one compiled XLA program ``vmap``-ed over buckets and, on a >1-device
mesh, ``shard_map``-ed so each shard joins its own slice of buckets in
parallel (replacing the reference's executor-parallel SMJ tasks).

Static-shape contract: buckets are padded to the max bucket length per
side; pad slots carry +INT64_MAX reps and are excluded via the per-bucket
valid lengths. The kernel returns, per left row, the [lo, hi) range of
matching rows in the right side's sorted order; the host expands ranges
into index pairs (O(matches), vectorized) and re-verifies the actual key
columns, so a 64-bit combine collision can only cost work, never
correctness.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import hyperspace_tpu.ops  # noqa: F401  (enables x64)
from hyperspace_tpu.parallel.mesh import SHARD_AXIS

try:  # jax >= 0.6
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

_PAD = jnp.int64(0x7FFFFFFFFFFFFFFF)

def combine_reps_np(reps: np.ndarray) -> np.ndarray:
    """[k, n] int64 -> [n] int64: splitmix64 mix of the composite key
    (identity copy for k == 1, where reps are already exact). Host numpy:
    the combine is O(k·n) bit arithmetic — cheaper than a device roundtrip
    on the serve path, and the kernel itself is key-agnostic."""
    if reps.shape[0] == 1:
        return reps[0].copy()
    with np.errstate(over="ignore"):
        h = np.zeros(reps.shape[1], dtype=np.uint64)
        m1 = np.uint64(0xBF58476D1CE4E5B9)
        m2 = np.uint64(0x94D049BB133111EB)
        gold = np.uint64(0x9E3779B97F4A7C15)
        for i in range(reps.shape[0]):
            x = h ^ (reps[i].view(np.uint64) + gold)
            x = x * m1
            x ^= x >> np.uint64(27)
            x = x * m2
            x ^= x >> np.uint64(31)
            h = x
    return h.view(np.int64)


# At or above this PAIR count the range expansion uses the native
# single-pass kernel (hyperspace_tpu/native); below it numpy's vectorized
# repeat/cumsum passes are already microseconds. FALLBACK DEFAULT: the
# effective threshold comes from the per-machine calibration probe
# (native/calibrate.py); this constant applies when calibration is
# disabled or a test overrides the module attribute (an override wins).
_NATIVE_EXPAND_MIN_ROWS_DEFAULT = 1 << 14
_NATIVE_EXPAND_MIN_ROWS = _NATIVE_EXPAND_MIN_ROWS_DEFAULT


def _native_expand_min_rows() -> int:
    if _NATIVE_EXPAND_MIN_ROWS != _NATIVE_EXPAND_MIN_ROWS_DEFAULT:
        return _NATIVE_EXPAND_MIN_ROWS  # explicit (test/ops) override wins
    from hyperspace_tpu.native import calibrate

    return (
        calibrate.thresholds().native_expand_min_rows
        or _NATIVE_EXPAND_MIN_ROWS
    )


def expand_match_ranges_numpy(
    lo: np.ndarray,
    cnt: np.ndarray,
    l_map: np.ndarray = None,
    r_map: np.ndarray = None,
    l_bias: int = 0,
    r_bias: int = 0,
):
    """Expand per-left-row match ranges into (li, ri) pairs, pure numpy —
    the registered twin of ``hs_expand_match_ranges_i64`` and the
    repeat/cumsum chain the serve path always ran. Left row ``i`` with
    ``cnt[i]`` matches starting at sorted-right position ``lo[i]`` emits
    pairs ``(l_map[i] + l_bias, r_map[lo[i]+j] + r_bias)`` for j in
    [0, cnt[i]); a None map is the identity. Pair order: left row
    ascending, right position ascending within each left row."""
    n = len(lo)
    cnt = cnt.astype(np.int64, copy=False)
    total = int(cnt.sum())
    if total == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    li = np.repeat(np.arange(n, dtype=np.int64), cnt)
    starts = np.concatenate([[0], np.cumsum(cnt)[:-1]])
    within = np.arange(total, dtype=np.int64) - np.repeat(starts, cnt)
    ri = np.repeat(lo.astype(np.int64, copy=False), cnt) + within
    if l_map is not None:
        li = l_map[li]
    if r_map is not None:
        ri = r_map[ri]
    if l_bias:
        li = li + np.int64(l_bias)
    if r_bias:
        ri = ri + np.int64(r_bias)
    return li, ri


def expand_match_ranges(
    lo: np.ndarray,
    cnt: np.ndarray,
    l_map: np.ndarray = None,
    r_map: np.ndarray = None,
    l_bias: int = 0,
    r_bias: int = 0,
):
    """Host dispatch of the match-range expansion: the native single-pass
    kernel at or above the calibrated pair-count crossover, else the
    numpy twin — identical output either way."""
    total = int(cnt.sum())
    if total >= _native_expand_min_rows():
        from hyperspace_tpu import native

        pair = native.expand_match_ranges_i64(
            lo, cnt, total, l_map, r_map, l_bias, r_bias
        )
        if pair is not None:
            return pair
    return expand_match_ranges_numpy(lo, cnt, l_map, r_map, l_bias, r_bias)


def _bucket_join(l_rep, l_len, r_rep, r_len):
    """One padded bucket pair -> (perm_l, perm_r, lo, cnt) in sorted space.

    Pad handling relies on a stability invariant, NOT on the pad value
    being unrepresentable (a real int64 key CAN equal ``_PAD``): real rows
    occupy indices < len, pads occupy indices >= len, and jnp.argsort is
    stable — so among equal keys real rows sort before pads, which means
    sorted positions [0, len) are exactly the real rows. Validity is
    therefore positional; a real key equal to ``_PAD`` still matches.
    """
    n = l_rep.shape[0]
    m = r_rep.shape[0]
    l_key = jnp.where(jnp.arange(n) < l_len, l_rep, _PAD)
    r_key = jnp.where(jnp.arange(m) < r_len, r_rep, _PAD)
    perm_l = jnp.argsort(l_key)
    perm_r = jnp.argsort(r_key)
    ls = l_key[perm_l]
    rs = r_key[perm_r]
    lo = jnp.searchsorted(rs, ls, side="left")
    hi = jnp.searchsorted(rs, ls, side="right")
    # clip pads out of the match range: real right rows (even those whose
    # key equals _PAD) all live at sorted positions < r_len
    hi = jnp.minimum(hi, r_len)
    valid_l_sorted = jnp.arange(n) < l_len  # positional (see docstring)
    cnt = jnp.where(valid_l_sorted, jnp.maximum(hi - lo, 0), 0)
    return perm_l, perm_r, lo, cnt


_vmapped = jax.vmap(_bucket_join, in_axes=(0, 0, 0, 0))


@functools.partial(jax.jit, static_argnames=("mesh",))
def _sharded_join(mesh, l_rep, l_len, r_rep, r_len):
    return shard_map(
        _vmapped,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
    )(l_rep, l_len, r_rep, r_len)


_jit_vmapped = jax.jit(_vmapped)


def _match_ranges_host(l_rep, l_len, r_rep, r_len):
    """Numpy twin of ``_bucket_join`` (identical algorithm and positional-
    validity contract) for workloads too small to amortize device dispatch
    and transfer latency."""
    B, n = l_rep.shape
    m = r_rep.shape[1]
    pad = np.int64(0x7FFFFFFFFFFFFFFF)
    col_l = np.arange(n)[None, :]
    col_r = np.arange(m)[None, :]
    l_key = np.where(col_l < l_len[:, None], l_rep, pad)
    r_key = np.where(col_r < r_len[:, None], r_rep, pad)
    perm_l = np.argsort(l_key, axis=1, kind="stable")
    perm_r = np.argsort(r_key, axis=1, kind="stable")
    ls = np.take_along_axis(l_key, perm_l, axis=1)
    rs = np.take_along_axis(r_key, perm_r, axis=1)
    lo = np.empty((B, n), dtype=np.int64)
    hi = np.empty((B, n), dtype=np.int64)
    for b in range(B):
        lo[b] = np.searchsorted(rs[b], ls[b], side="left")
        hi[b] = np.searchsorted(rs[b], ls[b], side="right")
    hi = np.minimum(hi, r_len[:, None])
    cnt = np.where(col_l < l_len[:, None], np.maximum(hi - lo, 0), 0)
    return perm_l, perm_r, lo, cnt


def bucketed_match_ranges(
    mesh,
    l_rep: np.ndarray,
    l_len: np.ndarray,
    r_rep: np.ndarray,
    r_len: np.ndarray,
    device_min_rows: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host entry. [B, n_max]/[B] per side; B padded to a multiple of the
    mesh size when sharded. Returns per-bucket (perm_l, perm_r, lo, cnt).

    Dispatches to the device program when total rows reach
    ``device_min_rows`` (or a >1-device mesh is available — sharded
    buckets amortize immediately); otherwise runs the numpy twin.
    """
    total = int(l_len.sum() + r_len.sum())
    use_mesh = (
        mesh is not None
        and mesh.devices.size > 1
        and l_rep.shape[0] % mesh.devices.size == 0
    )
    if not use_mesh and total < device_min_rows:
        return _match_ranges_host(l_rep, l_len, r_rep, r_len)
    args = (
        jnp.asarray(l_rep),
        jnp.asarray(l_len),
        jnp.asarray(r_rep),
        jnp.asarray(r_len),
    )
    if use_mesh:
        out = _sharded_join(mesh, *args)
    else:
        out = _jit_vmapped(*args)
    return tuple(np.asarray(o) for o in out)
