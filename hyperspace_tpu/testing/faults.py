"""Config-keyed fault injection for the serve plane.

Exoshuffle (PAPERS.md) argues fault handling belongs in the
application-level dataflow — retried or degraded at the operation
boundary — not bolted underneath it. To make that testable, the four
I/O-and-dispatch seams of the serve path carry an injection point each:

========================  ====================================================
point                     armed site
========================  ====================================================
``parquet_read``          ``io/parquet.read_table`` / ``read_file_row_groups``
                          (every data read, incl. scan-pool workers and the
                          fused pipeline's chunk reads)
``kernel_dispatch``       ``native.load(wait=False)`` — the single choke point
                          every native kernel wrapper passes through; a fired
                          fault makes the wrapper return None, which IS the
                          registered degrade path (numpy/interpreted twin,
                          ``KERNEL_TWINS``)
``log_read``              ``metadata/log_manager.py`` log-entry and
                          latestStable reads (snapshot pinning)
``cache_insert``          ``ServeCache.put`` — a fired fault drops the insert
                          (query still answers, just uncached; counted in
                          ``ServeCache.insert_failures``)
========================  ====================================================

Arming is always an explicit act: programmatic (:func:`set_fault`) or
config-keyed via ``faults.configure(session.conf)``, which reads the
``hyperspace.faults.<point>`` keys — merely setting the conf keys arms
nothing (production never injects into itself). Spec grammar::

    "transient"            fail the next 1 matching call, then recover
    "transient:3"          fail the next 3 matching calls, then recover
    "persistent"           fail every matching call until cleared
    "persistent;match=v__="  only calls whose detail (e.g. file path)
                           contains the substring — lets a test fail
                           index-version reads while source reads and the
                           degrade path keep working
    "off" / ""             disarm

Semantics at the site: ``check`` raises :class:`InjectedFault` (an
``OSError``, so the serve frontend's transient-I/O classification treats
injected and real faults identically); ``degraded`` returns True for
sites whose contract is fall-back-in-place rather than raise (kernel
dispatch, cache insert). ``transient``-armed faults recover on their
own; ``persistent`` ones model a dead dependency and exercise the
degrade paths. Per-point fired counters (:func:`stats`) let the test
suite and ``scripts/bench_smoke.sh`` assert each point actually fired.

Everything is process-global and thread-safe: the serve plane is
multi-threaded and a fault armed by the admitting thread must fire in
scan-pool workers. When nothing is armed the per-call cost is one dict
truthiness check.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

POINTS = ("parquet_read", "kernel_dispatch", "log_read", "cache_insert")


class InjectedFault(OSError):
    """A fault fired by an armed injection point.

    Subclasses ``OSError`` on purpose: the transient flavor must travel
    the exact classification path a real transient I/O error takes
    (``serve/frontend._is_transient``), so the retry machinery tested
    under injection is the machinery production errors hit.
    """

    def __init__(self, point: str, transient: bool):
        kind = "transient" if transient else "persistent"
        super().__init__(f"injected {kind} fault at {point}")
        self.point = point
        self.transient = transient


class _FaultPoint:
    """One armed point: remaining budget (None = unlimited), substring
    filter, fired counter. ``fire`` is the only mutator and holds the
    registry lock for its counter updates."""

    def __init__(
        self,
        point: str,
        transient: bool,
        remaining: Optional[int],
        match: Optional[str],
    ):
        self.point = point
        self.transient = transient
        self.remaining = remaining
        self.match = match
        self.fired = 0

    def fire(self, detail: str) -> bool:
        if self.match and self.match not in detail:
            return False
        with _lock:
            if self.remaining is not None:
                if self.remaining <= 0:
                    return False
                self.remaining -= 1
            self.fired += 1
            _fired_totals[self.point] = _fired_totals.get(self.point, 0) + 1
        return True


_lock = threading.Lock()
_active: Dict[str, _FaultPoint] = {}
# totals survive disarm/re-arm so a suite can assert "every point fired
# at least once" at the end of a run that armed points one at a time
_fired_totals: Dict[str, int] = {}


def parse_spec(spec: str):
    """``(transient, remaining, match)`` from a spec string, or None for
    off/empty. Raises ValueError on a malformed spec — arming is always
    an explicit test/operator act, so a typo should be loud."""
    s = str(spec).strip()
    if not s or s.lower() == "off":
        return None
    match = None
    parts = s.split(";")
    for opt in parts[1:]:
        k, _, v = opt.partition("=")
        if k.strip() == "match" and v:
            match = v
        else:
            raise ValueError(f"bad fault option {opt!r} in {spec!r}")
    head = parts[0].strip().lower()
    mode, _, count = head.partition(":")
    if mode == "transient":
        remaining = int(count) if count else 1
        if remaining <= 0:
            raise ValueError(f"transient count must be positive: {spec!r}")
        return True, remaining, match
    if mode == "persistent":
        if count:
            raise ValueError(f"persistent takes no count: {spec!r}")
        return False, None, match
    raise ValueError(f"unknown fault mode {mode!r} in {spec!r}")


def set_fault(point: str, spec: str) -> bool:
    """Arm (or disarm, spec="off") one injection point. Returns True
    when the point was armed, False when disarmed."""
    if point not in POINTS:
        raise ValueError(f"unknown fault point {point!r}; have {POINTS}")
    parsed = parse_spec(spec)
    with _lock:
        if parsed is None:
            _active.pop(point, None)
            return False
        transient, remaining, match = parsed
        _active[point] = _FaultPoint(point, transient, remaining, match)
        return True


def configure(conf) -> int:
    """Arm every ``hyperspace.faults.<point>`` key present in a session
    config (:meth:`Config.prefixed`). Returns the number of armed
    points. Unlisted points are left untouched — call :func:`clear`
    first for a clean slate."""
    from hyperspace_tpu.constants import FAULTS_KEY_PREFIX

    n = 0
    for key, spec in conf.prefixed(FAULTS_KEY_PREFIX).items():
        point = key[len(FAULTS_KEY_PREFIX):]
        if set_fault(point, str(spec)):
            n += 1
    return n


def clear() -> None:
    """Disarm every point (fired totals are kept; see module doc)."""
    with _lock:
        _active.clear()


def reset() -> None:
    """Disarm every point AND zero the fired totals (test isolation)."""
    with _lock:
        _active.clear()
        _fired_totals.clear()


def check(point: str, detail="") -> None:
    """Raise :class:`InjectedFault` when ``point`` is armed and fires.

    The raising flavor — for sites whose real failure mode is an
    exception (reads). No-op (one dict check) when nothing is armed;
    ``detail`` may be any object (e.g. a path list) — it is stringified
    only when the point is armed, so disarmed call sites pay nothing.
    """
    if not _active:
        return
    fp = _active.get(point)
    if fp is not None and fp.fire(str(detail)):
        raise InjectedFault(point, fp.transient)


def degraded(point: str, detail="") -> bool:
    """True when ``point`` is armed and fires — the non-raising flavor
    for sites whose contract is degrade-in-place (kernel dispatch falls
    back to the numpy twin, cache insert is dropped)."""
    if not _active:
        return False
    fp = _active.get(point)
    return fp is not None and fp.fire(str(detail))


def stats() -> Dict[str, int]:
    """Cumulative fired count per point (across disarm/re-arm)."""
    with _lock:
        return dict(_fired_totals)
