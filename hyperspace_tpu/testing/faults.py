"""Config-keyed fault injection for the serve plane.

Exoshuffle (PAPERS.md) argues fault handling belongs in the
application-level dataflow — retried or degraded at the operation
boundary — not bolted underneath it. To make that testable, the five
I/O-and-dispatch seams of the serve path carry an injection point each:

========================  ====================================================
point                     armed site
========================  ====================================================
``parquet_read``          ``io/parquet.read_table`` / ``read_file_row_groups``
                          (every data read, incl. scan-pool workers and the
                          fused pipeline's chunk reads)
``kernel_dispatch``       ``native.load(wait=False)`` — the single choke point
                          every native kernel wrapper passes through; a fired
                          fault makes the wrapper return None, which IS the
                          registered degrade path (numpy/interpreted twin,
                          ``KERNEL_TWINS``)
``log_read``              ``metadata/log_manager.py`` log-entry and
                          latestStable reads (snapshot pinning)
``cache_insert``          ``ServeCache.put`` — a fired fault drops the insert
                          (query still answers, just uncached; counted in
                          ``ServeCache.insert_failures``)
``fastbus_send``          ``serve/fastbus.push`` / ``request`` — the fleet
                          fast data plane's send seam; a fired fault is a
                          dead/unreachable peer socket, and the contract is
                          fall back to the durable planes (poll-delivered
                          fanout, claim/spool single-flight) with a
                          bit-identical answer
========================  ====================================================

Arming is always an explicit act: programmatic (:func:`set_fault`) or
config-keyed via ``faults.configure(session.conf)``, which reads the
``hyperspace.faults.<point>`` keys — merely setting the conf keys arms
nothing (production never injects into itself). Spec grammar::

    "transient"            fail the next 1 matching call, then recover
    "transient:3"          fail the next 3 matching calls, then recover
    "persistent"           fail every matching call until cleared
    "persistent;match=v__="  only calls whose detail (e.g. file path)
                           contains the substring — lets a test fail
                           index-version reads while source reads and the
                           degrade path keep working
    "off" / ""             disarm

Semantics at the site: ``check`` raises :class:`InjectedFault` (an
``OSError``, so the serve frontend's transient-I/O classification treats
injected and real faults identically); ``degraded`` returns True for
sites whose contract is fall-back-in-place rather than raise (kernel
dispatch, cache insert). ``transient``-armed faults recover on their
own; ``persistent`` ones model a dead dependency and exercise the
degrade paths. Per-point fired counters (:func:`stats`) let the test
suite and ``scripts/bench_smoke.sh`` assert each point actually fired.

Everything is process-global and thread-safe: the serve plane is
multi-threaded and a fault armed by the admitting thread must fire in
scan-pool workers. When nothing is armed the per-call cost is one dict
truthiness check.

Crash points (``hyperspace.faults.crash.<point>``) are the lifecycle
counterpart: named points inside every Action where a writer can die
mid-protocol, leaving a stranded transient log entry and orphan data
files for ``metadata/recovery.py`` to clean up. Spec grammar::

    "raise"            raise SimulatedCrash at the point (in-process
                       torn-state tests; tier-1 speed)
    "exit"             os._exit(CRASH_EXIT_CODE) — the process REALLY
                       dies mid-protocol, no finally blocks, no heartbeat
                       shutdown: the true torn state (slow-marked
                       subprocess tests)
    "raise;at=3"       fire on the 3rd matching call (crash after two
                       bucket files landed, mid version dir)
    "raise;match=v__=2"  only calls whose detail contains the substring

========================  ====================================================
crash point               armed site
========================  ====================================================
``after_begin_log``       ``actions/base.py`` — begin entry published,
                          before any data work (and before the lease
                          heartbeat starts)
``mid_data_write``        ``io/parquet.py`` bucket/table writes — between
                          index data files of the new version dir
``after_data_write``      ``actions/base.py`` — op() done, end entry not
                          yet written
``after_end_log``         ``actions/base.py`` — end entry committed,
                          latestStable pointer not yet republished
``mid_vacuum_delete``     ``actions/vacuum.py`` — between file deletes of
                          a vacuum / vacuum-outdated sweep
``mid_querylog_rotate``   ``obs/querylog.py`` — active segment fsynced,
                          sealed-segment rename not yet done (the query
                          log's rotation crash window)
========================  ====================================================

A crash point is ONE-SHOT in ``raise`` mode: it disarms itself when it
fires, so the recovery/retry that follows does not crash again.
:class:`SimulatedCrash` subclasses ``BaseException`` (like
``KeyboardInterrupt``): no ``except Exception`` cleanup handler may
swallow it, because a real crash would not have run that handler either.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

POINTS = (
    "parquet_read",
    "kernel_dispatch",
    "log_read",
    "cache_insert",
    "fastbus_send",
)

CRASH_POINTS = (
    "after_begin_log",
    "mid_data_write",
    "after_data_write",
    "after_end_log",
    "mid_vacuum_delete",
    "mid_sidecar_publish",
    "mid_querylog_rotate",
    "mid_spill_write",
)

#: ``exit``-mode crash status — distinctive, so a subprocess test can tell
#: a simulated crash from an ordinary failure of the child.
CRASH_EXIT_CODE = 86


class SimulatedCrash(BaseException):
    """An armed crash point fired in ``raise`` mode.

    Deliberately NOT an ``Exception``: the whole point is modeling a
    process death, and a ``try/except Exception`` that tidied up on the
    way out would be rehearsing a cleanup the real crash never runs.
    """

    def __init__(self, point: str):
        super().__init__(f"simulated crash at {point}")
        self.point = point


class InjectedFault(OSError):
    """A fault fired by an armed injection point.

    Subclasses ``OSError`` on purpose: the transient flavor must travel
    the exact classification path a real transient I/O error takes
    (``serve/frontend._is_transient``), so the retry machinery tested
    under injection is the machinery production errors hit.
    """

    def __init__(self, point: str, transient: bool):
        kind = "transient" if transient else "persistent"
        super().__init__(f"injected {kind} fault at {point}")
        self.point = point
        self.transient = transient


class _FaultPoint:
    """One armed point: remaining budget (None = unlimited), substring
    filter, fired counter. ``fire`` is the only mutator and holds the
    registry lock for its counter updates."""

    def __init__(
        self,
        point: str,
        transient: bool,
        remaining: Optional[int],
        match: Optional[str],
    ):
        self.point = point
        self.transient = transient
        self.remaining = remaining
        self.match = match
        self.fired = 0

    def fire(self, detail: str) -> bool:
        if self.match and self.match not in detail:
            return False
        with _lock:
            if self.remaining is not None:
                if self.remaining <= 0:
                    return False
                self.remaining -= 1
            self.fired += 1
            _fired_totals[self.point] = _fired_totals.get(self.point, 0) + 1
        return True


class _CrashPoint:
    """One armed crash point: fire mode (raise/exit), the 1-based call
    ordinal it fires at, substring filter. One-shot in raise mode."""

    def __init__(self, point: str, exit_: bool, at: int, match: Optional[str]):
        self.point = point
        self.exit = exit_
        self.at = at
        self.match = match
        self.calls = 0

    def fire(self, detail: str) -> bool:
        if self.match and self.match not in detail:
            return False
        with _lock:
            self.calls += 1
            if self.calls != self.at:
                return False
            _fired_totals["crash." + self.point] = (
                _fired_totals.get("crash." + self.point, 0) + 1
            )
        return True


_lock = threading.Lock()
_active: Dict[str, _FaultPoint] = {}
_crash_active: Dict[str, _CrashPoint] = {}
# totals survive disarm/re-arm so a suite can assert "every point fired
# at least once" at the end of a run that armed points one at a time
# (crash points count under a "crash." prefix)
_fired_totals: Dict[str, int] = {}


def parse_spec(spec: str):
    """``(transient, remaining, match)`` from a spec string, or None for
    off/empty. Raises ValueError on a malformed spec — arming is always
    an explicit test/operator act, so a typo should be loud."""
    s = str(spec).strip()
    if not s or s.lower() == "off":
        return None
    match = None
    parts = s.split(";")
    for opt in parts[1:]:
        k, _, v = opt.partition("=")
        if k.strip() == "match" and v:
            match = v
        else:
            raise ValueError(f"bad fault option {opt!r} in {spec!r}")
    head = parts[0].strip().lower()
    mode, _, count = head.partition(":")
    if mode == "transient":
        remaining = int(count) if count else 1
        if remaining <= 0:
            raise ValueError(f"transient count must be positive: {spec!r}")
        return True, remaining, match
    if mode == "persistent":
        if count:
            raise ValueError(f"persistent takes no count: {spec!r}")
        return False, None, match
    raise ValueError(f"unknown fault mode {mode!r} in {spec!r}")


def set_fault(point: str, spec: str) -> bool:
    """Arm (or disarm, spec="off") one injection point. Returns True
    when the point was armed, False when disarmed."""
    if point not in POINTS:
        raise ValueError(f"unknown fault point {point!r}; have {POINTS}")
    parsed = parse_spec(spec)
    with _lock:
        if parsed is None:
            _active.pop(point, None)
            return False
        transient, remaining, match = parsed
        _active[point] = _FaultPoint(point, transient, remaining, match)
        return True


def parse_crash_spec(spec: str):
    """``(exit, at, match)`` from a crash spec string, or None for
    off/empty. Same loud-on-typo stance as :func:`parse_spec`."""
    s = str(spec).strip()
    if not s or s.lower() == "off":
        return None
    match = None
    at = 1
    parts = s.split(";")
    for opt in parts[1:]:
        k, _, v = opt.partition("=")
        k = k.strip()
        if k == "match" and v:
            match = v
        elif k == "at":
            at = int(v)
            if at <= 0:
                raise ValueError(f"crash at= must be positive: {spec!r}")
        else:
            raise ValueError(f"bad crash option {opt!r} in {spec!r}")
    mode = parts[0].strip().lower()
    if mode not in ("raise", "exit"):
        raise ValueError(f"unknown crash mode {mode!r} in {spec!r}")
    return mode == "exit", at, match


def set_crash(point: str, spec: str) -> bool:
    """Arm (or disarm, spec="off") one crash point. Returns True when
    the point was armed."""
    if point not in CRASH_POINTS:
        raise ValueError(f"unknown crash point {point!r}; have {CRASH_POINTS}")
    parsed = parse_crash_spec(spec)
    with _lock:
        if parsed is None:
            _crash_active.pop(point, None)
            return False
        exit_, at, match = parsed
        _crash_active[point] = _CrashPoint(point, exit_, at, match)
        return True


def configure(conf) -> int:
    """Arm every ``hyperspace.faults.<point>`` /
    ``hyperspace.faults.crash.<point>`` key present in a session config
    (:meth:`Config.prefixed`). Returns the number of armed points.
    Unlisted points are left untouched — call :func:`clear` first for a
    clean slate."""
    from hyperspace_tpu.constants import CRASH_KEY_PREFIX, FAULTS_KEY_PREFIX

    n = 0
    for key, spec in conf.prefixed(CRASH_KEY_PREFIX).items():
        if set_crash(key[len(CRASH_KEY_PREFIX):], str(spec)):
            n += 1
    for key, spec in conf.prefixed(FAULTS_KEY_PREFIX).items():
        if key.startswith(CRASH_KEY_PREFIX):
            continue
        point = key[len(FAULTS_KEY_PREFIX):]
        if set_fault(point, str(spec)):
            n += 1
    return n


def clear() -> None:
    """Disarm every point (fired totals are kept; see module doc)."""
    with _lock:
        _active.clear()
        _crash_active.clear()


def reset() -> None:
    """Disarm every point AND zero the fired totals (test isolation)."""
    with _lock:
        _active.clear()
        _crash_active.clear()
        _fired_totals.clear()


def check(point: str, detail="") -> None:
    """Raise :class:`InjectedFault` when ``point`` is armed and fires.

    The raising flavor — for sites whose real failure mode is an
    exception (reads). No-op (one dict check) when nothing is armed;
    ``detail`` may be any object (e.g. a path list) — it is stringified
    only when the point is armed, so disarmed call sites pay nothing.
    """
    if not _active:
        return
    fp = _active.get(point)
    if fp is not None and fp.fire(str(detail)):
        raise InjectedFault(point, fp.transient)


def degraded(point: str, detail="") -> bool:
    """True when ``point`` is armed and fires — the non-raising flavor
    for sites whose contract is degrade-in-place (kernel dispatch falls
    back to the numpy twin, cache insert is dropped)."""
    if not _active:
        return False
    fp = _active.get(point)
    return fp is not None and fp.fire(str(detail))


def crash(point: str, detail="") -> None:
    """Die at ``point`` when armed: raise :class:`SimulatedCrash`
    (``raise`` mode, one-shot — the point disarms itself so the
    recovery/retry that follows runs clean) or ``os._exit`` (``exit``
    mode — the process really dies, skipping every finally block, exit
    handler and lease heartbeat, the way a kill -9 would). No-op (one
    dict truthiness check) when nothing is armed."""
    if not _crash_active:
        return
    cp = _crash_active.get(point)
    if cp is None or not cp.fire(str(detail)):
        return
    if cp.exit:
        os._exit(CRASH_EXIT_CODE)
    with _lock:
        _crash_active.pop(point, None)
    raise SimulatedCrash(point)


def stats() -> Dict[str, int]:
    """Cumulative fired count per point (across disarm/re-arm); crash
    points appear as ``crash.<point>``."""
    with _lock:
        return dict(_fired_totals)
