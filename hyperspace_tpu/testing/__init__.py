"""Test/robustness harnesses shipped with the package.

``testing.faults`` is imported by production modules (the injection
points), so everything in this package must stay stdlib-only and
import-cheap — it is on the cold-start path of ``hyperspace_tpu.native``.
"""
