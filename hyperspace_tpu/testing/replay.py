"""Workload replay — recorded query logs back through the serve plane.

Two halves:

* :func:`replay_records` takes query-log records (the PR 15 JSONL
  schema) and re-submits every record that carries a ``replay`` plan
  spec (``obs/planspec.py``) through the session's serve frontend —
  arrival order preserved (``ts_ms`` sort), optionally honoring the
  recorded inter-arrival gaps (``preserve_timing`` / ``speedup``), and
  passing each record's ``slo_class`` through to admission so a replay
  exercises the same per-tenant queues the original workload did.
  Records without a spec (recording predates ``recordPlans``, or the
  plan fell outside the replayable subset) are counted and skipped —
  a replay reports its coverage, it never crashes on a partial log.

* Scenario generators (:func:`skewed_keys`, :func:`hot_key_storm`,
  :func:`rolling_appends`, :func:`tenant_mix`) emit canned workloads IN
  query-log format — each record carries a replay spec by construction
  — so the bench gates and the advisor's e2e tests run on stable,
  seedable workloads without first operating a fleet.
  :func:`record_workload` writes any record list through a real
  :class:`~hyperspace_tpu.obs.querylog.QueryLog` (rotation, sealing,
  ``schema_v`` stamping) so generated scenarios are indistinguishable
  on disk from live ones.

Concurrency note: ``last_replay_stats`` follows the telemetry doctrine
(whole-dict rebind under SHARED_STATE); the replay counters live in
the metrics registry (OBS_SITES ``hyperspace_tpu.testing.replay``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import random
import time
from typing import Dict, List, Optional

from hyperspace_tpu.exceptions import ServeOverloadedError
from hyperspace_tpu.obs import metrics as _metrics
from hyperspace_tpu.obs import planspec as _planspec
from hyperspace_tpu.obs import querylog as _querylog

#: replay harness health (OBS_SITES: hyperspace_tpu.testing.replay)
replay_queries_total = _metrics.registry.counter(
    "hs_replay_queries_total", "queries re-submitted by the replay harness"
)
replay_skipped_total = _metrics.registry.counter(
    "hs_replay_skipped_total",
    "records skipped by replay (no replay spec, or spec rebuild failed)",
)
replay_failed_total = _metrics.registry.counter(
    "hs_replay_failed_total", "replayed queries that failed or were shed"
)

#: last completed replay's summary — telemetry, rebind-only
#: (SHARED_STATE: hyperspace_tpu.testing.replay.last_replay_stats)
last_replay_stats: Dict = {}


@dataclasses.dataclass
class ReplayResult:
    """One replay pass's outcome."""

    submitted: int = 0
    completed: int = 0
    failed: int = 0
    skipped: int = 0
    duration_s: float = 0.0
    latencies: List[float] = dataclasses.field(default_factory=list)

    @property
    def qps(self) -> float:
        return self.completed / self.duration_s if self.duration_s > 0 else 0.0

    def _pct(self, q: float) -> float:
        if not self.latencies:
            return 0.0
        s = sorted(self.latencies)
        return s[min(len(s) - 1, int(q * len(s)))]

    @property
    def p50_s(self) -> float:
        return self._pct(0.50)

    @property
    def p95_s(self) -> float:
        return self._pct(0.95)

    def to_dict(self) -> Dict:
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "failed": self.failed,
            "skipped": self.skipped,
            "duration_s": round(self.duration_s, 6),
            "qps": round(self.qps, 3),
            "p50_s": round(self.p50_s, 6),
            "p95_s": round(self.p95_s, 6),
        }


def replay_records(
    session,
    records: List[Dict],
    preserve_timing: bool = False,
    speedup: float = 1.0,
    use_slo_classes: bool = True,
    max_inflight: int = 1,
) -> ReplayResult:
    """Re-submit ``records`` through ``session.serve_frontend``.

    Arrival ORDER is always the recorded one (``ts_ms`` sort, stable).
    With ``preserve_timing`` the recorded inter-arrival gaps are
    honored too, compressed by ``speedup``; without it, submission is
    back-to-back. ``max_inflight`` bounds overlap: 1 (default) replays
    strictly serially — each latency is a clean closed-loop sample —
    while larger values pipeline submissions the way concurrent
    clients would. Per-query latency is measured submit-to-result."""
    frontend = session.serve_frontend
    ordered = sorted(records, key=lambda r: int(r.get("ts_ms", 0) or 0))
    result = ReplayResult()
    inflight: List = []  # (future, t_submit)
    base_ts: Optional[int] = None
    speedup = max(1e-9, float(speedup))
    max_inflight = max(1, int(max_inflight))
    t0 = time.perf_counter()

    def drain_one() -> None:
        fut, t_submit = inflight.pop(0)
        try:
            fut.result()
        except Exception:  # hslint: disable=HS402
            # replay reports failures, it never aborts on one query
            result.failed += 1
            replay_failed_total.inc()
        else:
            result.completed += 1
        result.latencies.append(time.perf_counter() - t_submit)

    for rec in ordered:
        spec = rec.get("replay")
        if not isinstance(spec, dict):
            result.skipped += 1
            replay_skipped_total.inc()
            continue
        try:
            plan = _planspec.from_spec(session, spec)
        except Exception:  # hslint: disable=HS402
            # spec outside this build's replayable subset: skip + count
            result.skipped += 1
            replay_skipped_total.inc()
            continue
        if preserve_timing:
            ts = int(rec.get("ts_ms", 0) or 0)
            if base_ts is None:
                base_ts = ts
            due = (ts - base_ts) / 1000.0 / speedup
            delay = due - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
        slo = rec.get("slo_class") if use_slo_classes else None
        t_submit = time.perf_counter()
        try:
            fut = frontend.submit(plan, slo_class=slo)
        except ServeOverloadedError:
            result.submitted += 1
            result.failed += 1
            replay_queries_total.inc()
            replay_failed_total.inc()
            continue
        result.submitted += 1
        replay_queries_total.inc()
        inflight.append((fut, t_submit))
        while len(inflight) >= max_inflight:
            drain_one()
    while inflight:
        drain_one()
    result.duration_s = time.perf_counter() - t0
    global last_replay_stats
    last_replay_stats = result.to_dict()  # rebind-only telemetry publish
    return result


# ---------------------------------------------------------------------------
# scenario generators — canned workloads in query-log format
# ---------------------------------------------------------------------------


def _spec_shape(spec: Dict) -> str:
    """Deterministic literal-free shape string for a generated spec —
    the generator-side stand-in for ``querylog.predicate_shape`` (live
    records get theirs from the real plan repr)."""

    def walk(node) -> str:
        if not isinstance(node, dict):
            return "?"
        op = node.get("op", "?")
        if op == "scan":
            return f"scan({node.get('fmt')})"
        if op == "col":
            return f"col:{node.get('name')}"
        if op == "lit":
            return "?"
        if op == "in":
            return f"in({walk(node.get('child'))},?)"
        parts = [
            walk(node[k])
            for k in ("cond", "child", "left", "right")
            if k in node
        ]
        extra = ""
        if op == "project":
            extra = ",".join(node.get("cols", []))
        elif op == "aggregate":
            extra = ",".join(node.get("group_by", []))
        return f"{op}({extra + ':' if extra else ''}{','.join(parts)})"

    return walk(spec)[:2048]


def _record(
    spec: Dict, ts_ms: int, slo_class: Optional[str] = None
) -> Dict:
    """One query-log-format record around a replay spec. Fingerprint is
    the spec hash (literals included — distinct lookups stay distinct,
    exactly like the serve plane's plan fingerprint)."""
    fp = hashlib.md5(
        json.dumps(spec, sort_keys=True, default=str).encode()
    ).hexdigest()
    rec = {
        "ts_ms": int(ts_ms),
        "fingerprint": fp,
        "duration_s": 0.0,
        "status": "ok",
        "stages": {},
        "rows_returned": 0,
        "predicate": _spec_shape(spec),
        "replay": spec,
    }
    if slo_class is not None:
        rec["slo_class"] = slo_class
    return rec


def _scan(paths: List[str], fmt: str = "parquet") -> Dict:
    return {"op": "scan", "fmt": fmt, "paths": list(paths)}


def _eq(col: str, value) -> Dict:
    return {
        "op": "eq",
        "left": {"op": "col", "name": col},
        "right": {"op": "lit", "value": value},
    }


def _point_lookup(
    paths: List[str], key: str, value, project: Optional[List[str]], fmt: str
) -> Dict:
    spec: Dict = {
        "op": "filter",
        "cond": _eq(key, value),
        "child": _scan(paths, fmt),
        "spec_v": _planspec.SPEC_V,
    }
    if project:
        spec = {
            "op": "project",
            "cols": list(project),
            "child": spec,
            "spec_v": _planspec.SPEC_V,
        }
    return spec


def skewed_keys(
    paths: List[str],
    key: str,
    values: List,
    n: int,
    zipf_s: float = 1.2,
    project: Optional[List[str]] = None,
    fmt: str = "parquet",
    start_ts_ms: int = 1_000,
    interarrival_ms: int = 10,
    seed: int = 7,
) -> List[Dict]:
    """Point lookups with Zipf-skewed key popularity: the canonical
    "one hot template dominates" workload an index advisor must catch.
    Deterministic for a given seed."""
    rng = random.Random(seed)
    weights = [1.0 / (i + 1) ** zipf_s for i in range(len(values))]
    out = []
    for i in range(n):
        v = rng.choices(values, weights=weights, k=1)[0]
        out.append(
            _record(
                _point_lookup(paths, key, v, project, fmt),
                start_ts_ms + i * interarrival_ms,
            )
        )
    return out


def hot_key_storm(
    paths: List[str],
    key: str,
    hot_value,
    background_values: List,
    n: int,
    storm_fraction: float = 0.8,
    project: Optional[List[str]] = None,
    fmt: str = "parquet",
    start_ts_ms: int = 1_000,
    interarrival_ms: int = 2,
    seed: int = 11,
) -> List[Dict]:
    """A burst where one key absorbs ``storm_fraction`` of traffic at
    tight inter-arrival — the single-flight/dedup stressor (identical
    in-flight plans collapse onto one execution on replay too)."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        if rng.random() < storm_fraction:
            v = hot_value
        else:
            v = rng.choice(background_values)
        out.append(
            _record(
                _point_lookup(paths, key, v, project, fmt),
                start_ts_ms + i * interarrival_ms,
            )
        )
    return out


def rolling_appends(
    paths: List[str],
    ts_col: str,
    watermarks: List,
    queries_per_watermark: int = 4,
    fmt: str = "parquet",
    start_ts_ms: int = 1_000,
    interarrival_ms: int = 50,
) -> List[Dict]:
    """Recent-window scans whose lower bound advances through
    ``watermarks`` — the append-heavy shape whose profile should push
    the advisor toward REFRESH recommendations, not new indexes."""
    out = []
    i = 0
    for mark in watermarks:
        cond = {
            "op": "ge",
            "left": {"op": "col", "name": ts_col},
            "right": {"op": "lit", "value": mark},
        }
        spec = {
            "op": "filter",
            "cond": cond,
            "child": _scan(paths, fmt),
            "spec_v": _planspec.SPEC_V,
        }
        for _ in range(queries_per_watermark):
            out.append(_record(spec, start_ts_ms + i * interarrival_ms))
            i += 1
    return out


def tenant_mix(
    paths: List[str],
    key: str,
    values: List,
    classes: Dict[str, int],
    project: Optional[List[str]] = None,
    fmt: str = "parquet",
    start_ts_ms: int = 1_000,
    interarrival_ms: int = 5,
    seed: int = 13,
) -> List[Dict]:
    """Interleaved per-tenant streams: ``classes`` maps an SLO class
    name to its query count; records carry ``slo_class`` so replay
    exercises the fleet's per-class admission queues."""
    rng = random.Random(seed)
    stream = [
        cls for cls, count in sorted(classes.items()) for _ in range(count)
    ]
    rng.shuffle(stream)
    out = []
    for i, cls in enumerate(stream):
        v = rng.choice(values)
        out.append(
            _record(
                _point_lookup(paths, key, v, project, fmt),
                start_ts_ms + i * interarrival_ms,
                slo_class=cls,
            )
        )
    return out


def record_workload(
    records: List[Dict],
    directory: str,
    max_bytes: Optional[int] = None,
    max_files: Optional[int] = None,
) -> int:
    """Write ``records`` through a real :class:`QueryLog` (rotation,
    sealing, ``schema_v``) so a generated scenario round-trips the same
    reader path a fleet's live segments do. Returns records written."""
    kwargs = {}
    if max_bytes is not None:
        kwargs["max_bytes"] = max_bytes
    if max_files is not None:
        kwargs["max_files"] = max_files
    log = _querylog.QueryLog(directory, **kwargs)
    n = 0
    for rec in records:
        if log.append(dict(rec)):
            n += 1
    log.close()
    return n
