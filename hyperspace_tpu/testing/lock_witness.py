"""Runtime lock witness: record what the lock model ACTUALLY does.

The HS5xx/HS6xx checkers reason about a *static* lock model — which
locks exist, which guard what (``SHARED_STATE``), which acquisition
edges are possible. A static model rots silently: a new code path can
take locks the analyzer cannot resolve, and then every cycle/guard
verdict is built on sand. This module closes the loop dynamically:

* :func:`install` wraps every lock named in ``SHARED_STATE``
  (``hyperspace_tpu/concurrency.py``) — module-level locks by attribute
  replacement, instance locks by hooking the owning class's
  ``__init__`` — with a recording proxy;
* while the stress / frontend suites run, the proxy records per-lock
  acquisition counts and the observed acquisition EDGES (lock B taken
  while A is held, per thread);
* :func:`dump` writes (merging with any prior artifact) a JSON witness:
  ``{"locks": {name: count}, "edges": [[a, b, count]…],
  "entries": {state: {"lock": name, "policy": …}}}``, lock names in the
  same canonical ``<rel>::<attr>`` / ``<rel>::<Class>.<attr>`` form the
  static model uses (``analysis/locks.canonical_lock_name``);
* ``hslint --witness <artifact>`` cross-checks
  (``analysis/shared_state.witness_cross_check``): a witnessed edge or
  lock the static graph lacks is a hard model-gap error; a static edge
  never witnessed is a staleness warning.

Enabled in the test suites via the ``HS_LOCK_WITNESS=<path>`` env var
(see ``tests/conftest.py``); ``scripts/bench_smoke.sh`` runs the slow
stress suite under it and gates on the cross-check.

Overhead is one thread-local list append per acquisition — fine for
tests, not meant for production serving. Stdlib-only, like everything
in ``testing/``.
"""

from __future__ import annotations

import importlib
import os
import threading
from typing import Dict, List, Optional, Tuple

_PKG = "hyperspace_tpu"

_rec_lock = threading.Lock()
_acquires: Dict[str, int] = {}
_edges: Dict[Tuple[str, str], int] = {}
_tls = threading.local()

_installed: Dict[str, "_WitnessLock"] = {}  # canonical name -> wrapper
_module_patches: List[Tuple[object, str, object]] = []  # (module, attr, orig)
_class_patches: List[Tuple[type, object]] = []  # (cls, orig __init__)


def _held_stack() -> List[str]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class _WitnessLock:
    """Recording proxy around a ``threading.Lock``/``RLock``. Supports
    the full acquire/release + context-manager protocol the package
    uses (including ``acquire(blocking=False)``)."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self.witness_name = name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._record_acquire()
        return got

    def release(self):
        self._inner.release()
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.witness_name:
                del stack[i]
                break

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self._inner.acquire()
        self._record_acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def _record_acquire(self) -> None:
        stack = _held_stack()
        with _rec_lock:
            _acquires[self.witness_name] = (
                _acquires.get(self.witness_name, 0) + 1
            )
            for held in stack:
                if held != self.witness_name:  # RLock re-entry is not an edge
                    edge = (held, self.witness_name)
                    _edges[edge] = _edges.get(edge, 0) + 1
        stack.append(self.witness_name)


# ---------------------------------------------------------------------------
# Registry resolution
# ---------------------------------------------------------------------------


def _module_rel(module) -> str:
    """'io/scan.py'-style path of a loaded module, relative to the
    package root — matches ``analysis/core.Project`` rel paths."""
    pkg = importlib.import_module(_PKG)
    root = os.path.dirname(os.path.abspath(pkg.__file__))
    return os.path.relpath(os.path.abspath(module.__file__), root).replace(
        os.sep, "/"
    )


def _resolve_module_lock(spec: str):
    """(module, attr) for a dotted module-lock spec, importing the
    module. Raises on a stale spec — the witness must never silently
    watch nothing."""
    mod_name, _, attr = spec.rpartition(".")
    module = importlib.import_module(mod_name)
    if not hasattr(module, attr):
        raise AttributeError(f"lock {spec!r} not found")
    return module, attr


def _resolve_class(state_path: str):
    """(class, class name, module) for a registered class-attr state
    path like ``pkg.mod.Class.attr``."""
    mod_name, _, _attr = state_path.rpartition(".")
    cls_mod, _, cls_name = mod_name.rpartition(".")
    module = importlib.import_module(cls_mod)
    return getattr(module, cls_name), cls_name, module


def install() -> Dict[str, str]:
    """Wrap every SHARED_STATE-declared lock; idempotent. Returns
    {registry state path -> canonical lock name} for the wrapped ones.
    Must run before the instances under test are constructed — instance
    locks are wrapped at ``__init__`` time."""
    from hyperspace_tpu.concurrency import SHARED_STATE

    wrapped: Dict[str, str] = {}
    for state_path, (lock_spec, _policy, _why) in SHARED_STATE.items():
        if not lock_spec:
            continue
        if lock_spec.startswith("self."):
            attr = lock_spec[len("self.") :]
            cls, cls_name, module = _resolve_class(state_path)
            name = f"{_module_rel(module)}::{cls_name}.{attr}"
            wrapped[state_path] = name
            if name in _installed:
                continue
            _installed[name] = _hook_class(cls, attr, name)
        else:
            module, attr = _resolve_module_lock(lock_spec)
            name = f"{_module_rel(module)}::{attr}"
            wrapped[state_path] = name
            if name in _installed:
                continue
            orig = getattr(module, attr)
            if isinstance(orig, _WitnessLock):
                _installed[name] = orig
                continue
            proxy = _WitnessLock(orig, name)
            _module_patches.append((module, attr, orig))
            setattr(module, attr, proxy)
            _installed[name] = proxy
    return wrapped


def _hook_class(cls: type, attr: str, name: str) -> "_WitnessLock":
    """Patch ``cls.__init__`` to wrap ``self.<attr>`` right after
    construction. Returns a placeholder proxy (per-instance proxies are
    created at init time; they all share the canonical name)."""
    orig_init = cls.__init__

    def init(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        inner = getattr(self, attr, None)
        if inner is not None and not isinstance(inner, _WitnessLock):
            setattr(self, attr, _WitnessLock(inner, name))

    _class_patches.append((cls, orig_init))
    cls.__init__ = init
    return _WitnessLock(threading.Lock(), name)


def uninstall() -> None:
    """Restore patched module attributes and class __init__s (existing
    wrapped instances keep their proxies — harmless pass-throughs)."""
    while _module_patches:
        module, attr, orig = _module_patches.pop()
        setattr(module, attr, orig)
    while _class_patches:
        cls, orig_init = _class_patches.pop()
        cls.__init__ = orig_init
    _installed.clear()


def reset() -> None:
    """Zero the recorded counts/edges (artifact isolation in tests)."""
    with _rec_lock:
        _acquires.clear()
        _edges.clear()


def snapshot() -> dict:
    """The witness document for what has been recorded so far."""
    from hyperspace_tpu.concurrency import SHARED_STATE

    entries = {}
    for state_path, (lock_spec, policy, _why) in SHARED_STATE.items():
        meta: dict = {"policy": policy}
        if lock_spec:
            if lock_spec.startswith("self."):
                try:
                    cls, cls_name, module = _resolve_class(state_path)
                    attr = lock_spec[len("self.") :]
                    meta["lock"] = f"{_module_rel(module)}::{cls_name}.{attr}"
                except Exception:  # hslint: disable=HS402
                    # a stale registry entry is HS603's finding to make,
                    # not a reason to lose the whole artifact
                    meta["lock"] = None
            else:
                try:
                    module, attr = _resolve_module_lock(lock_spec)
                    meta["lock"] = f"{_module_rel(module)}::{attr}"
                except Exception:  # hslint: disable=HS402
                    # same contract as above: record None, let hslint judge
                    meta["lock"] = None
        entries[state_path] = meta
    with _rec_lock:
        return {
            "version": 1,
            "package": _PKG,
            "locks": dict(_acquires),
            "edges": sorted(
                [a, b, n] for (a, b), n in _edges.items()
            ),
            "entries": entries,
        }


def dump(path: str, merge: bool = True) -> dict:
    """Write the witness artifact, summing counts with any existing one
    at ``path`` (several suites can accumulate into one artifact), via
    the shared temp + fsync + atomic-replace publish helper
    (``testing/artifacts.py`` — the ``calibrate._store_cache`` pattern,
    also used by the collective witness). Returns the document."""
    from hyperspace_tpu.testing import artifacts

    doc = snapshot()
    prev = artifacts.load_json(path) if merge else None
    if prev is not None:
        artifacts.merge_count_maps(doc["locks"], prev.get("locks", {}))
        merged: Dict[Tuple[str, str], int] = {
            (a, b): n for a, b, n in doc["edges"]
        }
        artifacts.merge_count_maps(
            merged, {(a, b): n for a, b, n in prev.get("edges", [])}
        )
        doc["edges"] = sorted([a, b, n] for (a, b), n in merged.items())
        for state, meta in prev.get("entries", {}).items():
            doc["entries"].setdefault(state, meta)
    artifacts.atomic_write_json(path, doc)
    return doc
