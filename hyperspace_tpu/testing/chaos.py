"""Chaos schedule harness: randomized lifecycles under injected crashes.

The crash matrix (``tests/test_crash_recovery.py``) kills ONE action at
ONE point from a known state. This module is the composition test: a
seeded randomized schedule of create / refresh / optimize / delete /
restore / vacuum / append / serve steps, with a crash injected at a
chosen (step, point) — then recovery, then the REST of the schedule.
After every crash the harness asserts the recovery plane's whole
contract at once:

* **state machine** — the log tip is back in a stable state (the HS2xx
  invariant, checked at runtime);
* **serve equivalence** — every serve step answers identically with and
  without index rewriting, and identically to the same schedule run
  crash-free (indexes are transparent: whichever version survived the
  rollback, the answer may not change);
* **zero orphans** — after GC (grace 0) no data file under the index
  dir is unreferenced by a stable entry, and a second GC pass finds
  nothing.

The schedule is a pure function of the seed, so a crash run and its
crash-free replica execute the same ops over byte-identical source
data. After recovery the crashed step is retried once (an op that had
already committed before the crash point surfaces as a graceful no-op
or an illegal-state rejection, both tolerated), so the two runs
converge to the same logical state and the remaining steps stay legal.

Used by ``tests/test_chaos.py`` (tier-1 subset + slow full matrix) and
the ``bench.py`` chaos rung that ``scripts/bench_smoke.sh`` gates on.
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu import constants as C
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException, NoChangesException
from hyperspace_tpu.metadata import recovery
from hyperspace_tpu.testing import faults
from hyperspace_tpu.testing.faults import SimulatedCrash

INDEX_NAME = "chaosidx"

#: lifecycle steps a crash point can be injected into
LIFECYCLE_OPS = (
    "create",
    "refresh_full",
    "refresh_incremental",
    "optimize",
    "delete",
    "restore",
    "vacuum",
)


@dataclasses.dataclass
class ChaosReport:
    schedule: List[Tuple]
    serve_results: List[pa.Table] = dataclasses.field(default_factory=list)
    crashes_fired: int = 0
    crashes_skipped: int = 0  # armed step no-op'ed, the point never ran
    recoveries: int = 0
    rolled_back: int = 0
    healed_pointers: int = 0
    stranded_after: int = 0
    orphans_after_gc: int = 0
    gc_quarantined: int = 0
    final_state: Optional[str] = None


def build_schedule(seed: int, n_steps: int) -> List[Tuple]:
    """A legal op sequence from a seeded walk of the lifecycle machine.

    Pure in the seed: both the crash run and its crash-free replica
    derive the same list. Every refresh is preceded by an append (so it
    cannot no-op) and the walk keeps serves sprinkled throughout."""
    rng = random.Random(seed)
    steps: List[Tuple] = [("create",), ("serve",)]
    state = "active"
    appended = 0
    while len(steps) < n_steps:
        if state == "none":
            steps.append(("create",))
            state = "active"
        elif state == "deleted":
            op = rng.choice(["restore", "vacuum", "serve"])
            steps.append((op,))
            if op == "restore":
                state = "active"
            elif op == "vacuum":
                state = "none"
        else:  # active
            op = rng.choice(
                [
                    "refresh_full",
                    "refresh_incremental",
                    "optimize",
                    "vacuum",  # ACTIVE -> vacuum-outdated sweep
                    "delete",
                    "serve",
                    "serve",
                ]
            )
            if op in ("refresh_full", "refresh_incremental"):
                appended += 1
                steps.append(("append", appended))
            steps.append((op,))
            if op == "delete":
                state = "deleted"
        if rng.random() < 0.3:
            steps.append(("serve",))
    return steps


class ChaosHarness:
    """One seeded schedule, executable crash-free or with a crash at a
    chosen (lifecycle-step index, crash point)."""

    def __init__(
        self,
        root: str,
        seed: int = 0,
        n_steps: int = 12,
        rows_per_file: int = 120,
        lease_ms: int = 50,
    ):
        self.root = root
        self.seed = seed
        self.rows_per_file = rows_per_file
        self.lease_ms = lease_ms
        self.schedule = build_schedule(seed, n_steps)

    # -- deterministic source data ------------------------------------------
    def _file_table(self, ordinal: int) -> pa.Table:
        rng = np.random.default_rng(self.seed * 1000 + ordinal)
        n = self.rows_per_file
        return pa.table(
            {
                "k": pa.array(rng.integers(0, 40, n), pa.int64()),
                "v": pa.array(rng.integers(-500, 500, n), pa.int64()),
                "q": pa.array(
                    [f"s{int(x)}" for x in rng.integers(0, 6, n)]
                ),
            }
        )

    def _write_source_file(self, src_dir: str, ordinal: int) -> None:
        pq.write_table(
            self._file_table(ordinal),
            os.path.join(src_dir, f"part-{ordinal:03d}.parquet"),
        )

    def _make_session(self, run_dir: str):
        from hyperspace_tpu.session import HyperspaceSession

        index_root = os.path.join(run_dir, "indexes")
        os.makedirs(index_root, exist_ok=True)
        s = HyperspaceSession()
        s.conf.set(C.INDEX_SYSTEM_PATH, index_root)
        s.conf.set(C.INDEX_NUM_BUCKETS, 4)
        s.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        s.conf.set(C.RECOVERY_LEASE_MS, self.lease_ms)
        s.conf.set(C.RECOVERY_ORPHAN_GRACE_MS, 0)
        return s, index_root

    # -- execution -----------------------------------------------------------
    def run(
        self,
        crash_step: Optional[int] = None,
        crash_point: Optional[str] = None,
        run_name: Optional[str] = None,
    ) -> ChaosReport:
        """Execute the schedule; when ``crash_step`` names the Nth
        LIFECYCLE step (0-based, ``lifecycle_steps()`` order), arm
        ``crash_point`` just before it, recover after the simulated
        death, assert the recovery contract, retry, continue."""
        if run_name is None:
            run_name = (
                "clean"
                if crash_step is None
                else f"crash_{crash_step}_{crash_point}"
            )
        run_dir = os.path.join(self.root, run_name)
        src_dir = os.path.join(run_dir, "source")
        os.makedirs(src_dir, exist_ok=True)
        self._write_source_file(src_dir, 0)
        session, index_root = self._make_session(run_dir)
        from hyperspace_tpu.hyperspace import Hyperspace

        hs = Hyperspace(session)
        report = ChaosReport(schedule=list(self.schedule))
        index_path = os.path.join(index_root, INDEX_NAME)
        lifecycle_i = -1
        for step in self.schedule:
            op = step[0]
            if op == "append":
                self._write_source_file(src_dir, step[1])
                continue
            if op == "serve":
                report.serve_results.append(self._serve(session, src_dir))
                continue
            lifecycle_i += 1
            armed = (
                crash_step is not None
                and lifecycle_i == crash_step
                and crash_point is not None
            )
            if armed:
                faults.set_crash(crash_point, "raise")
            try:
                self._lifecycle(hs, session, src_dir, op)
                if armed:
                    # the armed point never executed (the op no-op'ed or
                    # took a path without that seam): not a failure of
                    # recovery, but the matrix records it
                    faults.set_crash(crash_point, "off")
                    report.crashes_skipped += 1
            except SimulatedCrash:
                report.crashes_fired += 1
                faults.set_crash(crash_point, "off")
                self._recover_and_assert(session, hs, index_path, report)
                # retry once: a crash BEFORE commit redoes the op, a
                # crash AFTER commit surfaces as no-op/illegal-state
                try:
                    self._lifecycle(hs, session, src_dir, op)
                except (HyperspaceException, NoChangesException):
                    pass
        # end-of-schedule sweep: the contract the bench rung gates on
        self._recover_and_assert(session, hs, index_path, report, final=True)
        return report

    def lifecycle_steps(self) -> List[Tuple]:
        return [s for s in self.schedule if s[0] in LIFECYCLE_OPS]

    # -- pieces --------------------------------------------------------------
    def _serve(self, session, src_dir: str) -> pa.Table:
        """One serve step, differentially checked: the index-rewritten
        answer must equal the source-only answer (sorted — bucketed
        serves interleave row order)."""
        df = session.read.parquet(src_dir)
        q = df.filter(df["k"] >= 20).select("k", "v", "q")
        session.index_manager.clear_cache()
        session.enable_hyperspace()
        got = q.collect()
        session.disable_hyperspace()
        want = q.collect()
        got_s = _sorted(got)
        want_s = _sorted(want)
        if not got_s.equals(want_s):
            raise AssertionError(
                f"serve diverged from source truth: {got_s.num_rows} vs "
                f"{want_s.num_rows} rows"
            )
        return got_s

    def _lifecycle(self, hs, session, src_dir: str, op: str) -> None:
        session.index_manager.clear_cache()
        if op == "create":
            from hyperspace_tpu.indexes.covering import CoveringIndexConfig

            df = session.read.parquet(src_dir)
            hs.create_index(
                df, CoveringIndexConfig(INDEX_NAME, ["k"], ["v", "q"])
            )
        elif op == "refresh_full":
            hs.refresh_index(INDEX_NAME, "full")
        elif op == "refresh_incremental":
            hs.refresh_index(INDEX_NAME, "incremental")
        elif op == "optimize":
            try:
                hs.optimize_index(INDEX_NAME, "full")
            except NoChangesException:  # pragma: no cover - swallowed in run()
                pass
        elif op == "delete":
            hs.delete_index(INDEX_NAME)
        elif op == "restore":
            hs.restore_index(INDEX_NAME)
        elif op == "vacuum":
            hs.vacuum_index(INDEX_NAME)
        else:  # pragma: no cover
            raise ValueError(f"unknown op {op!r}")

    def _recover_and_assert(
        self, session, hs, index_path: str, report: ChaosReport, final=False
    ) -> None:
        """Recovery + the full invariant sweep the chaos contract names."""
        if not os.path.isdir(os.path.join(index_path, C.HYPERSPACE_LOG_DIR)):
            report.final_state = States.DOESNOTEXIST if final else None
            return
        # the dead writer's lease must age out (heartbeat died with it)
        time.sleep(self.lease_ms * 2.5 / 1000.0)
        rep = hs.recover(INDEX_NAME, gc=True)
        report.recoveries += 1
        report.rolled_back += bool(rep.get("rolled_back"))
        report.healed_pointers += bool(rep.get("healed_pointer"))
        gc_rep = rep.get("gc") or {}
        report.gc_quarantined += int(
            gc_rep.get("quarantined_files", 0)
        ) + int(gc_rep.get("quarantined_dirs", 0))
        # HS2xx invariant at runtime: the tip is stable
        log_mgr, _ = session.index_manager._managers(INDEX_NAME)
        tip = log_mgr.get_latest_log()
        state = tip.state if tip is not None else States.DOESNOTEXIST
        if state not in States.STABLE_STATES:
            report.stranded_after += 1
        # GC convergence: a second pass finds nothing left to take
        leftovers = recovery.find_orphans(index_path)
        report.orphans_after_gc += len(leftovers)
        if final:
            report.final_state = state


def _sorted(t: pa.Table) -> pa.Table:
    return t.sort_by([(c, "ascending") for c in t.column_names])


def run_crash_matrix(
    root: str,
    seed: int = 0,
    n_steps: int = 12,
    points: Tuple[str, ...] = faults.CRASH_POINTS,
    max_cells: Optional[int] = None,
) -> Dict[str, object]:
    """Crash the seeded schedule at every (lifecycle step × crash point)
    cell in turn and aggregate the invariant counters — the bench rung.

    Every run's serve results must match the crash-free replica's
    step-for-step; the aggregate must show zero stranded entries and
    zero orphans after GC. Returns the summary dict ``bench.py`` emits
    (and ``scripts/bench_smoke.sh`` asserts on)."""
    harness = ChaosHarness(root, seed=seed, n_steps=n_steps)
    clean = harness.run(run_name="clean")
    cells = [
        (i, p)
        for i in range(len(harness.lifecycle_steps()))
        for p in points
    ]
    if max_cells is not None:
        cells = cells[:max_cells]
    summary: Dict[str, object] = {
        "seed": seed,
        "schedule_steps": len(harness.schedule),
        "lifecycle_steps": len(harness.lifecycle_steps()),
        "cells": len(cells),
        "crashes_fired": 0,
        "crashes_skipped": 0,
        "rolled_back": 0,
        "healed_pointers": 0,
        "stranded_after_recovery": 0,
        "orphans_after_gc": 0,
        "serve_mismatches": 0,
        "serves_verified": 0,
    }
    for i, point in cells:
        rep = harness.run(crash_step=i, crash_point=point)
        summary["crashes_fired"] += rep.crashes_fired
        summary["crashes_skipped"] += rep.crashes_skipped
        summary["rolled_back"] += rep.rolled_back
        summary["healed_pointers"] += rep.healed_pointers
        summary["stranded_after_recovery"] += rep.stranded_after
        summary["orphans_after_gc"] += rep.orphans_after_gc
        for got, want in zip(rep.serve_results, clean.serve_results):
            summary["serves_verified"] += 1
            if not got.equals(want):
                summary["serve_mismatches"] += 1
    return summary
