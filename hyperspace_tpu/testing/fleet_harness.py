"""Multi-process fleet harness: N frontend processes over one lake.

The chaos harness (``testing/chaos.py``) kills one WRITER at one
protocol point; this module is its serve-tier generalization — the
composition test for the fleet planes (``serve/fleet.py``,
docs/fleet-serve.md). It spawns N real OS processes, each running a
``FleetFrontend`` over the SAME index lake, drives an identical query
schedule through all of them from a file barrier, and (on the chaos
rung) ``kill -9``\\ s one frontend mid-serve. The contract it asserts is
the fleet's whole promise at once:

* **zero wrong answers** — every surviving worker's per-query digest
  equals the parent's single-process ground truth (computed with AND
  without index rewriting);
* **cross-process dedup** — identical plans submitted to N processes
  elected one executor: the sum of ``spool_hits`` across workers is
  positive (the PR 8 dedup must not regress to zero at N processes);
* **zero leaked pins** — after the killed worker's pin lease expires,
  one GC pass reaps its durable pin files and the lake's file set
  converges (nothing pinned, nothing stranded, nothing deleted from
  under the survivors mid-serve).

Used by ``tests/test_fleet.py`` (slow rung), the ``bench.py``
multi-process QPS ladder, and the 2-process smoke in
``scripts/bench_smoke.sh``. Workers re-enter this module via
``python -m hyperspace_tpu.testing.fleet_harness --worker <spec.json>``.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import subprocess
import sys
import time
import uuid
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu import constants as C

INDEX_NAME = "fleetidx"

#: worker-side defaults; the parent overrides via the spec's conf map
WORKER_CONF = {
    C.INDEX_NUM_BUCKETS: 4,
    C.FLEET_ENABLED: True,
    C.SERVE_CACHE_ENABLED: True,
    C.FLEET_BUS_POLL_MS: 50,
    C.FLEET_PIN_LEASE_MS: 2_000,
    C.FLEET_SINGLEFLIGHT_WAIT_MS: 3_000,
    C.FLEET_SINGLEFLIGHT_CLAIM_MS: 4_000,
    # a GENEROUS member lease: in-rung reaping is lease-only, so a
    # kill -9 victim's member file survives the rung and the survivors'
    # probes deterministically exercise the dead-owner fallback (the
    # parent's convergence check reaps by pid liveness afterwards)
    C.FLEET_FAST_MEMBER_LEASE_MS: 60_000,
    C.FLEET_FAST_GOSSIP_MS: 50,
}


def _digest(table: pa.Table) -> str:
    """Stable cross-process content digest: sort by every column, then
    hash the plain-python rendering (int/string payloads only by
    harness construction, so repr is deterministic)."""
    t = table.sort_by([(c, "ascending") for c in table.column_names])
    return hashlib.sha256(repr(t.to_pydict()).encode("utf-8")).hexdigest()


def build_lake(
    root: str, rows: int = 20_000, n_files: int = 4, seed: int = 0
) -> Tuple[str, str]:
    """Write the shared source data + build the covering index once
    (parent-side). Returns (src_dir, index_system_path)."""
    src = os.path.join(root, "source")
    index_root = os.path.join(root, "indexes")
    os.makedirs(src, exist_ok=True)
    os.makedirs(index_root, exist_ok=True)
    rng = np.random.default_rng(seed)
    per = max(1, rows // n_files)
    for i in range(n_files):
        pq.write_table(
            pa.table(
                {
                    "k": pa.array(rng.integers(0, 200, per), pa.int64()),
                    "v": pa.array(rng.integers(-1000, 1000, per), pa.int64()),
                }
            ),
            os.path.join(src, f"part-{i:03d}.parquet"),
        )
    session = _make_session(src, index_root, fleet=False)
    from hyperspace_tpu.hyperspace import Hyperspace
    from hyperspace_tpu.indexes.covering import CoveringIndexConfig

    hs = Hyperspace(session)
    df = session.read.parquet(src)
    hs.create_index(df, CoveringIndexConfig(INDEX_NAME, ["k"], ["v"]))
    return src, index_root


def _make_session(src: str, index_root: str, fleet: bool, conf=None):
    from hyperspace_tpu.session import HyperspaceSession

    s = HyperspaceSession()
    s.conf.set(C.INDEX_SYSTEM_PATH, index_root)
    for k, v in WORKER_CONF.items():
        s.conf.set(k, v)
    s.conf.set(C.FLEET_ENABLED, fleet)
    for k, v in (conf or {}).items():
        s.conf.set(k, v)
    s.enable_hyperspace()
    return s


def build_queries(session, src: str, n_queries: int = 6) -> List:
    """The shared schedule: every worker runs the SAME DataFrames in the
    same order, so identical submissions meet at the claim plane. Int
    aggregates only — exact under any row order, keeping the digests
    bitwise across processes and degrade paths."""
    from hyperspace_tpu import functions as F

    out = []
    for i in range(n_queries):
        df = session.read.parquet(src)
        if i % 3 == 0:
            out.append(df.filter(df["k"] == (17 * i + 5) % 200))
        elif i % 3 == 1:
            lo = (i * 23) % 150
            out.append(
                df.filter((df["k"] >= lo) & (df["k"] < lo + 40)).agg(
                    F.count().alias("n"), F.sum("v").alias("sv")
                )
            )
        else:
            out.append(
                df.filter(df["k"] < 120 + i).group_by("k").agg(
                    F.count().alias("n")
                )
            )
    return out


def expected_digests(root: str, src: str, index_root: str, n_queries: int):
    """Parent-side ground truth, differentially checked: the indexed
    answer must equal the unindexed answer before it may serve as the
    workers' reference."""
    session = _make_session(src, index_root, fleet=False)
    queries = build_queries(session, src, n_queries)
    out = {}
    for qid, df in enumerate(queries):
        session.enable_hyperspace()
        got = df.collect()
        session.disable_hyperspace()
        want = df.collect()
        d_got, d_want = _digest(got), _digest(want)
        if d_got != d_want:
            raise AssertionError(
                f"parent ground truth diverged on query {qid}: indexed "
                f"{got.num_rows} rows vs source {want.num_rows}"
            )
        out[str(qid)] = d_got
    return out


# ---------------------------------------------------------------------------
# Worker side
# ---------------------------------------------------------------------------


def worker_main(spec_path: str) -> int:
    with open(spec_path, "r", encoding="utf-8") as fh:
        spec = json.load(fh)
    session = _make_session(
        spec["src"], spec["index_root"], fleet=True, conf=spec.get("conf")
    )
    fe = session.serve_frontend
    queries = build_queries(session, spec["src"], spec["n_queries"])
    # warm the engine BEFORE the barrier (trace/compile, scan pools,
    # calibration) on per-worker-distinct predicates — distinct digests,
    # so no warmup single-flights onto a peer and skips its own warm.
    # The measured window then times serving, not first-touch setup.
    if spec.get("warmup", True):
        from hyperspace_tpu import functions as F

        wid = int(spec["worker_id"])
        df = session.read.parquet(spec["src"])
        for wq in (
            df.filter(df["k"] == -(wid + 1)),
            df.filter(df["k"] >= -(wid + 2)).agg(F.count().alias("n")),
            df.filter(df["k"] < -(wid + 3)).group_by("k").agg(
                F.count().alias("n")
            ),
        ):
            fe.serve(wq)
    with open(spec["ready"], "w", encoding="utf-8") as fh:
        fh.write(str(os.getpid()))
    deadline = time.monotonic() + 60.0
    while not os.path.exists(spec["go"]):
        if time.monotonic() >= deadline:
            return 3
        time.sleep(0.01)
    digests: Dict[str, str] = {}
    latencies: List[float] = []
    t_start = time.perf_counter()
    served = 0
    slo_class = spec.get("slo_class")
    for _ in range(spec["iters"]):
        for qid, df in enumerate(queries):
            t0 = time.perf_counter()
            table = fe.serve(df, slo_class=slo_class)
            latencies.append(time.perf_counter() - t0)
            digests[str(qid)] = _digest(table)
            served += 1
            if served == 1 and spec.get("serving_marker"):
                with open(spec["serving_marker"], "w", encoding="utf-8") as fh:
                    fh.write("1")
    wall = time.perf_counter() - t_start
    probes = probe_mismatches = 0
    if spec.get("fastpath_phase"):
        # phase 2 (after the measured window — wall_s/qps are phase-1
        # numbers): the parent refreshes the index between done1 and
        # go2, so every live worker witnesses >=1 pushed fanout event;
        # then each worker serves one probe per OTHER member, chosen so
        # its digest rendezvous-routes to that member — a live target is
        # a deterministic spool-free handoff, a kill -9'd target is a
        # deterministic dead-owner fallback, and every probe answer is
        # differentially checked against the unindexed truth
        with open(spec["done1"], "w", encoding="utf-8") as fh:
            fh.write("1")
        deadline2 = time.monotonic() + 60.0
        while not os.path.exists(spec["go2"]):
            if time.monotonic() >= deadline2:
                return 4
            time.sleep(0.01)
        probes, probe_mismatches = _run_probes(session, fe, spec["src"])
    stats = fe.stats()
    fe.close()
    obs_report = None
    if session.conf.obs_enabled:
        # the parent asserts cross-process trace linkage: this worker's
        # root trace ids plus every winner id its spool hits linked to
        from hyperspace_tpu.obs import trace as obs_trace

        roots = obs_trace.finished("serve.query")
        obs_report = {
            "root_trace_ids": [r.trace_id for r in roots],
            "spool_hit_links": [
                e.get("winner_trace_id")
                for r in roots
                for e in r.events
                if e.get("name") == "spool_hit"
            ],
        }
    lat_ms = sorted(x * 1000 for x in latencies)
    out = {
        "worker": spec["worker_id"],
        "pid": os.getpid(),
        "digests": digests,
        "served": served,
        "obs": obs_report,
        "wall_s": wall,
        "p50_ms": lat_ms[len(lat_ms) // 2] if lat_ms else 0.0,
        "p99_ms": lat_ms[min(len(lat_ms) - 1, (len(lat_ms) * 99) // 100)]
        if lat_ms
        else 0.0,
        "probes": probes,
        "probe_mismatches": probe_mismatches,
        "stats": stats,
    }
    tmp = spec["out"] + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(out, fh)
    os.replace(tmp, spec["out"])
    return 0


def _run_probes(session, fe, src: str) -> Tuple[int, int]:
    """Serve one digest-targeted probe per OTHER fast-plane member.

    For each peer in the member directory this worker searches candidate
    predicates until it finds one whose (plan, snapshot) digest
    rendezvous-routes to that peer, then serves it and differentially
    checks the answer against the unindexed truth. Returns
    ``(probes, mismatches)``; a worker without a live router (fast plane
    disabled or degraded) probes nothing."""
    from hyperspace_tpu.serve import router as fleet_router

    router = getattr(fe, "_router", None)
    if router is None:
        return 0, 0
    members = fleet_router.read_members(fleet_router.members_dir(session.conf))
    targets = [o for o in members if o != router.owner]
    if not targets:
        return 0, 0
    pin = fe._pin()
    if not pin:
        return 0, 0
    df0 = session.read.parquet(src)
    probes = mismatches = 0
    for target in targets:
        probe = None
        # the probe predicate space is disjoint from the phase-1
        # schedule by shape (the extra always-true v bound), so probe
        # digests never collide with already-cached phase-1 results
        for kk in range(200):
            df = df0.filter((df0["k"] == kk) & (df0["v"] > -2000))
            digest = fe._plan_digest(df.logical_plan, pin)
            if (
                digest is not None
                and fleet_router.rendezvous_owner(members.keys(), digest)
                == target
            ):
                probe = df
                break
        if probe is None:
            continue
        got = fe.serve(probe)
        session.disable_hyperspace()
        want = probe.collect()
        session.enable_hyperspace()
        probes += 1
        if _digest(got) != _digest(want):
            mismatches += 1
    return probes, mismatches


# ---------------------------------------------------------------------------
# Parent side
# ---------------------------------------------------------------------------


def _spawn_worker(spec: dict, spec_path: str) -> subprocess.Popen:
    with open(spec_path, "w", encoding="utf-8") as fh:
        json.dump(spec, fh)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "hyperspace_tpu.testing.fleet_harness",
            "--worker",
            spec_path,
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT,
    )


def run_fleet(
    root: str,
    n_procs: int,
    iters: int = 4,
    rows: int = 20_000,
    n_queries: int = 6,
    kill_one: bool = False,
    conf: Optional[dict] = None,
    timeout_s: float = 180.0,
    reuse_lake: Optional[Tuple[str, str]] = None,
    fastpath_phase: bool = False,
) -> Dict[str, object]:
    """Run one fleet rung: N worker processes serving the same schedule
    against one lake from a barrier start (optionally ``kill -9`` one
    mid-serve). Returns the aggregate the bench ladder emits and the
    smoke asserts on — wrong answers, cross-process dedup, leaked pin
    files, aggregate QPS."""
    os.makedirs(root, exist_ok=True)
    if reuse_lake is not None:
        src, index_root = reuse_lake
    else:
        src, index_root = build_lake(root, rows=rows)
    # cold coordination plane per rung: a reused lake must not hand this
    # rung the previous rung's spooled results (the ladder measures each
    # process count in the same regime, not a progressively warmer spool)
    from hyperspace_tpu.utils import files as file_utils

    file_utils.delete(os.path.join(index_root, C.HYPERSPACE_FLEET_DIR))
    expected = expected_digests(root, src, index_root, n_queries)
    procs: List[subprocess.Popen] = []
    specs: List[dict] = []
    for i in range(n_procs):
        spec = {
            "worker_id": i,
            "src": src,
            "index_root": index_root,
            "iters": iters,
            "n_queries": n_queries,
            "ready": os.path.join(root, f"ready.{i}"),
            "go": os.path.join(root, "go"),
            "out": os.path.join(root, f"out.{i}.json"),
            "conf": conf or {},
        }
        if fastpath_phase:
            spec["fastpath_phase"] = True
            spec["done1"] = os.path.join(root, f"done1.{i}")
            spec["go2"] = os.path.join(root, "go2")
        if kill_one and i == 0:
            # the victim serves an effectively-endless schedule; the
            # parent SIGKILLs it as soon as its first serve lands
            spec["iters"] = max(iters * 1000, 100_000)
            spec["serving_marker"] = os.path.join(root, "serving.0")
        specs.append(spec)
        procs.append(_spawn_worker(spec, os.path.join(root, f"spec.{i}.json")))
    deadline = time.monotonic() + timeout_s
    try:
        for spec in specs:
            while not os.path.exists(spec["ready"]):
                if time.monotonic() >= deadline:
                    raise TimeoutError("fleet worker never became ready")
                _reap_early_exit(procs)
                time.sleep(0.02)
        with open(os.path.join(root, "go"), "w", encoding="utf-8") as fh:
            fh.write("1")
        killed_pid = None
        if kill_one:
            marker = specs[0]["serving_marker"]
            while not os.path.exists(marker):
                if time.monotonic() >= deadline:
                    raise TimeoutError("chaos victim never started serving")
                time.sleep(0.005)
            killed_pid = procs[0].pid
            os.kill(killed_pid, signal.SIGKILL)
        if fastpath_phase:
            # the survivors are parked at the phase-2 barrier; refresh
            # the index NOW (its fanout push is every live worker's
            # pushed-event witness), then release them into the probes
            for i, spec in enumerate(specs):
                if kill_one and i == 0:
                    continue
                while not os.path.exists(spec["done1"]):
                    if time.monotonic() >= deadline:
                        raise TimeoutError(
                            "fleet worker never finished phase 1"
                        )
                    _reap_early_exit(
                        [p for j, p in enumerate(procs) if not (kill_one and j == 0)]
                    )
                    time.sleep(0.02)
            from hyperspace_tpu.hyperspace import Hyperspace

            # the refresh needs actual changes (an unchanged source is a
            # no-op action, which publishes nothing): append one small
            # delta file, then fan the incremental refresh out
            delta_id = uuid.uuid4().hex[:8]
            rng = np.random.default_rng(int(delta_id, 16) % (1 << 31))
            pq.write_table(
                pa.table(
                    {
                        "k": pa.array(rng.integers(0, 200, 200), pa.int64()),
                        "v": pa.array(
                            rng.integers(-1000, 1000, 200), pa.int64()
                        ),
                    }
                ),
                # unique per rung: a reused lake must present the NEXT
                # rung's refresh with fresh changes too (an unchanged
                # source is a no-op, and no-ops publish nothing)
                os.path.join(src, f"part-phase2-{delta_id}.parquet"),
            )
            refresher = _make_session(src, index_root, fleet=True, conf=conf)
            Hyperspace(refresher).refresh_index(INDEX_NAME, "incremental")
            with open(os.path.join(root, "go2"), "w", encoding="utf-8") as fh:
                fh.write("1")
        for i, p in enumerate(procs):
            if kill_one and i == 0:
                p.wait()
                continue
            remain = max(1.0, deadline - time.monotonic())
            rc = p.wait(timeout=remain)
            if rc != 0:
                raise AssertionError(f"fleet worker {i} exited rc={rc}")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = []
    for i, spec in enumerate(specs):
        if kill_one and i == 0:
            continue
        with open(spec["out"], "r", encoding="utf-8") as fh:
            results.append(json.load(fh))
    wrong = 0
    for r in results:
        for qid, want in expected.items():
            if r["digests"].get(qid) != want:
                wrong += 1
    total_served = sum(r["served"] for r in results)
    max_wall = max((r["wall_s"] for r in results), default=0.0)
    lease_ms = int(
        (conf or {}).get(
            C.FLEET_PIN_LEASE_MS, WORKER_CONF[C.FLEET_PIN_LEASE_MS]
        )
    )
    # the ONE documented way to combine per-worker counter snapshots
    # (obs.merge_snapshots: counters sum, watermarks max, percentiles
    # drop) — this used to be three hand-rolled sum() folds
    from hyperspace_tpu.obs import merge_snapshots

    fleet_merged = merge_snapshots(
        *(r["stats"].get("fleet", {}) for r in results)
    )
    spool_hits = fleet_merged.get("spool_hits", 0)
    claims_won = fleet_merged.get("claims_won", 0)
    bus_events = fleet_merged.get("bus_events", 0)
    probes = sum(r.get("probes", 0) for r in results)
    probe_mismatches = sum(r.get("probe_mismatches", 0) for r in results)
    leaked = _converge_pins(index_root, lease_ms=lease_ms)
    leaked_fast = _converge_fast_members(index_root)
    return {
        "processes": n_procs,
        "workers_reporting": len(results),
        "killed": bool(kill_one),
        "queries": total_served,
        "wrong_answers": wrong + probe_mismatches,
        "qps": round(total_served / max_wall, 1) if max_wall > 0 else 0.0,
        "p50_ms": round(
            float(np.median([r["p50_ms"] for r in results])), 2
        )
        if results
        else 0.0,
        "p99_ms": round(max(r["p99_ms"] for r in results), 2)
        if results
        else 0.0,
        "cross_process_dedup": spool_hits,
        "claims_won": claims_won,
        "bus_events": bus_events,
        # fast data plane (merged across workers; fast_frontends is the
        # count of workers whose fast plane came up)
        "fast_frontends": fleet_merged.get("fast_frontends", 0),
        "fast_push_received": fleet_merged.get("fast_push_received", 0),
        "fast_handoffs": fleet_merged.get("fast_handoffs", 0),
        "fast_fallbacks": fleet_merged.get("fast_fallbacks", 0),
        "fast_result_hits": fleet_merged.get("fast_result_hits", 0),
        "fast_dedup_joins": fleet_merged.get("fast_dedup_joins", 0),
        "fast_wait_ms_total": fleet_merged.get("fast_wait_ms_total", 0.0),
        "fast_waits": fleet_merged.get("fast_waits", 0),
        "poll_wait_ms_total": fleet_merged.get("poll_wait_ms_total", 0.0),
        "poll_waits": fleet_merged.get("poll_waits", 0),
        "probes": probes,
        "probe_mismatches": probe_mismatches,
        "leaked_pin_files": leaked,
        "leaked_fast_members": leaked_fast,
        "worker_obs": [r.get("obs") for r in results if r.get("obs")],
    }


def _reap_early_exit(procs: List[subprocess.Popen]) -> None:
    for i, p in enumerate(procs):
        rc = p.poll()
        if rc is not None and rc != 0:
            raise AssertionError(
                f"fleet worker {i} died before the barrier (rc={rc})"
            )


def _converge_pins(index_root: str, lease_ms: Optional[int] = None) -> int:
    """Wait out the pin lease, run one GC pass per index (which reaps
    expired pin files), and count any pin file that SURVIVES — the
    killed frontend's leavings must converge to zero."""
    from hyperspace_tpu.metadata import recovery

    lease = lease_ms or WORKER_CONF[C.FLEET_PIN_LEASE_MS]
    time.sleep(lease * 1.5 / 1000.0)
    leaked = 0
    try:
        index_dirs = sorted(os.listdir(index_root))
    except OSError:
        return 0
    for name in index_dirs:
        index_path = os.path.join(index_root, name)
        if not os.path.isdir(index_path) or name.startswith("_"):
            continue
        recovery.gc_orphans(index_path, grace_ms=0)
        pins_dir = os.path.join(index_path, C.HYPERSPACE_PINS_DIR)
        if os.path.isdir(pins_dir):
            leaked += sum(
                1 for f in os.listdir(pins_dir) if f.endswith(".json")
            )
    return leaked


def _converge_fast_members(index_root: str) -> int:
    """After the rung, reap every member whose PROCESS is gone (kill -9
    victims leave lease-valid member files — the generous harness lease
    is deliberate, see ``WORKER_CONF``) and count member or socket files
    that survive the reap: the fast plane's leak witness."""
    from hyperspace_tpu.serve import router as fleet_router

    mdir = os.path.join(index_root, C.HYPERSPACE_FLEET_DIR, "members")
    _reaped, leftovers = fleet_router.reap_members(mdir, force_dead=True)
    leaked = len(leftovers)
    try:
        leaked += sum(1 for f in os.listdir(mdir) if f.endswith(".json"))
    except OSError:
        pass
    return leaked


def main(argv: List[str]) -> int:
    if len(argv) >= 2 and argv[0] == "--worker":
        return worker_main(argv[1])
    print(
        "usage: python -m hyperspace_tpu.testing.fleet_harness "
        "--worker <spec.json>",
        file=sys.stderr,
    )
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
