"""Runtime collective witness: record each process's collective program.

The HS8xx checkers (``analysis/spmd.py``) reason about a *static* model
of the multi-host plane: which call sites issue collectives, which
symmetry contract each declares (``COLLECTIVE_SITES`` in
``parallel/collectives.py``), and whether process-identity branches or
process-local loop bounds can make processes diverge. Like the lock
model, that model rots silently — a new code path can issue a collective
the analyzer cannot see, and a "symmetric" site can stop being
symmetric. This module closes the loop dynamically, the
``lock_witness.py`` recipe applied to collectives:

* :func:`install` wraps every callable named in ``COLLECTIVE_SITES`` by
  module-attribute replacement (in-module callers resolve the name
  through module globals at call time, so the wrapper is seen
  everywhere — the reason site paths must be module-level callables);
* while the multi-host dryrun runs, each wrapper appends one record to
  this process's ordered collective sequence: site, op, contract, wave
  index (per-site occurrence count) and a payload *signature* —
  dtype/ndim per array argument plus reprs of static scalars — chosen
  so symmetric sites produce identical signatures on every process
  while per-host payload SIZES may differ;
* :func:`dump` writes a per-process JSON artifact at
  ``<path>.p<process_index>.json`` via the shared atomic-write helper
  (``testing/artifacts.py``);
* ``hslint --witness <path>`` merges the per-process artifacts and
  cross-checks them (``analysis/spmd.py``): any cross-process sequence
  divergence, any witnessed-but-unregistered site, and any
  coordinator-gated site witnessed off the coordinator is a hard HS804
  error; a registered site never witnessed is a staleness warning.

Armed via ``HS_COLLECTIVE_WITNESS=<path prefix>`` in
``scripts/dryrun_multihost.py`` (each worker installs before
``initialize_distributed`` so even the bootstrap is witnessed);
``scripts/bench_smoke.sh`` runs the 2-process dryrun under it and gates
on zero divergence. Stdlib-only apart from a lazy numpy/jax sniff in the
signature helper.
"""

from __future__ import annotations

import importlib
import threading
from typing import Dict, List, Tuple

_PKG = "hyperspace_tpu"

_rec_lock = threading.Lock()
_records: List[dict] = []
_wave_counts: Dict[str, int] = {}

_installed: Dict[str, "_WitnessSite"] = {}  # site path -> wrapper
_module_patches: List[Tuple[object, str, object]] = []  # (module, attr, orig)


class _WitnessSite:
    """Recording wrapper around one registered collective site."""

    def __init__(self, inner, site: str, op: str, contract: str):
        self._inner = inner
        self.witness_site = site
        self._op = op
        self._contract = contract

    def __call__(self, *args, **kwargs):
        with _rec_lock:
            wave = _wave_counts.get(self.witness_site, 0)
            _wave_counts[self.witness_site] = wave + 1
            _records.append(
                {
                    "site": self.witness_site,
                    "op": self._op,
                    "contract": self._contract,
                    "wave": wave,
                    "sig": _signature(args, kwargs),
                }
            )
        return self._inner(*args, **kwargs)


def _signature(args: tuple, kwargs: dict) -> str:
    """A cheap cross-process-comparable payload signature: array
    arguments contribute dtype+rank (NOT extents — per-host-lane sites
    legitimately carry different row counts), static scalars/strings
    contribute their repr, containers recurse, everything else its type
    name. For ``symmetric-all`` sites the merge requires signatures to
    match position-by-position across processes."""
    parts = [_sig_one(a) for a in args]
    parts.extend(f"{k}={_sig_one(v)}" for k, v in sorted(kwargs.items()))
    return "(" + ", ".join(parts) + ")"


def _sig_one(v) -> str:
    if isinstance(v, (str, int, bool, float)) or v is None:
        return repr(v)
    if isinstance(v, (tuple, list)):
        return "[" + ", ".join(_sig_one(x) for x in v) + "]"
    if isinstance(v, dict):
        return (
            "{"
            + ", ".join(f"{k}: {_sig_one(x)}" for k, x in sorted(v.items()))
            + "}"
        )
    dtype = getattr(v, "dtype", None)
    ndim = getattr(v, "ndim", None)
    if dtype is not None and ndim is not None:
        return f"{dtype}[{ndim}d]"
    return type(v).__name__


def install() -> Dict[str, str]:
    """Wrap every COLLECTIVE_SITES callable; idempotent. Returns
    {site path -> contract} for the wrapped sites. Raises on a stale
    site path — the witness must never silently watch nothing."""
    from hyperspace_tpu.parallel.collectives import COLLECTIVE_SITES

    wrapped: Dict[str, str] = {}
    for site, (op, contract, _why) in COLLECTIVE_SITES.items():
        wrapped[site] = contract
        if site in _installed:
            continue
        mod_name, _, attr = site.rpartition(".")
        module = importlib.import_module(mod_name)
        orig = getattr(module, attr)  # AttributeError on a stale path
        if isinstance(orig, _WitnessSite):
            _installed[site] = orig
            continue
        proxy = _WitnessSite(orig, site, op, contract)
        _module_patches.append((module, attr, orig))
        setattr(module, attr, proxy)
        _installed[site] = proxy
    return wrapped


def uninstall() -> None:
    """Restore the patched module attributes."""
    while _module_patches:
        module, attr, orig = _module_patches.pop()
        setattr(module, attr, orig)
    _installed.clear()


def reset() -> None:
    """Zero the recorded sequence (artifact isolation in tests)."""
    with _rec_lock:
        _records.clear()
        _wave_counts.clear()


def snapshot() -> dict:
    """The witness document for this process so far. The process index
    is read lazily (and defaults to 0) so recording can start before —
    and even without — ``jax.distributed`` initialization."""
    from hyperspace_tpu.parallel.collectives import COLLECTIVE_SITES

    pid, nprocs = 0, 1
    try:
        import jax

        pid, nprocs = jax.process_index(), jax.process_count()
    except Exception:  # hslint: disable=HS402
        # no jax / no backend yet: a single-process recording is still a
        # valid artifact (process 0 of 1)
        pass
    with _rec_lock:
        return {
            "version": 1,
            "package": _PKG,
            "process": int(pid),
            "process_count": int(nprocs),
            "registered": {
                site: contract
                for site, (_op, contract, _why) in COLLECTIVE_SITES.items()
            },
            "sequence": list(_records),
        }


def artifact_path(prefix: str, process: int) -> str:
    """The per-process artifact path for a witness prefix — ONE naming
    rule shared with the hslint merge side (``analysis/spmd.py``)."""
    return f"{prefix}.p{process}.json"


def dump(prefix: str) -> dict:
    """Write this process's artifact at ``artifact_path(prefix, pid)``
    via the shared atomic-write helper. Returns the document."""
    from hyperspace_tpu.testing import artifacts

    doc = snapshot()
    artifacts.atomic_write_json(artifact_path(prefix, doc["process"]), doc)
    return doc
