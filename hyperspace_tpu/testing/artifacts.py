"""Witness-artifact plumbing shared by the runtime witnesses.

The lock witness (``testing/lock_witness.py``) and the collective
witness (``testing/collective_witness.py``) both publish JSON artifacts
that ``hslint --witness`` later consumes, and both need the same two
pieces:

* :func:`atomic_write_json` — the ``calibrate._store_cache`` publish
  pattern (pid-qualified temp, fsync, ``os.replace``): a reader — or a
  crash — must never observe a torn artifact, and concurrent writers
  must never clobber each other's temp file;
* :func:`merge_count_maps` — summing ``{key: count}`` maps so several
  suites (or several dumps from one process) can accumulate into one
  artifact.

Stdlib-only, like everything in ``testing/``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional


def atomic_write_json(path: str, doc: dict) -> None:
    """Publish ``doc`` at ``path`` via temp + fsync + atomic replace."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_json(path: str) -> Optional[dict]:
    """The JSON dict at ``path``, or None when absent/unreadable/torn —
    merge callers treat a bad prior artifact as 'nothing to merge'."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def merge_count_maps(base: Dict, extra: Dict) -> Dict:
    """``base`` updated in place with ``extra``'s counts summed in."""
    for key, n in extra.items():
        base[key] = base.get(key, 0) + n
    return base
