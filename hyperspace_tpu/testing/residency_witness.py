"""Runtime residency witness: record what ALLOC_SITES actually resides.

The HS10xx checker reasons about a *static* memory model — which
hot-path functions materialize row-proportional state, and which bound
class keeps each finite (``ALLOC_SITES``, ``hyperspace_tpu/memory.py``).
A static model rots silently: a declared "chunk-bounded" site can start
returning whole relations and every residency verdict is built on sand.
This module closes the loop dynamically, the lock/collective-witness
doctrine applied to bytes:

* :func:`install` wraps every function/method named in ``ALLOC_SITES``
  — module-level functions by attribute replacement (including stale
  ``from x import f`` references in already-imported package modules),
  methods by replacing the class attribute — with a recording proxy;
* each call records the site's call count and the peak resident-byte
  estimate of what it returned, sized with the SAME ruler the cache
  governor uses (``execution/serve_cache.estimate_nbytes``), so the
  witness and the byte ledgers cannot disagree about what a value
  weighs;
* :func:`dump` writes (merging with any prior artifact) a JSON witness:
  ``{"sites": {path: {"peak_bytes": n, "calls": n}},
  "budgets": {bound class: ceiling}, "rss_high_water": n}`` — budgets
  are stamped from ``memory.BOUND_CLASS_CEILINGS`` at runtime so the
  analyzer stays non-importing;
* ``hslint --witness <artifact>`` cross-checks
  (``analysis/residency.witness_cross_check``): a witnessed site the
  registry lacks is a hard model-gap error (HS1004), as is an observed
  peak past the site's declared bound-class ceiling; a declared site
  never witnessed is a staleness warning.

Enabled via the ``HS_RESIDENCY_WITNESS=<path>`` env var (see
``tests/conftest.py``); ``scripts/bench_smoke.sh`` runs a bench rung
under it and gates on the cross-check.

Overhead is one size estimate per wrapped call — fine for tests and
bench rungs, not meant for production serving. The size estimate sees
the value a site RETURNS (the materialization that escapes the site);
transient internals are covered by the process RSS high-water mark
recorded alongside (``/proc/self/status`` VmHWM, getrusage fallback).
"""

from __future__ import annotations

import importlib
import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

_PKG = "hyperspace_tpu"

_rec_lock = threading.Lock()
_sites: Dict[str, Dict[str, int]] = {}  # site -> {"peak_bytes", "calls"}

_installed: Dict[str, bool] = {}  # site path -> wrapped
_module_patches: List[Tuple[object, str, object]] = []  # (module, attr, orig)
_class_patches: List[Tuple[type, str, object]] = []  # (cls, attr, orig)


def rss_high_water_bytes() -> int:
    """Process resident-set high-water mark in bytes. Linux reads
    ``VmHWM`` from ``/proc/self/status``; elsewhere falls back to
    ``getrusage(RUSAGE_SELF).ru_maxrss`` (kilobytes on Linux). 0 when
    neither source exists — the witness records what it can."""
    try:
        with open("/proc/self/status", "r", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024
    except (ImportError, ValueError, OSError):
        return 0


def _record(site: str, nbytes: int) -> None:
    with _rec_lock:
        rec = _sites.get(site)
        if rec is None:
            rec = _sites[site] = {"peak_bytes": 0, "calls": 0}
        rec["calls"] += 1
        if nbytes > rec["peak_bytes"]:
            rec["peak_bytes"] = nbytes


def _make_wrapper(orig, site: str):
    from hyperspace_tpu.execution.serve_cache import estimate_nbytes

    def wrapper(*args, **kwargs):
        result = orig(*args, **kwargs)
        _record(site, estimate_nbytes(result))
        return result

    wrapper.__name__ = getattr(orig, "__name__", site.rpartition(".")[2])
    wrapper.__doc__ = getattr(orig, "__doc__", None)
    wrapper.__wrapped__ = orig  # uninstall + idempotence marker
    wrapper.__hs_residency_site__ = site
    return wrapper


def _resolve_site(path: str):
    """('module', module, attr) or ('class', cls, attr) for a registered
    dotted site path; None for a module-level (import-time) entry or a
    path whose module cannot be imported in this environment."""
    mod_name, _, attr = path.rpartition(".")
    try:
        module = importlib.import_module(mod_name)
        return ("module", module, attr)
    except ImportError:
        pass
    cls_mod, _, cls_name = mod_name.rpartition(".")
    try:
        module = importlib.import_module(cls_mod)
    except ImportError:
        return None
    cls = getattr(module, cls_name, None)
    if isinstance(cls, type):
        return ("class", cls, attr)
    return None


def _patch_module_function(module, attr: str, site: str) -> bool:
    orig = getattr(module, attr, None)
    if orig is None or not callable(orig):
        return False
    if getattr(orig, "__hs_residency_site__", None) == site:
        return True  # already wrapped (idempotent install)
    wrapper = _make_wrapper(orig, site)
    _module_patches.append((module, attr, orig))
    setattr(module, attr, wrapper)
    # `from x import f` copies the reference: patch every already-loaded
    # package module holding the same function object, or those callers
    # would silently bypass the witness
    for name, mod in list(sys.modules.items()):
        if mod is None or mod is module or not name.startswith(_PKG):
            continue
        for alias, val in list(getattr(mod, "__dict__", {}).items()):
            if val is orig:
                _module_patches.append((mod, alias, orig))
                setattr(mod, alias, wrapper)
    return True


def _patch_method(cls: type, attr: str, site: str) -> bool:
    raw = cls.__dict__.get(attr)
    if raw is None:
        return False
    if isinstance(raw, classmethod):
        orig = raw.__func__
        if getattr(orig, "__hs_residency_site__", None) == site:
            return True
        wrapped: object = classmethod(_make_wrapper(orig, site))
    elif isinstance(raw, staticmethod):
        orig = raw.__func__
        if getattr(orig, "__hs_residency_site__", None) == site:
            return True
        wrapped = staticmethod(_make_wrapper(orig, site))
    elif callable(raw):
        if getattr(raw, "__hs_residency_site__", None) == site:
            return True
        wrapped = _make_wrapper(raw, site)
    else:
        return False  # property / descriptor sites are not wrappable
    _class_patches.append((cls, attr, raw))
    setattr(cls, attr, wrapped)
    return True


def install() -> Dict[str, bool]:
    """Wrap every ALLOC_SITES-declared function/method; idempotent.
    Returns {site path -> wrapped} (False = unresolvable here, e.g. a
    module-level entry; HS1003 owns truly stale paths). Must run before
    the calls under test — callers that already bound a reference via
    ``from x import f`` are re-pointed for loaded modules only."""
    from hyperspace_tpu.memory import ALLOC_SITES

    out: Dict[str, bool] = {}
    for site in ALLOC_SITES:
        if site in _installed:
            out[site] = _installed[site]
            continue
        resolved = _resolve_site(site)
        ok = False
        if resolved is not None:
            kind, owner, attr = resolved
            if kind == "module":
                ok = _patch_module_function(owner, attr, site)
            else:
                ok = _patch_method(owner, attr, site)
        _installed[site] = ok
        out[site] = ok
    return out


def uninstall() -> None:
    """Restore patched module attributes and class methods."""
    while _class_patches:
        cls, attr, raw = _class_patches.pop()
        setattr(cls, attr, raw)
    while _module_patches:
        module, attr, orig = _module_patches.pop()
        setattr(module, attr, orig)
    _installed.clear()


def reset() -> None:
    """Zero the recorded per-site peaks/counts (artifact isolation)."""
    with _rec_lock:
        _sites.clear()


def snapshot() -> dict:
    """The witness document for what has been recorded so far. Budgets
    (the per-bound-class byte ceilings) are stamped here from
    ``memory.BOUND_CLASS_CEILINGS`` so the static cross-check never has
    to import the package."""
    from hyperspace_tpu.memory import BOUND_CLASS_CEILINGS

    with _rec_lock:
        sites = {k: dict(v) for k, v in _sites.items()}
    return {
        "version": 1,
        "package": _PKG,
        "sites": sites,
        "budgets": dict(BOUND_CLASS_CEILINGS),
        "rss_high_water": rss_high_water_bytes(),
    }


def dump(path: str, merge: bool = True) -> dict:
    """Write the witness artifact via the shared temp + fsync +
    atomic-replace publish helper (``testing/artifacts.py``), merging
    with any existing artifact at ``path``: peaks and the RSS high-water
    take the max, call counts sum — several suites/rungs accumulate into
    one artifact, like the lock witness. Returns the document."""
    from hyperspace_tpu.testing import artifacts

    doc = snapshot()
    prev = artifacts.load_json(path) if merge else None
    if isinstance(prev, dict):
        for site, rec in prev.get("sites", {}).items():
            if not isinstance(rec, dict):
                continue
            cur = doc["sites"].setdefault(
                site, {"peak_bytes": 0, "calls": 0}
            )
            cur["calls"] += int(rec.get("calls", 0))
            cur["peak_bytes"] = max(
                cur["peak_bytes"], int(rec.get("peak_bytes", 0))
            )
        doc["rss_high_water"] = max(
            doc["rss_high_water"], int(prev.get("rss_high_water", 0))
        )
    artifacts.atomic_write_json(path, doc)
    return doc
