"""DataFrame API — the user-facing query surface.

Mirrors the subset of Spark's DataFrame the reference operates on
(scan/filter/project/join; ``docs`` examples and
``python/hyperspace/hyperspace.py`` drive exactly these). A DataFrame is a
(session, logical plan) pair; ``collect()`` runs the session's optimizer —
where index rewrites happen when ``enable_hyperspace()`` is on, like the
reference's injected ``ApplyHyperspace`` rule (``package.scala:82-93``) —
then the executor.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import pyarrow as pa

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan import expressions as E
from hyperspace_tpu.plan.nodes import Filter, Join, LogicalPlan, Project, Scan


class DataFrame:
    def __init__(self, session, plan: LogicalPlan):
        self._session = session
        self._plan = plan

    # -- schema surface -----------------------------------------------------
    @property
    def columns(self) -> List[str]:
        return self._plan.output

    def schema(self):
        return self._plan.schema()

    @property
    def logical_plan(self) -> LogicalPlan:
        return self._plan

    def __getitem__(self, name: str) -> E.Col:
        if name not in self._plan.output:
            raise HyperspaceException(
                f"No such column {name!r}; available: {self._plan.output}"
            )
        return E.Col(name)

    # -- transformations ----------------------------------------------------
    def filter(self, condition: E.Expr) -> "DataFrame":
        if not isinstance(condition, E.Expr):
            raise HyperspaceException("filter() takes an expression")
        return DataFrame(self._session, Filter(condition, self._plan))

    where = filter

    def select(self, *columns: str) -> "DataFrame":
        cols = list(
            columns[0]
            if len(columns) == 1 and isinstance(columns[0], (list, tuple))
            else columns
        )
        return DataFrame(self._session, Project(cols, self._plan))

    def join(
        self,
        other: "DataFrame",
        on: Union[E.Expr, str, Sequence[str]],
        how: str = "inner",
    ) -> "DataFrame":
        if isinstance(on, (str, list, tuple)):
            raise HyperspaceException(
                "Same-name join keys are ambiguous in this IR; "
                "join with an expression like left['a'] == right['b']"
            )
        return DataFrame(self._session, Join(self._plan, other._plan, on, how))

    # -- actions ------------------------------------------------------------
    def collect(self) -> pa.Table:
        return self._session.execute(self._plan)

    def to_arrow(self) -> pa.Table:
        return self.collect()

    def count(self) -> int:
        return self.collect().num_rows

    def explain(self) -> str:
        """Optimized plan string (for the full with/without-index diff use
        ``Hyperspace.explain``)."""
        return self._session.optimize(self._plan).pretty()

    def __repr__(self):
        return f"DataFrame[{', '.join(self.columns)}]"
