"""DataFrame API — the user-facing query surface.

Mirrors the subset of Spark's DataFrame the reference operates on
(scan/filter/project/join; ``docs`` examples and
``python/hyperspace/hyperspace.py`` drive exactly these). A DataFrame is a
(session, logical plan) pair; ``collect()`` runs the session's optimizer —
where index rewrites happen when ``enable_hyperspace()`` is on, like the
reference's injected ``ApplyHyperspace`` rule (``package.scala:82-93``) —
then the executor.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import pyarrow as pa

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan import expressions as E
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    AggSpec,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Project,
    Scan,
    Sort,
)


def _resolve_plan_name(plan: LogicalPlan, name: str) -> str:
    """Map a user-facing name to a plan column. A dotted struct path
    (``nested.leaf.cnt``) resolves to its flattened
    ``__hs_nested.``-prefixed column when present — the query-surface side
    of the reference's nested-field support
    (``util/ResolverUtils.scala:130-234``); a literal column of the same
    dotted name always wins."""
    if name in plan.output:
        return name
    from hyperspace_tpu.constants import NESTED_FIELD_PREFIX

    prefixed = NESTED_FIELD_PREFIX + name
    if prefixed in plan.output:
        return prefixed
    raise HyperspaceException(
        f"No such column {name!r}; available: {plan.output}"
    )


class DataFrame:
    def __init__(self, session, plan: LogicalPlan):
        self._session = session
        self._plan = plan

    # -- schema surface -----------------------------------------------------
    @property
    def columns(self) -> List[str]:
        return self._plan.output

    def schema(self):
        return self._plan.schema()

    @property
    def logical_plan(self) -> LogicalPlan:
        return self._plan

    def _resolve_name(self, name: str) -> str:
        return _resolve_plan_name(self._plan, name)

    def __getitem__(self, name: str) -> E.Col:
        return E.Col(self._resolve_name(name))

    # -- transformations ----------------------------------------------------
    def filter(self, condition: E.Expr) -> "DataFrame":
        if not isinstance(condition, E.Expr):
            raise HyperspaceException("filter() takes an expression")
        return DataFrame(self._session, Filter(condition, self._plan))

    where = filter

    def select(self, *columns: str) -> "DataFrame":
        cols = list(
            columns[0]
            if len(columns) == 1 and isinstance(columns[0], (list, tuple))
            else columns
        )
        cols = [self._resolve_name(c) for c in cols]
        return DataFrame(self._session, Project(cols, self._plan))

    def join(
        self,
        other: "DataFrame",
        on: Union[E.Expr, str, Sequence[str]],
        how: str = "inner",
    ) -> "DataFrame":
        if isinstance(on, (str, list, tuple)):
            raise HyperspaceException(
                "Same-name join keys are ambiguous in this IR; "
                "join with an expression like left['a'] == right['b']"
            )
        return DataFrame(self._session, Join(self._plan, other._plan, on, how))

    def group_by(self, *columns: str) -> "GroupedData":
        cols = list(
            columns[0]
            if len(columns) == 1 and isinstance(columns[0], (list, tuple))
            else columns
        )
        cols = [self._resolve_name(c) for c in cols]
        return GroupedData(self._session, self._plan, cols)

    groupBy = group_by

    def agg(self, *aggs: AggSpec) -> "DataFrame":
        """Global aggregate (no grouping)."""
        return GroupedData(self._session, self._plan, []).agg(*aggs)

    def sort(self, *keys, ascending: Union[bool, Sequence[bool]] = True) -> "DataFrame":
        """``sort("a", "b")`` / ``sort(("a", False), "b")`` /
        ``sort("a", "b", ascending=[False, True])``."""
        names = list(
            keys[0]
            if len(keys) == 1 and isinstance(keys[0], list)
            else keys
        )
        if isinstance(ascending, bool):
            asc = [ascending] * len(names)
        else:
            asc = list(ascending)
            if len(asc) != len(names):
                raise HyperspaceException(
                    "ascending list length must match the number of sort keys"
                )
        resolved = []
        for k, a in zip(names, asc):
            if isinstance(k, tuple):
                resolved.append((self._resolve_name(k[0]), bool(k[1])))
            else:
                resolved.append((self._resolve_name(k), a))
        return DataFrame(self._session, Sort(resolved, self._plan))

    order_by = sort
    orderBy = sort

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self._session, Limit(n, self._plan))

    def create_or_replace_temp_view(self, name: str) -> None:
        """Register this DataFrame in the session catalog for
        ``session.sql`` (Spark's createOrReplaceTempView shape)."""
        self._session.register_view(name, self)

    createOrReplaceTempView = create_or_replace_temp_view

    # -- actions ------------------------------------------------------------
    def collect(self) -> pa.Table:
        return self._session.execute(self._plan)

    def to_arrow(self) -> pa.Table:
        return self.collect()

    def collect_approx(self, max_rel_error=None) -> pa.Table:
        """APPROXIMATE answer for an ungrouped — or single-key
        GROUPED — COUNT/SUM aggregate from the index's stratified row
        sample, with 95% confidence intervals (columns ``x`` / ``x_lo``
        / ``x_hi`` per aggregate ``x``; grouped shapes lead with the
        key column, one row per group the sample observed, key-sorted;
        see docs/agg-serve.md). Explicit opt-in behind
        ``hyperspace.serve.approx.enabled`` — exact serving NEVER
        substitutes this, and an estimate blowing the error budget
        (``max_rel_error`` or ``hyperspace.serve.approx.maxRelativeError``)
        in ANY group raises a typed ApproximationError instead of
        returning it."""
        from hyperspace_tpu.execution.approx_exec import approx_aggregate

        return approx_aggregate(self._session, self._plan, max_rel_error)

    def count(self) -> int:
        return self.collect().num_rows

    def explain(self) -> str:
        """Optimized plan string (for the full with/without-index diff use
        ``Hyperspace.explain``)."""
        return self._session.optimize(self._plan).pretty()

    def __repr__(self):
        return f"DataFrame[{', '.join(self.columns)}]"


class GroupedData:
    """Result of ``DataFrame.group_by`` — terminal ``agg(...)`` builds the
    Aggregate node (Spark's ``RelationalGroupedDataset`` shape)."""

    def __init__(self, session, plan: LogicalPlan, group_by: List[str]):
        self._session = session
        self._plan = plan
        self._group_by = group_by

    def agg(self, *aggs: AggSpec) -> DataFrame:
        specs = list(
            aggs[0]
            if len(aggs) == 1 and isinstance(aggs[0], (list, tuple))
            else aggs
        )
        for s in specs:
            if not isinstance(s, AggSpec):
                raise HyperspaceException(
                    f"agg() takes AggSpec values (hyperspace_tpu.functions); "
                    f"got {s!r}"
                )
        import dataclasses

        specs = [
            s
            if s.column is None
            else dataclasses.replace(
                s, column=_resolve_plan_name(self._plan, s.column)
            )
            for s in specs
        ]
        return DataFrame(
            self._session, Aggregate(self._group_by, specs, self._plan)
        )

    def count(self) -> DataFrame:
        from hyperspace_tpu import functions as F

        return self.agg(F.count())
