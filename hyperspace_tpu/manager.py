"""Index collection manager: dispatches lifecycle operations to Actions.

Reference: ``index/IndexCollectionManager.scala:28-206`` (per-index
log/data managers via PathResolver, action dispatch incl. refresh-mode and
vacuum-state branching) and ``index/CachingIndexCollectionManager.scala``
(TTL read-cache of all log entries, invalidated on any mutation).
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

from hyperspace_tpu import constants as C
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.metadata.path_resolver import PathResolver


class IndexCollectionManager:
    def __init__(self, session):
        self.session = session
        self.path_resolver = PathResolver(session.conf)
        # Session attach = the natural stranded-entry sweep point: a
        # writer that died in a PREVIOUS process left transient entries
        # whose leases have long expired — repair them before this
        # session reads or writes anything. Best-effort by contract
        # (attach must never fail on someone else's wreckage); indexes a
        # LIVE writer holds (unexpired lease) are left alone.
        if session.conf.recovery_enabled:
            try:
                self.recover_all(gc=False)
            except OSError:
                pass

    # -- recovery (metadata/recovery.py, docs/recovery.md) -------------------
    def recover(self, index_name: str, gc: bool = True) -> dict:
        """Repair one index: roll back a stranded transient entry, heal
        a stale latestStable pointer, and (``gc=True``) quarantine-then-
        delete orphan data files. Returns the combined report."""
        from hyperspace_tpu.metadata import recovery

        log_mgr, _ = self._managers(index_name)
        conf = self.session.conf
        report = recovery.ensure_recovered(log_mgr, conf.recovery_lease_ms)
        if gc:
            report["gc"] = recovery.gc_orphans(
                log_mgr.index_path,
                conf.recovery_orphan_grace_ms,
                lease_ms=conf.recovery_lease_ms,
            )
            # the spill tier is lake-level derived state: reap expired
            # files no live cache indexes (docs/out-of-core.md)
            report["spill_gc"] = recovery.reap_spill_orphans(
                self.path_resolver.system_path,
                conf.serve_spill_orphan_ttl_ms,
            )
        return report

    def recover_all(self, gc: bool = False) -> List[dict]:
        """Stranded-entry sweep over every index under the system path."""
        from hyperspace_tpu import factories
        from hyperspace_tpu.metadata import recovery

        conf = self.session.conf
        out = []
        for path in self.path_resolver.all_index_paths():
            log_mgr = factories.create_log_manager(path)
            report = recovery.ensure_recovered(log_mgr, conf.recovery_lease_ms)
            if gc:
                report["gc"] = recovery.gc_orphans(
                    path,
                    conf.recovery_orphan_grace_ms,
                    lease_ms=conf.recovery_lease_ms,
                )
            report["index_path"] = path
            out.append(report)
        if gc:
            spill_report = recovery.reap_spill_orphans(
                self.path_resolver.system_path,
                conf.serve_spill_orphan_ttl_ms,
            )
            # one lake-level summary row (the spill tier has no index);
            # rolled_back=False keeps per-index report iteration shapes
            out.append(
                {
                    "index_path": None,
                    "rolled_back": False,
                    "spill_gc": spill_report,
                }
            )
        return out

    # -- wiring -------------------------------------------------------------
    def _managers(self, index_name: str):
        from hyperspace_tpu import factories

        path = self.path_resolver.get_index_path(index_name)
        return (
            factories.create_log_manager(path),
            factories.create_data_manager(path),
        )

    # -- operations (IndexManager trait, index/IndexManager.scala:24-127) ---
    def create(self, df, index_config) -> None:
        from hyperspace_tpu.actions.create import CreateAction

        log_mgr, data_mgr = self._managers(index_config.index_name)
        CreateAction(self.session, df, index_config, log_mgr, data_mgr).run()

    def delete(self, index_name: str) -> None:
        from hyperspace_tpu.actions.delete import DeleteAction

        log_mgr, _ = self._managers(index_name)
        DeleteAction(self.session, index_name, log_mgr).run()

    def restore(self, index_name: str) -> None:
        from hyperspace_tpu.actions.delete import RestoreAction

        log_mgr, _ = self._managers(index_name)
        RestoreAction(self.session, index_name, log_mgr).run()

    def vacuum(self, index_name: str) -> None:
        """State-dependent: DELETED -> hard delete everything; ACTIVE ->
        vacuum outdated versions (IndexCollectionManager.vacuum:62-81)."""
        from hyperspace_tpu.actions.vacuum import VacuumAction, VacuumOutdatedAction

        log_mgr, data_mgr = self._managers(index_name)
        entry = log_mgr.get_latest_log()
        if entry is None:
            raise HyperspaceException(f"Index not found: {index_name!r}")
        if entry.state == States.DELETED:
            VacuumAction(self.session, index_name, log_mgr).run()
        elif entry.state == States.ACTIVE:
            VacuumOutdatedAction(self.session, index_name, log_mgr, data_mgr).run()
        else:
            raise HyperspaceException(
                f"Cannot vacuum index in state {entry.state}"
            )

    def refresh(self, index_name: str, mode: str) -> None:
        from hyperspace_tpu.actions.refresh import (
            RefreshAction,
            RefreshIncrementalAction,
            RefreshQuickAction,
        )

        mode = (mode or C.REFRESH_MODE_FULL).lower()
        if mode not in C.REFRESH_MODES:
            raise HyperspaceException(f"Unsupported refresh mode: {mode!r}")
        log_mgr, data_mgr = self._managers(index_name)
        cls = {
            C.REFRESH_MODE_FULL: RefreshAction,
            C.REFRESH_MODE_INCREMENTAL: RefreshIncrementalAction,
            C.REFRESH_MODE_QUICK: RefreshQuickAction,
        }[mode]
        cls(self.session, index_name, log_mgr, data_mgr).run()

    def optimize(self, index_name: str, mode: str) -> None:
        from hyperspace_tpu.actions.optimize import OptimizeAction

        mode = (mode or C.OPTIMIZE_MODE_QUICK).lower()
        if mode not in C.OPTIMIZE_MODES:
            raise HyperspaceException(f"Unsupported optimize mode: {mode!r}")
        log_mgr, data_mgr = self._managers(index_name)
        OptimizeAction(self.session, index_name, log_mgr, data_mgr, mode).run()

    def cancel(self, index_name: str) -> None:
        from hyperspace_tpu.actions.cancel import CancelAction

        log_mgr, _ = self._managers(index_name)
        CancelAction(self.session, index_name, log_mgr).run()

    # -- introspection ------------------------------------------------------
    def get_index_log_entry(self, index_name: str) -> Optional[IndexLogEntry]:
        log_mgr, _ = self._managers(index_name)
        return log_mgr.get_latest_stable_log()

    def get_indexes(self, states: Optional[List[str]] = None) -> List[IndexLogEntry]:
        from hyperspace_tpu import factories

        out = []
        for path in self.path_resolver.all_index_paths():
            entry = factories.create_log_manager(path).get_latest_stable_log()
            if entry is None:
                continue
            if states is None or entry.state in states:
                out.append(entry)
        return sorted(out, key=lambda e: e.name)

    def get_index_versions(self, index_name: str, states: List[str]) -> List[int]:
        log_mgr, _ = self._managers(index_name)
        return log_mgr.get_index_versions(states)


class CachingIndexCollectionManager(IndexCollectionManager):
    """TTL cache over ``get_indexes`` (CachingIndexCollectionManager:38-108):
    the query-time rule fetches all ACTIVE entries on every optimization, so
    reads are cached for ``hyperspace.index.cache.expiryDurationInSeconds``
    and the cache is cleared on any mutating operation."""

    def __init__(self, session):
        super().__init__(session)
        self._cache: Optional[List[IndexLogEntry]] = None
        self._cached_at: float = 0.0

    def clear_cache(self) -> None:
        self._cache = None

    def get_indexes(self, states: Optional[List[str]] = None) -> List[IndexLogEntry]:
        expiry = self.session.conf.cache_expiry_seconds
        now = time.time()
        # snapshot the cache slot ONCE: serve-frontend workers call this
        # concurrently with a lifecycle action's clear_cache() (sets
        # _cache = None); re-reading self._cache after the staleness
        # check could observe that None and crash. Racing refreshes at
        # worst duplicate the listing — both results are valid snapshots.
        entries = self._cache
        if entries is None or now - self._cached_at > expiry:
            entries = super().get_indexes(None)
            self._cache = entries
            self._cached_at = now
        if states is None:
            return list(entries)
        return [e for e in entries if e.state in states]

    def _mutate(self, fn, *args) -> None:
        self.clear_cache()
        try:
            fn(*args)
        finally:
            self.clear_cache()

    def create(self, df, index_config) -> None:
        self._mutate(super().create, df, index_config)

    def delete(self, index_name: str) -> None:
        self._mutate(super().delete, index_name)

    def restore(self, index_name: str) -> None:
        self._mutate(super().restore, index_name)

    def vacuum(self, index_name: str) -> None:
        self._mutate(super().vacuum, index_name)

    def refresh(self, index_name: str, mode: str) -> None:
        self._mutate(super().refresh, index_name, mode)

    def optimize(self, index_name: str, mode: str) -> None:
        self._mutate(super().optimize, index_name, mode)

    def cancel(self, index_name: str) -> None:
        self._mutate(super().cancel, index_name)

    def recover(self, index_name: str, gc: bool = True) -> dict:
        self.clear_cache()
        try:
            return super().recover(index_name, gc)
        finally:
            self.clear_cache()

    def recover_all(self, gc: bool = False) -> List[dict]:
        # clear_cache only ASSIGNS, so the virtual call from the base
        # __init__ (attach sweep, before this subclass's __init__ body
        # runs) is safe
        self.clear_cache()
        try:
            return super().recover_all(gc)
        finally:
            self.clear_cache()
