"""Parquet read/write (host side, Arrow).

The reference reads/writes through Spark's datasource machinery
(``index/DataFrameWriterExtensions.scala:50-80`` for the bucketed index
write, ``FileSourceScanExec`` for reads). Here the host does Arrow I/O and
hands SoA batches to the device; the bucketed write emits **one parquet
file per bucket** named like Spark's bucketed layout
(``part-<fileidx>-…_<bucket>.c000.parquet``) so bucket ids are recoverable
from file names at query time (the reference relies on
``BucketingUtils.getBucketId``, ``actions/OptimizeAction.scala:110``).
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson
import pyarrow.parquet as pq

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnarBatch

_BUCKET_FILE_RE = re.compile(r"part-\d+-bucket_(\d+)\.parquet$")


def _pool_map(fn, items):
    """Footer-metadata reads through a small thread pool (high-latency
    storage pays per-call latency N times otherwise)."""
    if len(items) <= 4:
        return [fn(x) for x in items]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(16, len(items))) as pool:
        return list(pool.map(fn, items))


def _file_schemas(paths: Sequence[str]) -> List[pa.Schema]:
    return _pool_map(lambda p: pq.ParquetFile(p).schema_arrow, list(paths))


def file_row_counts(paths: Sequence[str]) -> List[int]:
    """Per-file row counts from parquet footers (threaded)."""
    return _pool_map(
        lambda p: pq.ParquetFile(p).metadata.num_rows, list(paths)
    )


def read_table(
    paths: Sequence[str], columns: Optional[Sequence[str]] = None, fmt: str = "parquet"
) -> pa.Table:
    """Read and concatenate files into one Arrow table (row order follows
    ``paths`` order, file by file)."""
    if fmt in ("parquet", "delta", "iceberg") and len(paths) > 1:
        # One threaded dataset read beats N sequential reads ~3x and pyarrow
        # preserves the given file order — but it locks the first file's
        # schema, so it is only safe when all schemas match (always true
        # for index data; source tables can carry type-widening evolution,
        # which needs the permissive per-file concat below).
        schemas = _file_schemas(paths)
        if all(s.equals(schemas[0]) for s in schemas[1:]):
            return pq.read_table(
                list(paths), columns=list(columns) if columns else None
            )
    tables = []
    for p in paths:
        if fmt in ("parquet", "delta", "iceberg"):  # lake data files ARE parquet
            tables.append(pq.read_table(p, columns=list(columns) if columns else None))
        elif fmt == "csv":
            t = pacsv.read_csv(p)
            tables.append(t.select(list(columns)) if columns else t)
        elif fmt == "json":
            t = pajson.read_json(p)
            tables.append(t.select(list(columns)) if columns else t)
        else:
            raise HyperspaceException(f"Unsupported format: {fmt}")
    if not tables:
        raise HyperspaceException("No files to read")
    return pa.concat_tables(tables, promote_options="permissive")


def read_batch(
    paths: Sequence[str], columns: Optional[Sequence[str]] = None, fmt: str = "parquet"
) -> ColumnarBatch:
    return ColumnarBatch.from_arrow(read_table(paths, columns, fmt))


def list_format_files(root: str, fmt: str = "parquet") -> List[str]:
    """Leaf data files of a dataset directory (recursive, with the same
    hidden-path filtering Spark's ``DataPathFilter`` applies)."""
    from hyperspace_tpu.utils.files import list_leaf_files

    ext = {"parquet": ".parquet", "csv": ".csv", "json": ".json"}[fmt]
    return sorted(p for p, _s, _m in list_leaf_files(root, suffix=ext, data_only=True))


def bucket_file_name(file_idx: int, bucket: int) -> str:
    return f"part-{file_idx:05d}-bucket_{bucket:05d}.parquet"


def bucket_id_of_file(path: str) -> Optional[int]:
    m = _BUCKET_FILE_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def bucket_runs(bucket_ids: np.ndarray):
    """Yield ``(bucket_id, row_indices)`` per distinct bucket id.

    bucket_ids need not be globally sorted (shards interleave); runs are
    found via one stable argsort, and each run's indices are re-sorted
    ascending so rows keep their (key-sorted) relative order. Shared by
    the final bucketed write below and the streaming build's spill loop
    (``indexes/covering_build._write_bucketed_streaming``)."""
    if len(bucket_ids) == 0:
        return
    order = np.argsort(bucket_ids, kind="stable")
    sorted_ids = bucket_ids[order]
    boundaries = np.nonzero(np.diff(sorted_ids))[0] + 1
    starts = np.concatenate([[0], boundaries])
    ends = np.concatenate([boundaries, [len(sorted_ids)]])
    for s, e in zip(starts, ends):
        yield int(sorted_ids[s]), np.sort(order[s:e])


def write_bucket_files(
    out_dir: str,
    bucket_ids: np.ndarray,
    batch: ColumnarBatch,
    num_buckets: int,
    file_idx_offset: int = 0,
) -> List[str]:
    """Write rows (already grouped/sorted, see ``parallel/shuffle.py`` +
    ``ops/sort.py``) as one parquet file per non-empty bucket."""
    os.makedirs(out_dir, exist_ok=True)
    table = batch.to_arrow()
    written = []
    for b, idx in bucket_runs(bucket_ids):
        path = os.path.join(out_dir, bucket_file_name(file_idx_offset + b, b))
        pq.write_table(table.take(pa.array(idx)), path)
        written.append(path)
    return written


def write_table(path: str, table: pa.Table) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    pq.write_table(table, path)
