"""Parquet read/write (host side, Arrow).

The reference reads/writes through Spark's datasource machinery
(``index/DataFrameWriterExtensions.scala:50-80`` for the bucketed index
write, ``FileSourceScanExec`` for reads). Here the host does Arrow I/O and
hands SoA batches to the device; the bucketed write emits **one parquet
file per bucket** named like Spark's bucketed layout
(``part-<fileidx>-…_<bucket>.c000.parquet``) so bucket ids are recoverable
from file names at query time (the reference relies on
``BucketingUtils.getBucketId``, ``actions/OptimizeAction.scala:110``).
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson
import pyarrow.parquet as pq

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnarBatch

_BUCKET_FILE_RE = re.compile(r"part-\d+-bucket_(\d+)\.parquet$")


def _pool_map(fn, items):
    """Footer-metadata reads through a small thread pool (high-latency
    storage pays per-call latency N times otherwise)."""
    if len(items) <= 4:
        return [fn(x) for x in items]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(16, len(items))) as pool:
        return list(pool.map(fn, items))


def _file_schemas(paths: Sequence[str]) -> List[pa.Schema]:
    return _pool_map(lambda p: pq.ParquetFile(p).schema_arrow, list(paths))


def file_row_counts(paths: Sequence[str]) -> List[int]:
    """Per-file row counts from parquet footers (threaded)."""
    return _pool_map(
        lambda p: pq.ParquetFile(p).metadata.num_rows, list(paths)
    )


def read_table(
    paths: Sequence[str],
    columns: Optional[Sequence[str]] = None,
    fmt: str = "parquet",
    filters=None,
) -> pa.Table:
    """Read and concatenate files into one Arrow table (row order follows
    ``paths`` order, file by file). ``filters`` (parquet-like formats
    only) is a pyarrow DNF conjunction used for ROW-GROUP pruning — the
    executor re-applies its own mask afterwards, so filters only need to
    keep a superset of matching rows."""
    if fmt in ("parquet", "delta", "iceberg") and len(paths) > 1:
        # One threaded dataset read beats N sequential reads ~3x and pyarrow
        # preserves the given file order — but it locks the first file's
        # schema, so it is only safe when all schemas match (always true
        # for index data; source tables can carry type-widening evolution,
        # which needs the permissive per-file concat below).
        schemas = _file_schemas(paths)
        if all(s.equals(schemas[0]) for s in schemas[1:]):
            return pq.read_table(
                list(paths),
                columns=list(columns) if columns else None,
                filters=filters,
            )
    tables = []
    for p in paths:
        if fmt in ("parquet", "delta", "iceberg"):  # lake data files ARE parquet
            tables.append(
                pq.read_table(
                    p,
                    columns=list(columns) if columns else None,
                    filters=filters,
                )
            )
        elif fmt == "csv":
            t = pacsv.read_csv(p)
            tables.append(t.select(list(columns)) if columns else t)
        elif fmt == "json":
            t = pajson.read_json(p)
            tables.append(t.select(list(columns)) if columns else t)
        elif fmt == "orc":
            from pyarrow import orc as paorc

            t = paorc.read_table(p, columns=list(columns) if columns else None)
            tables.append(t)
        elif fmt == "text":
            # Spark's text source shape: one string column named `value`
            with open(p, "r", encoding="utf-8") as fh:
                lines = fh.read().splitlines()
            t = pa.table({"value": pa.array(lines, type=pa.string())})
            tables.append(t.select(list(columns)) if columns else t)
        elif fmt == "avro":
            from hyperspace_tpu.utils.avro import read_avro_with_schema

            avro_schema, records = read_avro_with_schema(p)
            arrow_schema = _avro_to_arrow_schema(avro_schema)
            if arrow_schema is not None:
                t = pa.Table.from_pylist(list(records), schema=arrow_schema)
            else:  # non-record / exotic top-level schema: infer from values
                t = pa.Table.from_pylist(list(records))
            tables.append(t.select(list(columns)) if columns else t)
        else:
            raise HyperspaceException(f"Unsupported format: {fmt}")
    if not tables:
        raise HyperspaceException("No files to read")
    return pa.concat_tables(tables, promote_options="permissive")


def read_batch(
    paths: Sequence[str], columns: Optional[Sequence[str]] = None, fmt: str = "parquet"
) -> ColumnarBatch:
    return ColumnarBatch.from_arrow(read_table(paths, columns, fmt))


def list_format_files(root: str, fmt: str = "parquet") -> List[str]:
    """Leaf data files of a dataset directory (recursive, with the same
    hidden-path filtering Spark's ``DataPathFilter`` applies)."""
    from hyperspace_tpu.utils.files import list_leaf_files

    ext = {
        "parquet": ".parquet",
        "csv": ".csv",
        "json": ".json",
        "orc": ".orc",
        "avro": ".avro",
        "text": ".txt",
    }[fmt]
    return sorted(p for p, _s, _m in list_leaf_files(root, suffix=ext, data_only=True))


def _avro_to_arrow_schema(avro_schema) -> Optional[pa.Schema]:
    """Arrow schema from an Avro record schema (embedded-schema-driven
    typing, so empty/all-null files concat cleanly with siblings). Returns
    None when the top level is not a record or a field type is beyond the
    primitive/union-with-null set (caller falls back to value inference)."""
    prim = {
        "boolean": pa.bool_(),
        "int": pa.int32(),
        "long": pa.int64(),
        "float": pa.float32(),
        "double": pa.float64(),
        "bytes": pa.binary(),
        "string": pa.string(),
    }

    def field_type(t):
        if isinstance(t, list):  # union: only [null, prim] shapes
            non_null = [x for x in t if x != "null"]
            if len(non_null) != 1:
                return None
            return field_type(non_null[0])
        if isinstance(t, str):
            return prim.get(t)
        return None

    if not isinstance(avro_schema, dict) or avro_schema.get("type") != "record":
        return None
    fields = []
    for f in avro_schema.get("fields", []):
        at = field_type(f["type"])
        if at is None:
            return None
        fields.append(pa.field(f["name"], at))
    return pa.schema(fields)


def has_glob_magic(path: str) -> bool:
    """True when the path is a glob pattern (single home of the
    magic-character rule — session reader and expansion must agree)."""
    return any(ch in path for ch in "*?[")


def expand_path(path: str, fmt: str) -> List[str]:
    """Data files for one reader path: a file, a directory, or a glob
    pattern (the reference validates globbed roots against their current
    expansion, DefaultFileBasedRelation.scala:159-187 — keeping the
    PATTERN as the root path and re-expanding on every listing gives the
    same always-current semantics)."""
    import glob as _glob
    import os

    if has_glob_magic(path):
        out: List[str] = []
        for m in sorted(_glob.glob(path)):
            if os.path.isfile(m):
                out.append(m)
            else:
                out.extend(list_format_files(m, fmt))
        return out
    if os.path.isfile(path):
        return [path]
    return list_format_files(path, fmt)


def bucket_file_name(file_idx: int, bucket: int) -> str:
    return f"part-{file_idx:05d}-bucket_{bucket:05d}.parquet"


def bucket_id_of_file(path: str) -> Optional[int]:
    m = _BUCKET_FILE_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def bucket_runs(bucket_ids: np.ndarray):
    """Yield ``(bucket_id, row_indices)`` per distinct bucket id.

    bucket_ids need not be globally sorted (shards interleave); runs are
    found via one stable argsort, and each run's indices are re-sorted
    ascending so rows keep their (key-sorted) relative order. Shared by
    the final bucketed write below and the streaming build's spill loop
    (``indexes/covering_build._write_bucketed_streaming``)."""
    if len(bucket_ids) == 0:
        return
    order = np.argsort(bucket_ids, kind="stable")
    sorted_ids = bucket_ids[order]
    boundaries = np.nonzero(np.diff(sorted_ids))[0] + 1
    starts = np.concatenate([[0], boundaries])
    ends = np.concatenate([boundaries, [len(sorted_ids)]])
    for s, e in zip(starts, ends):
        yield int(sorted_ids[s]), np.sort(order[s:e])


# Row-group size for index data files. Bucket files are KEY-SORTED, so
# each row group's min/max statistics cover a narrow key range — the
# serve-side predicate pushdown (executor._pushdown_filters) then reads
# only the row group(s) a point lookup can touch. Smaller groups prune
# tighter but cost more metadata; 64k rows balances both.
INDEX_ROW_GROUP_SIZE = 1 << 16


def write_bucket_files(
    out_dir: str,
    bucket_ids: np.ndarray,
    batch: ColumnarBatch,
    num_buckets: int,
    file_idx_offset: int = 0,
) -> List[str]:
    """Write rows (already grouped/sorted, see ``parallel/shuffle.py`` +
    ``ops/sort.py``) as one parquet file per non-empty bucket."""
    os.makedirs(out_dir, exist_ok=True)
    table = batch.to_arrow()
    written = []
    for b, idx in bucket_runs(bucket_ids):
        path = os.path.join(out_dir, bucket_file_name(file_idx_offset + b, b))
        pq.write_table(
            table.take(pa.array(idx)), path, row_group_size=INDEX_ROW_GROUP_SIZE
        )
        written.append(path)
    return written


def write_table(path: str, table: pa.Table) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    pq.write_table(table, path)
