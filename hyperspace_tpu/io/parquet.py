"""Parquet read/write (host side, Arrow).

The reference reads/writes through Spark's datasource machinery
(``index/DataFrameWriterExtensions.scala:50-80`` for the bucketed index
write, ``FileSourceScanExec`` for reads). Here the host does Arrow I/O and
hands SoA batches to the device; the bucketed write emits **one parquet
file per bucket** named like Spark's bucketed layout
(``part-<fileidx>-…_<bucket>.c000.parquet``) so bucket ids are recoverable
from file names at query time (the reference relies on
``BucketingUtils.getBucketId``, ``actions/OptimizeAction.scala:110``).
"""

from __future__ import annotations

import functools as _functools
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson
import pyarrow.parquet as pq

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnarBatch
from hyperspace_tpu.testing import faults

_BUCKET_FILE_RE = re.compile(r"part-\d+-bucket_(\d+)\.parquet$")


def _pool_map(fn, items):
    """Footer-metadata reads through a small thread pool (high-latency
    storage pays per-call latency N times otherwise)."""
    if len(items) <= 4:
        return [fn(x) for x in items]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(16, len(items))) as pool:
        return list(pool.map(fn, items))


def _file_schemas(paths: Sequence[str]) -> List[pa.Schema]:
    return _pool_map(lambda p: pq.ParquetFile(p).schema_arrow, list(paths))


def file_row_counts(paths: Sequence[str]) -> List[int]:
    """Per-file row counts from parquet footers (threaded)."""
    return _pool_map(
        lambda p: pq.ParquetFile(p).metadata.num_rows, list(paths)
    )


def _literal_column_names(path: str) -> frozenset:
    """Top-level column names of one parquet file, memoized by the file's
    identity (path, size, mtime_ns) — per-file read loops with nested
    columns would otherwise re-parse the same immutable footer per call."""
    st = os.stat(path)
    return _literal_column_names_cached(path, st.st_size, st.st_mtime_ns)


@_functools.lru_cache(maxsize=4096)
def _literal_column_names_cached(path, _size, _mtime_ns) -> frozenset:
    return frozenset(pq.read_schema(path).names)


def _resolve_nested_columns(paths, columns, fmt):
    """Split requested columns into (physical read list, extraction plan).

    A ``__hs_nested.``-prefixed column is VIRTUAL when the file does not
    carry it as a literal flat column (source tables store the struct;
    index data files store the literal flattened column — reference
    ``util/ResolverUtils.scala:130-234``): the struct ROOT is read instead
    and the leaf extracted post-read. Returns (read_cols, extract) where
    extract maps output name -> (root, leaf_path); extract is empty when
    nothing is virtual."""
    from hyperspace_tpu.constants import NESTED_FIELD_PREFIX

    prefixed = [c for c in columns if c.startswith(NESTED_FIELD_PREFIX)]
    if not prefixed:
        return list(columns), {}
    virtual = prefixed
    if fmt in ("parquet", "delta", "iceberg"):
        literal = _literal_column_names(paths[0])
        virtual = [c for c in prefixed if c not in literal]
    if not virtual:
        return list(columns), {}
    extract = {}
    read_cols = [c for c in columns if c not in virtual]
    for c in virtual:
        parts = c[len(NESTED_FIELD_PREFIX):].split(".")
        extract[c] = (parts[0], parts[1:])
        if parts[0] not in read_cols:
            read_cols.append(parts[0])
    return read_cols, extract


def read_table(
    paths: Sequence[str],
    columns: Optional[Sequence[str]] = None,
    fmt: str = "parquet",
    filters=None,
    memory_map: bool = False,
) -> pa.Table:
    """Read and concatenate files into one Arrow table (row order follows
    ``paths`` order, file by file).

    ``memory_map`` (parquet-like formats, ``hyperspace.io.mmap.enabled``)
    routes the read through OS memory mapping: pyarrow then borrows
    uncompressed/plain column chunks straight from the page cache instead
    of copying them onto the heap, so decoded columns can stay file-backed
    views (docs/out-of-core.md; the residency accounting in
    ``execution/serve_cache.estimate_nbytes`` charges registered mapped
    regions near zero). Row values are identical either way — mapping only
    changes where the bytes live.

    ``filters`` (parquet-like formats only) is a pyarrow DNF conjunction.
    REQUIRED INVARIANT: each pushed conjunct must keep a **row-level
    superset** of the rows the engine's own mask keeps — pyarrow >= 14
    routes ``pq.read_table`` through the dataset API, which applies
    filters per ROW (not merely per row group), so a conjunct that is
    only row-group-safe (e.g. a literal rounded/snapped toward the
    engine's semantics) would silently drop matching rows. The executor
    re-applies the full mask afterwards, so over-keeping is always safe;
    under-keeping never is.

    ``__hs_nested.``-prefixed columns that are not literal flat columns
    in the files are served by reading the struct root and extracting
    the leaf (``_resolve_nested_columns``)."""
    # fault-injection seam (testing/faults.py): every data read of the
    # serve path funnels through here or read_file_row_groups; the serve
    # frontend's retry/degrade under an armed "parquet_read" point is
    # the tested robustness contract (docs/serve-server.md). The detail
    # is the whole path list — a match= filter fires whichever position
    # the matching file occupies — passed as-is: check() stringifies it
    # only when the point is armed, so the disarmed hot path stays at
    # one dict truthiness check.
    faults.check("parquet_read", paths)
    if columns:
        read_cols, extract = _resolve_nested_columns(paths, columns, fmt)
        if extract:
            import pyarrow.compute as pc

            if filters:
                # a filter on a virtual column has no physical column to
                # act on; dropping conjuncts is superset-safe by contract
                filters = [
                    f for f in filters if f[0] not in extract
                ] or None
            t = read_table(paths, read_cols, fmt, filters, memory_map)
            out = {}
            for c in columns:
                if c in extract:
                    root, leaf_path = extract[c]
                    out[c] = pc.struct_field(t.column(root), leaf_path)
                else:
                    out[c] = t.column(c)
            return pa.table(out)
    if fmt in ("parquet", "delta", "iceberg") and len(paths) > 1:
        # One threaded dataset read beats N sequential reads ~3x and pyarrow
        # preserves the given file order — but it locks the first file's
        # schema, so it is only safe when all schemas match (always true
        # for index data; source tables can carry type-widening evolution,
        # which needs the permissive per-file concat below).
        schemas = _file_schemas(paths)
        if all(s.equals(schemas[0]) for s in schemas[1:]):
            # partitioning=None: these are EXPLICIT file lists — hive
            # partition values are injected by io/scan.py, never inferred
            # from directory names. The default "hive" inference read the
            # index version dirs (v__=N) as a partition column and made
            # every serve spanning two index versions (incremental
            # refresh MERGE, optimize's ignored files) fail with a
            # type-merge error.
            return pq.read_table(
                list(paths),
                columns=list(columns) if columns else None,
                filters=filters,
                partitioning=None,
                memory_map=memory_map,
            )
    tables = []
    for p in paths:
        if fmt in ("parquet", "delta", "iceberg"):  # lake data files ARE parquet
            tables.append(
                pq.read_table(
                    p,
                    columns=list(columns) if columns else None,
                    filters=filters,
                    partitioning=None,
                    memory_map=memory_map,
                )
            )
        elif fmt == "csv":
            t = pacsv.read_csv(p)
            tables.append(t.select(list(columns)) if columns else t)
        elif fmt == "json":
            t = pajson.read_json(p)
            tables.append(t.select(list(columns)) if columns else t)
        elif fmt == "orc":
            from pyarrow import orc as paorc

            t = paorc.read_table(p, columns=list(columns) if columns else None)
            tables.append(t)
        elif fmt == "text":
            # Spark's text source shape: one string column named `value`
            with open(p, "r", encoding="utf-8") as fh:
                lines = fh.read().splitlines()
            t = pa.table({"value": pa.array(lines, type=pa.string())})
            tables.append(t.select(list(columns)) if columns else t)
        elif fmt == "avro":
            from hyperspace_tpu.utils.avro import read_avro_with_schema

            avro_schema, records = read_avro_with_schema(p)
            arrow_schema = _avro_to_arrow_schema(avro_schema)
            if arrow_schema is not None:
                t = pa.Table.from_pylist(list(records), schema=arrow_schema)
            else:  # non-record / exotic top-level schema: infer from values
                t = pa.Table.from_pylist(list(records))
            tables.append(t.select(list(columns)) if columns else t)
        else:
            raise HyperspaceException(f"Unsupported format: {fmt}")
    if not tables:
        raise HyperspaceException("No files to read")
    return pa.concat_tables(tables, promote_options="permissive")


def read_batch(
    paths: Sequence[str], columns: Optional[Sequence[str]] = None, fmt: str = "parquet"
) -> ColumnarBatch:
    # Convenience wrapper for out-of-package tooling: the actual
    # materializations happen in read_table / ColumnarBatch.from_arrow,
    # both registered ALLOC_SITES; the bound is the caller's selection.
    # hslint: disable=HS1001
    return ColumnarBatch.from_arrow(read_table(paths, columns, fmt))


def read_table_row_groups(
    paths: Sequence[str],
    row_groups: Sequence[Optional[Sequence[int]]],
    columns: Optional[Sequence[str]] = None,
    fmt: str = "parquet",
) -> pa.Table:
    """Row-group-granular read: per file, only the listed row groups (None
    = the whole file), concatenated in ``paths`` order — the cold-read
    half of zone-map pruning (``executor._range_pruned_scan``). Row order
    within a file follows ascending row-group index, which is the file's
    own row order, so a selection of ALL groups is bit-identical to
    ``read_table``. Reads overlap on the shared scan pool
    (``io/scan.scan_pool``) when more than one file needs opening;
    parquet-like formats only (callers gate on fmt)."""
    if fmt not in ("parquet", "delta", "iceberg"):
        raise HyperspaceException(
            f"Row-group reads require a parquet-like format, got {fmt!r}"
        )
    cols = list(columns) if columns else None
    pairs = list(zip(paths, row_groups))
    if len(pairs) <= 1:
        tables = [read_file_row_groups(p, g, cols) for p, g in pairs]
    else:
        from hyperspace_tpu.io.scan import scan_pool

        futs = [
            scan_pool().submit(read_file_row_groups, p, g, cols)
            for p, g in pairs
        ]
        tables = [f.result() for f in futs]
    if not tables:
        raise HyperspaceException("No files to read")
    return pa.concat_tables(tables, promote_options="permissive")


def read_file_row_groups(
    path: str, groups: Optional[Sequence[int]], cols: Optional[List[str]]
) -> pa.Table:
    """ONE file's row groups (None = the whole file, () = zero rows with
    the right schema) — the per-file unit of :func:`read_table_row_groups`
    and of the fused serve-pipeline's chunk stream
    (``execution/pipeline_compiler._run_chunked``). Kept as the single
    definition so the fused pass and the interpreted chain can never
    read different bytes."""
    faults.check("parquet_read", path)
    pf = pq.ParquetFile(path)
    if groups is None:
        return pf.read(columns=cols)
    if len(groups) == 0:
        return pf.schema_arrow.empty_table().select(
            cols if cols is not None else pf.schema_arrow.names
        )
    return pf.read_row_groups(list(groups), columns=cols)


def list_format_files(root: str, fmt: str = "parquet") -> List[str]:
    """Leaf data files of a dataset directory (recursive, with the same
    hidden-path filtering Spark's ``DataPathFilter`` applies)."""
    from hyperspace_tpu.utils.files import list_leaf_files

    ext = {
        "parquet": ".parquet",
        "csv": ".csv",
        "json": ".json",
        "orc": ".orc",
        "avro": ".avro",
        "text": ".txt",
    }[fmt]
    return sorted(p for p, _s, _m in list_leaf_files(root, suffix=ext, data_only=True))


def _avro_to_arrow_schema(avro_schema) -> Optional[pa.Schema]:
    """Arrow schema from an Avro record schema (embedded-schema-driven
    typing, so empty/all-null files concat cleanly with siblings). Returns
    None when the top level is not a record or a field type is beyond the
    primitive/union-with-null set (caller falls back to value inference)."""
    prim = {
        "boolean": pa.bool_(),
        "int": pa.int32(),
        "long": pa.int64(),
        "float": pa.float32(),
        "double": pa.float64(),
        "bytes": pa.binary(),
        "string": pa.string(),
    }

    def field_type(t):
        if isinstance(t, list):  # union: only [null, prim] shapes
            non_null = [x for x in t if x != "null"]
            if len(non_null) != 1:
                return None
            return field_type(non_null[0])
        if isinstance(t, str):
            return prim.get(t)
        return None

    if not isinstance(avro_schema, dict) or avro_schema.get("type") != "record":
        return None
    fields = []
    for f in avro_schema.get("fields", []):
        at = field_type(f["type"])
        if at is None:
            return None
        fields.append(pa.field(f["name"], at))
    return pa.schema(fields)


def has_glob_magic(path: str) -> bool:
    """True when the path is a glob pattern (single home of the
    magic-character rule — session reader and expansion must agree)."""
    return any(ch in path for ch in "*?[")


def expand_path(path: str, fmt: str) -> List[str]:
    """Data files for one reader path: a file, a directory, or a glob
    pattern (the reference validates globbed roots against their current
    expansion, DefaultFileBasedRelation.scala:159-187 — keeping the
    PATTERN as the root path and re-expanding on every listing gives the
    same always-current semantics)."""
    import glob as _glob
    import os

    if has_glob_magic(path):
        out: List[str] = []
        for m in sorted(_glob.glob(path)):
            if os.path.isfile(m):
                out.append(m)
            else:
                out.extend(list_format_files(m, fmt))
        return out
    if os.path.isfile(path):
        return [path]
    return list_format_files(path, fmt)


def bucket_file_name(file_idx: int, bucket: int) -> str:
    return f"part-{file_idx:05d}-bucket_{bucket:05d}.parquet"


def bucket_id_of_file(path: str) -> Optional[int]:
    m = _BUCKET_FILE_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def bucket_runs(bucket_ids: np.ndarray):
    """Yield ``(bucket_id, row_indices)`` per distinct bucket id.

    bucket_ids need not be globally sorted (shards interleave); runs are
    found via one stable argsort, and each run's indices are re-sorted
    ascending so rows keep their (key-sorted) relative order. Shared by
    the final bucketed write below and the streaming build's spill loop
    (``indexes/covering_build._write_bucketed_streaming``)."""
    if len(bucket_ids) == 0:
        return
    order = np.argsort(bucket_ids, kind="stable")
    sorted_ids = bucket_ids[order]
    boundaries = np.nonzero(np.diff(sorted_ids))[0] + 1
    starts = np.concatenate([[0], boundaries])
    ends = np.concatenate([boundaries, [len(sorted_ids)]])
    for s, e in zip(starts, ends):
        yield int(sorted_ids[s]), np.sort(order[s:e])


# Row-group size for index data files. Bucket files are KEY-SORTED, so
# each row group's min/max statistics cover a narrow key range — the
# serve-side predicate pushdown (executor._pushdown_filters) then reads
# only the row group(s) a point lookup can touch. Smaller groups prune
# tighter but cost more metadata; 64k rows balances both.
INDEX_ROW_GROUP_SIZE = 1 << 16


_DICT_SAMPLE_ROWS = 4096


def _dictionary_columns(table: pa.Table):
    """Columns that should keep parquet dictionary encoding.

    For HIGH-cardinality numeric columns (index keys) dictionary encoding
    is pure CPU overhead — pyarrow builds the dictionary, overflows it,
    and falls back — measured 2.4x slower writes at identical file size.
    But LOW-cardinality numerics (dates, flags, quantities) genuinely
    shrink under RLE_DICTIONARY (~2x on such columns), so the opt-out is
    gated on sampled cardinality: a column keeps dictionary encoding when
    a STRIDED sample repeats values at least 4x. The stride matters —
    index tables arrive key-sorted, so a prefix sample would see only the
    clustered duplicates of the first few keys and re-enable dictionary
    encoding for globally high-cardinality columns. Strings/binary always
    keep it."""
    cols = []
    n = table.num_rows
    sample_idx = None
    if n > _DICT_SAMPLE_ROWS:
        sample_idx = pa.array(
            np.linspace(0, n - 1, _DICT_SAMPLE_ROWS).astype(np.int64)
        )
    for i, f in enumerate(table.schema):
        if (
            pa.types.is_string(f.type)
            or pa.types.is_large_string(f.type)
            or pa.types.is_binary(f.type)
            or pa.types.is_dictionary(f.type)
        ):
            cols.append(f.name)
            continue
        if n == 0:
            continue
        col = table.column(i)
        sample = col.take(sample_idx) if sample_idx is not None else col
        try:
            distinct = len(sample.unique())
        except pa.ArrowNotImplementedError:
            continue
        if distinct * 4 <= len(sample):
            cols.append(f.name)
    return cols if cols else False


def dictionary_columns_for_batch(batch: ColumnarBatch):
    """The dictionary-encoding decision of ``_dictionary_columns``
    computed from a strided sample of a :class:`ColumnarBatch` in its
    CURRENT row order — the single decision point shared by both build
    sort paths (legacy global lexsort and partition-first), computed on
    the common pre-sort input so the two layouts stay byte-identical."""
    n = batch.num_rows
    if n > _DICT_SAMPLE_ROWS:
        idx = np.linspace(0, n - 1, _DICT_SAMPLE_ROWS).astype(np.int64)
        batch = batch.take(idx)
    return _dictionary_columns(batch.to_arrow())


def write_bucket_file(
    out_dir: str,
    bucket: int,
    file_idx_offset: int,
    table: pa.Table,
    idx: np.ndarray,
    use_dictionary,
) -> str:
    """One bucket's parquet file from rows ``idx`` of ``table`` — the
    per-bucket unit of work of the pipelined partition-first writer
    (``indexes/covering_build._write_bucketed_pipelined``) and of
    :func:`write_bucket_files` below."""
    path = os.path.join(out_dir, bucket_file_name(file_idx_offset + bucket, bucket))
    # crash seam (testing/faults.py "mid_data_write", with at=N selecting
    # the Nth file): a build that dies here leaves a partially-populated
    # version dir under a transient log entry — the orphans recovery GC
    # must quarantine
    faults.crash("mid_data_write", path)
    if (
        len(idx)
        and len(idx) == int(idx[-1]) - int(idx[0]) + 1
        and bool(np.all(idx[1:] > idx[:-1]))
    ):
        # contiguous ascending run (the globally sorted layout):
        # zero-copy slice instead of a gather. The span test alone is not
        # enough — a key-sorted bucket whose rows happen to occupy a
        # contiguous pre-sort range (e.g. a mesh shuffle that already
        # grouped by bucket) is a PERMUTATION of the span, not the span.
        sub = table.slice(int(idx[0]), len(idx))
    else:
        sub = table.take(pa.array(idx))
    pq.write_table(
        sub,
        path,
        row_group_size=INDEX_ROW_GROUP_SIZE,
        use_dictionary=use_dictionary,
    )
    return path


def write_bucket_files(
    out_dir: str,
    bucket_ids: np.ndarray,
    batch: ColumnarBatch,
    num_buckets: int,
    file_idx_offset: int = 0,
    use_dictionary=None,
) -> List[str]:
    """Write rows (already grouped/sorted, see ``parallel/shuffle.py`` +
    ``ops/sort.py``) as one parquet file per non-empty bucket.
    ``use_dictionary`` overrides the per-table encoding decision (the
    build passes one decision computed on the pre-sort input so both
    sort paths emit identical bytes)."""
    os.makedirs(out_dir, exist_ok=True)
    table = batch.to_arrow()
    use_dict = (
        _dictionary_columns(table) if use_dictionary is None else use_dictionary
    )
    written = []
    for b, idx in bucket_runs(bucket_ids):
        written.append(
            write_bucket_file(
                out_dir, b, file_idx_offset, table, idx, use_dict
            )
        )
    return written


def write_table(path: str, table: pa.Table) -> None:
    # Same 64k row groups as the bucket files: z-order data files (and any
    # other index payload written through here) get row-group min/max
    # statistics narrow enough for the serve-side zone-map pruning
    # (indexes/zonemaps.py) to drop most groups under a range predicate.
    faults.crash("mid_data_write", path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    pq.write_table(
        table,
        path,
        row_group_size=INDEX_ROW_GROUP_SIZE,
        use_dictionary=_dictionary_columns(table),
    )
