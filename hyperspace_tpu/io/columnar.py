"""Device-friendly columnar batches (SoA).

The TPU data plane cannot operate on Arrow's variable-width layouts
directly: strings are dictionary-encoded at ingest (codes live on device,
dictionary bytes stay host-side), fixed-width columns become numpy/JAX
arrays, and nulls become validity masks. This replaces the role Spark's
``ColumnarBatch``/``UnsafeRow`` plays under the reference's scan and shuffle
(e.g. ``index/covering/CoveringIndex.scala:56-71`` writes via Spark's row
pipeline; our equivalent pipeline consumes these batches).

Key-representation ("key rep") contract
---------------------------------------
Bucketing and sorting on device need a stable ``int64`` per value that is
*identical across files, sessions and refreshes*:

* numeric / bool / date / timestamp → the value's 64-bit pattern
  (floats via bit view so NaN groups deterministically);
* strings → murmur3-128-derived 64-bit hash of the utf-8 bytes, computed
  host-side **per dictionary entry** (O(unique), not O(rows)) then gathered
  through the codes on device;
* null → a fixed sentinel.

Equality of key reps implies equality of values except for string hash
collisions, which consumers (merge join) must verify against the actual
bytes; ordering of reps is an arbitrary-but-consistent total order, which
is all hash bucketing and sort-merge joins require.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.utils.hashing import murmur3_64_bytes

# Key rep assigned to nulls: an arbitrary-but-consistent VALUE so nulls
# bucket/sort deterministically. It is NOT a detection mechanism — a real
# int64 key may legitimately equal it, so consumers that must distinguish
# null rows (joins, group-by) read the explicit null masks
# (Column.null_mask / ColumnarBatch.null_any), never compare reps to this.
NULL_KEY_REP = np.int64(-0x7FFF_FFFF_FFFF_FF13)

def _is_string(t: pa.DataType) -> bool:
    if pa.types.is_dictionary(t):
        t = t.value_type
    return pa.types.is_string(t) or pa.types.is_large_string(t)


def flatten_schema_fields(fields):
    """Replace struct-typed fields by their scalar leaf paths as flat
    ``__hs_nested.<path>`` columns (depth-first).

    The engine's data plane is SoA over fixed-width/dictionary columns —
    struct trees cannot live on device. The reference solves the same
    problem by indexing nested fields as prefix-flattened columns
    (``util/ResolverUtils.scala:130-234``); here the flattening happens at
    relation construction, so nested leaves are first-class columns
    everywhere (planner, rules, executor) and the struct root disappears.
    Non-scalar leaves (lists, maps) are dropped — same indexing
    restriction as the reference."""
    from hyperspace_tpu.constants import NESTED_FIELD_PREFIX

    def leaves(path, t):
        for i in range(t.num_fields):
            f = t.field(i)
            if "." in f.name:
                # a dot inside a field name cannot round-trip through the
                # dotted flattened name (the read path re-splits on ".");
                # drop it like other unindexable leaves
                continue
            if pa.types.is_struct(f.type):
                yield from leaves(path + "." + f.name, f.type)
            elif not pa.types.is_nested(f.type):
                # is_nested covers list/large_list/fixed_size_list/
                # list_view/map/union — none of them are scalar leaves
                yield (NESTED_FIELD_PREFIX + path + "." + f.name, f.type)

    out = []
    for name, t in fields:
        if pa.types.is_struct(t) and "." not in name:
            out.extend(leaves(name, t))
        else:
            out.append((name, t))
    return tuple(out)


@dataclasses.dataclass
class Column:
    """One column of a :class:`ColumnarBatch`.

    kind:
      * ``numeric`` — ``values`` holds the numpy array (ints/floats/bool/
        date/timestamp as their natural numpy dtype);
      * ``string`` — ``codes`` holds int32 dictionary codes (-1 = null)
        and ``dictionary`` the host-side list of Python strings.
    ``validity`` is None (no nulls) or a bool mask (True = valid).
    ``arrow_type`` preserves the logical type for lossless round-trip.
    """

    kind: str
    arrow_type: pa.DataType
    values: Optional[np.ndarray] = None
    codes: Optional[np.ndarray] = None
    dictionary: Optional[List[str]] = None
    validity: Optional[np.ndarray] = None

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_arrow(arr: pa.ChunkedArray | pa.Array) -> "Column":
        if isinstance(arr, pa.ChunkedArray):
            # combine_chunks COPIES even with exactly one chunk, which
            # would detach a memory-mapped column from its registered
            # region (docs/out-of-core.md) — take the lone chunk's
            # zero-copy view instead.
            arr = arr.chunk(0) if arr.num_chunks == 1 else arr.combine_chunks()
        t = arr.type
        if _is_string(t):
            atype = t.value_type if pa.types.is_dictionary(t) else t
            if not pa.types.is_dictionary(t):
                arr = arr.dictionary_encode()
            codes = arr.indices.to_numpy(zero_copy_only=False)
            codes = np.where(np.asarray(arr.indices.is_valid()), codes, -1).astype(
                np.int32
            )
            dictionary = arr.dictionary.to_pylist()
            return Column("string", atype, codes=codes, dictionary=dictionary)
        if pa.types.is_dictionary(t):
            # dictionary-of-non-string (e.g. parquet read_dictionary on an
            # int column): decode and treat as a plain fixed-width column.
            arr = arr.cast(t.value_type)
            t = arr.type
        if pa.types.is_time(t):
            # time32/time64 decode to python datetime.time objects via
            # to_numpy; go through the integer representation instead
            # (``to_arrow`` restores the logical type). ``t`` stays the
            # logical arrow_type.
            arr = arr.cast(
                pa.int32() if pa.types.is_time32(t) else pa.int64()
            )
        validity = None
        if arr.null_count:
            validity = np.asarray(arr.is_valid())
            # Fill nulls with a typed zero so to_numpy keeps the natural
            # dtype (nullable ints would otherwise decode as float64 and
            # break the cross-file key-rep stability contract). Typed by
            # arr.type, not t: time columns were just cast to ints above.
            fill = pa.scalar(
                False if pa.types.is_boolean(arr.type) else 0, type=arr.type
            )
            arr = arr.fill_null(fill)
        vals = arr.to_numpy(zero_copy_only=False)
        if vals.dtype == object:
            vals = vals.astype(_numpy_dtype_for(t))
        if vals.dtype.kind in "Mm":
            # datetime64 AND timedelta64 → int64 for device friendliness
            # (durations compare/lower through the same int64-tick path)
            vals = vals.view(np.int64)
        return Column("numeric", t, values=vals, validity=validity)

    # -- basic properties ---------------------------------------------------
    def __len__(self) -> int:
        n = self.values if self.kind == "numeric" else self.codes
        return len(n)

    @property
    def null_mask(self) -> Optional[np.ndarray]:
        """True where the value is null, or None when there are no nulls."""
        if self.kind == "string":
            if (self.codes < 0).any():
                return self.codes < 0
            return None
        if self.validity is not None:
            return ~self.validity
        return None

    # -- conversion ---------------------------------------------------------
    def to_arrow(self) -> pa.Array:
        if self.kind == "string":
            codes = self.codes
            mask = codes < 0
            safe = np.where(mask, 0, codes)
            arr = pa.DictionaryArray.from_arrays(
                pa.array(safe, type=pa.int32(), mask=mask),
                pa.array(self.dictionary, type=self.arrow_type),
            )
            return arr.cast(self.arrow_type)
        vals = self.values
        mask = None if self.validity is None else ~self.validity
        t = self.arrow_type
        if (
            pa.types.is_timestamp(t)
            or pa.types.is_date(t)
            or pa.types.is_time(t)
            or pa.types.is_duration(t)
        ):
            # stored as int64 epoch/tick units; 32-bit temporal types cast
            # via int32
            width = 32 if t in (pa.date32(), pa.time32("s"), pa.time32("ms")) else 64
            itype = pa.int32() if width == 32 else pa.int64()
            ivals = vals.astype(np.int32) if width == 32 else vals
            return pa.array(ivals, type=itype, mask=mask).cast(t)
        return pa.array(vals, type=t, mask=mask)

    def key_rep(self) -> np.ndarray:
        """Stable int64 representation for bucketing/sorting (see module
        docstring)."""
        if self.kind == "string":
            dict_reps = np.array(
                [murmur3_64_bytes(s.encode("utf-8")) for s in self.dictionary],
                dtype=np.int64,
            )
            if len(dict_reps) == 0:
                dict_reps = np.zeros(1, dtype=np.int64)
            reps = dict_reps[np.where(self.codes < 0, 0, self.codes)]
            return np.where(self.codes < 0, NULL_KEY_REP, reps)
        v = self.values
        if v.dtype.kind == "f":
            rep = v.astype(np.float64).view(np.int64)
            # canonicalize NaNs and -0.0 so equal-by-value keys group
            rep = np.where(np.isnan(v), np.int64(0x7FF8000000000000), rep)
            rep = np.where(v == 0.0, np.int64(0), rep)
        elif v.dtype.kind == "b":
            rep = v.astype(np.int64)
        elif v.dtype.kind == "u":
            rep = v.astype(np.uint64).view(np.int64)
        else:
            rep = v.astype(np.int64)
        if self.validity is not None:
            rep = np.where(self.validity, rep, NULL_KEY_REP)
        return rep

    # -- row ops ------------------------------------------------------------
    def take(self, idx: np.ndarray) -> "Column":
        if self.kind == "string":
            return Column(
                "string", self.arrow_type, codes=self.codes[idx],
                dictionary=self.dictionary,
            )
        return Column(
            "numeric",
            self.arrow_type,
            values=_gather(self.values, idx),
            validity=None if self.validity is None else self.validity[idx],
        )

    @staticmethod
    def concat(cols: Sequence["Column"]) -> "Column":
        first = cols[0]
        if len(cols) == 1:
            return first
        if first.kind == "string":
            # Re-map codes into a shared dictionary.
            merged: Dict[str, int] = {}
            parts = []
            for c in cols:
                remap = np.empty(max(len(c.dictionary), 1), dtype=np.int32)
                for i, s in enumerate(c.dictionary):
                    remap[i] = merged.setdefault(s, len(merged))
                part = np.where(c.codes < 0, -1, remap[np.maximum(c.codes, 0)])
                parts.append(part.astype(np.int32))
            return Column(
                "string",
                first.arrow_type,
                codes=np.concatenate(parts),
                dictionary=list(merged.keys()),
            )
        any_validity = any(c.validity is not None for c in cols)
        validity = (
            np.concatenate(
                [
                    c.validity
                    if c.validity is not None
                    else np.ones(len(c), dtype=bool)
                    for c in cols
                ]
            )
            if any_validity
            else None
        )
        return Column(
            "numeric",
            first.arrow_type,
            values=np.concatenate([c.values for c in cols]),
            validity=validity,
        )


# At or above this index count an 8-byte-element gather dispatches to the
# native threaded kernel (``native.gather_i64``/``gather_f64``); numpy's
# fancy indexing is single-threaded, and the serve join's assemble stage
# is a string of multi-million-row gathers. FALLBACK DEFAULT: the
# effective threshold comes from the per-machine calibration probe
# (native/calibrate.py); an explicit module-attribute override wins.
_NATIVE_GATHER_MIN_ROWS_DEFAULT = 1 << 16
_NATIVE_GATHER_MIN_ROWS = _NATIVE_GATHER_MIN_ROWS_DEFAULT


def _native_gather_min_rows() -> int:
    if _NATIVE_GATHER_MIN_ROWS != _NATIVE_GATHER_MIN_ROWS_DEFAULT:
        return _NATIVE_GATHER_MIN_ROWS  # explicit (test/ops) override wins
    from hyperspace_tpu.native import calibrate

    return (
        calibrate.thresholds().native_gather_min_rows
        or _NATIVE_GATHER_MIN_ROWS
    )


def _gather(values: np.ndarray, idx) -> np.ndarray:
    """``values[idx]`` with the native threaded gather for large
    contiguous 8-byte-element arrays; numpy everywhere else. Bit-exact
    either way: the kernel bounds-checks and returns None on any index
    outside [0, n) (negative wrapping, IndexError), so numpy's exact
    semantics are preserved by fallback, never emulated."""
    if (
        isinstance(idx, np.ndarray)
        and idx.dtype == np.int64
        and values.ndim == 1
        and values.dtype.itemsize == 8
        and values.dtype.kind in "ifuMm"
        and values.flags.c_contiguous
        and len(idx) >= _native_gather_min_rows()
    ):
        from hyperspace_tpu import native

        if values.dtype == np.float64:
            out = native.gather_f64(values, idx)
        else:
            out = native.gather_i64(values.view(np.int64), idx)
            if out is not None:
                out = out.view(values.dtype)
        if out is not None:
            return out
    return values[idx]


def column_value_range(col: "Column"):
    """(min, max) of the column's valid values, or (None, None) when none.

    Floats are NaN-aware: NaN rows are excluded from the range entirely.
    This matches THIS engine's comparison semantics (IEEE — numpy on host,
    XLA on device): a NaN row can never satisfy an =, range or IN
    predicate, so excluding it from min/max sketches and layout analysis
    is exact, not approximate. (Spark instead orders NaN greatest; we
    diverge deliberately and consistently engine-wide.) Strings use
    lexical order over present dictionary entries.
    """
    if col.kind == "string":
        mask = col.codes >= 0
        if not mask.any():
            return None, None
        present = sorted({col.dictionary[c] for c in col.codes[mask]})
        return present[0], present[-1]
    v = col.values
    if col.validity is not None:
        v = v[col.validity]
    if len(v) and v.dtype.kind == "f":
        v = v[~np.isnan(v)]
    if len(v) == 0:
        return None, None
    return v.min().item(), v.max().item()


def remap_codes(target_dictionary: List[str], col: "Column") -> np.ndarray:
    """A string column's codes re-expressed in another dictionary's space.

    Entries absent from ``target_dictionary`` map to -2, nulls to -3, so
    the result is directly comparable against the target column's codes
    (equal ⟺ same non-null string). Shared by cross-column string equality
    (plan/expressions) and join key verification (execution/join_exec).
    """
    lut = {s: i for i, s in enumerate(target_dictionary)}
    remap = np.array(
        [lut.get(s, -2) for s in col.dictionary] or [-2], dtype=np.int64
    )
    return np.where(col.codes < 0, -3, remap[np.maximum(col.codes, 0)])


def _numpy_dtype_for(t: pa.DataType):
    try:
        return t.to_pandas_dtype()
    except (NotImplementedError, TypeError):
        # pyarrow has no numpy analogue for this type (decimal, nested…)
        return np.int64


def open_mmap_table(path: str) -> pa.Table:
    """Zero-copy memory-mapped read of an Arrow IPC file: the returned
    table's buffers are views into the OS file mapping, not heap copies,
    and the mapping is registered with the residency accounting
    (``execution/serve_cache.register_mapped_region``) so
    ``estimate_nbytes`` charges these columns as file-backed views — the
    read-side half of the out-of-core serve doctrine
    (docs/out-of-core.md; the spill tier's restore path goes through the
    same registry). The region unregisters itself when the table is
    collected; until then every buffer whose address falls inside it is
    charged the near-zero mapped-view token instead of its byte length."""
    import pyarrow.ipc as ipc

    from hyperspace_tpu.execution.serve_cache import register_mapped_region

    source = pa.memory_map(path, "r")
    size = source.size()
    buf = source.read_buffer(size) if size else None
    table = ipc.open_file(source).read_all()
    if buf is not None and buf.size:
        register_mapped_region(buf.address, buf.size, owner=table)
    return table


class ColumnarBatch:
    """Ordered name → :class:`Column` mapping with row-aligned columns."""

    def __init__(self, columns: Dict[str, Column]):
        self.columns: Dict[str, Column] = dict(columns)
        lens = {len(c) for c in self.columns.values()}
        if len(lens) > 1:
            raise HyperspaceException(f"Ragged columnar batch: lengths {lens}")

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_arrow(table: pa.Table) -> "ColumnarBatch":
        return ColumnarBatch(
            {name: Column.from_arrow(table.column(name)) for name in table.column_names}
        )

    def to_arrow(self) -> pa.Table:
        return pa.table({n: c.to_arrow() for n, c in self.columns.items()})

    # -- properties ---------------------------------------------------------
    @property
    def num_rows(self) -> int:
        if not self.columns:
            return 0
        return len(next(iter(self.columns.values())))

    @property
    def column_names(self) -> List[str]:
        return list(self.columns)

    def column(self, name: str) -> Column:
        if name not in self.columns:
            raise HyperspaceException(
                f"Column {name!r} not in batch ({self.column_names})"
            )
        return self.columns[name]

    # -- ops ----------------------------------------------------------------
    def select(self, names: Sequence[str]) -> "ColumnarBatch":
        return ColumnarBatch({n: self.column(n) for n in names})

    def with_column(self, name: str, col: Column) -> "ColumnarBatch":
        d = dict(self.columns)
        d[name] = col
        return ColumnarBatch(d)

    def take(self, idx: np.ndarray) -> "ColumnarBatch":
        return ColumnarBatch({n: c.take(idx) for n, c in self.columns.items()})

    def filter(self, mask: np.ndarray) -> "ColumnarBatch":
        return self.take(np.nonzero(np.asarray(mask))[0])

    def key_reps(self, names: Sequence[str]) -> np.ndarray:
        """[num_keys, num_rows] int64 key representations."""
        return np.stack([self.column(n).key_rep() for n in names])

    def null_any(self, names: Sequence[str]) -> np.ndarray:
        """[num_rows] bool: True where ANY named column is null. The
        correct null-row detector for join/group-by semantics (reps encode
        null as an in-band value; see NULL_KEY_REP)."""
        out = np.zeros(self.num_rows, dtype=bool)
        for n in names:
            m = self.column(n).null_mask
            if m is not None:
                out |= m
        return out

    @staticmethod
    def concat(batches: Sequence["ColumnarBatch"]) -> "ColumnarBatch":
        if not batches:
            raise HyperspaceException("Cannot concat zero batches")
        non_empty = [b for b in batches if b.num_rows]
        batches = non_empty or [batches[0]]
        names = batches[0].column_names
        for b in batches[1:]:
            if b.column_names != names:
                raise HyperspaceException(
                    f"Schema mismatch in concat: {names} vs {b.column_names}"
                )
        return ColumnarBatch(
            {n: Column.concat([b.column(n) for b in batches]) for n in names}
        )
