"""Relation-aware file scanning: one place that knows how to turn a plan
Relation's files into an Arrow table.

Handles hive-partitioned lake sources (partition column values live in the
source metadata — Delta's ``add.partitionValues`` — not in the data files)
by injecting per-file constants, the role Spark's
``PartitioningAwareFileIndex`` plays for the reference.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import pyarrow as pa

from hyperspace_tpu.io import parquet as pio

# ---------------------------------------------------------------------------
# Shared read-ahead pool (pipelined serve; docs/serve-pipeline.md)
# ---------------------------------------------------------------------------

# SHARED_STATE-registered (hyperspace_tpu/concurrency.py, hslint HS6xx):
# double-checked publish under the lock, lock-free reads of the published
# executor ("guarded-writes").
_scan_pool = None
_scan_pool_lock = threading.Lock()


def scan_pool():
    """The process-wide read-ahead ThreadPoolExecutor the pipelined serve
    path submits per-bucket parquet reads (and the hybrid-scan delta
    prepare) to. Sized for I/O overlap, not CPU count: parquet reads
    spend most of their time in Arrow's own (GIL-releasing) decode and
    on storage latency, so even a 2-core host profits from several
    in-flight reads. One shared pool keeps a concurrent left+right side
    prepare from spawning 2x the threads; tasks submitted here must
    never block on other scan_pool futures (deadlock discipline — only
    the consuming side threads wait)."""
    global _scan_pool
    if _scan_pool is None:
        with _scan_pool_lock:
            if _scan_pool is None:
                import os
                from concurrent.futures import ThreadPoolExecutor

                workers = min(8, max(4, (os.cpu_count() or 1)))
                _scan_pool = ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="hs-scan",
                )
    return _scan_pool


def read_relation_files(
    relation, files: Sequence[str], columns: Optional[Sequence[str]]
) -> pa.Table:
    """Read ``files`` of ``relation`` projecting ``columns`` (None = all),
    injecting partition-value constants where the relation carries them."""
    pv = dict(relation.file_partition_values)
    want = list(columns) if columns is not None else relation.column_names
    if not pv:
        return pio.read_table(list(files), want, relation.fmt)
    schema = relation.schema
    tables = []
    for f in files:
        vals = dict(pv.get(f, ()))
        data_cols = [c for c in want if c not in vals]
        part_cols = [c for c in want if c in vals]
        if data_cols:
            t = pio.read_table([f], data_cols, relation.fmt)
            n = t.num_rows
        else:
            # only partition columns requested: still need the row count
            t = pio.read_table([f], None, relation.fmt)
            n = t.num_rows
            t = t.select([])
        for c in part_cols:
            v = vals[c]
            arr = pa.array([v] * n, type=pa.string()).cast(schema[c])
            t = t.append_column(c, arr)
        tables.append(t.select(want))
    return pa.concat_tables(tables, promote_options="permissive")
