"""Host I/O layer: Arrow/Parquet read-write and the host↔device columnar
batch representation.

The reference delegates all I/O to Spark's ``FileFormat``/``FileSourceScanExec``
machinery; here the host side is Arrow (no JVM) and the device side is SoA
numpy/JAX arrays (see :mod:`hyperspace_tpu.io.columnar`).
"""

from hyperspace_tpu.io.columnar import Column, ColumnarBatch

__all__ = ["Column", "ColumnarBatch"]
