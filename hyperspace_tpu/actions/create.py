"""CreateAction — build a new index.

Reference: ``actions/CreateAction.scala:29-100`` (validation: supported
relation `:52-57`, column resolution `:62-66`, name/state uniqueness
`:74-80`; op = ``index.write``) and ``actions/CreateActionBase.scala``
(log-entry construction: signature, relation metadata, enriched
properties, content-from-directory).
"""

from __future__ import annotations

from typing import Dict, Optional

from hyperspace_tpu import constants as C
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.indexes.context import IndexerContext
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.entry import (
    Content,
    FileIdTracker,
    IndexLogEntry,
    Source,
    SourcePlan,
)
from hyperspace_tpu.signatures import IndexSignatureProvider
from hyperspace_tpu.telemetry import CreateActionEvent
from hyperspace_tpu.utils import resolver


class CreateAction(Action):
    transient_state = States.CREATING
    final_state = States.ACTIVE

    def __init__(self, session, df, index_config, log_manager, data_manager):
        super().__init__(session, log_manager)
        self.df = df
        self.index_config = index_config
        self.data_manager: IndexDataManager = data_manager
        self._sources = session.source_manager
        self._resnapshot()

    def _resnapshot(self) -> None:
        super()._resnapshot()
        self.tracker = FileIdTracker()
        version = (self.data_manager.get_latest_version_id() or 0) + 1
        self.index_data_path = self.data_manager.get_path(version)
        self._index = None

    # -- validation (CreateAction.scala:50-81) ------------------------------
    def validate(self) -> None:
        leaves = self.df.logical_plan.collect_leaves()
        if len(leaves) != 1:
            raise HyperspaceException(
                "Only queries over a single supported relation can be indexed"
            )
        if not self._sources.is_supported(leaves[0].relation):
            raise HyperspaceException(
                f"Relation is not supported by any source provider: "
                f"{leaves[0].relation.root_paths}"
            )
        resolved = resolver.resolve(
            self.index_config.referenced_columns,
            self.df.columns,
            nested_available=resolver.nested_available_from(self.df.columns),
        )
        if resolved is None:
            raise HyperspaceException(
                f"Index columns {self.index_config.referenced_columns} could "
                f"not be resolved against {self.df.columns}"
            )
        # nested-field gate (CreateAction.scala:69-71): struct paths index
        # only when hyperspace.index.supportNestedFields is on
        if not self.session.conf.support_nested_fields and any(
            rc.normalized_name.startswith(C.NESTED_FIELD_PREFIX)
            for rc in resolved
        ):
            raise HyperspaceException(
                "Indexing nested (struct) fields requires "
                f"{C.INDEX_SUPPORT_NESTED_FIELDS}=true"
            )
        latest = self.log_manager.get_latest_log()
        if latest is not None and latest.state != States.DOESNOTEXIST:
            raise HyperspaceException(
                f"Index {self.index_config.index_name!r} already exists "
                f"(state {latest.state})"
            )

    # -- op (CreateAction.scala:85) -----------------------------------------
    def op(self) -> None:
        ctx = IndexerContext(self.session, self.tracker, self.index_data_path)
        index, index_data = self.index_config.create_index(
            ctx, self.df, self._enriched_properties()
        )
        index.write(ctx, index_data)
        # zone-map sidecar for the range serve plane (best-effort: the
        # serve path backfills from parquet footers when absent), and the
        # aggregate-plane partials/sample sidecars (docs/agg-serve.md)
        from hyperspace_tpu.indexes import aggindex, zonemaps

        zonemaps.capture_safely(self.index_data_path, index)
        aggindex.capture_safely(self.index_data_path, index, self.session.conf)
        self._index = index

    def _enriched_properties(self) -> Dict[str, str]:
        """CreateActionBase 'enriched' index properties: lineage flag and
        source-format hint, plus provider enrichment."""
        props = {
            C.LINEAGE_PROPERTY: str(self.session.conf.lineage_enabled).lower(),
        }
        leaf = self.df.logical_plan.collect_leaves()[0]
        if leaf.relation.fmt in ("parquet", "delta", "iceberg"):
            props[C.HAS_PARQUET_AS_SOURCE_FORMAT_PROPERTY] = "true"
        rel = self._sources.get_relation(leaf.relation)
        # final entry commits at base_id + 2 (Action id arithmetic)
        return rel.enrich_index_properties(props, self.base_id + 2)

    # -- log entry (CreateActionBase.getIndexLogEntry:41-83) ----------------
    def begin_log_entry(self) -> IndexLogEntry:
        return self._build_entry(content=Content.from_leaf_files([]))

    def log_entry(self) -> IndexLogEntry:
        content = Content.from_directory_scan(self.index_data_path, self.tracker)
        return self._build_entry(content)

    def _build_entry(self, content: Content) -> IndexLogEntry:
        leaf = self.df.logical_plan.collect_leaves()[0]
        source_rel = self._sources.get_relation(leaf.relation)
        meta_relation = source_rel.create_metadata_relation(self.tracker)
        fingerprint = IndexSignatureProvider(self._sources).fingerprint(
            self.df.logical_plan
        )
        if self._index is None:
            # begin-phase: materialize the index object without building data
            ctx = IndexerContext(self.session, self.tracker, self.index_data_path)
            index = self.index_config.describe_index(
                ctx, self.df, self._enriched_properties()
            )
        else:
            index = self._index
        return IndexLogEntry(
            name=self.index_config.index_name,
            derived_dataset=index,
            content=content,
            source=Source(SourcePlan([meta_relation], provider="default")),
            fingerprint=fingerprint,
            properties={},
        )

    def event(self, success: bool, message: str = ""):
        return CreateActionEvent(
            index_name=self.index_config.index_name, message=message
        )
