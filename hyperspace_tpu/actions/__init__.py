"""Action / lifecycle layer (L2): the index state machine.

Reference: ``src/main/scala/com/microsoft/hyperspace/actions/`` — every
mutation of an index runs as an Action with the begin/op/end protocol over
the operation log (``Action.scala:34-108``): write log id ``base+1`` with a
transient state, run the data-plane op, write ``base+2`` with the final
state and refresh ``latestStable``. Optimistic concurrency comes from
``write_log`` failing when the id already exists.
"""

from hyperspace_tpu.actions.base import Action

__all__ = ["Action"]
