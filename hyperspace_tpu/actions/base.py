"""Action protocol: validate / begin / op / end.

Reference: ``actions/Action.scala:34-108``. The id arithmetic (`:35-36`):
``baseId`` = latest existing log id (0 if none); begin writes ``baseId+1``
(transient), end writes ``baseId+2`` (final) and recreates the
``latestStable`` pointer. A concurrent writer loses the ``write_log``
create-if-absent race and aborts. ``NoChangesException`` from ``validate``
makes the whole action a graceful no-op (refresh/optimize with nothing to
do).
"""

from __future__ import annotations

import abc
from typing import Optional

from hyperspace_tpu.exceptions import (
    ConcurrentWriteException,
    HyperspaceException,
    NoChangesException,
)
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.telemetry import HyperspaceEvent


class Action(abc.ABC):
    transient_state: str = ""
    final_state: str = ""

    def __init__(self, session, log_manager: IndexLogManager):
        self.session = session
        self.log_manager = log_manager
        self.base_id: int = log_manager.get_latest_id() or 0

    # -- protocol pieces ----------------------------------------------------
    def validate(self) -> None:
        """Raise HyperspaceException on an illegal state, or
        NoChangesException to make the action a no-op."""

    @abc.abstractmethod
    def op(self) -> None:
        """The data-plane work (device pipeline / file IO)."""

    @abc.abstractmethod
    def log_entry(self) -> IndexLogEntry:
        """The final log entry content (state is stamped by run())."""

    def begin_log_entry(self) -> IndexLogEntry:
        """Entry written at begin; defaults to log_entry(). Actions whose
        content only exists after op() (create/refresh) override this."""
        return self.log_entry()

    def event(self, success: bool, message: str = "") -> Optional[HyperspaceEvent]:
        return None

    # -- driver (Action.run:84-105) -----------------------------------------
    def run(self) -> None:
        try:
            self.validate()
        except NoChangesException:
            self._log_event(True, "No-op action")
            return
        begin = self.begin_log_entry().with_state(self.transient_state)
        begin.id = self.base_id + 1
        if not self.log_manager.write_log(self.base_id + 1, begin):
            raise ConcurrentWriteException(
                f"Another operation is in progress (log id "
                f"{self.base_id + 1} already exists)"
            )
        try:
            self.op()
            final = self.log_entry().with_state(self.final_state)
            final.id = self.base_id + 2
            if not self.log_manager.write_log(self.base_id + 2, final):
                raise ConcurrentWriteException(
                    f"Concurrent write at log id {self.base_id + 2}"
                )
            self.log_manager.create_latest_stable_log(self.base_id + 2)
        except Exception as e:
            self._log_event(False, str(e))
            raise
        self._log_event(True)

    def _log_event(self, success: bool, message: str = "") -> None:
        ev = self.event(success, message)
        if ev is not None:
            self.session.event_logging.log_event(ev)
