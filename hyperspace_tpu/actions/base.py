"""Action protocol: validate / begin / op / end.

Reference: ``actions/Action.scala:34-108``. The id arithmetic (`:35-36`):
``baseId`` = latest existing log id (0 if none); begin writes ``baseId+1``
(transient), end writes ``baseId+2`` (final) and recreates the
``latestStable`` pointer. A concurrent writer loses the ``write_log``
create-if-absent race — and, since the recovery plane (PR 10), retries
from a fresh snapshot with backoff instead of aborting on the first
collision. ``NoChangesException`` from ``validate`` makes the whole
action a graceful no-op (refresh/optimize with nothing to do).

Crash safety (``metadata/recovery.py``, docs/recovery.md): ``run()``
first repairs any dead writer's leavings at the log tip
(``ensure_recovered`` — rollback of lease-expired transient entries,
latestStable healing), re-snapshots ``base_id`` (the ``__init__``-time
read is advisory only; a queued action must see the tip as of *run*,
not construction), stamps a writer lease into the begin entry, and
heartbeats that lease while ``op()`` runs so a slow writer is never
mistaken for a dead one. The named crash points
(``testing/faults.py``: after_begin_log / after_data_write /
after_end_log here; mid_data_write / mid_vacuum_delete at the data
seams) let the test matrix kill the writer between any two protocol
steps and assert recovery.

Multi-process jobs (docs/MULTIHOST.md "collective symmetry doctrine"):
the metadata plane stays single-writer — only the coordinator
(``MeshRuntime.is_coordinator``, process 0) runs recovery, the OCC
begin/commit log writes (:func:`_publish_log`) and the latestStable
publish (:func:`_publish_latest_stable`), via
:meth:`Action._run_coordinated`; every other process runs the
data-plane replica (:meth:`Action._run_data_plane`): the same snapshot
+ validate discipline, then ``op()`` — whose exchange collectives and
``_global_written`` barrier every process must reach identically.
Three ABORT-AWARE rendezvous (:func:`_action_rendezvous`, a registered
``per-host-lane`` collective site: an allgather of per-process step
verdicts) order the protocol and make every one-sided failure a
job-wide typed error instead of a hang: workers snapshot only after
the coordinator's recovery repair (``recovered``), workers finish
validating before the coordinator's begin entry exists (``validate`` —
a worker must never see its own action's transient state; a no-op
verdict must be unanimous), and no worker enters the data plane before
the begin entry is durable (``begin`` — a crash mid-op must leave a
rollbackable transient tip, and a begin-write OCC loss aborts the
workers instead of stranding them). One action at a time per
multi-process job: the OCC retry loop is disabled on the coordinator
because a silent re-validate on one process would desynchronize the
rendezvous program.
"""

from __future__ import annotations

import abc
import time
from typing import Optional

from hyperspace_tpu.exceptions import (
    ConcurrentWriteException,
    HyperspaceException,
    NoChangesException,
)
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.telemetry import HyperspaceEvent
from hyperspace_tpu.testing import faults


def _multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


#: per-process step verdicts exchanged at each rendezvous
_STEP_FAIL, _STEP_PROCEED, _STEP_NOOP = 0, 1, 2


def _action_rendezvous(step: str, verdict: int) -> int:
    """Abort-aware cross-process rendezvous of the action protocol:
    allgather every process's verdict for ``step`` and return the
    unanimous one. Any process reporting failure — or a proceed/no-op
    disagreement — raises on EVERY process, so a one-sided exception
    (a begin-write OCC loss on the coordinator, a validate error on one
    worker) becomes a job-wide typed abort instead of peers blocking
    forever in a barrier. Registered in ``COLLECTIVE_SITES`` as
    ``per-host-lane``: same sequence position on every process, each
    carrying its own verdict payload. Callers guard with
    :func:`_multiprocess` — a single-process job has no peers to meet."""
    import numpy as np
    from jax.experimental import multihost_utils as mhu

    flags = np.asarray(
        mhu.process_allgather(np.asarray([verdict], dtype=np.int32))
    ).ravel()
    if (flags == _STEP_FAIL).any() or len(set(flags.tolist())) > 1:
        raise ConcurrentWriteException(
            f"multi-process action aborted at step {step!r}: per-process "
            f"verdicts {flags.tolist()} (0=failed, 1=proceed, 2=no-op)"
        )
    return int(flags[0])


def _publish_log(log_manager: IndexLogManager, log_id: int, entry) -> bool:
    """Coordinator-gated OCC log write (``COLLECTIVE_SITES``): the
    operation log has exactly one writer per action — on a multi-process
    job only the coordinator ever reaches this seam."""
    return log_manager.write_log(log_id, entry)


def _publish_latest_stable(log_manager: IndexLogManager, log_id: int) -> bool:
    """Coordinator-gated latestStable pointer publish — the same
    single-writer metadata seam as the log entries themselves."""
    return log_manager.create_latest_stable_log(log_id)


class Action(abc.ABC):
    transient_state: str = ""
    final_state: str = ""

    def __init__(self, session, log_manager: IndexLogManager):
        self.session = session
        self.log_manager = log_manager
        self.base_id: int = log_manager.get_latest_id() or 0

    # -- protocol pieces ----------------------------------------------------
    def validate(self) -> None:
        """Raise HyperspaceException on an illegal state, or
        NoChangesException to make the action a no-op."""

    @abc.abstractmethod
    def op(self) -> None:
        """The data-plane work (device pipeline / file IO)."""

    @abc.abstractmethod
    def log_entry(self) -> IndexLogEntry:
        """The final log entry content (state is stamped by run())."""

    def begin_log_entry(self) -> IndexLogEntry:
        """Entry written at begin; defaults to log_entry(). Actions whose
        content only exists after op() (create/refresh) override this."""
        return self.log_entry()

    def event(self, success: bool, message: str = "") -> Optional[HyperspaceEvent]:
        return None

    def _resnapshot(self) -> None:
        """Re-read every log-derived member off the CURRENT tip.

        ``__init__`` snapshots ``base_id`` (and, in subclasses, the
        previous entry / version dir / tracker), but an action may run
        long after construction — and the OCC retry loop re-enters here
        after a collision. Subclasses that cache more than ``base_id``
        extend this; nothing outside ``run()`` may rely on the
        construction-time snapshot."""
        self.base_id = self.log_manager.get_latest_id() or 0

    # -- driver (Action.run:84-105 + recovery/retry) ------------------------
    def run(self) -> None:
        """Obs wrapper around the protocol: one ROOT span per lifecycle
        action (child stage spans — scan/shuffle/sort/write/
        sidecar_capture/log_commit — attach via the build breakdown
        hooks), finished whatever the outcome, so every action is
        explainable after the fact (docs/observability.md)."""
        # configure, not just set_enabled: action-only processes (build
        # workers with no frontend) must still honor the trace bounds
        obs_trace.configure(self.session.conf)
        index_name = getattr(self, "index_name", "") or getattr(
            getattr(self, "index_config", None), "index_name", ""
        )
        root = obs_trace.root(
            f"action.{type(self).__name__}", index=str(index_name)
        )
        with obs_trace.activate(root):
            try:
                self._run_protocol()
                root.set("status", "ok")
            except BaseException:
                root.set("status", "failed")
                raise
            finally:
                root.finish()

    def _run_protocol(self) -> None:
        from hyperspace_tpu.metadata import recovery

        if _multiprocess():
            if self.session.runtime.is_coordinator:
                self._run_coordinated()
            else:
                self._run_data_plane()
            return
        conf = self.session.conf
        recovery_on = conf.recovery_enabled
        attempts = conf.recovery_retry_max_attempts if recovery_on else 1
        backoff = conf.recovery_retry_backoff_ms / 1000.0
        lease_ms = conf.recovery_lease_ms
        owner = recovery.new_owner_id()
        begin = None
        for attempt in range(1, attempts + 1):
            if attempt > 1 and backoff > 0:
                time.sleep(backoff * (1 << (attempt - 2)))
            # fix a dead writer's leavings BEFORE snapshotting: a
            # stranded transient tip rolls back (appending an entry), a
            # stale latestStable pointer heals — then the snapshot below
            # sees the repaired log
            if recovery_on:
                recovery.ensure_recovered(self.log_manager, lease_ms)
            self._resnapshot()
            try:
                self.validate()
            except NoChangesException:
                self._log_event(True, "No-op action")
                return
            begin = self.begin_log_entry().with_state(self.transient_state)
            if recovery_on:
                recovery.stamp_lease(begin, owner, lease_ms)
            begin.id = self.base_id + 1
            if _publish_log(self.log_manager, self.base_id + 1, begin):
                break
            if attempt >= attempts:
                raise ConcurrentWriteException(
                    f"Another operation is in progress (log id "
                    f"{self.base_id + 1} already exists after {attempts} "
                    f"attempts)"
                )
        faults.crash("after_begin_log", type(self).__name__)
        heartbeat = None
        if recovery_on:
            heartbeat = recovery.LeaseHeartbeat(
                self.log_manager, self.base_id + 1, begin, owner, lease_ms
            ).start()
        try:
            self.op()
            faults.crash("after_data_write", type(self).__name__)
            with obs_trace.span("log_commit"):
                final = self.log_entry().with_state(self.final_state)
                final.id = self.base_id + 2
                if not _publish_log(self.log_manager, self.base_id + 2, final):
                    # the end id exists already: a cancel()/recovery
                    # rolled our transient entry back under us — the
                    # data work must not be published over their write
                    raise ConcurrentWriteException(
                        f"Concurrent write at log id {self.base_id + 2}"
                    )
                faults.crash("after_end_log", type(self).__name__)
                _publish_latest_stable(self.log_manager, self.base_id + 2)
        except Exception as e:
            self._log_event(False, str(e))
            raise
        finally:
            # stopped on every in-process exit, incl. SimulatedCrash —
            # mirroring reality: when the process dies the heartbeat
            # thread dies with it, and the lease starts aging
            if heartbeat is not None:
                heartbeat.stop()
        self._publish_fleet_event(final)
        self._log_event(True)

    def _rendezvous_step(self, step: str, fn) -> int:
        """Run one protocol step locally, then rendezvous on its
        verdict. The local exception (if any) wins over the collective
        abort, so the failing process reports its own root cause while
        its peers get the typed ConcurrentWriteException instead of
        blocking forever."""
        verdict, err = _STEP_PROCEED, None
        try:
            fn()
        except NoChangesException:
            verdict = _STEP_NOOP
        # deliberate catch-all: the verdict must reach the peers (they
        # are entering the same allgather) BEFORE this process unwinds
        except Exception as e:  # hslint: disable=HS402
            verdict, err = _STEP_FAIL, e
        try:
            return _action_rendezvous(step, verdict)
        except ConcurrentWriteException:
            if err is not None:
                raise err
            raise

    def _run_coordinated(self) -> None:
        """The coordinator side of a multi-process action: the
        single-writer metadata plane plus the shared data plane, with an
        abort-aware rendezvous at each protocol step (module
        docstring). ONE begin-write attempt — an OCC loss aborts the
        whole job symmetrically at the ``begin`` rendezvous rather than
        silently re-validating out of sync with the workers (one action
        at a time per multi-process job)."""
        from hyperspace_tpu.metadata import recovery

        conf = self.session.conf
        recovery_on = conf.recovery_enabled
        lease_ms = conf.recovery_lease_ms
        owner = recovery.new_owner_id()

        def repair():
            # a dead writer's leavings repair BEFORE anyone snapshots:
            # the rendezvous orders every worker's snapshot after this
            if recovery_on:
                recovery.ensure_recovered(self.log_manager, lease_ms)

        self._rendezvous_step("recovered", repair)

        def snapshot_validate():
            self._resnapshot()
            self.validate()

        if self._rendezvous_step("validate", snapshot_validate) == _STEP_NOOP:
            self._log_event(True, "No-op action")
            return

        begin_box = []

        def begin_write():
            # only now may the transient entry appear — every worker
            # has finished validating (the rendezvous above), so none
            # can mistake our own begin entry for a concurrent writer
            begin = self.begin_log_entry().with_state(self.transient_state)
            if recovery_on:
                recovery.stamp_lease(begin, owner, lease_ms)
            begin.id = self.base_id + 1
            if not _publish_log(self.log_manager, self.base_id + 1, begin):
                raise ConcurrentWriteException(
                    f"Another operation is in progress (log id "
                    f"{self.base_id + 1} already exists)"
                )
            begin_box.append(begin)

        self._rendezvous_step("begin", begin_write)
        heartbeat = None
        if recovery_on:
            heartbeat = recovery.LeaseHeartbeat(
                self.log_manager, self.base_id + 1, begin_box[0], owner,
                lease_ms,
            ).start()
        try:
            self.op()
            with obs_trace.span("log_commit"):
                final = self.log_entry().with_state(self.final_state)
                final.id = self.base_id + 2
                if not _publish_log(self.log_manager, self.base_id + 2, final):
                    raise ConcurrentWriteException(
                        f"Concurrent write at log id {self.base_id + 2}"
                    )
                _publish_latest_stable(self.log_manager, self.base_id + 2)
        except Exception as e:
            self._log_event(False, str(e))
            raise
        finally:
            if heartbeat is not None:
                heartbeat.stop()
        # coordinator-only, like every other metadata-plane write: the
        # fanout is plain file I/O, one publisher per action
        self._publish_fleet_event(final)
        self._log_event(True)

    def _run_data_plane(self) -> None:
        """The non-coordinator replica of :meth:`_run_coordinated`: the
        identical rendezvous program and the identical ``op()``
        collective program, but NO log writes, no recovery, no lease —
        the coordinator owns the metadata plane (ROADMAP item 4; this
        process already receives the global file list through
        ``_global_written``'s barrier + union listing)."""
        self._rendezvous_step("recovered", lambda: None)

        def snapshot_validate():
            # ordered AFTER the coordinator's recovery repair by the
            # rendezvous above: both sides validate the repaired log
            self._resnapshot()
            self.validate()

        if self._rendezvous_step("validate", snapshot_validate) == _STEP_NOOP:
            self._log_event(True, "No-op action")
            return
        self._rendezvous_step("begin", lambda: None)
        try:
            self.op()
        except Exception as e:
            self._log_event(False, str(e))
            raise
        self._log_event(True)

    def _publish_fleet_event(self, entry: Optional[IndexLogEntry]) -> None:
        """Fan the committed action out to peer serve frontends
        (``serve/bus.py``; no-op outside fleet mode, never raises — the
        commit already happened, a failed fanout only costs peers a lazy
        re-read)."""
        if not self.session.conf.fleet_enabled:
            return
        from hyperspace_tpu.serve import bus

        bus.publish_action_event(
            self.session,
            getattr(self, "index_name", ""),
            self.log_manager.index_path,
            type(self).__name__,
            entry,
        )

    def _log_event(self, success: bool, message: str = "") -> None:
        ev = self.event(success, message)
        if ev is not None:
            self.session.event_logging.log_event(ev)
