"""Action protocol: validate / begin / op / end.

Reference: ``actions/Action.scala:34-108``. The id arithmetic (`:35-36`):
``baseId`` = latest existing log id (0 if none); begin writes ``baseId+1``
(transient), end writes ``baseId+2`` (final) and recreates the
``latestStable`` pointer. A concurrent writer loses the ``write_log``
create-if-absent race — and, since the recovery plane (PR 10), retries
from a fresh snapshot with backoff instead of aborting on the first
collision. ``NoChangesException`` from ``validate`` makes the whole
action a graceful no-op (refresh/optimize with nothing to do).

Crash safety (``metadata/recovery.py``, docs/recovery.md): ``run()``
first repairs any dead writer's leavings at the log tip
(``ensure_recovered`` — rollback of lease-expired transient entries,
latestStable healing), re-snapshots ``base_id`` (the ``__init__``-time
read is advisory only; a queued action must see the tip as of *run*,
not construction), stamps a writer lease into the begin entry, and
heartbeats that lease while ``op()`` runs so a slow writer is never
mistaken for a dead one. The named crash points
(``testing/faults.py``: after_begin_log / after_data_write /
after_end_log here; mid_data_write / mid_vacuum_delete at the data
seams) let the test matrix kill the writer between any two protocol
steps and assert recovery.
"""

from __future__ import annotations

import abc
import time
from typing import Optional

from hyperspace_tpu.exceptions import (
    ConcurrentWriteException,
    HyperspaceException,
    NoChangesException,
)
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.telemetry import HyperspaceEvent
from hyperspace_tpu.testing import faults


class Action(abc.ABC):
    transient_state: str = ""
    final_state: str = ""

    def __init__(self, session, log_manager: IndexLogManager):
        self.session = session
        self.log_manager = log_manager
        self.base_id: int = log_manager.get_latest_id() or 0

    # -- protocol pieces ----------------------------------------------------
    def validate(self) -> None:
        """Raise HyperspaceException on an illegal state, or
        NoChangesException to make the action a no-op."""

    @abc.abstractmethod
    def op(self) -> None:
        """The data-plane work (device pipeline / file IO)."""

    @abc.abstractmethod
    def log_entry(self) -> IndexLogEntry:
        """The final log entry content (state is stamped by run())."""

    def begin_log_entry(self) -> IndexLogEntry:
        """Entry written at begin; defaults to log_entry(). Actions whose
        content only exists after op() (create/refresh) override this."""
        return self.log_entry()

    def event(self, success: bool, message: str = "") -> Optional[HyperspaceEvent]:
        return None

    def _resnapshot(self) -> None:
        """Re-read every log-derived member off the CURRENT tip.

        ``__init__`` snapshots ``base_id`` (and, in subclasses, the
        previous entry / version dir / tracker), but an action may run
        long after construction — and the OCC retry loop re-enters here
        after a collision. Subclasses that cache more than ``base_id``
        extend this; nothing outside ``run()`` may rely on the
        construction-time snapshot."""
        self.base_id = self.log_manager.get_latest_id() or 0

    # -- driver (Action.run:84-105 + recovery/retry) ------------------------
    def run(self) -> None:
        from hyperspace_tpu.metadata import recovery

        conf = self.session.conf
        recovery_on = conf.recovery_enabled
        attempts = conf.recovery_retry_max_attempts if recovery_on else 1
        backoff = conf.recovery_retry_backoff_ms / 1000.0
        lease_ms = conf.recovery_lease_ms
        owner = recovery.new_owner_id()
        begin = None
        for attempt in range(1, attempts + 1):
            if attempt > 1 and backoff > 0:
                time.sleep(backoff * (1 << (attempt - 2)))
            # fix a dead writer's leavings BEFORE snapshotting: a
            # stranded transient tip rolls back (appending an entry), a
            # stale latestStable pointer heals — then the snapshot below
            # sees the repaired log
            if recovery_on:
                recovery.ensure_recovered(self.log_manager, lease_ms)
            self._resnapshot()
            try:
                self.validate()
            except NoChangesException:
                self._log_event(True, "No-op action")
                return
            begin = self.begin_log_entry().with_state(self.transient_state)
            if recovery_on:
                recovery.stamp_lease(begin, owner, lease_ms)
            begin.id = self.base_id + 1
            if self.log_manager.write_log(self.base_id + 1, begin):
                break
            if attempt >= attempts:
                raise ConcurrentWriteException(
                    f"Another operation is in progress (log id "
                    f"{self.base_id + 1} already exists after {attempts} "
                    f"attempts)"
                )
        faults.crash("after_begin_log", type(self).__name__)
        heartbeat = None
        if recovery_on:
            heartbeat = recovery.LeaseHeartbeat(
                self.log_manager, self.base_id + 1, begin, owner, lease_ms
            ).start()
        try:
            self.op()
            faults.crash("after_data_write", type(self).__name__)
            final = self.log_entry().with_state(self.final_state)
            final.id = self.base_id + 2
            if not self.log_manager.write_log(self.base_id + 2, final):
                # the end id exists already: a cancel()/recovery rolled
                # our transient entry back under us — the data work must
                # not be published over their write
                raise ConcurrentWriteException(
                    f"Concurrent write at log id {self.base_id + 2}"
                )
            faults.crash("after_end_log", type(self).__name__)
            self.log_manager.create_latest_stable_log(self.base_id + 2)
        except Exception as e:
            self._log_event(False, str(e))
            raise
        finally:
            # stopped on every in-process exit, incl. SimulatedCrash —
            # mirroring reality: when the process dies the heartbeat
            # thread dies with it, and the lease starts aging
            if heartbeat is not None:
                heartbeat.stop()
        self._log_event(True)

    def _log_event(self, success: bool, message: str = "") -> None:
        ev = self.event(success, message)
        if ev is not None:
            self.session.event_logging.log_event(ev)
