"""Delete (soft) and Restore actions.

Reference: ``actions/DeleteAction.scala`` (ACTIVE → DELETING → DELETED; no
data touched — queries just stop seeing the index) and
``actions/RestoreAction.scala`` (DELETED → RESTORING → ACTIVE).
"""

from __future__ import annotations

from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.telemetry import DeleteActionEvent, RestoreActionEvent


class _StateFlipAction(Action):
    """Shared shape: require a stable state, rewrite the same entry with a
    new state; op() touches no data."""

    required_state = ""

    def __init__(self, session, index_name: str, log_manager):
        super().__init__(session, log_manager)
        self.index_name = index_name
        self._previous: IndexLogEntry | None = None
        self._resnapshot()

    def _resnapshot(self) -> None:
        super()._resnapshot()
        # Validate against the LATEST entry, stable or not: a dangling
        # transient state (failed action) blocks every operation until
        # cancel()/recovery (reference Action validations read the
        # latest entry; SURVEY §5 failure-detection notes).
        self._previous = self.log_manager.get_latest_log()

    def validate(self) -> None:
        if self._previous is None:
            raise HyperspaceException(f"Index not found: {self.index_name!r}")
        if self._previous.state != self.required_state:
            raise HyperspaceException(
                f"{type(self).__name__} requires state {self.required_state}; "
                f"index {self.index_name!r} is {self._previous.state}"
            )

    def op(self) -> None:
        pass

    def log_entry(self) -> IndexLogEntry:
        return self._previous.copy()


class DeleteAction(_StateFlipAction):
    transient_state = States.DELETING
    final_state = States.DELETED
    required_state = States.ACTIVE

    def event(self, success, message=""):
        return DeleteActionEvent(index_name=self.index_name, message=message)


class RestoreAction(_StateFlipAction):
    transient_state = States.RESTORING
    final_state = States.ACTIVE
    required_state = States.DELETED

    def event(self, success, message=""):
        return RestoreActionEvent(index_name=self.index_name, message=message)
