"""OptimizeAction — compact small index files bucket-wise.

Reference: ``actions/OptimizeAction.scala:57-148``: candidates are index
files below ``optimize.fileSizeThreshold`` (quick mode, default 256MB) or
all files (full mode), grouped by bucket id recovered from the file name
(`:96-114`, ``BucketingUtils.getBucketId``); single-file buckets are left
alone. The op rewrites those files into a new version dir; the final
content is the rewritten files merged with the untouched ("ignored") ones
(`:116-143`).
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu import constants as C
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException, NoChangesException
from hyperspace_tpu.indexes.context import IndexerContext
from hyperspace_tpu.io.parquet import bucket_id_of_file
from hyperspace_tpu.metadata.entry import Content, IndexLogEntry
from hyperspace_tpu.telemetry import OptimizeActionEvent


class OptimizeAction(Action):
    transient_state = States.OPTIMIZING
    final_state = States.ACTIVE

    def __init__(self, session, index_name, log_manager, data_manager, mode):
        super().__init__(session, log_manager)
        self.index_name = index_name
        self.data_manager = data_manager
        self.mode = mode
        self._resnapshot()

    def _resnapshot(self) -> None:
        super()._resnapshot()
        # latest (not latest-stable): a dangling transient state blocks
        # optimize until cancel()/recovery
        self._previous: Optional[IndexLogEntry] = (
            self.log_manager.get_latest_log()
        )
        version = (self.data_manager.get_latest_version_id() or 0) + 1
        self.index_data_path = self.data_manager.get_path(version)
        self.tracker = (
            self._previous.file_id_tracker() if self._previous else None
        )

    # -- candidate selection (filesToOptimize:96-114) -----------------------
    def _partition_files(self) -> Tuple[List[str], List[Tuple[str, object]]]:
        """-> (files_to_optimize, ignored (path, FileInfo))."""
        threshold = self.session.conf.optimize_file_size_threshold
        by_bucket: Dict[int, List[Tuple[str, object]]] = collections.defaultdict(
            list
        )
        ignored: List[Tuple[str, object]] = []
        for path, info in self._previous.content.file_infos:
            bucket = bucket_id_of_file(path)
            small = self.mode == C.OPTIMIZE_MODE_FULL or info.size < threshold
            if bucket is None or not small:
                ignored.append((path, info))
                continue
            by_bucket[bucket].append((path, info))
        to_optimize: List[str] = []
        for bucket, files in sorted(by_bucket.items()):
            if len(files) < 2:  # single-file buckets stay as-is
                ignored.extend(files)
                continue
            to_optimize.extend(p for p, _ in files)
        return to_optimize, ignored

    def validate(self) -> None:
        if self._previous is None:
            raise HyperspaceException(f"Index not found: {self.index_name!r}")
        if self._previous.state != States.ACTIVE:
            raise HyperspaceException(
                f"Optimize requires ACTIVE; index {self.index_name!r} is "
                f"{self._previous.state}"
            )
        files, _ignored = self._partition_files()
        if not files:
            raise NoChangesException(
                "Optimize aborted: no index files eligible for compaction "
                f"in mode {self.mode!r}"
            )

    def op(self) -> None:
        ctx = IndexerContext(self.session, self.tracker, self.index_data_path)
        files, self._ignored = self._partition_files()
        self._previous.derived_dataset.optimize(ctx, files)
        from hyperspace_tpu.indexes import aggindex, zonemaps

        zonemaps.capture_safely(
            self.index_data_path, self._previous.derived_dataset
        )
        aggindex.capture_safely(
            self.index_data_path,
            self._previous.derived_dataset,
            self.session.conf,
        )

    def log_entry(self) -> IndexLogEntry:
        new_content = Content.from_directory_scan(
            self.index_data_path, self.tracker
        )
        ignored_content = Content.from_leaf_files(
            [(p, i.size, i.modified_time) for p, i in self._ignored],
            self.tracker,
        )
        entry = self._previous.copy()
        entry.content = new_content.merge(ignored_content)
        return entry

    def begin_log_entry(self) -> IndexLogEntry:
        return self._previous.copy()

    def event(self, success, message=""):
        return OptimizeActionEvent(
            index_name=self.index_name, mode=self.mode, message=message
        )
