"""Refresh actions: full rebuild, incremental, quick (metadata-only).

Reference: ``actions/RefreshActionBase.scala:37-129`` (reconstruct the
source from stored relation metadata, diff current vs indexed file sets),
``RefreshAction.scala:33-64`` (full rebuild; no-op when unchanged),
``RefreshIncrementalAction.scala`` (index appended files, lineage
anti-filter for deletes, Directory.merge content),
``RefreshQuickAction.scala:32-80`` (record the delta in ``Update`` + new
fingerprint; Hybrid Scan compensates at query time).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException, NoChangesException
from hyperspace_tpu.indexes.base import UpdateMode
from hyperspace_tpu.indexes.context import IndexerContext
from hyperspace_tpu.metadata.entry import (
    Content,
    FileIdTracker,
    IndexLogEntry,
    Source,
    SourcePlan,
)
from hyperspace_tpu.plan.nodes import Relation as PlanRelation
from hyperspace_tpu.plan.nodes import Scan
from hyperspace_tpu.signatures import IndexSignatureProvider
from hyperspace_tpu.telemetry import (
    RefreshActionEvent,
    RefreshIncrementalActionEvent,
    RefreshQuickActionEvent,
)


class RefreshActionBase(Action):
    transient_state = States.REFRESHING
    final_state = States.ACTIVE

    def __init__(self, session, index_name: str, log_manager, data_manager):
        super().__init__(session, log_manager)
        self.index_name = index_name
        self.data_manager = data_manager
        self._resnapshot()

    def _resnapshot(self) -> None:
        """Derive previous entry, target version dir, tracker and the
        source-file snapshot off the current log tip — at construction
        AND again at run() (OCC retry / queued-action safety). Previous
        = latest (not latest-stable): a dangling transient state blocks
        refresh until cancel()/recovery."""
        super()._resnapshot()
        self._previous: Optional[IndexLogEntry] = (
            self.log_manager.get_latest_log()
        )
        version = (self.data_manager.get_latest_version_id() or 0) + 1
        self.index_data_path = self.data_manager.get_path(version)
        self.tracker: FileIdTracker = (
            self._previous.file_id_tracker() if self._previous else FileIdTracker()
        )
        self._source_rel = None
        self._current_infos = None

    # -- source reconstruction (RefreshActionBase.df:54-76) -----------------
    def source_relation(self):
        """Current source state, re-listed through the provider."""
        if self._source_rel is None:
            meta = self._previous.relation
            fields = tuple(
                (name, _parse_type(t)) for name, t in json.loads(meta.schema_json)
            )
            stale = PlanRelation(
                root_paths=tuple(meta.root_paths),
                files=(),
                fmt=meta.file_format,
                schema_fields=fields,
                options=tuple(sorted(meta.options.items())),
            )
            provider_rel = self.session.source_manager.get_relation(stale)
            self._source_rel = provider_rel.refresh()
        return self._source_rel

    def current_file_infos(self) -> Dict[str, Tuple[int, int]]:
        # Snapshot ONCE per action: validate/op/log_entry must all see the
        # same file view even if the source changes mid-action, and each
        # listing is a full O(N) stat pass.
        if self._current_infos is None:
            self._current_infos = {
                p: (size, mtime)
                for p, size, mtime in self.source_relation().all_file_infos()
            }
        return self._current_infos

    # -- diffs (RefreshActionBase.deletedFiles/appendedFiles:97-128) --------
    # Diff against the raw build-time snapshot (relation.content), NOT the
    # quick-refresh-adjusted view: files recorded by a quick refresh were
    # never materialized into index data, so they must still count as
    # appended/deleted here (the reference reads "files for which the index
    # was never updated in the past", RefreshActionBase.scala:97-128).
    def _indexed_data_files(self):
        return dict(self._previous.relation.content.file_infos)

    def appended_files(self) -> List[Tuple[str, int, int]]:
        prev = self._indexed_data_files()
        out = []
        for p, (size, mtime) in sorted(self.current_file_infos().items()):
            info = prev.get(p)
            if info is None or info.size != size or info.modified_time != mtime:
                out.append((p, size, mtime))
        return out

    def deleted_files(self) -> List[Tuple[str, int]]:
        """(path, file_id) of indexed files that are gone/overwritten."""
        current = self.current_file_infos()
        out = []
        for p, info in sorted(self._indexed_data_files().items()):
            cur = current.get(p)
            if cur is None or cur != (info.size, info.modified_time):
                out.append((p, info.id))
        return out

    # -- shared validation --------------------------------------------------
    def validate(self) -> None:
        if self._previous is None:
            raise HyperspaceException(f"Index not found: {self.index_name!r}")
        if self._previous.state != States.ACTIVE:
            raise HyperspaceException(
                f"Refresh requires ACTIVE; index {self.index_name!r} is "
                f"{self._previous.state}"
            )
        if not self.appended_files() and not self.deleted_files():
            raise NoChangesException("Refresh aborted: source is unchanged")

    # -- df construction ----------------------------------------------------
    def _df_over(self, files: List[str]):
        from hyperspace_tpu.dataframe import DataFrame

        import dataclasses

        rel = dataclasses.replace(
            self.source_relation().plan_relation, files=tuple(files)
        )
        return DataFrame(self.session, Scan(rel))

    # -- log entry construction ---------------------------------------------
    def _build_entry(self, index, content: Content) -> IndexLogEntry:
        source_rel = self.source_relation()
        # provider bookkeeping moves forward with each refresh (e.g. the
        # Delta indexLogVersion:deltaVersion history)
        index.properties = source_rel.enrich_index_properties(
            index.properties, self.base_id + 2
        )
        meta_relation = source_rel.create_metadata_relation(self.tracker)
        current_plan = Scan(source_rel.plan_relation)
        fingerprint = IndexSignatureProvider(
            self.session.source_manager
        ).fingerprint(current_plan)
        return IndexLogEntry(
            name=self._previous.name,
            derived_dataset=index,
            content=content,
            source=Source(SourcePlan([meta_relation], provider="default")),
            fingerprint=fingerprint,
            properties=dict(self._previous.properties),
        )


def _parse_type(s: str):
    from hyperspace_tpu.rules.rule_utils import parse_arrow_type

    return parse_arrow_type(s)


class RefreshAction(RefreshActionBase):
    """Full rebuild into a new version dir (RefreshAction.scala:33-64)."""

    def begin_log_entry(self) -> IndexLogEntry:
        return self._build_entry(
            self._previous.derived_dataset, self._previous.content
        )

    def op(self) -> None:
        ctx = IndexerContext(self.session, self.tracker, self.index_data_path)
        df = self._df_over(list(self.source_relation().plan_relation.files))
        self._index = self._previous.derived_dataset.refresh_full(ctx, df)
        from hyperspace_tpu.indexes import aggindex, zonemaps

        zonemaps.capture_safely(self.index_data_path, self._index)
        aggindex.capture_safely(
            self.index_data_path, self._index, self.session.conf
        )

    def log_entry(self) -> IndexLogEntry:
        content = Content.from_directory_scan(self.index_data_path, self.tracker)
        return self._build_entry(self._index, content)

    def event(self, success, message=""):
        return RefreshActionEvent(index_name=self.index_name, message=message)


class RefreshIncrementalAction(RefreshActionBase):
    """Index only the delta (RefreshIncrementalAction.scala:52-128)."""

    def validate(self) -> None:
        super().validate()
        if self.deleted_files() and not (
            self._previous.derived_dataset.can_handle_deleted_files
        ):
            raise HyperspaceException(
                "Refresh (incremental) aborted: deleted source files but the "
                "index has no lineage; recreate with "
                "hyperspace.index.lineage.enabled=true"
            )

    def begin_log_entry(self) -> IndexLogEntry:
        return self._build_entry(
            self._previous.derived_dataset, self._previous.content
        )

    def op(self) -> None:
        ctx = IndexerContext(self.session, self.tracker, self.index_data_path)
        appended = [p for p, _s, _m in self.appended_files()]
        deleted_ids = [fid for _p, fid in self.deleted_files() if fid != -1]
        appended_df = self._df_over(appended) if appended else None
        index = self._previous.derived_dataset
        self._index, self._mode = index.refresh_incremental(
            ctx, appended_df, deleted_ids, self._previous.content
        )
        # new version dir only: files from earlier versions keep their own
        # sidecars (MERGE mode), so zone maps / aggregate partials stay
        # consistent per dir — an incremental refresh folds ONLY the
        # appended files' partials (earlier dirs' sidecars are untouched)
        from hyperspace_tpu.indexes import aggindex, zonemaps

        zonemaps.capture_safely(self.index_data_path, self._index)
        aggindex.capture_safely(
            self.index_data_path, self._index, self.session.conf
        )

    def log_entry(self) -> IndexLogEntry:
        new_content = Content.from_directory_scan(
            self.index_data_path, self.tracker
        )
        if self._mode == UpdateMode.MERGE:
            content = self._previous.content.merge(new_content)
        else:
            content = new_content
        return self._build_entry(self._index, content)

    def event(self, success, message=""):
        return RefreshIncrementalActionEvent(
            index_name=self.index_name, message=message
        )


class RefreshQuickAction(RefreshActionBase):
    """Metadata-only refresh (RefreshQuickAction.scala:32-80): record the
    file-set delta + new fingerprint; query-time Hybrid Scan compensates."""

    def op(self) -> None:
        pass

    def begin_log_entry(self) -> IndexLogEntry:
        return self.log_entry()

    def log_entry(self) -> IndexLogEntry:
        appended = Content.from_leaf_files(self.appended_files(), self.tracker)
        deleted_triples = []
        # look up in the same view deleted_files() diffs against — the raw
        # build-time snapshot (a prior quick refresh already removed the
        # path from source_file_info_set())
        prev = self._indexed_data_files()
        for p, _fid in self.deleted_files():
            info = prev[p]
            deleted_triples.append((p, info.size, info.modified_time))
        deleted = Content.from_leaf_files(deleted_triples, self.tracker)
        current_plan = Scan(self.source_relation().plan_relation)
        fingerprint = IndexSignatureProvider(
            self.session.source_manager
        ).fingerprint(current_plan)
        return self._previous.copy_with_update(appended, deleted, fingerprint)

    def event(self, success, message=""):
        return RefreshQuickActionEvent(
            index_name=self.index_name, message=message
        )
