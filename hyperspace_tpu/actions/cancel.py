"""CancelAction — roll an interrupted operation back to the last stable
state.

Reference: ``actions/CancelAction.scala`` (validates the index is stuck in
a transient state, then appends a copy of the last stable entry so every
operation sees the pre-failure state again; ``Hyperspace.scala:139-151``).
Does not follow the begin/op/end protocol — it writes exactly one log
entry — so it overrides ``run``.
"""

from __future__ import annotations

from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import ConcurrentWriteException, HyperspaceException
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.telemetry import CancelActionEvent


class CancelAction(Action):
    transient_state = ""  # unused; run() is overridden
    final_state = ""

    def __init__(self, session, index_name: str, log_manager):
        super().__init__(session, log_manager)
        self.index_name = index_name

    def validate(self) -> None:
        latest = self.log_manager.get_latest_log()
        if latest is None:
            raise HyperspaceException(f"Index not found: {self.index_name!r}")
        if latest.state in States.STABLE_STATES:
            raise HyperspaceException(
                f"Cancel is only supported for transient states; index "
                f"{self.index_name!r} is {latest.state}"
            )

    def op(self) -> None:  # pragma: no cover - not used
        pass

    def log_entry(self) -> IndexLogEntry:  # pragma: no cover - not used
        raise NotImplementedError

    def run(self) -> None:
        self.validate()
        stable = self.log_manager.get_latest_stable_log()
        if stable is None:
            # Nothing stable ever existed (failed create): mark DOESNOTEXIST
            latest = self.log_manager.get_latest_log()
            entry = latest.with_state(States.DOESNOTEXIST)
        else:
            entry = stable.copy()
        entry.id = self.base_id + 1
        if not self.log_manager.write_log(self.base_id + 1, entry):
            raise ConcurrentWriteException(
                f"Concurrent write at log id {self.base_id + 1}"
            )
        self.log_manager.create_latest_stable_log(self.base_id + 1)
        self._log_event(True)

    def event(self, success, message=""):
        return CancelActionEvent(index_name=self.index_name, message=message)
