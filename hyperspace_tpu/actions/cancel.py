"""CancelAction — roll an interrupted operation back to the last stable
state.

Reference: ``actions/CancelAction.scala`` (validates the index is stuck in
a transient state, then appends a copy of the last stable entry so every
operation sees the pre-failure state again; ``Hyperspace.scala:139-151``).
Does not follow the begin/op/end protocol — it writes exactly one log
entry — so it overrides ``_run_protocol`` (keeping the base ``run``'s
obs root span: a cancel is a lifecycle action and must trace like one).

Since the recovery plane (PR 10) the actual rollback write lives in
``metadata/recovery.rollback`` and is shared with automatic
stranded-entry recovery. Cancel is the MANUAL override on top of it: it
does not consult the writer lease (the operator said the writer is
dead; a live writer racing a cancel loses its end-commit OCC write at
``base_id + 2`` — exactly the id the rollback takes — and aborts), while
automatic recovery only rolls back expired leases.
"""

from __future__ import annotations

from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import (
    ConcurrentWriteException,
    HyperspaceException,
    LogCorruptedError,
)
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.telemetry import CancelActionEvent


class CancelAction(Action):
    transient_state = ""  # unused; _run_protocol() is overridden
    final_state = ""

    def __init__(self, session, index_name: str, log_manager):
        super().__init__(session, log_manager)
        self.index_name = index_name

    def validate(self) -> None:
        try:
            latest = self.log_manager.get_latest_log()
        except LogCorruptedError:
            # a torn tip is a crashed writer's leavings — exactly what
            # cancel exists to clear; rollback() knows how to roll past
            # (or clear) it
            return
        if latest is None:
            raise HyperspaceException(f"Index not found: {self.index_name!r}")
        if latest.state in States.STABLE_STATES:
            raise HyperspaceException(
                f"Cancel is only supported for transient states; index "
                f"{self.index_name!r} is {latest.state}"
            )

    def op(self) -> None:  # pragma: no cover - not used
        pass

    def log_entry(self) -> IndexLogEntry:  # pragma: no cover - not used
        raise NotImplementedError

    def _run_protocol(self) -> None:
        from hyperspace_tpu.metadata import recovery

        self._resnapshot()
        self.validate()
        _tip, we_wrote = recovery.rollback(self.log_manager, self.base_id)
        if not we_wrote:
            # OUR rollback write lost the OCC race. The survivor may even
            # be the live writer's own end-commit — a stable tip, but the
            # OPPOSITE of what the operator asked for — so a cancel that
            # didn't perform the cancellation must say so, like any OCC
            # conflict
            raise ConcurrentWriteException(
                f"Concurrent write at log id {self.base_id + 1}"
            )
        self._log_event(True)

    def event(self, success, message=""):
        return CancelActionEvent(index_name=self.index_name, message=message)
