"""Vacuum actions: hard-delete a DELETED index, or garbage-collect outdated
versions of an ACTIVE one.

Reference: ``actions/VacuumAction.scala`` (DELETED → VACUUMING →
DOESNOTEXIST: delete all index files; a later create may reuse the name)
and ``actions/VacuumOutdatedAction.scala:34-144`` (ACTIVE →
VACUUMINGOUTDATED → ACTIVE: delete every non-latest ``v__=N`` dir and any
file in retained dirs that the live content no longer references; resets
the Delta version-history property `:56-67`).
"""

from __future__ import annotations

import os

from hyperspace_tpu.actions.delete import _StateFlipAction
from hyperspace_tpu.constants import (
    DELTA_VERSION_HISTORY_PROPERTY,
    States,
)
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.telemetry import VacuumActionEvent, VacuumOutdatedActionEvent
from hyperspace_tpu.testing import faults
from hyperspace_tpu.utils import files as file_utils


class VacuumAction(_StateFlipAction):
    transient_state = States.VACUUMING
    final_state = States.DOESNOTEXIST
    required_state = States.DELETED

    def op(self) -> None:
        # delete all index data (every version dir referenced or not) —
        # except files under a LIVE pin (fleet mode: a query in another
        # process pinned this snapshot while the index was still
        # readable; its durable pin file outranks the vacuum until the
        # lease expires, then orphan GC converges the leftovers)
        index_path = self.log_manager.index_path
        from hyperspace_tpu.constants import (
            HYPERSPACE_LOG_DIR,
            HYPERSPACE_PINS_DIR,
        )
        from hyperspace_tpu.metadata import recovery

        pinned = recovery.all_pinned_files(index_path)
        for name in sorted(os.listdir(index_path)):
            if name == HYPERSPACE_LOG_DIR or name == HYPERSPACE_PINS_DIR:
                continue
            # crash seam: a vacuum that dies between deletes leaves a
            # half-emptied index dir under a VACUUMING entry — recovery
            # rolls the log back to DELETED and a re-vacuum finishes
            faults.crash("mid_vacuum_delete", name)
            root = os.path.join(index_path, name)
            leaves = (
                [p for p, _s, _m in file_utils.list_leaf_files(root)]
                if pinned
                else []
            )
            if not any(p.replace("\\", "/") in pinned for p in leaves):
                file_utils.delete(root)
                continue
            for p in leaves:
                if p.replace("\\", "/") not in pinned:
                    file_utils.delete(p)

    def log_entry(self) -> IndexLogEntry:
        entry = self._previous.copy()
        from hyperspace_tpu.metadata.entry import Content

        entry.content = Content.from_leaf_files([])
        return entry

    def event(self, success, message=""):
        return VacuumActionEvent(index_name=self.index_name, message=message)


class VacuumOutdatedAction(_StateFlipAction):
    transient_state = States.VACUUMINGOUTDATED
    final_state = States.ACTIVE
    required_state = States.ACTIVE

    def __init__(self, session, index_name, log_manager, data_manager):
        super().__init__(session, index_name, log_manager)
        self.data_manager = data_manager

    def op(self) -> None:
        """Delete non-latest version dirs + unreferenced files in retained
        dirs (VacuumOutdatedAction.op:86-120). Files under a LIVE pin
        (in-memory or a peer process's durable pin file, fleet mode) are
        skipped — a serve that pinned the outgoing version finishes from
        it, and orphan GC reclaims the leftovers once the lease expires."""
        from hyperspace_tpu.metadata import recovery
        from hyperspace_tpu.utils import paths as path_utils

        index_path = self.log_manager.index_path
        pinned = recovery.all_pinned_files(index_path)
        live_files = set(self._previous.content.files)
        live_versions = {
            v
            for v in (
                self._version_of(f) for f in live_files
            )
            if v is not None
        }
        for version in self.data_manager.get_all_versions():
            if version not in live_versions:
                faults.crash("mid_vacuum_delete", f"v__={version}")
                root = self.data_manager.get_path(version)
                leaves = (
                    [p for p, _s, _m in file_utils.list_leaf_files(root)]
                    if pinned
                    else []
                )
                if any(p.replace("\\", "/") in pinned for p in leaves):
                    for p in leaves:
                        if p.replace("\\", "/") not in pinned:
                            file_utils.delete(p)
                    continue
                self.data_manager.delete(version)
                continue
            root = self.data_manager.get_path(version)
            for path, _s, _m in file_utils.list_leaf_files(root):
                # underscore/hidden sidecars (_zonemaps.json, _aggstate.
                # json, _aggsample.parquet) are never in the content, so
                # the live-file check must not delete them from RETAINED
                # dirs — a sidecar is dropped with the dir it describes.
                # Crash-leaked publish temps (.<name>.tmp.<pid>) ARE
                # garbage, though: vacuum is their only sweeper.
                if not path_utils.is_data_path(path):
                    if ".tmp." in os.path.basename(path):
                        file_utils.delete(path)
                    continue
                if path not in live_files:
                    if path.replace("\\", "/") in pinned:
                        continue
                    faults.crash("mid_vacuum_delete", path)
                    file_utils.delete(path)
            # rewrite the aggregate-plane sidecars to drop entries for
            # the files just deleted (per-file staleness would defuse
            # them anyway; this keeps the sidecar ≡ the dir's files)
            from hyperspace_tpu.indexes import aggindex

            aggindex.prune_missing(root)

    @staticmethod
    def _version_of(path: str):
        from hyperspace_tpu.metadata.data_manager import version_from_path

        return version_from_path(path)

    def log_entry(self) -> IndexLogEntry:
        entry = self._previous.copy()
        # reset provider version-history bookkeeping: only the surviving
        # index version remains addressable (Delta reset :56-67)
        index = entry.derived_dataset
        if DELTA_VERSION_HISTORY_PROPERTY in index.properties:
            history = index.properties[DELTA_VERSION_HISTORY_PROPERTY]
            last = history.split(",")[-1] if history else ""
            index.properties[DELTA_VERSION_HISTORY_PROPERTY] = last
        return entry

    def event(self, success, message=""):
        return VacuumOutdatedActionEvent(
            index_name=self.index_name, message=message
        )
