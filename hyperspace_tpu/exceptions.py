"""Exception types.

Reference: ``HyperspaceException.scala:19`` (single exception type) and
``actions/NoChangesException.scala`` (no-op refresh/optimize marker).
"""


class HyperspaceException(Exception):
    """Any user-visible failure inside the framework."""


class NoChangesException(HyperspaceException):
    """Raised by refresh/optimize validation when there is nothing to do.

    ``Action.run`` treats it as a graceful no-op: the transient log entry is
    never written and the index stays in its previous stable state
    (reference: ``actions/Action.scala:84-105``).
    """


class ConcurrentWriteException(HyperspaceException):
    """Optimistic-concurrency conflict on the operation log.

    Equivalent to ``writeLog`` returning false in the reference
    (``index/IndexLogManager.scala:178-194``): another writer created the
    same log id first.
    """


class LogCorruptedError(HyperspaceException):
    """An operation-log entry exists but does not parse (truncated or
    torn JSON — e.g. a crash on a filesystem without atomic
    publish-by-link).

    Typed so the recovery plane (``metadata/recovery.py``) can treat a
    torn entry as STRANDED — recoverable by rollback, like any other
    dead writer's leavings — instead of the raw decode traceback
    aborting every read of the index."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupted log entry {path}: {reason}")
        self.path = path
        self.reason = reason


class ApproximationError(HyperspaceException):
    """The approximate serve plane cannot honestly answer this query
    (``execution/approx_exec.py``): approx serving is disabled, the plan
    is not served by a sampled covering index, an aggregate is outside
    the estimable set (COUNT/SUM), or the 95% confidence interval blows
    the per-query error budget.

    Deliberately TYPED and raised instead of degrading: an approximate
    answer is only ever produced through the explicit
    ``DataFrame.collect_approx`` opt-in, and a bound the estimator
    cannot meet must surface as "run exact", never as a number the
    caller would over-trust."""


class ServeOverloadedError(HyperspaceException):
    """Admission control shed this query: the serve frontend's queue of
    admitted-but-not-running queries reached
    ``hyperspace.serve.maxQueueDepth`` (``serve/frontend.py``).

    Deliberately a TYPED error raised at submit time, before any work is
    queued: a caller (load balancer, client retry budget) can
    distinguish "the system is saturated, back off" from a query that
    failed — queueing past the bound would only convert overload into
    unbounded tail latency.
    """
