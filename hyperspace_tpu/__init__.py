"""hyperspace_tpu — a TPU-native data-lake indexing framework.

A ground-up re-design of the capabilities of Microsoft Hyperspace (the
reference at /root/reference, Scala/Spark) for TPU hardware:

* The *metadata plane* — a lake-resident, versioned operation log with
  optimistic concurrency (reference: ``index/IndexLogManager.scala``,
  ``index/IndexLogEntry.scala``) — is pure host Python, as it is pure JVM
  code in the reference.
* The *data plane* — index build (hash-bucket shuffle, sort, bucketed
  columnar write; reference: ``index/covering/CoveringIndex.scala:56-71``)
  and index-backed query execution (filter/join kernels) — runs on TPU as
  XLA-compiled JAX programs: ``shard_map`` + ``lax.all_to_all`` over an ICI
  device mesh replaces the Spark shuffle, device sort replaces
  sort-within-bucket, and columnar filter / merge-join kernels replace
  Spark's ``FileSourceScanExec``/SMJ.
* The *planner* — in the reference an injected Catalyst rule
  (``rules/ApplyHyperspace.scala``) — is here a small relational IR plus a
  score-based optimizer that we own end to end.

Public API (mirrors reference ``Hyperspace.scala:27-193`` and
``python/hyperspace/hyperspace.py``)::

    from hyperspace_tpu import HyperspaceSession, Hyperspace, CoveringIndexConfig

    sess = HyperspaceSession()
    hs = Hyperspace(sess)
    df = sess.read.parquet("/data/t")
    hs.create_index(df, CoveringIndexConfig("idx", ["k"], ["v"]))
    sess.enable_hyperspace()
    df.filter(df["k"] == 3).select("v").collect()   # served from the index
"""

from hyperspace_tpu.exceptions import HyperspaceException  # noqa: F401

__version__ = "0.5.0"

# Lazy top-level convenience imports (PEP 562) to avoid import cycles and
# keep `import hyperspace_tpu` cheap (no JAX import until a session is made).
_LAZY = {
    "HyperspaceSession": ("hyperspace_tpu.session", "HyperspaceSession"),
    "Hyperspace": ("hyperspace_tpu.hyperspace", "Hyperspace"),
    "CoveringIndexConfig": ("hyperspace_tpu.indexes.covering", "CoveringIndexConfig"),
    "IndexConfig": ("hyperspace_tpu.indexes.covering", "CoveringIndexConfig"),
    "ZOrderCoveringIndexConfig": (
        "hyperspace_tpu.indexes.zorder",
        "ZOrderCoveringIndexConfig",
    ),
    "DataSkippingIndexConfig": (
        "hyperspace_tpu.indexes.dataskipping",
        "DataSkippingIndexConfig",
    ),
    "functions": ("hyperspace_tpu.functions", None),
    "ServeFrontend": ("hyperspace_tpu.serve", "ServeFrontend"),
    "ServeOverloadedError": (
        "hyperspace_tpu.exceptions",
        "ServeOverloadedError",
    ),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod, attr = _LAZY[name]
        m = importlib.import_module(mod)
        return m if attr is None else getattr(m, attr)
    raise AttributeError(f"module 'hyperspace_tpu' has no attribute {name!r}")


__all__ = ["HyperspaceException", "__version__"] + sorted(_LAZY)
