"""HyperspaceSession — conf + mesh + reader + optimizer hook.

The analogue of a SparkSession *for this framework's scope*: it owns the
config (reference: Spark SQL conf, ``util/HyperspaceConf.scala``), the
device-mesh runtime (reference: the Spark cluster), source reading
(reference: ``DataFrameReader``), and the optimizer extension point where
``enable_hyperspace()`` injects the index-rewrite rule — mirroring the
implicit ``spark.enableHyperspace()`` (``package.scala:26-95``) and the
session extension (``HyperspaceSparkSessionExtension.scala:44-69``).
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Sequence

import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu.config import Config
from hyperspace_tpu.dataframe import DataFrame
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.parallel.mesh import MeshRuntime
from hyperspace_tpu.plan.nodes import Relation, Scan
from hyperspace_tpu.telemetry import EventLogging


class DataFrameReader:
    """``session.read.parquet(path)`` etc. — builds a Scan over a file
    snapshot (listing happens here, like Spark's ``InMemoryFileIndex``)."""

    def __init__(self, session: "HyperspaceSession"):
        self._session = session

    def _scan(self, fmt: str, paths: Sequence[str]) -> DataFrame:
        from hyperspace_tpu.io.parquet import expand_path, read_table

        files: List[str] = []
        for p in paths:
            files.extend(expand_path(p, fmt))
        if not files:
            raise HyperspaceException(f"No {fmt} files under {list(paths)}")
        if fmt == "parquet":
            schema = pq.read_schema(files[0])
            fields = tuple((f.name, f.type) for f in schema)
        else:
            head = read_table(files[:1], None, fmt)
            fields = tuple((n, head.schema.field(n).type) for n in head.column_names)
        # struct columns surface as flat __hs_nested.<path> leaf columns
        # (the engine's data plane is SoA; see io/columnar.py)
        from hyperspace_tpu.io.columnar import flatten_schema_fields

        fields = flatten_schema_fields(fields)
        # glob patterns stay patterns in root_paths (re-expanded on every
        # refresh/signature listing) — but absolutized like plain paths,
        # or re-expansion would depend on the process cwd
        rel = Relation(
            root_paths=tuple(os.path.abspath(p) for p in paths),
            files=tuple(os.path.abspath(f) for f in files),
            fmt=fmt,
            schema_fields=fields,
        )
        return DataFrame(self._session, Scan(rel))

    def parquet(self, *paths: str) -> DataFrame:
        return self._scan("parquet", paths)

    def csv(self, *paths: str) -> DataFrame:
        return self._scan("csv", paths)

    def json(self, *paths: str) -> DataFrame:
        return self._scan("json", paths)

    def orc(self, *paths: str) -> DataFrame:
        return self._scan("orc", paths)

    def avro(self, *paths: str) -> DataFrame:
        return self._scan("avro", paths)

    def text(self, *paths: str) -> DataFrame:
        return self._scan("text", paths)

    def delta(self, path: str, version_as_of: Optional[int] = None) -> DataFrame:
        """Read a Delta Lake table (optionally pinned to a version — the
        reference records ``versionAsOf`` for time travel,
        DeltaLakeRelation.scala:96-99)."""
        from hyperspace_tpu.sources import delta_log

        snap = delta_log.read_snapshot(path, version_as_of)
        options = [("deltaVersion", str(snap.version))]
        if version_as_of is not None:
            options.append(("versionAsOf", str(version_as_of)))
        from hyperspace_tpu.io.columnar import flatten_schema_fields

        rel = Relation(
            root_paths=(os.path.abspath(path),),
            files=tuple(snap.file_paths),
            fmt="delta",
            schema_fields=flatten_schema_fields(snap.schema_fields),
            options=tuple(options),
        )
        return DataFrame(self._session, Scan(rel))

    def iceberg(self, path: str, snapshot_id: Optional[int] = None) -> DataFrame:
        """Read an Iceberg table (optionally pinned to a snapshot — the
        reference pins scans to snapshot ids, IcebergRelation.scala:222-223)."""
        from hyperspace_tpu.sources import iceberg_meta

        snap = iceberg_meta.read_snapshot(path, snapshot_id)
        options = [("snapshotId", str(snap.snapshot_id))]
        if snapshot_id is not None:
            options.append(("snapshotAsOf", str(snapshot_id)))
        from hyperspace_tpu.io.columnar import flatten_schema_fields

        rel = Relation(
            root_paths=(os.path.abspath(path),),
            files=tuple(snap.file_paths),
            fmt="iceberg",
            schema_fields=flatten_schema_fields(snap.schema_fields),
            options=tuple(options),
        )
        return DataFrame(self._session, Scan(rel))


class HyperspaceSession:
    def __init__(self, devices: Optional[Sequence] = None):
        self.conf = Config()
        self.runtime = MeshRuntime(devices)
        self.event_logging = EventLogging(self.conf)
        self._hyperspace_enabled = False
        self._source_manager = None
        self._index_manager = None
        self._serve_cache = None
        self._serve_cache_lock = threading.Lock()
        self._serve_frontend = None
        self._serve_frontend_lock = threading.Lock()
        self._catalog: dict = {}
        # Pre-warm the native host kernels off-thread: the one-time g++
        # compile (~2s, cached per machine) then lands during session
        # setup instead of inside the first large sort or join; hot paths
        # use load(wait=False) and fall back to numpy until it finishes.
        # The same thread then warms the dispatch-calibration probe
        # (native/calibrate.py) — a once-per-machine microbenchmark whose
        # JSON cache lives next to the .so, so later sessions only read
        # a file. Until it lands, dispatch uses the fallback constants.
        from hyperspace_tpu import native

        def _warm():
            native.load()
            from hyperspace_tpu.native import calibrate

            calibrate.thresholds()

        threading.Thread(target=_warm, daemon=True).start()

    # -- context (HyperspaceContext, Hyperspace.scala:195-223) --------------
    @property
    def source_manager(self):
        if self._source_manager is None:
            from hyperspace_tpu.sources.manager import SourceProviderManager

            self._source_manager = SourceProviderManager(self)
        return self._source_manager

    @property
    def index_manager(self):
        if self._index_manager is None:
            from hyperspace_tpu.manager import CachingIndexCollectionManager

            self._index_manager = CachingIndexCollectionManager(self)
        return self._index_manager

    @property
    def serve_cache(self):
        """The serve-server data cache (``execution/serve_cache.py``) when
        ``hyperspace.serve.cache.enabled`` is on, else None. Stale entries
        are impossible (keys fingerprint the immutable index file set);
        ``clear_serve_cache()`` just frees the memory."""
        if not self.conf.serve_cache_enabled:
            return None
        max_bytes = self.conf.serve_cache_max_bytes
        spill_max_bytes = self.conf.serve_spill_max_bytes
        with self._serve_cache_lock:
            if (
                self._serve_cache is None
                or self._serve_cache.max_bytes != max_bytes
                or self._serve_cache.spill_max_bytes != spill_max_bytes
            ):
                from hyperspace_tpu.execution.serve_cache import (
                    ServeCache,
                    spill_root,
                )

                self._serve_cache = ServeCache(
                    max_bytes,
                    spill_dir=(
                        spill_root(self.conf) if spill_max_bytes > 0 else None
                    ),
                    spill_max_bytes=spill_max_bytes,
                )
            return self._serve_cache

    def clear_serve_cache(self) -> None:
        if self._serve_cache is not None:
            self._serve_cache.clear()

    @property
    def serve_frontend(self):
        """The session's long-lived concurrent serve frontend
        (``serve/frontend.py``): admission control, snapshot-consistent
        pinning, retry/degrade. With ``hyperspace.fleet.enabled`` it is
        a :class:`~hyperspace_tpu.serve.fleet.FleetFrontend` — the same
        surface plus durable cross-process pins, fanout-bus
        subscription and cross-process single-flight
        (docs/fleet-serve.md). Created lazily; pool size, SLO classes
        and the fleet flag are read at first touch (construct a
        frontend directly for a differently-configured or short-lived
        one). A closed — or mode-mismatched, after a fleet-flag flip —
        frontend is discarded and replaced on the next touch;
        ``close()`` must not brick serving on the session forever."""
        with self._serve_frontend_lock:
            from hyperspace_tpu.serve import ServeFrontend

            fe = self._serve_frontend
            if self.conf.fleet_enabled:
                from hyperspace_tpu.serve.fleet import FleetFrontend

                if fe is None or fe.closed or not isinstance(fe, FleetFrontend):
                    if fe is not None and not fe.closed:
                        fe.close(wait=False)
                    self._serve_frontend = FleetFrontend(self)
            elif fe is None or fe.closed or type(fe) is not ServeFrontend:
                if fe is not None and not fe.closed:
                    fe.close(wait=False)
                self._serve_frontend = ServeFrontend(self)
            return self._serve_frontend

    # -- reading ------------------------------------------------------------
    @property
    def read(self) -> DataFrameReader:
        return DataFrameReader(self)

    # -- SQL surface (HyperspaceSparkSessionExtension.scala:44-69 analogue:
    # SQL flows through the same optimizer, so index rewrites apply) ------
    def register_view(self, name: str, df: DataFrame) -> None:
        self._catalog[name.lower()] = df

    def sql(self, query: str) -> DataFrame:
        from hyperspace_tpu.sql import parse_sql

        return parse_sql(self, query, self._catalog)

    # -- hyperspace enable/disable (package.scala:40-80) --------------------
    def enable_hyperspace(self) -> "HyperspaceSession":
        self._hyperspace_enabled = True
        return self

    def disable_hyperspace(self) -> "HyperspaceSession":
        self._hyperspace_enabled = False
        return self

    def is_hyperspace_enabled(self) -> bool:
        return self._hyperspace_enabled

    # -- planning & execution ----------------------------------------------
    def optimize(self, plan):
        """Apply the Hyperspace rewrite when enabled (the injected-rule
        equivalent of ``ApplyHyperspace``, rules/ApplyHyperspace.scala:45-66)."""
        if self._hyperspace_enabled and self.conf.apply_enabled:
            from hyperspace_tpu.rules.apply import apply_hyperspace

            return apply_hyperspace(self, plan)
        return plan

    def execute(self, plan) -> pa.Table:
        from hyperspace_tpu.execution import execute

        trace_dir = self.conf.profile_trace_dir
        if trace_dir:
            # XLA profiler integration (SURVEY §5): device kernels, host
            # callbacks and transfers land in a TensorBoard/Perfetto trace
            import jax

            with jax.profiler.trace(trace_dir):
                return execute(self.optimize(plan), self)
        return execute(self.optimize(plan), self)
