"""Workload profile — the query-log aggregation the advisor mines.

The fleet-safe reader side of the PR 15 query log turned into the
structure ROADMAP item 4 asks for: union every process's JSONL
segments (``querylog.read_valid_records`` — torn tails skipped,
unsealed active files of crashed writers picked up, unknown
``schema_v`` records counted and dropped) and fold them into per-shape
groups keyed by the literal-scrubbed predicate shape. Each group
carries frequency x cost x stage breakdown x indexes-chosen x
degrade/retry events — everything the what-if scorer
(``advisor/whatif.py``) and the CLI report need, with no user data
(shapes are scrubbed; the opt-in ``replay`` spec is carried through
verbatim for shapes that recorded one).

Residency contract (ALLOC_SITES const-bounded): the profile holds at
most ``hyperspace.advisor.profile.maxShapes`` groups — records for
further shapes fold into ``overflow_records`` (and a counter) instead
of growing the dict — and per-group duration samples are capped at
``_DURATION_SAMPLES``. The profile is O(maxShapes), never O(records).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from hyperspace_tpu.obs import metrics as _metrics
from hyperspace_tpu.obs import querylog as _querylog
from hyperspace_tpu.obs import trace as obs_trace

#: per-shape duration-sample cap (p50 estimates; oldest kept — the
#: profile answers "what is this shape like", not "what changed")
_DURATION_SAMPLES = 512

#: advisor plane health (OBS_SITES: hyperspace_tpu.advisor.profile)
profiles_total = _metrics.registry.counter(
    "hs_advisor_profiles_total", "workload profiles built"
)
profile_overflow_total = _metrics.registry.counter(
    "hs_advisor_profile_overflow_total",
    "query-log records folded into the overflow bucket (shape cap)",
)


@dataclasses.dataclass
class ShapeStats:
    """One predicate-shape group of the workload profile."""

    shape: str
    count: int = 0
    failed: int = 0
    total_s: float = 0.0
    durations: List[float] = dataclasses.field(default_factory=list)
    stages: Dict[str, float] = dataclasses.field(default_factory=dict)
    indexes: Dict[str, int] = dataclasses.field(default_factory=dict)
    rules: Dict[str, int] = dataclasses.field(default_factory=dict)
    slo_classes: Dict[str, int] = dataclasses.field(default_factory=dict)
    degrades: int = 0
    retries: int = 0
    rows_returned: int = 0
    rows_pruned: int = 0
    last_ts_ms: int = 0
    #: first recorded re-executable plan spec (obs/planspec.py), when
    #: the workload was recorded with querylog.recordPlans on
    replay: Optional[Dict] = None

    def add(self, rec: Dict) -> None:
        self.count += 1
        dur = float(rec.get("duration_s", 0.0))
        self.total_s += dur
        if len(self.durations) < _DURATION_SAMPLES:
            self.durations.append(dur)
        if rec.get("status") != "ok":
            self.failed += 1
        for stage, v in (rec.get("stages") or {}).items():
            if isinstance(v, (int, float)):
                self.stages[stage] = self.stages.get(stage, 0.0) + float(v)
        for name in rec.get("indexes") or []:
            self.indexes[name] = self.indexes.get(name, 0) + 1
        rule = rec.get("rule")
        if rule:
            self.rules[rule] = self.rules.get(rule, 0) + 1
        slo = rec.get("slo_class")
        if slo:
            self.slo_classes[slo] = self.slo_classes.get(slo, 0) + 1
        for ev in rec.get("events") or []:
            name = ev.get("name") if isinstance(ev, dict) else None
            if name == "degrade":
                self.degrades += 1
            elif name == "retry":
                self.retries += 1
        self.rows_returned += int(rec.get("rows_returned", 0) or 0)
        self.rows_pruned += int(rec.get("rows_pruned", 0) or 0)
        self.last_ts_ms = max(self.last_ts_ms, int(rec.get("ts_ms", 0) or 0))
        if self.replay is None and isinstance(rec.get("replay"), dict):
            self.replay = rec["replay"]

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0

    @property
    def p50_s(self) -> float:
        if not self.durations:
            return 0.0
        s = sorted(self.durations)
        return s[len(s) // 2]

    def to_dict(self) -> Dict:
        return {
            "shape": self.shape,
            "count": self.count,
            "failed": self.failed,
            "total_s": round(self.total_s, 6),
            "mean_s": round(self.mean_s, 6),
            "p50_s": round(self.p50_s, 6),
            "stages": {k: round(v, 6) for k, v in sorted(self.stages.items())},
            "indexes": dict(sorted(self.indexes.items())),
            "rules": dict(sorted(self.rules.items())),
            "slo_classes": dict(sorted(self.slo_classes.items())),
            "degrades": self.degrades,
            "retries": self.retries,
            "rows_returned": self.rows_returned,
            "rows_pruned": self.rows_pruned,
            "last_ts_ms": self.last_ts_ms,
            "has_replay": self.replay is not None,
        }


@dataclasses.dataclass
class WorkloadProfile:
    """Bounded aggregate of one query-log directory's records."""

    records: int = 0
    failed: int = 0
    total_s: float = 0.0
    shapes: Dict[str, ShapeStats] = dataclasses.field(default_factory=dict)
    #: records whose shape arrived after the maxShapes cap filled
    overflow_records: int = 0
    max_shapes: int = 256

    def add(self, rec: Dict) -> None:
        self.records += 1
        if rec.get("status") != "ok":
            self.failed += 1
        self.total_s += float(rec.get("duration_s", 0.0))
        shape = str(rec.get("predicate", "") or "")
        group = self.shapes.get(shape)
        if group is None:
            if len(self.shapes) >= self.max_shapes:
                self.overflow_records += 1
                profile_overflow_total.inc()
                return
            group = self.shapes[shape] = ShapeStats(shape=shape)
        group.add(rec)

    def hot_shapes(self, n: Optional[int] = None) -> List[ShapeStats]:
        """Shape groups by aggregate cost (count x duration), hottest
        first — the candidate-enumeration order."""
        out = sorted(
            self.shapes.values(),
            key=lambda s: (-s.total_s, -s.count, s.shape),
        )
        return out if n is None else out[:n]

    def to_dict(self, top: Optional[int] = None) -> Dict:
        return {
            "records": self.records,
            "failed": self.failed,
            "total_s": round(self.total_s, 6),
            "shapes": len(self.shapes),
            "overflow_records": self.overflow_records,
            "hot_shapes": [s.to_dict() for s in self.hot_shapes(top)],
        }


def build_profile(records, max_shapes: int = 256) -> WorkloadProfile:
    """Fold an iterable of querylog records into a bounded profile
    (``advisor.scan`` stage under the advise() root)."""
    with obs_trace.span("advisor.scan"):
        profile = WorkloadProfile(max_shapes=max(1, int(max_shapes)))
        for rec in records:
            profile.add(rec)
        profiles_total.inc()
        return profile


def profile_directory(directory: str, max_shapes: int = 256) -> WorkloadProfile:
    """Union one obs directory's query-log segments (every process,
    torn tails and unknown schema_v skipped) into a profile."""
    return build_profile(
        _querylog.read_valid_records(directory), max_shapes=max_shapes
    )
