"""Advisor CLI — ``python -m hyperspace_tpu.advisor <subcommand>``.

Subcommands:

``report``      profile a query-log directory (no session needed) and
                print the hot-shape table or JSON.
``recommend``   build a session, mine the log, run the what-if scorer,
                print ranked recommendations; ``--apply`` executes them
                under the byte/time budget (typing ``--apply`` IS the
                opt-in — it forces past ``advisor.apply.enabled``).
``replay``      re-run a recorded workload through the serve frontend
                and print the latency/QPS summary.

Sessions are built fresh per invocation: ``--system-path`` sets
``hyperspace.system.path``; repeated ``--conf key=value`` pairs set
anything else (values parsed as JSON when possible, else kept as
strings — so ``--conf hyperspace.serve.maxWorkers=8`` is an int).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from hyperspace_tpu import constants as C


def _build_session(args):
    from hyperspace_tpu.session import HyperspaceSession

    session = HyperspaceSession()
    if getattr(args, "system_path", None):
        session.conf.set(C.INDEX_SYSTEM_PATH, args.system_path)
    for pair in getattr(args, "conf", None) or []:
        key, sep, raw = pair.partition("=")
        if not sep:
            raise SystemExit(f"--conf expects key=value, got {pair!r}")
        try:
            value = json.loads(raw)
        except ValueError:
            value = raw
        session.conf.set(key, value)
    return session


def _log_dir(args, session=None) -> str:
    if getattr(args, "log_dir", None):
        return args.log_dir
    if session is not None:
        from hyperspace_tpu.obs import querylog as _querylog

        return _querylog.obs_root(session.conf)
    raise SystemExit("--log-dir is required (no session to derive it from)")


def _cmd_report(args) -> int:
    from hyperspace_tpu.advisor import profile as _profile

    prof = _profile.profile_directory(
        _log_dir(args), max_shapes=args.max_shapes
    )
    if args.json:
        print(json.dumps(prof.to_dict(top=args.top), indent=2))
        return 0
    print(
        f"records={prof.records} failed={prof.failed} "
        f"shapes={len(prof.shapes)} total_s={prof.total_s:.3f} "
        f"overflow={prof.overflow_records}"
    )
    for s in prof.hot_shapes(args.top):
        print(
            f"  {s.count:6d}x  total={s.total_s:8.3f}s  p50={s.p50_s:.4f}s "
            f"fail={s.failed} degrade={s.degrades} retry={s.retries} "
            f"replay={'y' if s.replay else 'n'}  {s.shape[:100]}"
        )
    return 0


def _cmd_recommend(args) -> int:
    from hyperspace_tpu.advisor import recommend as _recommend

    session = _build_session(args)
    report = _recommend.advise(
        session,
        directory=_log_dir(args, session),
        max_candidates=args.max_candidates,
    )
    if args.json and not args.apply:
        print(json.dumps(report.to_dict(top=args.top), indent=2))
        return 0
    recs = report.recommendations
    print(
        f"scored {report.candidates_scored} candidates "
        f"({report.candidates_skipped} skipped) over "
        f"{report.shapes_with_plans} replayable shapes -> "
        f"{len(recs)} recommendations"
    )
    for r in recs[: args.top]:
        cols = ",".join(r.indexed_columns)
        print(
            f"  [{r.kind:8s}] {r.index_name:16s} {r.index_kind:20s} "
            f"on ({cols})  benefit~{r.estimated_benefit_s:.3f}s "
            f"build~{r.estimated_build_bytes >> 20}MiB  {r.reason}"
        )
    if args.apply and recs:
        from hyperspace_tpu.advisor import apply as _apply

        summary = _apply.apply_recommendations(
            session,
            recs,
            max_bytes=args.max_bytes,
            max_seconds=args.max_seconds,
            force=True,
        )
        if args.json:
            print(json.dumps(summary, indent=2))
        else:
            print(
                f"applied={summary['applied']} failed={summary['failed']} "
                f"skipped={summary['skipped']} "
                f"spent={summary['spent_bytes'] >> 20}MiB "
                f"elapsed={summary['elapsed_s']:.1f}s"
            )
    return 0


def _cmd_replay(args) -> int:
    from hyperspace_tpu.obs import querylog as _querylog
    from hyperspace_tpu.testing import replay as _replay

    session = _build_session(args)
    records = _querylog.read_valid_records(_log_dir(args, session))
    result = _replay.replay_records(
        session,
        records,
        preserve_timing=args.preserve_timing,
        speedup=args.speedup,
        use_slo_classes=not args.no_slo,
        max_inflight=args.max_inflight,
    )
    print(json.dumps(result.to_dict(), indent=2))
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m hyperspace_tpu.advisor",
        description="Hyperspace workload advisor (docs/advisor.md)",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    def common(p, session: bool) -> None:
        p.add_argument("--log-dir", help="query-log directory (default: "
                       "<system.path>/_hyperspace_obs)")
        p.add_argument("--top", type=int, default=10)
        p.add_argument("--json", action="store_true")
        if session:
            p.add_argument("--system-path", help="hyperspace.system.path")
            p.add_argument("--conf", action="append", metavar="KEY=VALUE",
                           help="extra session config (repeatable)")

    p = sub.add_parser("report", help="profile a query-log directory")
    common(p, session=False)
    p.add_argument("--max-shapes", type=int,
                   default=C.ADVISOR_PROFILE_MAX_SHAPES_DEFAULT)
    p.set_defaults(func=_cmd_report)

    p = sub.add_parser("recommend", help="what-if index recommendations")
    common(p, session=True)
    p.add_argument("--max-candidates", type=int, default=None)
    p.add_argument("--apply", action="store_true",
                   help="execute recommendations under the budget")
    p.add_argument("--max-bytes", type=int, default=None,
                   help="apply byte budget (default: conf)")
    p.add_argument("--max-seconds", type=float, default=None,
                   help="apply time budget (default: conf)")
    p.set_defaults(func=_cmd_recommend)

    p = sub.add_parser("replay", help="replay a recorded workload")
    common(p, session=True)
    p.add_argument("--preserve-timing", action="store_true",
                   help="honor recorded inter-arrival gaps")
    p.add_argument("--speedup", type=float, default=1.0)
    p.add_argument("--max-inflight", type=int, default=1)
    p.add_argument("--no-slo", action="store_true",
                   help="ignore recorded slo_class on submit")
    p.set_defaults(func=_cmd_replay)
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
