"""``python -m hyperspace_tpu.advisor`` entry point."""

import sys

from hyperspace_tpu.advisor.cli import main

sys.exit(main())
