"""Workload advisor — query-log mining, what-if scoring, budgeted apply.

ROADMAP item 4's closed loop (docs/advisor.md):

1. ``profile``   — union the fleet's query-log segments into a bounded
                   per-shape workload profile (frequency x cost x
                   stages x indexes x degrade events).
2. ``recommend`` — enumerate candidate indexes from the hot shapes and
                   score each with a HYPOTHETICAL ``IndexLogEntry``
                   through the real ``ScoreBasedIndexPlanOptimizer``
                   rule chain (``whatif``) — no parallel cost model.
3. ``apply``     — opt-in, budget-bounded execution of the ranked
                   recommendations through the ``Hyperspace`` facade
                   (lease-stamped lifecycle actions, like any operator).

Replay (``testing/replay.py``) closes the loop empirically: re-run the
recorded workload before/after apply and compare latencies. CLI:
``python -m hyperspace_tpu.advisor report|recommend|apply|replay``.
"""

from hyperspace_tpu.advisor.apply import apply_recommendations
from hyperspace_tpu.advisor.profile import (
    ShapeStats,
    WorkloadProfile,
    build_profile,
    profile_directory,
)
from hyperspace_tpu.advisor.recommend import (
    AdvisorReport,
    Recommendation,
    advise,
)
from hyperspace_tpu.advisor.whatif import (
    hypothetical_entry,
    score_plan,
    score_workload,
)

__all__ = [
    "AdvisorReport",
    "Recommendation",
    "ShapeStats",
    "WorkloadProfile",
    "advise",
    "apply_recommendations",
    "build_profile",
    "hypothetical_entry",
    "profile_directory",
    "score_plan",
    "score_workload",
]
