"""What-if scoring — hypothetical indexes through the REAL rule chain.

The contract (docs/advisor.md): a candidate index is evaluated by
building a hypothetical :class:`IndexLogEntry` — the exact entry
``CreateAction.begin_log_entry`` would stamp (real
``IndexSignatureProvider`` fingerprint over the current source
snapshot, real ``describe_index`` schema, EMPTY content) — injecting
it into ``collect_candidates`` beside the lake's ACTIVE entries, and
re-running ``ScoreBasedIndexPlanOptimizer`` over the recorded plan.
Nothing is ever written: no index data, no metadata log — the entry
lives only in this process.

Because the fingerprint is computed the same way a real create
computes it, the candidate passes the same ``FileSignatureFilter`` a
real index must pass; because the content is empty (size 0), the
rules' min-size ranking prefers the hypothetical exactly when a
fresh real index would win. The score DELTA (with-candidate minus
baseline) is therefore the rule chain's own opinion of the candidate
— never a parallel cost model that could drift from what serve
actually rewrites.

Convergence falls out of the same property: once a recommendation is
applied, the baseline already contains the real index, the
hypothetical twin adds no score, the gain is 0, and the next advise()
pass recommends nothing.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from hyperspace_tpu import constants as C
from hyperspace_tpu.constants import States
from hyperspace_tpu.indexes.context import IndexerContext
from hyperspace_tpu.metadata.entry import (
    Content,
    FileIdTracker,
    IndexLogEntry,
    Source,
    SourcePlan,
)
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.plan.nodes import LogicalPlan
from hyperspace_tpu.rules.candidate import collect_candidates
from hyperspace_tpu.rules.score import ScoreBasedIndexPlanOptimizer
from hyperspace_tpu.signatures import IndexSignatureProvider


def hypothetical_entry(session, df, index_config) -> IndexLogEntry:
    """The no-execute twin of ``CreateAction.begin_log_entry``: a fully
    formed ACTIVE entry for ``index_config`` over ``df``'s (single)
    source relation, with ``Content.from_leaf_files([])`` — never
    written to the lake."""
    tracker = FileIdTracker()
    leaf = df.logical_plan.collect_leaves()[0]
    source_rel = session.source_manager.get_relation(leaf.relation)
    meta_relation = source_rel.create_metadata_relation(tracker)
    fingerprint = IndexSignatureProvider(session.source_manager).fingerprint(
        df.logical_plan
    )
    props = {
        C.LINEAGE_PROPERTY: str(session.conf.lineage_enabled).lower(),
    }
    if leaf.relation.fmt in ("parquet", "delta", "iceberg"):
        props[C.HAS_PARQUET_AS_SOURCE_FORMAT_PROPERTY] = "true"
    ctx = IndexerContext(session, tracker, index_data_path="")
    index = index_config.describe_index(ctx, df, props)
    return IndexLogEntry(
        name=index_config.index_name,
        derived_dataset=index,
        content=Content.from_leaf_files([]),
        source=Source(SourcePlan([meta_relation], provider="default")),
        fingerprint=fingerprint,
        properties={},
        state=States.ACTIVE,
    )


def score_plan(
    session, plan: LogicalPlan, entries: List[IndexLogEntry]
) -> int:
    """One plan's best score against an entry set — the optimizer's own
    number (0 = no rule applies, the unrewritten plan)."""
    if not entries:
        return 0
    from hyperspace_tpu.plan.nodes import prune_join_columns

    pruned = prune_join_columns(plan)
    candidates = collect_candidates(session, pruned, entries)
    if not candidates:
        return 0
    _best, score = ScoreBasedIndexPlanOptimizer(session).apply_with_score(
        pruned, candidates
    )
    return score


def score_workload(
    session,
    plans: List[Tuple[LogicalPlan, float]],
    active: List[IndexLogEntry],
    candidate: Optional[IndexLogEntry],
) -> Dict[str, float]:
    """Score a weighted workload (plan, weight_seconds) against the
    ACTIVE entries, with ``candidate`` optionally injected. Returns::

        score          Σ weight·score(active + candidate)
        gain           Σ weight·(score - baseline)   (score units)
        benefit_s      Σ weight·(score - baseline)/score — the gain as
                       a fraction of each plan's winning score, in the
                       weight's unit (recorded seconds): the advisor's
                       estimated-benefit heuristic
        plans_improved plans whose score strictly rose

    The ``advisor.score`` stage of the advise() trace."""
    with obs_trace.span("advisor.score"):
        entries = list(active) + ([candidate] if candidate is not None else [])
        total = 0.0
        gain = 0.0
        benefit_s = 0.0
        improved = 0
        for plan, weight in plans:
            s = score_plan(session, plan, entries)
            total += weight * s
            if candidate is not None:
                base = score_plan(session, plan, active)
                if s > base:
                    gain += weight * (s - base)
                    benefit_s += weight * (s - base) / s
                    improved += 1
        return {
            "score": total,
            "gain": gain,
            "benefit_s": benefit_s,
            "plans_improved": improved,
        }
