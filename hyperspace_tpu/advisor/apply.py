"""Budgeted apply — recommendations into lifecycle actions.

Opt-in (``hyperspace.advisor.apply.enabled``): walk a ranked
recommendation list and execute each through the :class:`Hyperspace`
facade — which means every create/refresh/optimize runs as a normal
lifecycle action, lease-stamped and heartbeat-renewed by the PR 10
recovery plane, so a fleet's serve traffic sees advisor maintenance
exactly like operator maintenance (pinned snapshots keep serving; a
dead advisor's lease expires and its half-built index is recoverable).

Two budgets bound a pass (both from config, overridable per call):
``maxBytes`` caps the summed ESTIMATED build bytes of executed
recommendations — a recommendation whose estimate would cross the
remaining budget is skipped (cheaper ones later in the ranking may
still fit); ``maxSeconds`` caps wall time — once spent, the pass stops
outright. Failures are recorded per recommendation and never abort the
pass (one bad candidate must not starve the rest of the budget).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from hyperspace_tpu.advisor.recommend import Recommendation
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig


def _config_for(rec: Recommendation):
    cls = (
        ZOrderCoveringIndexConfig
        if rec.index_kind == "ZOrderCoveringIndex"
        else CoveringIndexConfig
    )
    return cls(
        rec.index_name, list(rec.indexed_columns), list(rec.included_columns)
    )


def apply_recommendations(
    session,
    recommendations: List[Recommendation],
    max_bytes: Optional[int] = None,
    max_seconds: Optional[float] = None,
    force: bool = False,
) -> Dict:
    """Execute ``recommendations`` in order under the byte/time budget.
    Requires ``hyperspace.advisor.apply.enabled`` unless ``force`` (the
    CLI's explicit ``apply`` subcommand sets it — typing the command IS
    the opt-in). Returns a summary dict: per-recommendation outcomes
    plus budget accounting."""
    conf = session.conf
    if not (force or conf.advisor_apply_enabled):
        raise HyperspaceException(
            "advisor apply is disabled; set "
            "hyperspace.advisor.apply.enabled=true to opt in"
        )
    budget_bytes = max_bytes if max_bytes is not None else conf.advisor_apply_max_bytes
    budget_s = (
        max_seconds if max_seconds is not None else conf.advisor_apply_max_seconds
    )
    from hyperspace_tpu.hyperspace import Hyperspace

    hs = Hyperspace(session)
    t0 = time.perf_counter()
    spent_bytes = 0
    outcomes: List[Dict] = []
    for rec in recommendations:
        elapsed = time.perf_counter() - t0
        if elapsed >= budget_s:
            outcomes.append(
                {"index": rec.index_name, "kind": rec.kind, "outcome": "skipped",
                 "why": f"time budget exhausted ({elapsed:.1f}s)"}
            )
            continue
        cost = max(0, int(rec.estimated_build_bytes))
        if spent_bytes + cost > budget_bytes:
            outcomes.append(
                {"index": rec.index_name, "kind": rec.kind, "outcome": "skipped",
                 "why": f"byte budget exhausted ({spent_bytes + cost} > "
                        f"{budget_bytes})"}
            )
            continue
        try:
            if rec.kind == "create":
                reader = getattr(
                    session.read, rec.source_fmt, session.read.parquet
                )
                df = reader(*rec.source_paths)
                hs.create_index(df, _config_for(rec))
            elif rec.kind == "refresh":
                hs.refresh_index(rec.index_name, mode=rec.mode or "incremental")
            elif rec.kind == "optimize":
                hs.optimize_index(rec.index_name, mode=rec.mode or "quick")
            else:
                raise HyperspaceException(
                    f"Unknown recommendation kind {rec.kind!r}"
                )
        except Exception as exc:  # hslint: disable=HS402
            # one bad candidate must not starve the rest of the budget
            outcomes.append(
                {"index": rec.index_name, "kind": rec.kind,
                 "outcome": "failed", "why": str(exc)[:200]}
            )
            continue
        spent_bytes += cost
        outcomes.append(
            {"index": rec.index_name, "kind": rec.kind, "outcome": "applied",
             "estimated_bytes": cost}
        )
    return {
        "applied": sum(1 for o in outcomes if o["outcome"] == "applied"),
        "failed": sum(1 for o in outcomes if o["outcome"] == "failed"),
        "skipped": sum(1 for o in outcomes if o["outcome"] == "skipped"),
        "spent_bytes": spent_bytes,
        "elapsed_s": round(time.perf_counter() - t0, 3),
        "outcomes": outcomes,
    }
