"""Recommendation engine — profile in, ranked actions out.

``advise()`` is the tentpole loop (docs/advisor.md): take a workload
profile (``advisor/profile.py``), enumerate candidate indexes from the
hot shapes' recorded plans, score each through the what-if machinery
(``advisor/whatif.py`` — the real rule chain, hypothetical entry,
nothing written), and emit ranked CREATE / REFRESH / OPTIMIZE
recommendations with an estimated workload benefit and an estimated
build cost. The whole pass runs under one ``advisor.run`` root span
with ``advisor.scan`` / ``advisor.score`` stages, so the advisor's own
cost is visible in the plane it consumes.

Candidate enumeration is plan-shape-driven, mirroring the rules that
would consume each candidate:

* Filter[->Project] over a source scan -> covering index (indexed =
  equality columns then range columns — FilterIndexRule requires the
  FIRST indexed column in the predicate; included = every other
  referenced column), plus a z-order covering index when >= 2 range
  columns filter the same scan (ZOrderFilterIndexRule relaxes the
  leading-column requirement).
* Inner equi-join -> one covering index per side (indexed = exactly
  that side's join keys — JoinIndexRule's eligibility — included =
  the side's other referenced columns).
* Aggregate over a source scan -> covering index (indexed = group-by
  keys, included = aggregated columns; consumed by AggregateIndexRule).

REFRESH is recommended for ACTIVE entries serving with a pending
quick-refresh source delta (``has_source_update`` — every query pays
Hybrid-Scan compensation), OPTIMIZE for entries whose data has >= 2
files under ``hyperspace.index.optimize.fileSizeThreshold``. Both are
no-ops once applied, so a second advise() pass converges to empty.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu import constants as C
from hyperspace_tpu.constants import States
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig
from hyperspace_tpu.obs import planspec as obs_planspec
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.plan import expressions as E
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    Filter,
    Join,
    LogicalPlan,
    Project,
    Scan,
)
from hyperspace_tpu.advisor import whatif
from hyperspace_tpu.advisor.profile import WorkloadProfile, profile_directory
from hyperspace_tpu.obs import querylog as obs_querylog
from hyperspace_tpu.utils.hashing import md5_hex


@dataclasses.dataclass
class Recommendation:
    """One ranked advisor action."""

    kind: str  # "create" | "refresh" | "optimize"
    index_name: str
    index_kind: str  # "CoveringIndex" | "ZOrderCoveringIndex" | existing kind
    indexed_columns: List[str]
    included_columns: List[str]
    source_paths: List[str]
    estimated_benefit_s: float
    estimated_build_bytes: int
    score_gain: float
    shapes: List[str]  # predicate shapes this recommendation serves
    reason: str
    mode: Optional[str] = None  # refresh/optimize mode
    source_fmt: str = "parquet"

    def to_dict(self) -> Dict:
        return {
            "kind": self.kind,
            "index_name": self.index_name,
            "index_kind": self.index_kind,
            "indexed_columns": list(self.indexed_columns),
            "included_columns": list(self.included_columns),
            "source_paths": list(self.source_paths),
            "estimated_benefit_s": round(self.estimated_benefit_s, 6),
            "estimated_build_bytes": int(self.estimated_build_bytes),
            "score_gain": round(self.score_gain, 3),
            "shapes": list(self.shapes),
            "reason": self.reason,
            "mode": self.mode,
            "source_fmt": self.source_fmt,
        }


@dataclasses.dataclass
class AdvisorReport:
    profile: WorkloadProfile
    recommendations: List[Recommendation]
    candidates_scored: int
    candidates_skipped: int
    shapes_with_plans: int

    def to_dict(self, top: Optional[int] = None) -> Dict:
        return {
            "profile": self.profile.to_dict(top),
            "recommendations": [r.to_dict() for r in self.recommendations],
            "candidates_scored": self.candidates_scored,
            "candidates_skipped": self.candidates_skipped,
            "shapes_with_plans": self.shapes_with_plans,
        }


# ---------------------------------------------------------------------------
# Candidate enumeration
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Candidate:
    config: object  # IndexConfigTrait
    kind: str
    source_paths: Tuple[str, ...]
    fmt: str
    shapes: List[str] = dataclasses.field(default_factory=list)


def _split_filter_cols(cond: E.Expr) -> Tuple[List[str], List[str]]:
    """(equality columns, range columns) of a conjunctive predicate, in
    first-appearance order."""
    eq_cols: List[str] = []
    range_cols: List[str] = []
    for conj in E.split_conjuncts(cond):
        cols = sorted(E.references(conj))
        if isinstance(conj, (E.Eq, E.In, E.IsNull)):
            target = eq_cols
        elif isinstance(conj, (E.Lt, E.Le, E.Gt, E.Ge)):
            target = range_cols
        else:
            target = range_cols  # Or/Not/mixed: usable but not leading
        for c in cols:
            if c not in eq_cols and c not in range_cols:
                target.append(c)
    return eq_cols, range_cols


def _source_scan(node: LogicalPlan) -> Optional[Scan]:
    """The node itself when it is a non-index source Scan."""
    if isinstance(node, Scan) and node.relation.index_info is None:
        return node
    return None


def _linear_scan(node: LogicalPlan) -> Optional[Tuple[Scan, set]]:
    """Walk Project/Filter chains to a source scan, collecting every
    referenced column on the way (JoinIndexRule's 'linear' children)."""
    refs: set = set()
    while True:
        scan = _source_scan(node)
        if scan is not None:
            return scan, refs
        if isinstance(node, Project):
            refs |= set(node.columns)
            node = node.child
        elif isinstance(node, Filter):
            refs |= set(E.references(node.condition))
            node = node.child
        else:
            return None


def _candidate_name(kind: str, paths, indexed, included) -> str:
    sig = md5_hex(
        "|".join([kind, ",".join(paths), ",".join(indexed), ",".join(included)])
    )[:10]
    return f"adv_{sig}"


def _mk(kind: str, scan: Scan, indexed, included) -> Optional[_Candidate]:
    indexed = [c for c in indexed if c in scan.output]
    included = sorted(
        c for c in included if c in scan.output and c not in indexed
    )
    if not indexed:
        return None
    paths = tuple(scan.relation.root_paths)
    name = _candidate_name(kind, paths, indexed, included)
    cls = (
        ZOrderCoveringIndexConfig
        if kind == "ZOrderCoveringIndex"
        else CoveringIndexConfig
    )
    return _Candidate(
        config=cls(name, list(indexed), list(included)),
        kind=kind,
        source_paths=paths,
        fmt=scan.relation.fmt,
    )


def enumerate_candidates(plan: LogicalPlan) -> List[_Candidate]:
    """Candidate index configs one recorded plan motivates (see module
    docstring for the shape -> candidate mapping)."""
    out: List[_Candidate] = []
    stack: List[LogicalPlan] = [plan]
    while stack:
        node = stack.pop()
        stack.extend(node.children)
        if isinstance(node, Filter):
            scan = _source_scan(node.child)
            if scan is None:
                continue
            eq_cols, range_cols = _split_filter_cols(node.condition)
            indexed = eq_cols + range_cols
            covered = set(E.references(node.condition)) | set(plan.output)
            cand = _mk("CoveringIndex", scan, indexed, covered)
            if cand is not None:
                out.append(cand)
            if len(range_cols) >= 2:
                z = _mk("ZOrderCoveringIndex", scan, range_cols, covered)
                if z is not None:
                    out.append(z)
        elif isinstance(node, Join):
            pairs = E.equi_join_pairs(node.condition)
            if not pairs:
                continue
            for side, keys in (
                (node.left, [l for l, _ in pairs]),
                (node.right, [r for _, r in pairs]),
            ):
                got = _linear_scan(side)
                if got is None:
                    continue
                scan, refs = got
                side_keys = [k for k in keys if k in scan.output]
                if not side_keys:
                    continue
                covered = (refs | set(side.output)) & set(scan.output)
                # JoinIndexRule eligibility: indexed columns must equal
                # the join keys exactly — nothing more, nothing less
                cand = _mk("CoveringIndex", scan, side_keys, covered)
                if cand is not None:
                    out.append(cand)
        elif isinstance(node, Aggregate):
            inner = node.child
            refs: set = set()
            while isinstance(inner, Project):
                refs |= set(inner.columns)
                inner = inner.child
            scan = _source_scan(inner)
            if scan is None or not node.group_by:
                continue
            covered = set(node.input_columns) | refs
            cand = _mk("CoveringIndex", scan, list(node.group_by), covered)
            if cand is not None:
                out.append(cand)
    return out


# ---------------------------------------------------------------------------
# Scoring + ranking
# ---------------------------------------------------------------------------


def _source_bytes(session, scan: Scan) -> int:
    try:
        rel = session.source_manager.get_relation(scan.relation)
        return sum(size for _, size, _ in rel.all_file_infos())
    except Exception:  # hslint: disable=HS402
        # estimation helper: a missing source means cost 0, not a crash
        return 0


def _build_cost_bytes(session, scan: Scan, config) -> int:
    """Source bytes x referenced-column fraction — the advisor's build
    cost estimate (a covering index rewrites the referenced projection
    of the source, bucketed)."""
    total = _source_bytes(session, scan)
    ncols = max(1, len(scan.output))
    frac = min(1.0, len(config.referenced_columns) / ncols)
    return int(total * frac)


def advise(
    session,
    directory: Optional[str] = None,
    profile: Optional[WorkloadProfile] = None,
    max_candidates: Optional[int] = None,
) -> AdvisorReport:
    """The full advisor pass: profile (built from ``directory`` unless
    given), candidate enumeration, what-if scoring, ranked output —
    nothing executed, nothing written (that is ``advisor.apply``'s
    job)."""
    root = obs_trace.root("advisor.run")
    with obs_trace.activate(root):
        try:
            return _advise_under_root(
                session, directory, profile, max_candidates, root
            )
        finally:
            root.finish()


def _advise_under_root(
    session, directory, profile, max_candidates, root
) -> AdvisorReport:
    conf = session.conf
    if profile is None:
        if directory is None:
            directory = obs_querylog.obs_root(conf)
        profile = profile_directory(
            directory, max_shapes=conf.advisor_profile_max_shapes
        )
    cap = max_candidates or conf.advisor_max_candidates

    # rebuild the hot shapes' recorded plans (hottest first — the
    # candidate budget spends itself on the expensive shapes). Weight =
    # recorded seconds; a log with no durations at all (generated
    # scenarios record 0) falls back to frequency, else every gain
    # would multiply to zero
    use_counts = profile.total_s <= 0
    plans: List[Tuple[str, LogicalPlan, float]] = []
    for shape in profile.hot_shapes():
        if shape.replay is None:
            continue
        try:
            plan = obs_planspec.from_spec(session, shape.replay)
        except Exception:  # hslint: disable=HS402
            # a shape whose source moved away must not kill the pass
            continue
        weight = float(shape.count) if use_counts else shape.total_s
        plans.append((shape.shape, plan, weight))

    # enumerate + dedup candidates, attributing shapes to each
    candidates: Dict[str, _Candidate] = {}
    truncated = 0
    for shape_key, plan, _w in plans:
        for cand in enumerate_candidates(plan):
            known = candidates.get(cand.config.index_name)
            if known is None:
                if len(candidates) >= cap:
                    truncated += 1
                    continue
                known = candidates[cand.config.index_name] = cand
            if shape_key not in known.shapes:
                known.shapes.append(shape_key)

    active = session.index_manager.get_indexes([States.ACTIVE])
    existing = {e.name for e in active}

    recs: List[Recommendation] = []
    scored = 0
    skipped = 0
    for cand in candidates.values():
        if cand.config.index_name in existing:
            # an applied recommendation's twin scores gain 0 anyway;
            # skip the what-if pass outright (fast convergence)
            continue
        reader = getattr(session.read, cand.fmt, session.read.parquet)
        try:
            df = reader(*cand.source_paths)
            hypo = whatif.hypothetical_entry(session, df, cand.config)
        except Exception:  # hslint: disable=HS402
            # unindexable source / unresolvable columns: skip candidate
            skipped += 1
            continue
        workload = [
            (plan, weight)
            for shape_key, plan, weight in plans
            if shape_key in cand.shapes
        ]
        result = whatif.score_workload(session, workload, active, hypo)
        scored += 1
        if result["gain"] <= 0:
            continue
        leaf = df.logical_plan.collect_leaves()[0]
        benefit = result["benefit_s"]
        recs.append(
            Recommendation(
                kind="create",
                index_name=cand.config.index_name,
                index_kind=cand.kind,
                indexed_columns=cand.config.indexed_columns,
                included_columns=cand.config.included_columns,
                source_paths=list(cand.source_paths),
                estimated_benefit_s=benefit,
                estimated_build_bytes=_build_cost_bytes(
                    session, leaf, cand.config
                ),
                score_gain=result["gain"],
                shapes=list(cand.shapes),
                reason=(
                    f"what-if gain {result['gain']:.0f} over "
                    f"{result['plans_improved']} recorded plan(s)"
                ),
                source_fmt=cand.fmt,
            )
        )

    recs.extend(_maintenance_recommendations(session, active, profile))
    recs.sort(key=lambda r: (-r.estimated_benefit_s, r.index_name))
    root.set("recommendations", len(recs))
    root.set("candidates_scored", scored)
    if truncated:
        root.add_event("candidates_truncated", dropped=truncated)
    return AdvisorReport(
        profile=profile,
        recommendations=recs,
        candidates_scored=scored,
        candidates_skipped=skipped + truncated,
        shapes_with_plans=len(plans),
    )


def _index_workload_s(profile: WorkloadProfile, index_name: str) -> float:
    """Seconds of recorded workload served by ``index_name``."""
    total = 0.0
    for shape in profile.shapes.values():
        if index_name in shape.indexes:
            total += shape.total_s * (
                shape.indexes[index_name] / max(1, shape.count)
            )
    return total


def _maintenance_recommendations(
    session, active, profile: WorkloadProfile
) -> List[Recommendation]:
    recs: List[Recommendation] = []
    threshold = session.conf.optimize_file_size_threshold
    for entry in active:
        served_s = _index_workload_s(profile, entry.name)
        index = entry.derived_dataset
        if entry.has_source_update:
            recs.append(
                Recommendation(
                    kind="refresh",
                    index_name=entry.name,
                    index_kind=index.kind,
                    indexed_columns=list(index.indexed_columns),
                    included_columns=[],
                    source_paths=[],
                    # every serve of this index pays Hybrid-Scan delta
                    # compensation until the data catches up
                    estimated_benefit_s=served_s * 0.5,
                    estimated_build_bytes=entry.source_files_size_in_bytes,
                    score_gain=0.0,
                    shapes=[],
                    reason="pending quick-refresh source delta "
                    "(queries pay compensation)",
                    mode=C.REFRESH_MODE_INCREMENTAL,
                )
            )
            continue
        small = [
            info
            for _, info in entry.content.file_infos
            if 0 <= info.size < threshold
        ]
        if len(small) >= 2:
            recs.append(
                Recommendation(
                    kind="optimize",
                    index_name=entry.name,
                    index_kind=index.kind,
                    indexed_columns=list(index.indexed_columns),
                    included_columns=[],
                    source_paths=[],
                    estimated_benefit_s=served_s * 0.1,
                    estimated_build_bytes=sum(i.size for i in small),
                    score_gain=0.0,
                    shapes=[],
                    reason=f"{len(small)} index files under the optimize "
                    "threshold (per-file open cost on every serve)",
                    mode=C.OPTIMIZE_MODE_QUICK,
                )
            )
    return recs
