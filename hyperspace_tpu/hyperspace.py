"""Hyperspace — the user-facing API facade.

Reference: ``Hyperspace.scala:27-193`` and its Python binding
(``python/hyperspace/hyperspace.py:9-192``). Every method delegates to the
collection manager (actions) or the plan-analysis tooling; index
maintenance runs with the query-rewrite rule disabled so maintenance scans
never get rewritten to use the index being maintained
(``ApplyHyperspace.withHyperspaceRuleDisabled``,
rules/ApplyHyperspace.scala:68-75).
"""

from __future__ import annotations

from typing import List, Optional

import pyarrow as pa

from hyperspace_tpu import constants as C
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException


class Hyperspace:
    def __init__(self, session):
        self.session = session
        self._manager = session.index_manager

    # -- index CRUD (Hyperspace.scala:43-151) -------------------------------
    def create_index(self, df, index_config) -> None:
        with self._maintenance():
            self._manager.create(df, index_config)

    def delete_index(self, index_name: str) -> None:
        with self._maintenance():
            self._manager.delete(index_name)

    def restore_index(self, index_name: str) -> None:
        with self._maintenance():
            self._manager.restore(index_name)

    def vacuum_index(self, index_name: str) -> None:
        with self._maintenance():
            self._manager.vacuum(index_name)

    def refresh_index(self, index_name: str, mode: str = C.REFRESH_MODE_FULL) -> None:
        with self._maintenance():
            self._manager.refresh(index_name, mode)

    def optimize_index(
        self, index_name: str, mode: str = C.OPTIMIZE_MODE_QUICK
    ) -> None:
        with self._maintenance():
            self._manager.optimize(index_name, mode)

    def cancel(self, index_name: str) -> None:
        with self._maintenance():
            self._manager.cancel(index_name)

    def recover(self, index_name: str, gc: bool = True) -> dict:
        """Repair a crashed writer's leavings on one index: roll back a
        stranded transient log entry (lease-expired or torn), heal a
        stale latestStable pointer, and garbage-collect orphan data
        files (quarantine + grace TTL; ``metadata/recovery.py``,
        docs/recovery.md). Idempotent; returns the repair report."""
        with self._maintenance():
            return self._manager.recover(index_name, gc=gc)

    def _maintenance(self):
        from hyperspace_tpu.rules.apply import hyperspace_rule_disabled

        return hyperspace_rule_disabled()

    # -- introspection (Hyperspace.scala:33-41, 153-193) --------------------
    def indexes(self) -> pa.Table:
        """Summary DataFrame of all indexes (IndexStatistics summary columns,
        index/IndexStatistics.scala:58-60)."""
        from hyperspace_tpu.plananalysis.statistics import indexes_summary_table

        return indexes_summary_table(self._manager.get_indexes())

    def index(self, index_name: str) -> pa.Table:
        """Extended statistics for one index (Hyperspace.scala:153-158)."""
        from hyperspace_tpu.plananalysis.statistics import index_stats_table

        entry = self._manager.get_index_log_entry(index_name)
        if entry is None or entry.state == States.DOESNOTEXIST:
            raise HyperspaceException(f"Index not found: {index_name!r}")
        return index_stats_table(entry)

    def explain(self, df, verbose: bool = False, mode: str = None) -> str:
        """Plan diff with vs without Hyperspace (PlanAnalyzer.explainString).
        ``mode``: plaintext (default) / console (ANSI highlight) / html."""
        from hyperspace_tpu.plananalysis.explain import explain_string

        return explain_string(df, self.session, self._manager, verbose, mode)

    def why_not(
        self, df, index_name: Optional[str] = None, extended: bool = False
    ) -> str:
        """Why indexes were not applied to df's plan
        (CandidateIndexAnalyzer.whyNotIndexString:30-43)."""
        from hyperspace_tpu.plananalysis.why_not import why_not_string

        return why_not_string(df, self.session, self._manager, index_name, extended)
