"""Logical-plan signature providers — index validity fingerprints.

Reference: ``index/FileBasedSignatureProvider.scala:30-62`` (md5 over
per-relation file fingerprints), ``index/PlanSignatureProvider.scala``
(operator-kind walk), ``index/IndexSignatureProvider.scala:33-51``
(combines both), ``index/LogicalPlanSignatureProvider.scala`` (factory by
provider name). At query time the candidate filter recomputes the
signature of the query's source and compares it to the one stored in the
log entry (``rules/FileSignatureFilter.scala:70-88``).
"""

from __future__ import annotations

from typing import List, Optional

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.metadata.entry import LogicalPlanFingerprint, Signature
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.utils.hashing import md5_hex


class FileBasedSignatureProvider:
    """Fingerprint of the *data*: fold of every leaf relation's file
    snapshot signature (delegated to its source provider)."""

    name = "FileBasedSignatureProvider"

    def __init__(self, source_manager):
        self._sources = source_manager

    def sign(self, plan: LogicalPlan) -> Optional[str]:
        parts = []
        for leaf in plan.collect_leaves():
            rel = self._sources.get_relation(leaf.relation)
            parts.append(rel.signature())
        if not parts:
            return None
        return md5_hex("".join(parts))


class PlanSignatureProvider:
    """Fingerprint of the *plan shape*: fold over operator kinds
    (PlanSignatureProvider.scala)."""

    name = "PlanSignatureProvider"

    def sign(self, plan: LogicalPlan) -> str:
        kinds: List[str] = []

        def walk(p: LogicalPlan):
            kinds.append(type(p).__name__)
            for c in p.children:
                walk(c)

        walk(plan)
        return md5_hex("".join(kinds))


class IndexSignatureProvider:
    """File-based + plan signatures combined
    (IndexSignatureProvider.scala:33-51)."""

    name = "IndexSignatureProvider"

    def __init__(self, source_manager):
        self._file = FileBasedSignatureProvider(source_manager)
        self._plan = PlanSignatureProvider()

    def fingerprint(self, plan: LogicalPlan) -> LogicalPlanFingerprint:
        file_sig = self._file.sign(plan)
        if file_sig is None:
            raise HyperspaceException("Plan has no file-based relations to sign")
        return LogicalPlanFingerprint(
            [
                Signature(self._file.name, file_sig),
                Signature(self._plan.name, self._plan.sign(plan)),
            ]
        )

    def fingerprint_source_only(self, scan: Scan) -> Signature:
        """Signature of one relation's data snapshot (what the candidate
        filter compares; FileSignatureFilter.scala:70-88)."""
        sig = self._file.sign(scan)
        return Signature(self._file.name, sig)
