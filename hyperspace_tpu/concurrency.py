"""SHARED_STATE — the registry of cross-thread mutable state.

The KERNEL_TWINS doctrine applied to concurrency: every module-level
(and registered class-level) mutable object that a thread-pool-submitted
callable can reach is declared HERE, together with the lock that guards
it and the guarding *policy* — so "is this shared state guarded?" is a
mechanical question (``hslint`` HS6xx, ``analysis/shared_state.py``),
not an archaeology project. The runtime lock witness
(``testing/lock_witness.py``) wraps the locks named here during the
stress suites and cross-checks what actually happened against this
model (``hslint --witness``).

Entry shape::

    "<dotted path of the state object>": (
        "<dotted module lock | self.<attr> | ''>",
        "<policy>",
        "<one-line justification — why this policy is sound>",
    )

State paths name a module-level global
(``hyperspace_tpu.io.scan._scan_pool``) or a class instance attribute
(``hyperspace_tpu.execution.serve_cache.ServeCache._entries``; guarded
by an instance lock spelled ``self.<attr>``). Policies:

``guarded``
    Every access (read or write) holds the declared lock. The strictest
    contract; HS602 flags any access outside it.
``guarded-writes``
    Writes hold the lock; unguarded reads are a documented benign race
    (double-checked publication fast paths, monotonic flags, telemetry
    probes). HS602 flags unguarded writes only.
``rebind-only``
    No lock: the object is never mutated in place — writers build a new
    object and publish it with one atomic name rebind, readers grab the
    reference once. HS602 flags any in-place mutation (``.update()``,
    ``x[k] = v``, ``+=``); plain rebinds and reads pass.
``frozen``
    Populated at import time (decorator registration), read-only once
    threads exist. HS602 flags writes from any thread-pool-reachable
    function.

Class-level state is registered opt-in (HS602 then audits every method
of the class, ``__init__`` excluded — construction happens-before
sharing); module-level globals are the default blast radius and HS601
flags any unregistered one a pool-submitted callable can reach.

Keep this module stdlib-only and import-cheap: the lock witness imports
it inside test processes before any session exists.
"""

from __future__ import annotations

from typing import Dict, Tuple

SHARED_STATE: Dict[str, Tuple[str, str, str]] = {
    # -- thread pools and loaders (publish-once, read forever) ---------------
    "hyperspace_tpu.io.scan._scan_pool": (
        "hyperspace_tpu.io.scan._scan_pool_lock",
        "guarded-writes",
        "double-checked create under the lock; the published executor is "
        "a stable reference, post-publish reads need no lock",
    ),
    "hyperspace_tpu.native._lib": (
        "hyperspace_tpu.native._lock",
        "guarded-writes",
        "one-time CDLL load serialized by the compile lock; the unguarded "
        "fast-path read sees None or the published library, never a torn "
        "value",
    ),
    "hyperspace_tpu.native._load_failed": (
        "hyperspace_tpu.native._lock",
        "guarded-writes",
        "monotonic False->True flag set under the compile lock; a stale "
        "False read only costs one extra trip through load()",
    ),
    "hyperspace_tpu.native.calibrate._cached": (
        "hyperspace_tpu.native.calibrate._probe_lock",
        "guarded-writes",
        "probe result published under the probe lock (invalidate() takes "
        "it too); the lock-free fast path reads None or a complete "
        "Thresholds tuple",
    ),
    "hyperspace_tpu.native.calibrate._probing": (
        "hyperspace_tpu.native.calibrate._probe_lock",
        "guarded-writes",
        "re-entrancy guard for the probe's own dispatches; written only "
        "under the probe lock, racy reads just take the defaults branch",
    ),
    # -- serve-plane caches --------------------------------------------------
    "hyperspace_tpu.indexes.zonemaps._local_cache": (
        "hyperspace_tpu.indexes.zonemaps._local_lock",
        "guarded",
        "bounded LRU shared by every serve thread when serve-cache mode "
        "is off; get/put/evict/clear all run under the one lock",
    ),
    "hyperspace_tpu.indexes.zonemaps._local_bytes": (
        "hyperspace_tpu.indexes.zonemaps._local_lock",
        "guarded",
        "byte ledger of the zonemap module LRU (residency bound, "
        "ALLOC_SITES doctrine); every read-modify-write runs under the "
        "same lock as the cache it accounts for",
    ),
    "hyperspace_tpu.indexes.aggindex._local_cache": (
        "hyperspace_tpu.indexes.aggindex._local_lock",
        "guarded",
        "bounded LRU of assembled aggregate-plane state shared by every "
        "serve thread when serve-cache mode is off; get/put/evict/clear "
        "all run under the one lock",
    ),
    "hyperspace_tpu.indexes.aggindex._local_bytes": (
        "hyperspace_tpu.indexes.aggindex._local_lock",
        "guarded",
        "byte ledger of the aggregate-plane module LRU (residency "
        "bound, ALLOC_SITES doctrine); every read-modify-write runs "
        "under the same lock as the cache it accounts for",
    ),
    "hyperspace_tpu.execution.serve_cache.ServeCache._entries": (
        "self._lock",
        "guarded",
        "the memory governor's entry map: every public method takes the "
        "lock for its whole critical section (docs in serve_cache.py)",
    ),
    "hyperspace_tpu.execution.serve_cache.ServeCache._bytes": (
        "self._lock",
        "guarded-writes",
        "byte ledger mutated only under the cache lock; resident_bytes "
        "is a documented unsynchronized telemetry probe",
    ),
    "hyperspace_tpu.execution.serve_cache.ServeCache._spill": (
        "self._lock",
        "guarded",
        "the spill-tier index (key -> (path, nbytes)): get/put/demote/"
        "evict/clear mutate it only inside the cache lock; file I/O "
        "(encode, fsync'd publish, restore) runs outside with the key "
        "already removed, so a racing get just misses and re-derives",
    ),
    "hyperspace_tpu.execution.serve_cache.ServeCache._spill_bytes": (
        "self._lock",
        "guarded",
        "byte ledger of the spill tier, mutated in the same critical "
        "sections as _spill so the hyperspace.serve.spill.maxBytes cap "
        "can never be overshot by a torn read-modify-write",
    ),
    "hyperspace_tpu.execution.serve_cache._mmap_regions": (
        "hyperspace_tpu.execution.serve_cache._mmap_lock",
        "guarded-writes",
        "the file-backed address-range registry estimate_nbytes "
        "consults: register (spill restore / open_mmap_table), "
        "finalizer-driven unregister and range iteration hold the one "
        "lock; the sizing hot path's `if _mmap_regions` emptiness probe "
        "is a deliberate lock-free read — a stale answer only mis-sizes "
        "one estimate by the mmap token",
    ),
    "hyperspace_tpu.execution.serve_cache._LIVE_CACHES": (
        "",
        "rebind-only",
        "WeakSet of live caches consulted by the spill orphan reaper; "
        "membership changes are single add() at construction (before "
        "the cache is shared) plus GC-driven removal — CPython WeakSet "
        "discard is atomic at that granularity, readers snapshot via "
        "list() before iterating",
    ),
    "hyperspace_tpu.execution.executor.last_stream_stats": (
        "hyperspace_tpu.execution.executor._stream_stats_lock",
        "guarded",
        "per-query streaming-join wave/bucket counters accumulated from "
        "the wave worker threads; reset and add both hold the stream "
        "stats lock (last-writer-wins by contract, like the breakdown)",
    ),
    "hyperspace_tpu.serve.frontend.ServeFrontend._inflight": (
        "self._lock",
        "guarded",
        "single-flight dedup map: lookup+insert must be atomic or two "
        "identical plans both execute; all accesses hold the frontend "
        "lock",
    ),
    # -- telemetry (process-global, last-writer-wins by contract) ------------
    "hyperspace_tpu.execution.join_exec.last_serve_breakdown": (
        "hyperspace_tpu.execution.join_exec._serve_bd_lock",
        "guarded",
        "per-stage serve timings accumulated from pipelined worker "
        "threads; reset and add both hold the breakdown lock",
    ),
    "hyperspace_tpu.indexes.covering_build.last_build_breakdown": (
        "hyperspace_tpu.indexes.covering_build._build_bd_lock",
        "guarded",
        "per-stage build timings accumulated from sharded-tail workers; "
        "reset and add both hold the breakdown lock",
    ),
    "hyperspace_tpu.indexes.covering_build.last_build_telemetry": (
        "hyperspace_tpu.indexes.covering_build._build_bd_lock",
        "guarded",
        "shuffle-skew snapshot copied per data op under the same "
        "breakdown lock its readers and reset take",
    ),
    "hyperspace_tpu.parallel.shuffle.last_shuffle_stats": (
        "",
        "rebind-only",
        "diagnostic snapshot of the most recent exchange: the writer "
        "builds a fresh dict and publishes it with one atomic rebind, "
        "readers copy the reference they grabbed",
    ),
    "hyperspace_tpu.parallel.shuffle._skew_warned": (
        "",
        "rebind-only",
        "once-per-build skew-warning latch: plain bool rebinds "
        "(False at data-op entry, True at first warn); a racy "
        "check-then-warn can only duplicate one log line",
    ),
    "hyperspace_tpu.indexes.zonemaps.last_prune_stats": (
        "",
        "rebind-only",
        "per-serve prune telemetry published as a whole new dict in one "
        "rebind; concurrent serves interleave whole snapshots, never "
        "torn ones",
    ),
    "hyperspace_tpu.execution.pipeline_compiler.last_fused_stats": (
        "",
        "rebind-only",
        "fused-pass telemetry of the most recent execution, published as "
        "one rebind of a freshly-built dict",
    ),
    "hyperspace_tpu.execution.pipeline_compiler.last_aggplane_stats": (
        "",
        "rebind-only",
        "metadata-plane telemetry of the most recent execution, "
        "published as one rebind of a freshly-built dict",
    ),
    "hyperspace_tpu.execution.approx_exec.last_approx_stats": (
        "",
        "rebind-only",
        "approximate-serve telemetry of the most recent estimate, "
        "published as one rebind of a freshly-built dict",
    ),
    "hyperspace_tpu.testing.replay.last_replay_stats": (
        "",
        "rebind-only",
        "last completed replay's summary dict published whole in one "
        "rebind; concurrent replays interleave snapshots, never torn "
        "ones",
    ),
    # -- observability plane (hyperspace_tpu/obs/) ---------------------------
    "hyperspace_tpu.obs.trace._enabled": (
        "",
        "rebind-only",
        "the process-global tracing switch: plain bool rebinds; a racy "
        "read costs one span (recorded or skipped), never a torn value",
    ),
    "hyperspace_tpu.obs.trace._max_spans": (
        "",
        "rebind-only",
        "per-trace span cap republished whole by configure(); a stale "
        "read caps one trace at the previous bound",
    ),
    "hyperspace_tpu.obs.trace._finished": (
        "hyperspace_tpu.obs.trace._rec_lock",
        "guarded",
        "the finished-trace ring: root finish/append, drain and reset "
        "all hold the record lock (configure() swaps the deque under "
        "it too)",
    ),
    # -- recovery plane (metadata/recovery.py) -------------------------------
    "hyperspace_tpu.metadata.recovery._active_pins": (
        "hyperspace_tpu.metadata.recovery._pins_lock",
        "guarded",
        "serve snapshot pin registry consulted by orphan GC; register/"
        "release/union all hold the pins lock (the frozensets handed out "
        "are immutable)",
    ),
    "hyperspace_tpu.metadata.recovery._pin_seq": (
        "hyperspace_tpu.metadata.recovery._pins_lock",
        "guarded",
        "monotonic pin-token counter incremented only under the pins "
        "lock",
    ),
    "hyperspace_tpu.metadata.recovery._durable_pins": (
        "hyperspace_tpu.metadata.recovery._pins_lock",
        "guarded",
        "durable-pin renewal map (token -> pin files) consulted by the "
        "heartbeat sweep; record/release/snapshot all hold the pins "
        "lock, pin-file I/O happens outside it",
    ),
    "hyperspace_tpu.metadata.recovery._pin_heartbeat": (
        "hyperspace_tpu.metadata.recovery._pins_lock",
        "guarded-writes",
        "singleton renewal thread published by one rebind under the "
        "pins lock; the unguarded read sees None or the started "
        "heartbeat, never a torn value",
    ),
    # -- fleet fanout bus (serve/bus.py) -------------------------------------
    "hyperspace_tpu.serve.bus._seq": (
        "hyperspace_tpu.serve.bus._seq_lock",
        "guarded",
        "process-wide bus event sequence: every publisher (frontends, "
        "the lifecycle-action hook) increments under the one lock so "
        "same-millisecond publishes cannot collide on a file name",
    ),
    # -- fleet fast plane (serve/fleet.py) -----------------------------------
    "hyperspace_tpu.serve.fleet.FleetFrontend._fast_results": (
        "self._lock",
        "guarded",
        "the digest->Arrow-result LRU served to routed peers; get/put/"
        "evict from serve workers and fast-bus handler threads all hold "
        "the frontend lock",
    ),
    "hyperspace_tpu.serve.fleet.FleetFrontend._fast_results_bytes": (
        "self._lock",
        "guarded",
        "byte ledger of the fast result cache (resultCacheBytes bound); "
        "every read-modify-write runs under the same lock as the cache "
        "it accounts for",
    ),
    "hyperspace_tpu.serve.fleet.FleetFrontend._fast_inflight": (
        "self._lock",
        "guarded",
        "owner-side single-flight map (digest -> Future): lookup+insert "
        "must be atomic or two identical routed requests both execute",
    ),
    "hyperspace_tpu.serve.fleet.FleetFrontend._wake_events": (
        "self._lock",
        "guarded",
        "digest -> (Event, waiters) parking lot for spool waiters woken "
        "by result-ready pushes; register/unregister/wake from poll "
        "loops and handler threads all hold the frontend lock",
    ),
    "hyperspace_tpu.serve.fleet.FleetFrontend._fast_applied": (
        "self._lock",
        "guarded",
        "bus-event names applied via fast push, consulted by the "
        "durable poll to dedup push-vs-poll delivery; add/discard/"
        "membership all hold the frontend lock",
    ),
    "hyperspace_tpu.serve.fleet.FleetFrontend._fast_applied_order": (
        "self._lock",
        "guarded",
        "FIFO eviction order of the applied-name dedup set, mutated in "
        "the same critical sections as the set it bounds",
    ),
    "hyperspace_tpu.serve.fleet.FleetFrontend._peer_slo": (
        "self._lock",
        "guarded",
        "gossiped per-peer SLO class depths (owner -> (stamp, classes)) "
        "read by the admission check and written by the gossip handler; "
        "both hold the frontend lock",
    ),
    # -- fault injection (testing/faults.py) ---------------------------------
    "hyperspace_tpu.testing.faults._crash_active": (
        "hyperspace_tpu.testing.faults._lock",
        "guarded-writes",
        "crash-point arm/disarm mutate under the registry lock; the "
        "disarmed-path read is the same deliberate lock-free truthiness "
        "check the fault registry documents",
    ),
    "hyperspace_tpu.testing.faults._active": (
        "hyperspace_tpu.testing.faults._lock",
        "guarded-writes",
        "arm/disarm mutate under the registry lock; the disarmed-path "
        "read is a deliberate lock-free truthiness check (module doc)",
    ),
    "hyperspace_tpu.testing.faults._fired_totals": (
        "hyperspace_tpu.testing.faults._lock",
        "guarded",
        "fired counters updated inside fire() and snapshotted by stats() "
        "under the one registry lock",
    ),
    # -- residency witness (testing/residency_witness.py) --------------------
    "hyperspace_tpu.testing.residency_witness._sites": (
        "hyperspace_tpu.testing.residency_witness._rec_lock",
        "guarded",
        "per-site peak-bytes/call counters updated by the recording "
        "wrappers on every thread that calls a registered allocation "
        "site; record/snapshot/reset all hold the recorder lock "
        "(install/uninstall are single-threaded test setup by contract)",
    ),
    # -- collective witness (testing/collective_witness.py) ------------------
    "hyperspace_tpu.testing.collective_witness._records": (
        "hyperspace_tpu.testing.collective_witness._rec_lock",
        "guarded",
        "the per-process ordered collective sequence: record/snapshot/"
        "reset all hold the recorder lock (install/uninstall are "
        "single-threaded test setup by contract)",
    ),
    "hyperspace_tpu.testing.collective_witness._wave_counts": (
        "hyperspace_tpu.testing.collective_witness._rec_lock",
        "guarded",
        "per-site wave counters incremented with the matching sequence "
        "append under the same recorder lock",
    ),
    # -- import-time registries ----------------------------------------------
    "hyperspace_tpu.indexes.registry._REGISTRY": (
        "",
        "frozen",
        "index classes register at import time via decorator; serve/build "
        "threads only read it",
    ),
    "hyperspace_tpu.indexes.sketches._SKETCH_REGISTRY": (
        "",
        "frozen",
        "sketch classes register at import time via decorator; query "
        "threads only read it",
    ),
}
