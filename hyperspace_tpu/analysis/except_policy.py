"""HS4xx — exception-handling policy.

The native-fallback layer speaks in rc codes and ``None`` returns, and
the operation-log commit path speaks in typed exceptions
(``ConcurrentWriteException``, ``NoChangesException``). A bare
``except:`` (HS401) or an ``except Exception`` that swallows instead of
re-raising (HS402) can mask both contracts: an rc-2 bad_alloc fallback
becomes a silent wrong answer, a lost OCC race looks like success, and
``KeyboardInterrupt``/``SystemExit`` get eaten mid-commit.

The rules, package-wide:

* HS401: ``except:`` with no exception type — always flagged;
* HS402: ``except Exception`` / ``except BaseException`` whose handler
  does not re-raise (a bare ``raise`` anywhere in the handler makes it
  a log-and-propagate pattern, which is fine).

Deliberate catch-alls (a plan-rewrite fallback that must never break a
query, version-dependent library probing) stay — suppressed with
``# hslint: disable=HS402`` and a one-line justification.
"""

from __future__ import annotations

import ast
from typing import List

from hyperspace_tpu.analysis.core import Finding, Project, dotted_name

RULES = {
    "HS401": "bare except: masks rc-code and OCC contracts",
    "HS402": "except Exception without re-raise swallows unrelated failures",
}

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    names = []
    if t is None:
        return False  # bare except, handled as HS401
    if isinstance(t, ast.Tuple):
        names = [dotted_name(e) for e in t.elts]
    else:
        names = [dotted_name(t)]
    return any(n and n.split(".")[-1] in _BROAD for n in names)


def _reraises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
    return False


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for _rel, sf in sorted(project.files.items()):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                findings.append(
                    Finding(
                        "HS401",
                        sf.rel_path,
                        node.lineno,
                        "bare except: catches SystemExit/KeyboardInterrupt "
                        "and masks typed contracts — name the exceptions",
                    )
                )
            elif _is_broad(node) and not _reraises(node):
                findings.append(
                    Finding(
                        "HS402",
                        sf.rel_path,
                        node.lineno,
                        "except Exception without re-raise — type the "
                        "handler, or suppress with a justification if the "
                        "catch-all is the contract",
                    )
                )
    return findings
