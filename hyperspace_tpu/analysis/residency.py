"""HS10xx — memory-residency lints.

ROADMAP item 1 (out-of-core serve: budgeted streaming, spill-aware
caching) needs what KERNEL_TWINS gave kernels and SHARED_STATE gave
concurrency: a complete, statically checked inventory of every site
whose resident bytes grow with relation size, each declaring the bound
that keeps it finite. ``ALLOC_SITES`` (``hyperspace_tpu/memory.py``)
is that inventory; this checker keeps it honest.

* HS1001 — a row-proportional materialization (``read_table``,
  ``.to_numpy`` / ``.combine_chunks`` on a full table,
  ``np.concatenate`` of an unbounded accumulation, ``np.empty(n, …)``
  with a relation-derived size) inside a serve/build hot-path function
  (``execution/`` / ``indexes/`` / ``io/`` / ``serve/``, restricted to
  the cross-module reach closure from the public surface) whose
  enclosing function has no ``ALLOC_SITES`` entry. Per-function size
  taint decides "row-proportional": a value derived from a full
  relation's file list (``.files``, a ``files``/``paths`` parameter, a
  ``read_table`` result) is unbounded; a per-row-group or per-chunk
  slice (subscripts, loop targets, ``read_table_row_groups``) is not;
  an accumulator appended to across an unbounded loop is.
* HS1002 — a registered site whose declared bound class is not
  structurally enforced: ``cache-governed`` but the value never flows
  through a ``.put(...)`` (in the site or a direct caller);
  ``chunk-bounded`` but the site has no chunk loop;
  ``row-group-bounded`` but the site never touches the row-group read
  path; ``wave-budget`` but the site references no wave/budget/pool
  machinery.
* HS1003 — a stale ``ALLOC_SITES`` entry: unknown plane or bound
  class, missing justification, unresolved path, or a site whose
  function no longer contains any allocation primitive.
* HS1004 — residency-witness model gap (``hslint --witness``): the
  runtime witness (``testing/residency_witness.py``) observed an
  allocation site absent from the registry, or a site's recorded peak
  bytes exceed its declared bound class's ceiling
  (``memory.BOUND_CLASS_CEILINGS``). Registered sites never witnessed
  print as staleness warnings.

Trees without an ``ALLOC_SITES`` registry skip the checker entirely
(fixture mini-packages opt in by shipping a ``memory.py``).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.analysis.core import (
    Finding,
    Project,
    const_str,
    dotted_name,
    import_aliases,
)

RULES = {
    "HS1001": "row-proportional hot-path materialization absent from "
    "ALLOC_SITES",
    "HS1002": "declared allocation bound class is not structurally enforced",
    "HS1003": "stale ALLOC_SITES registry entry",
    "HS1004": "residency witness model gap",
}

#: candidate homes of the ALLOC_SITES literal, first hit wins
REGISTRY_FILES = ("memory.py",)

PLANES = ("build", "serve", "maintenance")
BOUND_CLASSES = (
    "cache-governed",
    "wave-budget",
    "chunk-bounded",
    "row-group-bounded",
    "const-bounded",
    "spill-bounded",
)

#: top-level package dirs whose functions are the serve/build hot path
HOT_DIRS = ("execution", "indexes", "io", "serve")

#: full-relation read primitives (always unbounded) vs the per-selection
#: row-group read path (bounded by construction)
READ_PRIMS = frozenset({"read_table"})
SLICE_READ_PRIMS = frozenset(
    {"read_table_row_groups", "read_file_row_groups"}
)
#: arrow materializers — unbounded iff their base value is tainted
ARROW_PRIMS = frozenset(
    {"to_numpy", "combine_chunks", "to_pandas", "to_pylist",
     "dictionary_encode"}
)
#: numpy allocators keyed on a relation-derived shape argument
NP_SHAPE_PRIMS = frozenset({"empty", "zeros", "ones", "full"})
#: mmap materializers — bounded by construction (the bytes are
#: file-backed views; resident charge is the page cache's problem), but
#: still allocation sites the registry must be able to declare
MMAP_PRIMS = frozenset({"frombuffer", "read_buffer", "memory_map"})
#: concatenators — unbounded iff the concatenated value is tainted
CONCAT_PRIMS = frozenset(
    {"concatenate", "vstack", "hstack", "stack", "concat_tables"}
)
_NP_BASES = frozenset({"np", "numpy"})
#: parameter names that carry a relation's file list into a function
FILE_LIST_PARAMS = frozenset(
    {"files", "paths", "file_paths", "filepaths", "file_list"}
)
_GROW_BUILTINS = frozenset({"list", "tuple", "sorted", "set"})


@dataclasses.dataclass
class SiteEntry:
    path: str
    plane: str
    bound: str
    why: str
    line: int


# ---------------------------------------------------------------------------
# Registry parsing
# ---------------------------------------------------------------------------


def registry_file(project: Project) -> Optional[str]:
    for rel in REGISTRY_FILES:
        sf = project.file(rel)
        if sf is None or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            targets: List[str] = []
            if isinstance(node, ast.Assign):
                targets = [
                    t.id for t in node.targets if isinstance(t, ast.Name)
                ]
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                targets = [node.target.id]
            if "ALLOC_SITES" in targets:
                return rel
    return None


def parse_sites(
    project: Project,
) -> Tuple[List[SiteEntry], Optional[str]]:
    """(entries, registry rel) from the ALLOC_SITES literal;
    ([], None) when absent — trees without a residency contract skip
    the checker."""
    rel = registry_file(project)
    if rel is None:
        return [], None
    sf = project.file(rel)
    entries: List[SiteEntry] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            targets = [node.target.id]
        else:
            continue
        if "ALLOC_SITES" not in targets or not isinstance(
            node.value, ast.Dict
        ):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            key = const_str(k) if k is not None else None
            if key is None:
                continue
            plane = bound = why = ""
            if isinstance(v, (ast.Tuple, ast.List)) and len(v.elts) >= 3:
                plane = const_str(v.elts[0]) or ""
                bound = const_str(v.elts[1]) or ""
                why = const_str(v.elts[2]) or ""
            entries.append(SiteEntry(key, plane, bound, why, v.lineno))
    return entries, rel


# ---------------------------------------------------------------------------
# Per-function size taint
# ---------------------------------------------------------------------------


class _Taint:
    """Names in one function whose values are relation-proportional.

    Seeds: file-list parameters, ``.files`` attribute loads,
    ``read_table`` results. Propagates through assignments, growing
    builtins and accumulators appended to across an unbounded loop;
    stops at subscripts and loop targets (the per-chunk slice
    doctrine)."""

    def __init__(self, body: List[ast.stmt], arg_names: Set[str]):
        self.body = body
        self.tainted: Set[str] = {
            a for a in arg_names if a in FILE_LIST_PARAMS
        }

    def run(self) -> Set[str]:
        changed = True
        while changed:
            changed = False
            for stmt in self.body:
                for node in ast.walk(stmt):
                    changed |= self._stmt(node)
        return self.tainted

    def _add(self, name: str) -> bool:
        if name in self.tainted:
            return False
        self.tainted.add(name)
        return True

    def _stmt(self, node: ast.AST) -> bool:
        changed = False
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = getattr(node, "value", None)
            if value is not None and self.expr(value):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            changed |= self._add(sub.id)
        elif isinstance(node, ast.AugAssign):
            if self.expr(node.value) and isinstance(node.target, ast.Name):
                changed |= self._add(node.target.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            if self.expr(node.iter):
                # accumulation doctrine: a value grown once per element
                # of an unbounded iterable is itself unbounded — the
                # loop target stays bounded (one slice), the
                # accumulator does not
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in ("append", "extend", "add")
                        and isinstance(sub.func.value, ast.Name)
                    ):
                        changed |= self._add(sub.func.value.id)
                    elif isinstance(sub, ast.AugAssign) and isinstance(
                        sub.target, ast.Name
                    ):
                        changed |= self._add(sub.target.id)
        return changed

    def expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr == "files":
                return True
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return False  # a slice of anything is bounded by doctrine
        if isinstance(node, ast.Call):
            f = node.func
            last = (
                f.attr
                if isinstance(f, ast.Attribute)
                else f.id
                if isinstance(f, ast.Name)
                else ""
            )
            if last in SLICE_READ_PRIMS:
                return False
            if last in READ_PRIMS:
                return True
            if isinstance(f, ast.Name) and f.id in _GROW_BUILTINS:
                return any(self.expr(a) for a in node.args)
            if isinstance(f, ast.Attribute) and f.attr in ARROW_PRIMS:
                return self.expr(f.value)
            return any(self.expr(a) for a in node.args) or any(
                self.expr(k.value) for k in node.keywords
            )
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            return any(self.expr(g.iter) for g in node.generators)
        if isinstance(node, ast.BinOp):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.IfExp):
            return self.expr(node.body) or self.expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        return False


# ---------------------------------------------------------------------------
# Function index + allocation-primitive scan
# ---------------------------------------------------------------------------


def _module_dotted(project: Project, rel: str) -> str:
    pkg = os.path.basename(project.package_dir)
    mod = rel[: -len(".py")] if rel.endswith(".py") else rel
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    mod = mod.replace("/", ".")
    return pkg if mod in ("__init__", "") else f"{pkg}.{mod}"


FnKey = Tuple[str, Optional[str], str]  # (rel, class, name) — "" = module


@dataclasses.dataclass
class _Alloc:
    line: int
    prim: str
    unbounded: bool


@dataclasses.dataclass
class _Fn:
    key: FnKey
    rel: str
    site: str  # dotted path
    public: bool
    body: List[ast.stmt]
    arg_names: Set[str]
    allocs: List[_Alloc] = dataclasses.field(default_factory=list)
    calls: Set[FnKey] = dataclasses.field(default_factory=set)
    has_put: bool = False
    has_loop: bool = False
    idents: Set[str] = dataclasses.field(default_factory=set)


def _np_base(node: ast.AST, aliases: Dict[str, str]) -> bool:
    base = dotted_name(node)
    if base is None:
        return False
    root = base.split(".", 1)[0]
    return root in _NP_BASES or aliases.get(root) == "numpy"


def _resolve_module_rel(
    project: Project, fq: str, pkg: str
) -> Optional[str]:
    if not fq.startswith(pkg + ".") and fq != pkg:
        return None
    rest = "" if fq == pkg else fq[len(pkg) + 1 :].replace(".", "/")
    cands = (
        ("__init__.py",)
        if not rest
        else (f"{rest}.py", f"{rest}/__init__.py")
    )
    for cand in cands:
        if cand in project.files:
            return cand
    return None


def build_index(project: Project) -> Dict[FnKey, _Fn]:
    """Every outermost function/method (plus each module's top-level
    statements) with its allocation primitives, size taint, and
    resolved same-package calls — the structure HS1001/HS1002/HS1003
    and the engagement tests share."""
    pkg = os.path.basename(project.package_dir)
    index: Dict[FnKey, _Fn] = {}
    class_names: Dict[str, Set[str]] = {}
    for rel, sf in sorted(project.files.items()):
        if sf.tree is None:
            continue
        mod = _module_dotted(project, rel)
        class_names[rel] = {
            n.name for n in sf.tree.body if isinstance(n, ast.ClassDef)
        }
        mod_body = [
            s
            for s in sf.tree.body
            if not isinstance(
                s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            )
        ]
        index[(rel, None, "")] = _Fn(
            (rel, None, ""), rel, mod, True, mod_body, set()
        )
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                index[(rel, None, node.name)] = _Fn(
                    (rel, None, node.name),
                    rel,
                    f"{mod}.{node.name}",
                    not node.name.startswith("_"),
                    node.body,
                    {a.arg for a in node.args.args + node.args.kwonlyargs},
                )
            elif isinstance(node, ast.ClassDef):
                for m in node.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        index[(rel, node.name, m.name)] = _Fn(
                            (rel, node.name, m.name),
                            rel,
                            f"{mod}.{node.name}.{m.name}",
                            not node.name.startswith("_")
                            and not m.name.startswith("_"),
                            m.body,
                            {
                                a.arg
                                for a in m.args.args + m.args.kwonlyargs
                            },
                        )
    for key, fn in index.items():
        sf = project.file(fn.rel)
        aliases = import_aliases(sf.tree)
        taint = _Taint(fn.body, fn.arg_names).run()
        tt = _Taint(fn.body, fn.arg_names)
        tt.tainted = taint
        for stmt in fn.body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    fn.has_loop = True
                if isinstance(node, ast.Name):
                    fn.idents.add(node.id)
                elif isinstance(node, ast.Attribute):
                    fn.idents.add(node.attr)
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "put":
                    fn.has_put = True
                # -- allocation primitives ------------------------------
                last = (
                    f.attr
                    if isinstance(f, ast.Attribute)
                    else f.id
                    if isinstance(f, ast.Name)
                    else ""
                )
                if last in READ_PRIMS:
                    fn.allocs.append(_Alloc(node.lineno, last, True))
                elif last in SLICE_READ_PRIMS or last in MMAP_PRIMS:
                    fn.allocs.append(_Alloc(node.lineno, last, False))
                elif isinstance(f, ast.Attribute) and f.attr in ARROW_PRIMS:
                    fn.allocs.append(
                        _Alloc(node.lineno, f.attr, tt.expr(f.value))
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr in CONCAT_PRIMS
                    and (
                        f.attr == "concat_tables"
                        or _np_base(f.value, aliases)
                    )
                ):
                    fn.allocs.append(
                        _Alloc(
                            node.lineno,
                            f.attr,
                            any(tt.expr(a) for a in node.args),
                        )
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr in NP_SHAPE_PRIMS
                    and _np_base(f.value, aliases)
                    and node.args
                ):
                    fn.allocs.append(
                        _Alloc(node.lineno, f.attr, tt.expr(node.args[0]))
                    )
                # -- call graph (reach closure + HS1002 put-flow) -------
                callee = _resolve_call(
                    project, pkg, fn, f, aliases, class_names
                )
                if callee is not None and callee in index:
                    fn.calls.add(callee)
    return index


def _resolve_call(
    project: Project,
    pkg: str,
    fn: _Fn,
    f: ast.AST,
    aliases: Dict[str, str],
    class_names: Dict[str, Set[str]],
) -> Optional[FnKey]:
    if isinstance(f, ast.Name):
        fq = aliases.get(f.id)
        if fq is not None and "." in fq:
            mod_fq, name = fq.rsplit(".", 1)
            rel = _resolve_module_rel(project, mod_fq, pkg)
            if rel is not None:
                return (rel, None, name)
        return (fn.rel, None, f.id)
    if isinstance(f, ast.Attribute):
        base = dotted_name(f.value)
        if base is None:
            return None
        if base == "self" and fn.key[1] is not None:
            return (fn.rel, fn.key[1], f.attr)
        if base in class_names.get(fn.rel, ()):
            return (fn.rel, base, f.attr)
        fq = aliases.get(base.split(".", 1)[0])
        if fq is not None:
            tail = base.split(".", 1)[1] if "." in base else ""
            full = f"{fq}.{tail}" if tail else fq
            rel = _resolve_module_rel(project, full, pkg)
            if rel is not None:
                return (rel, None, f.attr)
            # imported CLASS: pkg.mod.Cls.method — strip the class
            # component and address the method key
            if "." in full:
                mod_fq, cls = full.rsplit(".", 1)
                rel = _resolve_module_rel(project, mod_fq, pkg)
                if rel is not None:
                    return (rel, cls, f.attr)
    return None


def reach_closure(index: Dict[FnKey, _Fn]) -> Set[FnKey]:
    """Functions transitively reachable from the public serve/build
    surface (public hot-dir functions/methods + module bodies) — the
    set HS1001 audits; orphaned private helpers stay out."""
    roots = [
        k
        for k, fn in index.items()
        if fn.public and fn.rel.split("/", 1)[0] in HOT_DIRS
    ]
    seen: Set[FnKey] = set()
    frontier = list(roots)
    while frontier:
        k = frontier.pop()
        if k in seen:
            continue
        seen.add(k)
        for callee in index[k].calls:
            if callee in index and callee not in seen:
                frontier.append(callee)
    return seen


# ---------------------------------------------------------------------------
# Checker
# ---------------------------------------------------------------------------

_HS1002_HINTS = {
    "cache-governed": "the value never flows through a ServeCache "
    ".put(...) in the site or a direct caller",
    "chunk-bounded": "the site contains no chunk loop bounding the "
    "allocation",
    "row-group-bounded": "the site never touches the row-group read "
    "path (read_table_row_groups / row_groups selection)",
    "wave-budget": "the site references no wave/budget/pool machinery",
    "spill-bounded": "the site references no spill/mmap machinery",
}


def _put_flow_closure(index: Dict[FnKey, _Fn]) -> Set[FnKey]:
    """Functions whose result can flow through a ``.put(...)``: the
    putters themselves plus everything they transitively call (the
    value returns up the same chain the calls went down). Method calls
    through variables are resolved by method name — the registry-style
    name matching the locks checker uses."""
    attr_callers: Dict[str, Set[FnKey]] = {}
    for fn in index.values():
        for stmt in fn.body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ):
                    attr_callers.setdefault(node.func.attr, set()).add(
                        fn.key
                    )
    closure: Set[FnKey] = set()
    frontier = [k for k, fn in index.items() if fn.has_put]
    while frontier:
        k = frontier.pop()
        if k in closure:
            continue
        closure.add(k)
        fn = index[k]
        for callee in fn.calls:
            if callee in index and callee not in closure:
                frontier.append(callee)
        # name-matched method edges (obj.method() on an unresolvable
        # receiver): a putter mentioning .m() reaches every method m
        for name, meth_key in [
            (mk[2], mk) for mk in index if mk[1] is not None
        ]:
            if (
                meth_key not in closure
                and k in attr_callers.get(name, ())
            ):
                frontier.append(meth_key)
    return closure


def _bound_enforced(
    fn: _Fn, bound: str, put_closure: Set[FnKey]
) -> bool:
    if bound == "const-bounded":
        return True
    if bound == "cache-governed":
        return fn.has_put or fn.key in put_closure
    if bound == "chunk-bounded":
        return fn.has_loop
    if bound == "row-group-bounded":
        return any("row_group" in i for i in fn.idents)
    if bound == "wave-budget":
        return any(
            any(s in i for s in ("wave", "budget", "pool"))
            for i in fn.idents
        )
    if bound == "spill-bounded":
        return any(
            any(s in i for s in ("spill", "mmap", "mapped", "memory_map"))
            for i in fn.idents
        )
    return True


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    entries, reg_rel = parse_sites(project)
    if reg_rel is None:
        return findings
    reg_sf = project.file(reg_rel)
    reg_path = reg_sf.rel_path if reg_sf is not None else reg_rel
    declared: Dict[str, SiteEntry] = {e.path: e for e in entries}
    index = build_index(project)
    closure = reach_closure(index)
    put_closure = _put_flow_closure(index)
    by_site: Dict[str, _Fn] = {fn.site: fn for fn in index.values()}

    # -- HS1001: every unbounded hot-path materialization is declared --------
    for key in sorted(closure, key=str):
        fn = index[key]
        if fn.rel.split("/", 1)[0] not in HOT_DIRS:
            continue
        if fn.site in declared:
            continue
        sf = project.file(fn.rel)
        for alloc in fn.allocs:
            if not alloc.unbounded:
                continue
            findings.append(
                Finding(
                    "HS1001",
                    sf.rel_path if sf is not None else fn.rel,
                    alloc.line,
                    f"row-proportional materialization ({alloc.prim}) in "
                    f"{fn.site!r} but the site has no ALLOC_SITES entry "
                    "(memory.py) — declare its plane and bound class, or "
                    "bound the allocation to a per-chunk/per-row-group "
                    "slice",
                )
            )

    # -- HS1002/HS1003: the registry stays sound -----------------------------
    for e in entries:
        if e.plane not in PLANES:
            findings.append(
                Finding(
                    "HS1003",
                    reg_path,
                    e.line,
                    f"ALLOC_SITES entry {e.path!r} has unknown plane "
                    f"{e.plane!r} (want one of {PLANES})",
                )
            )
            continue
        if e.bound not in BOUND_CLASSES:
            findings.append(
                Finding(
                    "HS1003",
                    reg_path,
                    e.line,
                    f"ALLOC_SITES entry {e.path!r} has unknown bound "
                    f"class {e.bound!r} (want one of {BOUND_CLASSES})",
                )
            )
            continue
        if not e.why.strip():
            findings.append(
                Finding(
                    "HS1003",
                    reg_path,
                    e.line,
                    f"ALLOC_SITES entry {e.path!r} has no justification — "
                    "every declared bound says why it holds in one line",
                )
            )
            continue
        fn = by_site.get(e.path)
        if fn is None:
            findings.append(
                Finding(
                    "HS1003",
                    reg_path,
                    e.line,
                    f"ALLOC_SITES entry {e.path!r} does not resolve to a "
                    "module, function or method in the package — stale "
                    "registry entry",
                )
            )
            continue
        live = (
            bool(fn.allocs)
            or fn.has_put
            or any(
                index[c].allocs for c in fn.calls if c in index
            )
        )
        if not live:
            findings.append(
                Finding(
                    "HS1003",
                    reg_path,
                    e.line,
                    f"ALLOC_SITES entry {e.path!r} resolves but its site "
                    "neither allocates, charges the governor, nor calls "
                    "an allocating function — stale entry (remove it or "
                    "restore the allocation)",
                )
            )
            continue
        if not _bound_enforced(fn, e.bound, put_closure):
            findings.append(
                Finding(
                    "HS1002",
                    reg_path,
                    e.line,
                    f"ALLOC_SITES entry {e.path!r} declares "
                    f"{e.bound!r} but {_HS1002_HINTS[e.bound]}",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# Residency-witness cross-check (``hslint --witness``)
# ---------------------------------------------------------------------------


def load_witness(path: str, doc: Optional[dict] = None) -> dict:
    """Parse a residency witness artifact; raises ValueError on a
    malformed one (the CLI maps that to a usage error — a corrupt
    artifact must never pass as 'zero model gaps'). Pass a pre-parsed
    ``doc`` to validate without re-reading the file."""
    if doc is None:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    if not isinstance(doc, dict) or "sites" not in doc:
        raise ValueError(f"not a residency-witness artifact: {path}")
    sites = doc["sites"]
    if not isinstance(sites, dict) or not all(
        isinstance(k, str)
        and isinstance(v, dict)
        and isinstance(v.get("peak_bytes"), int)
        and isinstance(v.get("calls"), int)
        for k, v in sites.items()
    ):
        raise ValueError(f"malformed witness 'sites' map: {path}")
    budgets = doc.get("budgets", {})
    if not isinstance(budgets, dict) or not all(
        isinstance(k, str) and isinstance(v, int)
        for k, v in budgets.items()
    ):
        raise ValueError(f"malformed witness 'budgets' map: {path}")
    return doc


def witness_cross_check(
    projects: List[Project], doc: dict, artifact: str
) -> Tuple[List[Finding], List[str]]:
    """(model-gap findings, staleness warnings) of a residency witness
    against the static registry — the UNION over ``projects``, since
    one artifact records every wrapped site in its process.

    A WITNESSED allocation site absent from ``ALLOC_SITES`` is a hard
    HS1004 error (the runtime materialized something the model cannot
    see), as is a site whose observed peak bytes exceed its declared
    bound class's ceiling (the declared bound does not hold). A
    registered site never witnessed is only a staleness warning — the
    run may simply not have driven that path."""
    declared: Dict[str, SiteEntry] = {}
    for project in projects:
        entries, reg_rel = parse_sites(project)
        if reg_rel is not None:
            for e in entries:
                declared.setdefault(e.path, e)
    findings: List[Finding] = []
    warnings: List[str] = []
    budgets: Dict[str, int] = dict(doc.get("budgets", {}))
    sites: Dict[str, dict] = doc.get("sites", {})
    for site in sorted(sites):
        rec = sites[site]
        entry = declared.get(site)
        if entry is None:
            findings.append(
                Finding(
                    "HS1004",
                    artifact,
                    1,
                    f"witnessed allocation site {site!r} "
                    f"({rec.get('peak_bytes', 0)} peak bytes) is absent "
                    "from ALLOC_SITES — memory materialized at runtime "
                    "that the residency model cannot see",
                )
            )
            continue
        ceiling = budgets.get(entry.bound)
        if ceiling is not None and rec.get("peak_bytes", 0) > ceiling:
            findings.append(
                Finding(
                    "HS1004",
                    artifact,
                    1,
                    f"site {site!r} peaked at {rec['peak_bytes']} bytes, "
                    f"past its declared {entry.bound!r} ceiling of "
                    f"{ceiling} — the declared bound does not hold",
                )
            )
    for path in sorted(declared):
        rec = sites.get(path)
        if rec is None or rec.get("calls", 0) == 0:
            warnings.append(
                f"ALLOC_SITES entry {path} was never witnessed during "
                "the recorded run — stale model or an unexercised path"
            )
    return findings, warnings
