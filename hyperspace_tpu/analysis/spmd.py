"""HS8xx — SPMD collective-symmetry sanitizer + collective witness.

PR 11 made the multi-host exchange fast; its review had to hand-fix a
whole class of *collective-symmetry* bugs: zero-row processes skipping
the ``all_to_all``, waves planned over per-process file lists, barriers
reachable from only some processes. Exoshuffle (PAPERS.md) shows shuffle
planes live or die by every participant issuing the same collective
program — a property nothing checked mechanically. This checker does,
against the ``COLLECTIVE_SITES`` registry in
``parallel/collectives.py`` (the SHARED_STATE doctrine applied to the
multi-host plane: every collective/barrier call site declares its
symmetry contract — ``symmetric-all``, ``per-host-lane``,
``coordinator-gated`` — with a one-line justification).

Statically, the checker:

* finds every call to a collective primitive (``lax.all_to_all``,
  ``ppermute``, ``psum``/``all_gather`` family,
  ``multihost_utils.process_allgather`` / ``sync_global_devices``,
  ``jax.distributed.initialize``) and attributes it to its enclosing
  module-level function or method (nested defs and lambdas — shard_map
  bodies — attribute to their outermost def, which is what the registry
  names);
* builds the transitive *may-reach-collective* set of every function
  over the same cross-module call resolution as :mod:`analysis.locks`;
* tracks, per function, which local names are *process-identity
  tainted* (assigned from ``jax.process_index()`` / ``is_coordinator``
  / ``.process_local()``, transitively through local assignments;
  ``jax.process_count()`` is deliberately NOT tainted — every process
  agrees on it, so branching on it alone cannot diverge) and which are
  sanitized by ``process_allgather``.

Rules:

* HS801 — an ``if`` that branches on process identity
  (``process_index()`` / ``is_coordinator`` / a tainted local) can
  reach a collective on only some of its paths: the processes that take
  the other path never issue the collective and the job deadlocks (the
  PR 11 zero-row-batch bug, statically). Sites whose registered
  contract is ``coordinator-gated`` are exempt — gating THOSE on
  ``is_coordinator`` is the contract.
* HS802 — a function issues a collective primitive but has no
  ``COLLECTIVE_SITES`` entry, or a registry entry is stale (unresolved
  path, unknown contract, missing justification, or a non-gated entry
  whose function issues no collective).
* HS803 — a loop that encloses a collective iterates over
  process-local data (a ``.process_local()`` subset, a
  ``[process_index()::n]`` stripe): different processes run different
  iteration counts and issue different numbers of collectives — the
  wave-count bug. Loop bounds must derive from allgathered/global
  values.
* HS804 — only in ``--witness`` mode: the runtime collective witness
  (``testing/collective_witness.py``, armed via
  ``HS_COLLECTIVE_WITNESS=<prefix>`` in the multi-host dryrun) recorded
  per-process collective sequences that diverge, a witnessed site the
  registry lacks, or a coordinator-gated site witnessed off process 0.
  Registered-but-never-witnessed is a staleness *warning*, not an
  error.

Like every checker here this is an approximation (no aliasing, local
taint only); it is tuned to be quiet on correct code and loud on the
divergence shapes PR 11's review caught by hand.
"""

from __future__ import annotations

import ast
import dataclasses
import glob as _glob
import json
import os
from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.analysis.core import (
    Finding,
    Project,
    const_str,
    dotted_name,
)
from hyperspace_tpu.analysis import locks as _locks

RULES = {
    "HS801": "process-identity branch can reach a collective on only "
    "some paths",
    "HS802": "collective call site absent from COLLECTIVE_SITES (or "
    "stale registry entry)",
    "HS803": "loop enclosing a collective iterates over process-local "
    "data",
    "HS804": "collective witness diverges from the registry or contract",
}

#: candidate homes of the COLLECTIVE_SITES literal, first hit wins
REGISTRY_FILES = ("parallel/collectives.py", "collectives.py", "parallel/__init__.py")

CONTRACTS = ("symmetric-all", "per-host-lane", "coordinator-gated")

#: last path component of a collective primitive call
COLLECTIVE_PRIMITIVES = frozenset(
    {
        "all_to_all",
        "ppermute",
        "psum",
        "psum_scatter",
        "all_gather",
        "pmean",
        "pmax",
        "pmin",
        "process_allgather",
        "sync_global_devices",
        "broadcast_one_to_all",
    }
)

FuncKey = Tuple[str, Optional[str], str]  # (rel, class or None, name)


# ---------------------------------------------------------------------------
# Registry parsing + resolution
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SiteEntry:
    path: str
    op: str
    contract: str
    why: str
    line: int
    key: Optional[FuncKey] = None  # resolved


def registry_file(project: Project) -> Optional[str]:
    for rel in REGISTRY_FILES:
        sf = project.file(rel)
        if sf is None or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            targets: List[str] = []
            if isinstance(node, ast.Assign):
                targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                targets = [node.target.id]
            if "COLLECTIVE_SITES" in targets:
                return rel
    return None


def parse_sites(project: Project) -> Tuple[List[SiteEntry], Optional[str]]:
    """(entries, registry rel) from the COLLECTIVE_SITES literal;
    ([], None) when absent — trees without a multi-host plane simply
    skip the registry-backed rules."""
    rel = registry_file(project)
    if rel is None:
        return [], None
    sf = project.file(rel)
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            targets = [node.target.id]
        else:
            continue
        if "COLLECTIVE_SITES" not in targets or not isinstance(
            node.value, ast.Dict
        ):
            continue
        entries: List[SiteEntry] = []
        for k, v in zip(node.value.keys, node.value.values):
            key = const_str(k) if k is not None else None
            if key is None:
                continue
            op = contract = why = ""
            if isinstance(v, (ast.Tuple, ast.List)) and len(v.elts) >= 3:
                op = const_str(v.elts[0]) or ""
                contract = const_str(v.elts[1]) or ""
                why = const_str(v.elts[2]) or ""
            entries.append(SiteEntry(key, op, contract, why, v.lineno))
        return entries, rel
    return [], None


class _Resolver:
    """Dotted paths <-> FuncKeys over the package tree."""

    def __init__(self, project: Project):
        self.project = project
        self.pkg = os.path.basename(project.package_dir)
        self.indexes = _locks._model(project)[0]

    def rel_for(self, qualified_mod: str) -> Optional[str]:
        if qualified_mod == self.pkg:
            return "__init__.py" if "__init__.py" in self.project.files else None
        if not qualified_mod.startswith(self.pkg + "."):
            return None
        tail = qualified_mod[len(self.pkg) + 1 :].replace(".", "/")
        for cand in (f"{tail}.py", f"{tail}/__init__.py"):
            if cand in self.project.files:
                return cand
        return None

    def resolve_site_path(self, path: str) -> Optional[FuncKey]:
        parts = path.split(".")
        if len(parts) < 2 or parts[0] != self.pkg:
            return None
        for i in range(len(parts) - 1, 0, -1):
            rel = self.rel_for(".".join(parts[:i]))
            if rel is None:
                continue
            rest = parts[i:]
            idx = self.indexes[rel]
            if len(rest) == 1 and rest[0] in idx.functions:
                return (rel, None, rest[0])
            if (
                len(rest) == 2
                and rest[0] in idx.classes
                and rest[1] in idx.classes[rest[0]]
            ):
                return (rel, rest[0], rest[1])
            return None
        return None

    def dotted_path(self, key: FuncKey) -> str:
        rel, cls, name = key
        mod = rel[: -len(".py")] if rel.endswith(".py") else rel
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        mod = mod.replace("/", ".")
        base = self.pkg if mod == "__init__" else f"{self.pkg}.{mod}"
        return f"{base}.{cls}.{name}" if cls else f"{base}.{name}"


# ---------------------------------------------------------------------------
# Per-function facts
# ---------------------------------------------------------------------------


def _primitive_op(call: ast.Call) -> Optional[str]:
    name = dotted_name(call.func)
    if name is None:
        return None
    if name.endswith("distributed.initialize"):
        return "distributed.initialize"
    leaf = name.split(".")[-1]
    return leaf if leaf in COLLECTIVE_PRIMITIVES else None


@dataclasses.dataclass
class _FnFacts:
    key: FuncKey
    rel_path: str
    node: ast.AST
    primitives: List[Tuple[str, int]] = dataclasses.field(default_factory=list)
    calls: Set[FuncKey] = dataclasses.field(default_factory=set)


class _Analysis:
    def __init__(self, project: Project):
        self.project = project
        self.resolver = _Resolver(project)
        self.indexes = self.resolver.indexes
        self.facts: Dict[FuncKey, _FnFacts] = {}
        for rel, sf in project.files.items():
            if sf.tree is None:
                continue
            for node in sf.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._collect(rel, sf.rel_path, None, node)
                elif isinstance(node, ast.ClassDef):
                    for m in node.body:
                        if isinstance(
                            m, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            self._collect(rel, sf.rel_path, node.name, m)
        self.reach = self._reach_closure()

    def _collect(
        self, rel: str, rel_path: str, cls: Optional[str], fn: ast.AST
    ) -> None:
        """Full-subtree facts: collectives inside nested defs/lambdas
        (shard_map bodies) attribute to the OUTERMOST def — the
        granularity the registry names."""
        key: FuncKey = (rel, cls, fn.name)
        facts = _FnFacts(key, rel_path, fn)
        idx = self.indexes[rel]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            op = _primitive_op(node)
            if op is not None:
                facts.primitives.append((op, node.lineno))
                continue
            callee = _locks._resolve_call(idx, self.indexes, cls, node)
            if callee is not None and callee != key:
                facts.calls.add(callee)
        self.facts[key] = facts

    def _reach_closure(self) -> Dict[FuncKey, Set[FuncKey]]:
        """FuncKey -> set of collective-BEARING functions transitively
        reachable from it (a function with a direct primitive counts as
        reaching itself)."""
        bearing = {k for k, f in self.facts.items() if f.primitives}
        reach: Dict[FuncKey, Set[FuncKey]] = {
            k: ({k} if k in bearing else set()) for k in self.facts
        }
        changed = True
        while changed:
            changed = False
            for key, facts in self.facts.items():
                for callee in facts.calls:
                    extra = reach.get(callee)
                    if extra and not extra <= reach[key]:
                        reach[key] |= extra
                        changed = True
        return reach

    # -- site naming --------------------------------------------------------
    def site_name(self, key: FuncKey) -> str:
        return self.resolver.dotted_path(key)

    def reach_of_stmts(
        self, facts: _FnFacts, stmts: List[ast.stmt]
    ) -> Set[str]:
        """Collective sites reachable from a statement list: direct
        primitives (named ``<op>@<rel>``) plus the transitive reach of
        every resolvable call, as registry-comparable dotted names."""
        out: Set[str] = set()
        idx = self.indexes[facts.key[0]]
        for stmt in stmts:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                op = _primitive_op(node)
                if op is not None:
                    out.add(f"{op}@{facts.key[0]}")
                    continue
                callee = _locks._resolve_call(
                    idx, self.indexes, facts.key[1], node
                )
                if callee is None:
                    continue
                for reached in self.reach.get(callee, ()):
                    out.add(self.site_name(reached))
        return out


# ---------------------------------------------------------------------------
# Identity taint (per function, local)
# ---------------------------------------------------------------------------


def _expr_has_identity_source(node: ast.AST, tainted: Set[str]) -> bool:
    """True when the expression derives from process identity: a
    ``process_index()`` call, an ``is_coordinator`` reference, a
    ``.process_local()`` call, or a name already tainted. A
    ``process_allgather(...)`` call sanitizes its own subtree — its
    result is global by construction, whatever fed it."""
    if isinstance(node, ast.Call):
        name = dotted_name(node.func) or ""
        leaf = name.split(".")[-1]
        if leaf == "process_allgather":
            return False  # sanitized: the result is global
        if leaf in ("process_index", "process_local"):
            return True
    if isinstance(node, ast.Attribute) and node.attr == "is_coordinator":
        return True
    if isinstance(node, ast.Name) and (
        node.id == "is_coordinator" or node.id in tainted
    ):
        return True
    return any(
        _expr_has_identity_source(child, tainted)
        for child in ast.iter_child_nodes(node)
    )


def _identity_tainted_names(fn: ast.AST) -> Set[str]:
    """Local names assigned (anywhere in the function subtree) from a
    process-identity expression, to a local fixpoint."""
    assigns: List[Tuple[List[str], ast.AST]] = []
    for node in ast.walk(fn):
        targets: List[ast.expr] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets, value = [node.target], node.value
        if value is None:
            continue
        names = [
            t.id
            for tt in targets
            for t in ast.walk(tt)
            if isinstance(t, ast.Name)
        ]
        if names:
            assigns.append((names, value))
    tainted: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for names, value in assigns:
            if any(n in tainted for n in names):
                continue
            if _expr_has_identity_source(value, tainted):
                tainted.update(names)
                changed = True
    return tainted


def _terminates(stmts: List[ast.stmt]) -> bool:
    """The arm never falls through to the code after the branch."""
    return any(
        isinstance(s, (ast.Return, ast.Raise, ast.Break, ast.Continue))
        for s in stmts
    )


# ---------------------------------------------------------------------------
# The checker
# ---------------------------------------------------------------------------


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    analysis = _Analysis(project)
    entries, reg_rel = parse_sites(project)
    reg_sf = project.file(reg_rel) if reg_rel else None
    reg_path = reg_sf.rel_path if reg_sf is not None else "parallel/collectives.py"

    # -- HS802 (registry side): every entry must resolve --------------------
    contracts: Dict[str, str] = {}
    for e in entries:
        ok = True
        e.key = analysis.resolver.resolve_site_path(e.path)
        if e.key is None:
            findings.append(
                Finding(
                    "HS802",
                    reg_path,
                    e.line,
                    f"COLLECTIVE_SITES entry {e.path!r} names no "
                    "module-level callable in the package (stale "
                    "registry?)",
                )
            )
            ok = False
        if e.contract not in CONTRACTS:
            findings.append(
                Finding(
                    "HS802",
                    reg_path,
                    e.line,
                    f"{e.path}: unknown contract {e.contract!r} "
                    f"(have {', '.join(CONTRACTS)})",
                )
            )
            ok = False
        if not e.why.strip():
            findings.append(
                Finding(
                    "HS802",
                    reg_path,
                    e.line,
                    f"{e.path}: missing justification — every registry "
                    "entry must say why its symmetry contract holds",
                )
            )
            ok = False
        if (
            ok
            and e.contract != "coordinator-gated"
            and not analysis.facts[e.key].primitives
        ):
            findings.append(
                Finding(
                    "HS802",
                    reg_path,
                    e.line,
                    f"{e.path}: registered as a {e.contract} collective "
                    "site but its body issues no collective primitive "
                    "(stale registry?)",
                )
            )
            ok = False
        if ok:
            contracts[e.path] = e.contract

    def effective(sites: Set[str]) -> Set[str]:
        """Drop coordinator-gated sites — asymmetric reach of those is
        the contract, not a divergence."""
        return {
            s for s in sites if contracts.get(s) != "coordinator-gated"
        }

    # -- HS802 (call side): every collective-bearing function registered ----
    declared = {e.path for e in entries}  # broken entries already flagged
    for key in sorted(analysis.facts, key=str):
        facts = analysis.facts[key]
        if not facts.primitives:
            continue
        path = analysis.site_name(key)
        if path in contracts or path in declared:
            continue
        op, line = facts.primitives[0]
        findings.append(
            Finding(
                "HS802",
                facts.rel_path,
                line,
                f"{key[2]}() issues {op} but has no COLLECTIVE_SITES "
                f"entry — declare {path!r} with its symmetry contract in "
                "parallel/collectives.py",
            )
        )

    # -- HS801 + HS803: per-function control-flow sweep ---------------------
    for key in sorted(analysis.facts, key=str):
        facts = analysis.facts[key]
        fn = facts.node
        tainted = _identity_tainted_names(fn)

        for node in ast.walk(fn):
            if isinstance(node, ast.If):
                if not _expr_has_identity_source(node.test, tainted):
                    continue
                body_sites = effective(
                    analysis.reach_of_stmts(facts, node.body)
                )
                else_sites = effective(
                    analysis.reach_of_stmts(facts, node.orelse)
                )
                after = [
                    s
                    for s in ast.walk(fn)
                    if isinstance(s, ast.stmt)
                    and s.lineno > (node.end_lineno or node.lineno)
                ]
                after_sites = effective(analysis.reach_of_stmts(facts, after))
                path_body = body_sites | (
                    set() if _terminates(node.body) else after_sites
                )
                path_else = else_sites | (
                    set() if _terminates(node.orelse) else after_sites
                )
                if path_body == path_else or not (path_body | path_else):
                    continue
                only = sorted(path_body.symmetric_difference(path_else))
                findings.append(
                    Finding(
                        "HS801",
                        facts.rel_path,
                        node.lineno,
                        f"branch on process identity in {key[2]}() "
                        "reaches a collective on only some paths "
                        f"({', '.join(only[:3])}) — processes taking the "
                        "other path never issue it and the job deadlocks",
                    )
                )
            elif isinstance(node, ast.For):
                if not _expr_has_identity_source(node.iter, tainted):
                    continue
                body_sites = effective(
                    analysis.reach_of_stmts(facts, node.body)
                )
                if not body_sites:
                    continue
                findings.append(
                    Finding(
                        "HS803",
                        facts.rel_path,
                        node.lineno,
                        f"loop in {key[2]}() encloses a collective "
                        f"({', '.join(sorted(body_sites)[:3])}) but "
                        "iterates over process-local data — iteration "
                        "counts diverge across processes; derive the "
                        "bound from an allgathered/global value",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# Collective-witness cross-check (``hslint --witness``)
# ---------------------------------------------------------------------------


def load_collective_witness(path: str) -> List[dict]:
    """Per-process witness documents for a prefix (``<path>.p<i>.json``
    as written by ``testing/collective_witness.dump``) or a single
    artifact file; ValueError on a malformed or absent artifact (the
    CLI maps that to a usage error — a corrupt artifact must never pass
    as 'zero divergence')."""
    if os.path.isfile(path):
        paths = [path]
    else:
        paths = sorted(_glob.glob(f"{path}.p*.json"))
        if not paths:
            raise ValueError(
                f"no collective witness artifacts at {path} "
                f"(expected {path}.p<i>.json)"
            )
    docs = []
    for p in paths:
        with open(p, "r", encoding="utf-8") as f:
            doc = json.load(f)
        _validate_witness(doc, p)
        docs.append(doc)
    docs.sort(key=lambda d: d["process"])
    if len({d["process"] for d in docs}) != len(docs):
        raise ValueError(f"duplicate process indexes in artifacts at {path}")
    return docs


def _validate_witness(doc, path: str) -> None:
    if (
        not isinstance(doc, dict)
        or not isinstance(doc.get("process"), int)
        or not isinstance(doc.get("sequence"), list)
    ):
        raise ValueError(f"not a collective-witness artifact: {path}")
    for r in doc["sequence"]:
        if (
            not isinstance(r, dict)
            or not isinstance(r.get("site"), str)
            or not isinstance(r.get("op"), str)
            or not isinstance(r.get("wave"), int)
        ):
            raise ValueError(f"malformed witness 'sequence' record: {path}")
    if not isinstance(doc.get("registered", {}), dict):
        raise ValueError(f"malformed witness 'registered' map: {path}")


def collective_cross_check(
    projects: List[Project], docs: List[dict], artifact: str
) -> Tuple[List[Finding], List[str]]:
    """(divergence findings, staleness warnings) of per-process witness
    artifacts against the COLLECTIVE_SITES registry — the UNION over
    ``projects`` when several package dirs are analyzed.

    Hard HS804 errors: a witnessed site the registry lacks; a
    coordinator-gated site witnessed on a non-coordinator process; any
    cross-process divergence of the (coordinator-gated-filtered)
    collective sequences — length, site/op/wave order, or payload
    signature where the contract is ``symmetric-all``. A registered site
    never witnessed by any process is a staleness warning only — the
    dryrun may simply not have driven that path this run."""
    registry: Dict[str, str] = {}
    for project in projects:
        entries, _rel = parse_sites(project)
        for e in entries:
            if e.contract in CONTRACTS:
                registry[e.path] = e.contract
    findings: List[Finding] = []
    warnings: List[str] = []

    seen_unregistered: Set[str] = set()
    seen_gated: Set[Tuple[str, int]] = set()
    seen_drift: Set[str] = set()
    witnessed: Set[str] = set()
    for doc in docs:
        pid = doc["process"]
        for r in doc["sequence"]:
            site = r["site"]
            witnessed.add(site)
            contract = registry.get(site)
            if contract is None:
                if site not in seen_unregistered:
                    seen_unregistered.add(site)
                    findings.append(
                        Finding(
                            "HS804",
                            artifact,
                            1,
                            f"witnessed collective site {site!r} is not "
                            "in COLLECTIVE_SITES — a collective ran that "
                            "the registry (and every HS80x verdict) "
                            "cannot see",
                        )
                    )
            elif contract != r.get("contract", contract):
                if site not in seen_drift:
                    seen_drift.add(site)
                    warnings.append(
                        f"contract drift for {site}: registry says "
                        f"{contract!r}, artifact recorded "
                        f"{r.get('contract')!r} — re-record the witness"
                    )
            if contract == "coordinator-gated" and pid != 0:
                if (site, pid) not in seen_gated:
                    seen_gated.add((site, pid))
                    findings.append(
                        Finding(
                            "HS804",
                            artifact,
                            1,
                            f"coordinator-gated site {site!r} was "
                            f"witnessed on process {pid} — the "
                            "single-writer contract is violated",
                        )
                    )

    def filtered(doc: dict) -> List[dict]:
        return [
            r
            for r in doc["sequence"]
            if registry.get(r["site"]) != "coordinator-gated"
        ]

    if len(docs) > 1:
        base = filtered(docs[0])
        base_pid = docs[0]["process"]
        for doc in docs[1:]:
            seq = filtered(doc)
            pid = doc["process"]
            n = min(len(base), len(seq))
            divergence = None
            for i in range(n):
                a, b = base[i], seq[i]
                if (a["site"], a["op"], a["wave"]) != (
                    b["site"],
                    b["op"],
                    b["wave"],
                ):
                    divergence = (
                        i,
                        f"process {base_pid} issued {a['site']} "
                        f"(wave {a['wave']}) where process {pid} issued "
                        f"{b['site']} (wave {b['wave']})",
                    )
                    break
                if registry.get(a["site"]) == "symmetric-all" and a.get(
                    "sig"
                ) != b.get("sig"):
                    divergence = (
                        i,
                        f"payload signatures differ at symmetric-all "
                        f"site {a['site']}: {a.get('sig')} vs "
                        f"{b.get('sig')}",
                    )
                    break
            if divergence is None and len(base) != len(seq):
                divergence = (
                    n,
                    f"process {base_pid} recorded {len(base)} "
                    f"collectives, process {pid} recorded {len(seq)} — "
                    "some processes issued collectives others never "
                    "reached",
                )
            if divergence is not None:
                idx, detail = divergence
                findings.append(
                    Finding(
                        "HS804",
                        artifact,
                        1,
                        f"cross-process collective sequence divergence "
                        f"at position {idx}: {detail}",
                    )
                )
    for site in sorted(registry):
        if site not in witnessed:
            warnings.append(
                f"registered collective site never witnessed: {site} — "
                "stale registry or an unexercised dryrun path"
            )
    return findings, warnings
