"""HS5xx — lock-order and lock-held-I/O lint.

The concurrency seams of this codebase are few but sharp: the native
loader's one-time compile lock (``native/__init__.py``), the
calibration probe lock (``native/calibrate.py``), the serve-cache LRU
lock (``execution/serve_cache.py``) and the session's cache-construction
lock (``session.py``). The concurrent first-compile race fixed in
history shows these bite in practice.

The checker builds, statically:

* the set of lock objects — module-level ``X = threading.Lock()`` /
  ``RLock()`` and instance ``self.x = threading.Lock()`` assignments;
* per function/method: which locks it acquires (``with X:`` /
  ``X.acquire()``), which calls happen while each lock is held, and
  whether its body performs I/O (``open``, ``os.*``, ``subprocess.*``,
  ``shutil.*``, ``socket.*``, ``tempfile.*``, ``ctypes.CDLL``);
* a cross-module call graph (imports resolved within the package, one
  pass, no execution) and from it the transitive *may-acquire* set of
  every function.

Rules:

* HS501 — the lock-acquisition graph (edge A→B when B is acquired, or a
  function that may acquire B is called, while A is held) contains a
  cycle: two threads taking the locks in opposite orders deadlock.
* HS502 — I/O performed while a lock is held (directly in the held
  region, or by a directly-called function): the canonical slow-lock
  anti-pattern. One finding per held region, anchored at the acquire
  site, so a single suppression covers a deliberately-serialized region
  (e.g. the one-time native compile under ``_lock``).

Both rules are approximations (no aliasing, attribute-chain resolution
one level deep); they are tuned to be quiet on correct code and loud on
the two failure modes named above.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.analysis.core import (
    Finding,
    Project,
    dotted_name,
    import_aliases,
)

RULES = {
    "HS501": "lock-acquisition cycle (potential deadlock)",
    "HS502": "I/O while holding a lock",
}

#: dotted-prefix roots treated as I/O
IO_ROOTS = ("os", "subprocess", "shutil", "socket", "tempfile")
#: os.* members that are pure/cheap (string manipulation, process
#: introspection, config reads) — not I/O
IO_EXCLUDED_PREFIXES = (
    "os.environ",
    "os.path.join",
    "os.path.basename",
    "os.path.dirname",
    "os.path.split",
    "os.path.splitext",
    "os.path.expanduser",
    "os.path.normpath",
    "os.getpid",
    "os.cpu_count",
    "os.sched_getaffinity",
    "os.fspath",
    "os.sep",
    "os.name",
)

LockId = Tuple[str, str]  # ("mod:<rel>" | "cls:<rel>:<Class>", attr)
FuncKey = Tuple[str, Optional[str], str]  # (rel, class or None, name)


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    return name is not None and name.split(".")[-1] in ("Lock", "RLock")


def _is_io_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if name is None:
        return False
    if name == "open" or name == "ctypes.CDLL":
        return True
    if any(name.startswith(p) for p in IO_EXCLUDED_PREFIXES):
        return False
    root = name.split(".")[0]
    return root in IO_ROOTS and "." in name


@dataclasses.dataclass
class FuncInfo:
    key: FuncKey
    rel_path: str  # display path of the defining file
    direct_locks: Set[LockId] = dataclasses.field(default_factory=set)
    direct_io: List[Tuple[str, int]] = dataclasses.field(default_factory=list)
    calls: Set[FuncKey] = dataclasses.field(default_factory=set)
    # (held lock, acquired lock, line) — both held and acquired directly
    direct_edges: List[Tuple[LockId, LockId, int]] = dataclasses.field(
        default_factory=list
    )
    # (held lock, acquire line, callee key) — call made under the lock
    held_calls: List[Tuple[LockId, int, FuncKey]] = dataclasses.field(
        default_factory=list
    )
    # (held lock, acquire line, description, io line) — direct I/O under it
    held_io: List[Tuple[LockId, int, str, int]] = dataclasses.field(
        default_factory=list
    )


class _ModuleIndex:
    """Resolution context for one file."""

    def __init__(self, project: Project, rel: str):
        self.project = project
        self.rel = rel
        self.sf = project.files[rel]
        self.aliases = import_aliases(self.sf.tree) if self.sf.tree else {}
        self.pkg = os.path.basename(project.package_dir)
        self.module_locks: Set[str] = set()
        self.functions: Set[str] = set()
        self.classes: Dict[str, Set[str]] = {}  # class -> method names
        self.class_locks: Dict[str, Set[str]] = {}  # class -> lock attrs
        # class -> base-class exprs, as written (resolved after all
        # modules are indexed — a base usually lives in another file)
        self.class_bases: Dict[str, List[ast.expr]] = {}
        # class -> {lock attr -> base-class LockId} for locks the class
        # INHERITS rather than assigns: ``self._lock`` in a subclass
        # method is the base's lock object (attribute lookup is
        # dynamic), so it must resolve to the base's identity or the
        # ordering graph would fork one lock into two
        self.inherited_locks: Dict[str, Dict[str, LockId]] = {}
        # class -> {(rel, class)} of every in-package ancestor,
        # transitively (dynamic dispatch: a call through a base-class
        # method key may execute a subclass override)
        self.resolved_bases: Dict[str, Set[Tuple[str, str]]] = {}

    def qualified_to_rel(self, qualified: str) -> Optional[str]:
        """'hyperspace_tpu.native' -> 'native/__init__.py' (or .py file)."""
        if not qualified.startswith(self.pkg + "."):
            return None
        tail = qualified[len(self.pkg) + 1 :].replace(".", "/")
        for cand in (f"{tail}.py", f"{tail}/__init__.py"):
            if cand in self.project.files:
                return cand
        return None


def _collect_defs(project: Project) -> Tuple[Dict[str, _ModuleIndex], Set[LockId]]:
    indexes: Dict[str, _ModuleIndex] = {}
    locks: Set[LockId] = set()
    for rel, sf in project.files.items():
        idx = _ModuleIndex(project, rel)
        indexes[rel] = idx
        if sf.tree is None:
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        idx.module_locks.add(t.id)
                        locks.add((f"mod:{rel}", t.id))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                idx.functions.add(node.name)
            elif isinstance(node, ast.ClassDef):
                methods = {
                    m.name
                    for m in node.body
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
                idx.classes[node.name] = methods
                lock_attrs: Set[str] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and _is_lock_ctor(sub.value):
                        for t in sub.targets:
                            if (
                                isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                            ):
                                lock_attrs.add(t.attr)
                                locks.add((f"cls:{rel}:{node.name}", t.attr))
                idx.class_locks[node.name] = lock_attrs
                if node.bases:
                    idx.class_bases[node.name] = list(node.bases)
    _link_inherited_locks(indexes)
    return indexes, locks


def _resolve_base_class(
    idx: _ModuleIndex, indexes: Dict[str, _ModuleIndex], base: ast.expr
) -> Optional[Tuple[str, str]]:
    """``(rel, class)`` a base-class expression names, or None for
    anything outside the package (stdlib/third-party bases hold no locks
    we model)."""
    name = dotted_name(base)
    if not name:
        return None
    head, _, rest = name.partition(".")
    full = idx.aliases.get(head, head) + (f".{rest}" if rest else "")
    mod, _, cls = full.rpartition(".")
    if not mod:  # same-module base, unqualified
        return (idx.rel, cls) if cls in idx.classes else None
    brel = idx.qualified_to_rel(mod)
    if brel is None or cls not in indexes[brel].classes:
        return None
    return brel, cls


def _link_inherited_locks(indexes: Dict[str, _ModuleIndex]) -> None:
    """Propagate lock attributes down single-inheritance chains: a
    subclass that does NOT assign ``self.<attr>`` itself sees the base's
    lock under the base's LockId. Fixpoint handles multi-level chains
    regardless of file iteration order; a subclass re-assigning the
    attr shadows the base (its own class_locks entry wins)."""
    changed = True
    while changed:
        changed = False
        for idx in indexes.values():
            for cls, bases in idx.class_bases.items():
                own = idx.inherited_locks.setdefault(cls, {})
                ancestors = idx.resolved_bases.setdefault(cls, set())
                for base in bases:
                    target = _resolve_base_class(idx, indexes, base)
                    if target is None:
                        continue
                    brel, bcls = target
                    bidx = indexes[brel]
                    lineage = {target} | bidx.resolved_bases.get(bcls, set())
                    if not lineage <= ancestors:
                        ancestors |= lineage
                        changed = True
                    merged: Dict[str, LockId] = dict(
                        bidx.inherited_locks.get(bcls, {})
                    )
                    for attr in bidx.class_locks.get(bcls, ()):
                        merged[attr] = (f"cls:{brel}:{bcls}", attr)
                    for attr, lock_id in merged.items():
                        if attr in idx.class_locks.get(cls, ()):
                            continue  # shadowed by the subclass's own lock
                        if own.get(attr) != lock_id:
                            own[attr] = lock_id
                            changed = True


def _resolve_lock(
    idx: _ModuleIndex, cls: Optional[str], node: ast.AST
) -> Optional[LockId]:
    if isinstance(node, ast.Name) and node.id in idx.module_locks:
        return (f"mod:{idx.rel}", node.id)
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and cls is not None
    ):
        if node.attr in idx.class_locks.get(cls, ()):
            return (f"cls:{idx.rel}:{cls}", node.attr)
        return idx.inherited_locks.get(cls, {}).get(node.attr)
    return None


def _resolve_call(
    idx: _ModuleIndex,
    indexes: Dict[str, _ModuleIndex],
    cls: Optional[str],
    node: ast.Call,
) -> Optional[FuncKey]:
    f = node.func
    if isinstance(f, ast.Name):
        if f.id in idx.functions:
            return (idx.rel, None, f.id)
        if f.id in idx.classes:
            return (idx.rel, f.id, "__init__")
        target = idx.aliases.get(f.id)
        if target:  # from pkg.mod import fn / Class
            mod, _, leaf = target.rpartition(".")
            rel2 = idx.qualified_to_rel(mod) if mod else None
            if rel2 and rel2 in indexes:
                if leaf in indexes[rel2].functions:
                    return (rel2, None, leaf)
                if leaf in indexes[rel2].classes:
                    return (rel2, leaf, "__init__")
        return None
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name):
            base = f.value.id
            if base == "self" and cls is not None:
                if f.attr in idx.classes.get(cls, ()):
                    return (idx.rel, cls, f.attr)
                return None
            target = idx.aliases.get(base)
            if target:
                rel2 = idx.qualified_to_rel(target)
                if rel2 and rel2 in indexes:
                    if f.attr in indexes[rel2].functions:
                        return (rel2, None, f.attr)
                    if f.attr in indexes[rel2].classes:
                        return (rel2, f.attr, "__init__")
    return None


class _FuncAnalyzer:
    """Sequential statement walk of one function maintaining the held-lock
    set; ``with lock:`` holds for the block, ``lock.acquire()`` holds for
    the rest of the function (``release()`` drops it)."""

    def __init__(
        self,
        info: FuncInfo,
        idx: _ModuleIndex,
        indexes: Dict[str, _ModuleIndex],
        cls: Optional[str],
    ):
        self.info = info
        self.idx = idx
        self.indexes = indexes
        self.cls = cls
        self.held: List[Tuple[LockId, int]] = []  # (lock, acquire line)

    def run(self, fn: ast.FunctionDef) -> None:
        self._stmts(fn.body)

    # -- statements ---------------------------------------------------------
    def _stmts(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in stmt.items:
                lock = _resolve_lock(self.idx, self.cls, item.context_expr)
                if lock is not None:
                    self._acquire(lock, item.context_expr.lineno)
                    acquired.append(lock)
                else:
                    self._expr(item.context_expr)
            self._stmts(stmt.body)
            for lock in acquired:
                self._release(lock)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs analyzed when (if) they run, not here
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.stmt):
                self._stmt(node)
            elif isinstance(node, ast.expr):
                self._expr(node)
            elif isinstance(node, (ast.ExceptHandler,)):
                self._stmts(node.body)

    # -- expressions --------------------------------------------------------
    def _expr(self, node: ast.AST) -> None:
        for call in [
            n for n in ast.walk(node) if isinstance(n, ast.Call)
        ]:
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr in ("acquire", "release"):
                lock = _resolve_lock(self.idx, self.cls, f.value)
                if lock is not None:
                    if f.attr == "acquire":
                        self._acquire(lock, call.lineno)
                    else:
                        self._release(lock)
                    continue
            self._record_call(call)

    # -- events -------------------------------------------------------------
    def _acquire(self, lock: LockId, line: int) -> None:
        self.info.direct_locks.add(lock)
        for held, _hline in self.held:
            if held != lock:
                self.info.direct_edges.append((held, lock, line))
        self.held.append((lock, line))

    def _release(self, lock: LockId) -> None:
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i][0] == lock:
                del self.held[i]
                return

    def _record_call(self, call: ast.Call) -> None:
        if _is_io_call(call):
            desc = dotted_name(call.func) or "open"
            self.info.direct_io.append((desc, call.lineno))
            for held, hline in self.held:
                self.info.held_io.append((held, hline, desc, call.lineno))
        callee = _resolve_call(self.idx, self.indexes, self.cls, call)
        if callee is not None:
            self.info.calls.add(callee)
            for held, hline in self.held:
                self.info.held_calls.append((held, hline, callee))


def _analyze_functions(
    project: Project, indexes: Dict[str, _ModuleIndex]
) -> Dict[FuncKey, FuncInfo]:
    infos: Dict[FuncKey, FuncInfo] = {}
    for rel, sf in project.files.items():
        if sf.tree is None:
            continue
        idx = indexes[rel]

        def handle(fn: ast.FunctionDef, cls: Optional[str]) -> None:
            key: FuncKey = (rel, cls, fn.name)
            info = FuncInfo(key, sf.rel_path)
            _FuncAnalyzer(info, idx, indexes, cls).run(fn)
            infos[key] = info

        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                handle(node, None)
            elif isinstance(node, ast.ClassDef):
                for m in node.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        handle(m, node.name)
    return infos


def _may_acquire(infos: Dict[FuncKey, FuncInfo]) -> Dict[FuncKey, Set[LockId]]:
    """Transitive closure of lock acquisition over the call graph."""
    may: Dict[FuncKey, Set[LockId]] = {
        k: set(v.direct_locks) for k, v in infos.items()
    }
    changed = True
    while changed:
        changed = False
        for key, info in infos.items():
            for callee in info.calls:
                extra = may.get(callee)
                if extra and not extra <= may[key]:
                    may[key] |= extra
                    changed = True
    return may


def _find_cycle(
    edges: Dict[LockId, Set[LockId]]
) -> Optional[List[LockId]]:
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[LockId, int] = {}
    stack: List[LockId] = []

    def dfs(u: LockId) -> Optional[List[LockId]]:
        color[u] = GRAY
        stack.append(u)
        for v in sorted(edges.get(u, ())):
            c = color.get(v, WHITE)
            if c == GRAY:
                return stack[stack.index(v) :] + [v]
            if c == WHITE:
                got = dfs(v)
                if got:
                    return got
        stack.pop()
        color[u] = BLACK
        return None

    for u in sorted(edges):
        if color.get(u, WHITE) == WHITE:
            got = dfs(u)
            if got:
                return got
    return None


def _lock_name(lock: LockId) -> str:
    scope, attr = lock
    if scope.startswith("cls:"):
        return f"{scope.rsplit(':', 1)[1]}.{attr}"
    return attr


def canonical_lock_name(lock: LockId) -> str:
    """Stable cross-artifact name for a lock: ``<rel>::<attr>`` for a
    module lock, ``<rel>::<Class>.<attr>`` for an instance lock. The
    runtime lock witness (``testing/lock_witness.py``) emits the same
    names, so witness artifacts and the static model compare directly."""
    scope, attr = lock
    if scope.startswith("cls:"):
        _, rel, cls = scope.split(":", 2)
        return f"{rel}::{cls}.{attr}"
    return f"{scope.split(':', 1)[1]}::{attr}"


def _model(project: Project):
    """The full lock model of a tree — (indexes, all locks, per-function
    infos, may-acquire sets, edges, edge anchor sites) — computed ONCE
    per Project and memoized on it: the HS501/HS502 pass and the
    lock-witness cross-check share one analysis."""
    cached = getattr(project, "_locks_model_cache", None)
    if cached is None:
        indexes, all_locks = _collect_defs(project)
        infos = _analyze_functions(project, indexes)
        may = _may_acquire(infos)
        edges, edge_sites = _edges_from(infos, may)
        cached = (indexes, all_locks, infos, may, edges, edge_sites)
        project._locks_model_cache = cached
    return cached


def build_lock_graph(
    project: Project,
) -> Tuple[
    Set[LockId],
    Dict[LockId, Set[LockId]],
    Dict[Tuple[LockId, LockId], Tuple[str, int]],
]:
    """(all locks, edges, edge anchor sites) of the static lock model:
    edge A→B when B is acquired — directly or via any callee's
    may-acquire set — while A is held. Shared by the HS501 cycle check
    and the lock-witness cross-check (``analysis/shared_state.py``);
    memoized per Project."""
    _indexes, all_locks, _infos, _may, edges, edge_sites = _model(project)
    return all_locks, edges, edge_sites


def _edges_from(
    infos: Dict[FuncKey, FuncInfo], may: Dict[FuncKey, Set[LockId]]
) -> Tuple[
    Dict[LockId, Set[LockId]], Dict[Tuple[LockId, LockId], Tuple[str, int]]
]:
    """Edges: direct nested acquires + acquires via calls made while
    holding (through the transitive may-acquire set)."""
    edges: Dict[LockId, Set[LockId]] = {}
    edge_sites: Dict[Tuple[LockId, LockId], Tuple[str, int]] = {}
    for info in infos.values():
        for held, acquired, line in info.direct_edges:
            edges.setdefault(held, set()).add(acquired)
            edge_sites.setdefault((held, acquired), (info.rel_path, line))
        for held, hline, callee in info.held_calls:
            for acquired in may.get(callee, ()):
                if acquired == held:
                    continue
                edges.setdefault(held, set()).add(acquired)
                edge_sites.setdefault((held, acquired), (info.rel_path, hline))
    return edges, edge_sites


def check(project: Project) -> List[Finding]:
    _indexes, _locks_, infos, _may, edges, edge_sites = _model(project)
    findings: List[Finding] = []

    cycle = _find_cycle(edges)
    if cycle:
        pairs = list(zip(cycle, cycle[1:]))
        path = " -> ".join(_lock_name(l) for l in cycle)
        rel_path, line = edge_sites.get(pairs[0], ("", 1))
        findings.append(
            Finding(
                "HS501",
                rel_path,
                line,
                f"lock-acquisition cycle: {path} — threads taking these in "
                "opposite orders deadlock",
            )
        )

    # -- lock-held I/O, one finding per held region -------------------------
    grouped: Dict[Tuple[FuncKey, LockId, int], List[str]] = {}
    for info in infos.values():
        for held, hline, desc, io_line in info.held_io:
            grouped.setdefault((info.key, held, hline), []).append(
                f"{desc} (line {io_line})"
            )
        for held, hline, callee in info.held_calls:
            callee_info = infos.get(callee)
            if callee_info and callee_info.direct_io:
                desc, io_line = callee_info.direct_io[0]
                grouped.setdefault((info.key, held, hline), []).append(
                    f"{callee[2]}() -> {desc} ({callee_info.rel_path}:{io_line})"
                )
    for (key, held, hline), sites in sorted(
        grouped.items(), key=lambda kv: (str(kv[0][0]), kv[0][1], kv[0][2])
    ):
        info = infos[key]
        shown = ", ".join(dict.fromkeys(sites))
        if len(shown) > 200:
            shown = shown[:200] + "…"
        findings.append(
            Finding(
                "HS502",
                info.rel_path,
                hline,
                f"I/O while holding {_lock_name(held)!r} in {key[2]}(): "
                f"{shown} — blocks every other thread on this lock for the "
                "I/O's duration",
            )
        )
    return findings
