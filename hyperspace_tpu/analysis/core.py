"""hslint core: findings, suppressions, and the analyzed-project model.

The analyzer is pure stdlib ``ast`` — it never imports the code it
checks (so it runs in any environment, including ones without jax or a
compiler) and never executes it (a broken tree still lints).

Suppression contract: a comment ``# hslint: disable=HS402`` (or a
comma-separated list, or ``all``) suppresses matching findings anchored
on the SAME line; a comment-only line suppresses the line directly
below it as well. Text after the rule list (an inline justification) is
ignored. Suppressed findings are still collected (with
``suppressed=True``) so the CLI can report them under
``--show-suppressed``, but they never fail the run.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: Schema-stable finding fields (tests/test_hslint.py golden-checks this).
FINDING_FIELDS = ("rule", "path", "line", "message", "suppressed")

# The rule list stops at the first token that is not a rule id or comma,
# so an inline justification after the ids does not break the match.
_SUPPRESS_RE = re.compile(
    r"#\s*hslint:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)


@dataclasses.dataclass
class Finding:
    """One rule violation: ``path:line: rule message``."""

    rule: str
    path: str  # relative to the analyzed package's parent
    line: int
    message: str
    suppressed: bool = False

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{tag}"

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in FINDING_FIELDS}


def _parse_suppressions(lines: List[str]) -> Dict[int, Set[str]]:
    """1-based line -> set of suppressed rule ids ("all" wildcards)."""
    out: Dict[int, Set[str]] = {}
    for i, raw in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if raw.lstrip().startswith("#"):
            # standalone comment: also covers the statement below it
            out.setdefault(i + 1, set()).update(rules)
    return out


class SourceFile:
    """One parsed python file: text, lines, AST, suppression map."""

    def __init__(self, abs_path: str, rel_path: str):
        self.abs_path = abs_path
        self.rel_path = rel_path
        with open(abs_path, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.suppressions = _parse_suppressions(self.lines)
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.text, filename=abs_path)
        except SyntaxError as e:
            self.parse_error = f"{e.msg} (line {e.lineno})"

    def is_suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule in rules or "all" in rules)


class Project:
    """The analyzed tree: every ``*.py`` under ``package_dir``, plus the
    sibling ``tests/`` directory (used by the kernel-parity checker) and
    the native C++ source when present.

    ``package_dir`` is the python package root (the directory holding
    ``constants.py``, ``actions/``, ``native/`` …). Checkers address
    files by path relative to it, so fixture mini-packages in tests
    exercise the same code paths as the real tree.
    """

    def __init__(self, package_dir: str, tests_dir: Optional[str] = None):
        self.package_dir = os.path.abspath(package_dir)
        parent = os.path.dirname(self.package_dir)
        if tests_dir is None:
            cand = os.path.join(parent, "tests")
            tests_dir = cand if os.path.isdir(cand) else None
        self.tests_dir = tests_dir
        self.files: Dict[str, SourceFile] = {}
        self.findings: List[Finding] = []
        for abs_path in self._walk_py(self.package_dir):
            rel = os.path.relpath(abs_path, self.package_dir)
            rel = rel.replace(os.sep, "/")
            sf = SourceFile(abs_path, self.display_path(rel))
            self.files[rel] = sf
            if sf.parse_error:
                self.findings.append(
                    Finding("HS001", sf.rel_path, 1, f"syntax error: {sf.parse_error}")
                )

    @staticmethod
    def _walk_py(root: str) -> Iterable[str]:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__" and not d.startswith(".")
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)

    def display_path(self, rel: str) -> str:
        return f"{os.path.basename(self.package_dir)}/{rel}"

    # -- lookups used by checkers ------------------------------------------
    def file(self, rel: str) -> Optional[SourceFile]:
        return self.files.get(rel)

    def files_under(self, *subdirs: str) -> List[Tuple[str, SourceFile]]:
        out = []
        for rel, sf in self.files.items():
            top = rel.split("/", 1)[0]
            if top in subdirs:
                out.append((rel, sf))
        return out

    def native_cpp_path(self) -> Optional[str]:
        p = os.path.join(self.package_dir, "native", "hs_native.cpp")
        return p if os.path.isfile(p) else None

    def doc_lines(self, name: str) -> Optional[List[str]]:
        """Lines of ``docs/<name>`` next to the package (the contract
        checker reads ``CONFIG.md``), or None when absent — fixture
        trees without docs simply skip the doc-backed rules."""
        return self.aux_lines("docs", name)

    def aux_lines(self, *relpath: str) -> Optional[List[str]]:
        """Lines of any file next to the package (the contract checker
        reads ``scripts/dryrun_multihost.py`` for the collective-site
        witness matrix), or None when absent — fixture trees without it
        simply skip the file-backed rules."""
        p = os.path.join(os.path.dirname(self.package_dir), *relpath)
        if not os.path.isfile(p):
            return None
        with open(p, "r", encoding="utf-8") as f:
            return f.read().splitlines()

    def test_files(self) -> List[Tuple[str, str]]:
        """(relative display path, text) for every test file."""
        if not self.tests_dir or not os.path.isdir(self.tests_dir):
            return []
        out = []
        for abs_path in self._walk_py(self.tests_dir):
            rel = os.path.relpath(abs_path, os.path.dirname(self.tests_dir))
            with open(abs_path, "r", encoding="utf-8") as f:
                out.append((rel.replace(os.sep, "/"), f.read()))
        return out


# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Local alias -> fully-qualified module name, from every import
    statement in the file (including ones nested in functions — this
    codebase imports lazily inside hot functions)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
