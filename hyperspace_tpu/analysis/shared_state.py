"""HS6xx — shared-state race detector + lock-witness cross-check.

PR 8 moved the serve plane in-process: a thread-pool frontend, the
shared ServeCache, the scan read-ahead pool and per-bucket/per-shard
pools all run under real contention. The HS5xx lint reasons about lock
*ordering*; nothing proved that shared mutable state is guarded at all.
This checker does, against the ``SHARED_STATE`` registry in
``hyperspace_tpu/concurrency.py`` (the KERNEL_TWINS doctrine applied to
concurrency — every cross-thread mutable object declares its lock and
guarding policy).

Statically, the checker:

* finds every thread-pool boundary — ``<pool>.submit(fn, …)`` /
  ``<pool>.map(fn, …)`` call sites (the shared ``scan_pool``, the
  ServeFrontend executor, the per-bucket/per-shard worker pools) — and
  resolves the submitted callables, including closures defined inside
  the submitting function;
* computes the set of functions transitively reachable from those
  callables (the same resolution discipline as the may-acquire walk in
  :mod:`analysis.locks`, extended to nested defs and one-level
  re-exports);
* records every access to a module-level global or registered instance
  attribute together with the locks held at the access site.

Rules:

* HS601 — a module-level mutable global that some function writes is
  read or written from a pool-reachable function but has no
  ``SHARED_STATE`` entry: undeclared cross-thread state.
* HS602 — registered state is accessed in violation of its declared
  policy (``guarded``: any access outside the lock; ``guarded-writes``:
  a write outside the lock; ``rebind-only``: an in-place mutation;
  ``frozen``: a write from a pool-reachable function). ``__init__``
  bodies are exempt for instance attributes — construction
  happens-before sharing.
* HS603 — a registry entry that no longer resolves (stale state path,
  unknown lock, unknown policy, or a missing justification).
* HS604 — only in ``--witness`` mode: the runtime lock witness
  (``testing/lock_witness.py``) observed an acquisition edge or a lock
  the static model does not contain — the model has a gap and every
  HS5xx/HS6xx verdict built on it is suspect. The reverse direction
  (static edge never witnessed) is a staleness *warning*, not an error.

Like every checker here this is an approximation (no aliasing, no
dynamic dispatch); it is tuned to be quiet on correct code and loud on
unguarded telemetry dicts, caches and registries — the bugs Sparkle
(PAPERS.md) shows dominate at large-box scale.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.analysis.core import (
    Finding,
    Project,
    const_str,
    dotted_name,
)
from hyperspace_tpu.analysis import locks as _locks
from hyperspace_tpu.analysis.locks import (
    LockId,
    _ModuleIndex,
    _resolve_lock,
    canonical_lock_name,
)

RULES = {
    "HS601": "shared mutable global reachable from a thread pool is not "
    "registered in SHARED_STATE",
    "HS602": "registered shared state accessed outside its declared "
    "lock/policy",
    "HS603": "SHARED_STATE registry entry does not resolve",
    "HS604": "lock witness observed an edge absent from the static model",
}

REGISTRY_FILE = "concurrency.py"
POLICIES = ("guarded", "guarded-writes", "rebind-only", "frozen")

#: in-place mutators of the stdlib containers shared state is made of
_MUTATORS = frozenset(
    {
        "append",
        "appendleft",
        "extend",
        "extendleft",
        "insert",
        "add",
        "update",
        "clear",
        "pop",
        "popitem",
        "setdefault",
        "remove",
        "discard",
        "move_to_end",
        "sort",
        "reverse",
    }
)

#: constructors whose result is NOT cross-thread-hazardous state
_NONSHARED_CTORS = frozenset(
    {
        "Lock",
        "RLock",
        "Condition",
        "Event",
        "Semaphore",
        "BoundedSemaphore",
        "local",
        "Barrier",
    }
)

_MUTABLE_CTORS = frozenset(
    {"dict", "list", "set", "OrderedDict", "defaultdict", "deque", "Counter"}
)

FuncKey = Tuple[str, Optional[str], str]  # (rel, class or None, qualname)
StateId = Tuple[str, ...]  # ("mod", rel, name) | ("cls", rel, Class, attr)


@dataclasses.dataclass
class Access:
    state: StateId
    line: int
    kind: str  # "read" | "rebind" | "mutate"
    held: frozenset  # of LockId


@dataclasses.dataclass
class FnInfo:
    key: FuncKey
    rel: str
    rel_path: str  # display path
    calls: Set[FuncKey] = dataclasses.field(default_factory=set)
    submits: Set[FuncKey] = dataclasses.field(default_factory=set)
    accesses: List[Access] = dataclasses.field(default_factory=list)
    # (callee, locks held at the call site) — feeds the ``*_locked``
    # caller-held credit (one entry per call expression, so the
    # intersection over edges is over ALL call sites)
    call_held: List[Tuple[FuncKey, frozenset]] = dataclasses.field(
        default_factory=list
    )


# ---------------------------------------------------------------------------
# Registry parsing + resolution (HS603)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Entry:
    path: str
    lock_spec: str
    policy: str
    why: str
    line: int
    state: Optional[StateId] = None  # resolved
    lock: Optional[LockId] = None  # resolved (None for lock-free policies)


def parse_registry(project: Project) -> Tuple[List[Entry], int]:
    """(entries, registry line) from the SHARED_STATE literal in
    ``concurrency.py``; ([], 0) when the module or literal is absent."""
    sf = project.file(REGISTRY_FILE)
    if sf is None or sf.tree is None:
        return [], 0
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            targets = [node.target.id]
        else:
            continue
        if "SHARED_STATE" not in targets or not isinstance(node.value, ast.Dict):
            continue
        entries: List[Entry] = []
        for k, v in zip(node.value.keys, node.value.values):
            key = const_str(k) if k is not None else None
            if key is None:
                continue
            lock = policy = why = ""
            if isinstance(v, (ast.Tuple, ast.List)) and len(v.elts) >= 3:
                lock = const_str(v.elts[0]) or ""
                policy = const_str(v.elts[1]) or ""
                why = const_str(v.elts[2]) or ""
            entries.append(Entry(key, lock, policy, why, v.lineno))
        return entries, node.lineno
    return [], 0


class _PkgIndex:
    """Per-module facts the checker needs beyond locks._ModuleIndex:
    module-level assigned globals (with mutability), class attribute
    assigns, and nested-def-aware function records."""

    def __init__(self, project: Project):
        self.project = project
        self.pkg = os.path.basename(project.package_dir)
        # share the memoized lock model with the HS5xx pass
        self.locks_idx, self.all_locks = _locks._model(project)[:2]
        # rel -> {global name -> (line, is_mutable_literal)}
        self.module_globals: Dict[str, Dict[str, Tuple[int, bool]]] = {}
        # rel -> {class -> set of self-assigned attrs}
        self.class_attrs: Dict[str, Dict[str, Set[str]]] = {}
        for rel, sf in project.files.items():
            g: Dict[str, Tuple[int, bool]] = {}
            cattrs: Dict[str, Set[str]] = {}
            if sf.tree is not None:
                for node in sf.tree.body:
                    tgts: List[ast.expr] = []
                    val: Optional[ast.AST] = None
                    if isinstance(node, ast.Assign):
                        tgts, val = node.targets, node.value
                    elif isinstance(node, ast.AnnAssign) and node.value is not None:
                        tgts, val = [node.target], node.value
                    for t in tgts:
                        if isinstance(t, ast.Name) and not _is_nonshared(val):
                            g.setdefault(
                                t.id, (node.lineno, _is_mutable_literal(val))
                            )
                    if isinstance(node, ast.ClassDef):
                        attrs: Set[str] = set()
                        for sub in ast.walk(node):
                            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                                sub_t = (
                                    sub.targets
                                    if isinstance(sub, ast.Assign)
                                    else [sub.target]
                                )
                                for t in sub_t:
                                    if (
                                        isinstance(t, ast.Attribute)
                                        and isinstance(t.value, ast.Name)
                                        and t.value.id == "self"
                                    ):
                                        attrs.add(t.attr)
                        cattrs[node.name] = attrs
            self.module_globals[rel] = g
            self.class_attrs[rel] = cattrs

    def rel_for(self, qualified_mod: str) -> Optional[str]:
        if qualified_mod == self.pkg:
            return "__init__.py" if "__init__.py" in self.project.files else None
        if not qualified_mod.startswith(self.pkg + "."):
            return None
        tail = qualified_mod[len(self.pkg) + 1 :].replace(".", "/")
        for cand in (f"{tail}.py", f"{tail}/__init__.py"):
            if cand in self.project.files:
                return cand
        return None

    def resolve_state_path(self, path: str) -> Optional[StateId]:
        parts = path.split(".")
        if len(parts) < 2 or parts[0] != self.pkg:
            return None
        # longest module prefix first: "a.b.c.d" tries module a.b.c
        # (global d), then a.b (Class c, attr d)
        for i in range(len(parts) - 1, 0, -1):
            rel = self.rel_for(".".join(parts[:i]))
            if rel is None:
                continue
            rest = parts[i:]
            if len(rest) == 1:
                if rest[0] in self.module_globals.get(rel, {}):
                    return ("mod", rel, rest[0])
            elif len(rest) == 2:
                if rest[0] in self.class_attrs.get(rel, {}) and rest[1] in (
                    self.class_attrs[rel][rest[0]]
                ):
                    return ("cls", rel, rest[0], rest[1])
            return None
        return None

    def resolve_lock_spec(
        self, spec: str, state: Optional[StateId]
    ) -> Optional[LockId]:
        if spec.startswith("self."):
            if state is None or state[0] != "cls":
                return None
            _, rel, cls, _attr = state
            attr = spec[len("self.") :]
            if attr in self.locks_idx[rel].class_locks.get(cls, ()):
                return (f"cls:{rel}:{cls}", attr)
            # a lock the class INHERITS resolves to the base's identity
            # (locks._link_inherited_locks), so `self._lock` entries on
            # a subclass audit against the one real lock object
            return self.locks_idx[rel].inherited_locks.get(cls, {}).get(attr)
        parts = spec.split(".")
        for i in range(len(parts) - 1, 0, -1):
            rel = self.rel_for(".".join(parts[:i]))
            if rel is None:
                continue
            rest = parts[i:]
            if len(rest) == 1 and rest[0] in self.locks_idx[rel].module_locks:
                return (f"mod:{rel}", rest[0])
            return None
        return None


def _is_mutable_literal(node: Optional[ast.AST]) -> bool:
    if isinstance(
        node,
        (ast.Dict, ast.List, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp),
    ):
        return True
    if isinstance(node, ast.Call):
        name = dotted_name(node.func) or ""
        return name.split(".")[-1] in _MUTABLE_CTORS
    return False


def _is_nonshared(node: Optional[ast.AST]) -> bool:
    if isinstance(node, ast.Call):
        name = dotted_name(node.func) or ""
        return name.split(".")[-1] in _NONSHARED_CTORS
    return False


# ---------------------------------------------------------------------------
# Function analysis: accesses, calls, submit targets, held locks
# ---------------------------------------------------------------------------


def _scope_stmts(body: List[ast.stmt]):
    """Every statement of one function scope, stopping at nested
    def/class boundaries (those are their own scopes)."""
    for node in body:
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                yield from _scope_stmts([child])
            elif isinstance(child, ast.ExceptHandler):
                yield from _scope_stmts(child.body)


def _local_names(fn: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(locals, global-declared) of one function body — locals are
    params plus every name bound in THIS scope (assignments, for/with
    targets, imports, nested def/class names, except aliases), minus
    ``global``-declared ones. Nested scopes do not leak in."""
    locals_: Set[str] = set()
    globals_: Set[str] = set()
    args = fn.args
    for a in (
        list(args.posonlyargs)
        + list(args.args)
        + list(args.kwonlyargs)
        + ([args.vararg] if args.vararg else [])
        + ([args.kwarg] if args.kwarg else [])
    ):
        locals_.add(a.arg)
    for node in _scope_stmts(fn.body):
        if isinstance(node, ast.Global):
            globals_.update(node.names)
        elif isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            locals_.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                locals_.add((a.asname or a.name).split(".")[0])
        else:
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.expr):
                    for n in ast.walk(sub):
                        if isinstance(n, ast.Name) and isinstance(
                            n.ctx, (ast.Store, ast.Del)
                        ):
                            locals_.add(n.id)
            if isinstance(node, ast.ExceptHandler) and node.name:
                locals_.add(node.name)
    return locals_ - globals_, globals_


def _direct_nested_defs(fn: ast.AST) -> List[ast.AST]:
    """Nested defs of THIS scope, wherever they sit in the body (inside
    ``if``/``with``/``try`` blocks included), excluding deeper nesting."""
    return [
        n
        for n in _scope_stmts(fn.body)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]


class _FnWalker:
    """One function (or nested def / lambda) walked statement by
    statement, maintaining the held-lock stack exactly like
    ``locks._FuncAnalyzer`` and recording shared-state accesses, call
    edges and pool-submit targets."""

    def __init__(
        self,
        checker: "_Checker",
        info: FnInfo,
        idx: _ModuleIndex,
        cls: Optional[str],
        scope_chain: List[Set[str]],
        globals_decl: Set[str],
        nested_defs: Dict[str, FuncKey],
    ):
        self.checker = checker
        self.info = info
        self.idx = idx
        self.cls = cls
        self.scope_chain = scope_chain
        self.globals_decl = globals_decl
        self.nested_defs = nested_defs
        self.held: List[LockId] = []

    # -- resolution ---------------------------------------------------------
    def _is_local(self, name: str) -> bool:
        if name in self.globals_decl:
            return False
        return any(name in scope for scope in self.scope_chain)

    def _global_target(self, name: str) -> Optional[StateId]:
        """The module-global StateId ``name`` refers to at this site, or
        None (local/builtin/untracked)."""
        if self._is_local(name):
            return None
        if name in self.checker.pkg_idx.module_globals.get(self.info.rel, {}):
            return ("mod", self.info.rel, name)
        return None

    def _ref_target(self, node: ast.AST) -> Optional[StateId]:
        """StateId of an expression that names shared state: a bare
        global, ``self.attr``, or ``<module alias>.global``."""
        if isinstance(node, ast.Name):
            return self._global_target(node.id)
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            base = node.value.id
            if base == "self" and self.cls is not None:
                if node.attr in self.checker.pkg_idx.class_attrs.get(
                    self.info.rel, {}
                ).get(self.cls, ()):
                    return ("cls", self.info.rel, self.cls, node.attr)
                return None
            if not self._is_local(base) or base in self.idx.aliases:
                target = self.idx.aliases.get(base)
                if target:
                    rel2 = self.checker.pkg_idx.rel_for(target)
                    if rel2 is not None and node.attr in (
                        self.checker.pkg_idx.module_globals.get(rel2, {})
                    ):
                        return ("mod", rel2, node.attr)
        return None

    def _resolve_callable(self, node: ast.AST, depth: int = 0) -> Optional[FuncKey]:
        """FuncKey of a function-valued expression: nested def, module
        function, imported function (one re-export level followed), or
        ``self.method``."""
        if depth > 2:
            return None
        if isinstance(node, ast.Name):
            if node.id in self.nested_defs:
                return self.nested_defs[node.id]
            if not self._is_local(node.id):
                if node.id in self.idx.functions:
                    return (self.info.rel, None, node.id)
                target = self.idx.aliases.get(node.id)
                if target:
                    return self._resolve_qualified(target, depth)
            return None
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            base = node.value.id
            if base == "self" and self.cls is not None:
                if node.attr in self.idx.classes.get(self.cls, ()):
                    return (self.info.rel, self.cls, node.attr)
                return None
            target = self.idx.aliases.get(base)
            if target:
                return self._resolve_qualified(f"{target}.{node.attr}", depth)
        return None

    def _resolve_qualified(self, qualified: str, depth: int) -> Optional[FuncKey]:
        mod, _, leaf = qualified.rpartition(".")
        if not mod:
            return None
        rel2 = self.checker.pkg_idx.rel_for(mod)
        if rel2 is None:
            return None
        idx2 = self.checker.pkg_idx.locks_idx[rel2]
        if leaf in idx2.functions:
            return (rel2, None, leaf)
        if leaf in idx2.classes:
            return (rel2, leaf, "__init__")
        # one re-export hop: ``from .executor import execute`` in an
        # __init__ — the call graph must cross package facades
        reexport = idx2.aliases.get(leaf)
        if reexport and depth < 2:
            return self._resolve_qualified(reexport, depth + 1)
        return None

    # -- recording ----------------------------------------------------------
    def _record(self, state: StateId, line: int, kind: str) -> None:
        self.info.accesses.append(
            Access(state, line, kind, frozenset(self.held))
        )

    # -- statements ---------------------------------------------------------
    def run_body(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in stmt.items:
                lock = _resolve_lock(self.idx, self.cls, item.context_expr)
                if lock is not None:
                    self.held.append(lock)
                    acquired.append(lock)
                else:
                    self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._expr(item.optional_vars)
            for s in stmt.body:
                self._stmt(s)
            for lock in acquired:
                if lock in self.held:
                    self.held.remove(lock)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, not here — analyze it as its own
            # function with an EMPTY held set (a lock held at def time
            # is not held at call time)
            self.checker.analyze_function(
                stmt,
                self.info.rel,
                self.cls,
                f"{self.info.key[2]}.{stmt.name}",
                self.scope_chain,
                self.nested_defs,
            )
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._assign(stmt)
            return
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.stmt):
                self._stmt(node)
            elif isinstance(node, ast.expr):
                self._expr(node)
            elif isinstance(node, ast.ExceptHandler):
                for s in node.body:
                    self._stmt(s)

    def _assign(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.AnnAssign) and stmt.value is None:
            return  # annotation only: binds nothing
        aug = isinstance(stmt, ast.AugAssign)
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        if getattr(stmt, "value", None) is not None:
            self._expr(stmt.value)
        for t in targets:
            self._target(t, "mutate" if aug else "rebind")

    def _target(self, t: ast.expr, rebind_kind: str) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._target(el, rebind_kind)
            return
        if isinstance(t, ast.Starred):
            self._target(t.value, rebind_kind)
            return
        if isinstance(t, ast.Name):
            state = self._global_target(t.id)
            if state is not None:
                self._record(state, t.lineno, rebind_kind)
            return
        if isinstance(t, ast.Subscript):
            state = self._ref_target(t.value)
            if state is not None:
                self._record(state, t.lineno, "mutate")
            else:
                self._expr(t.value)
            self._expr(t.slice)
            return
        if isinstance(t, ast.Attribute):
            state = self._ref_target(t)
            if state is not None:
                self._record(state, t.lineno, rebind_kind)
            else:
                self._expr(t.value)

    # -- expressions --------------------------------------------------------
    def _expr(self, node: ast.AST) -> None:
        if isinstance(node, ast.Lambda):
            # runs later: empty held set, params shadow
            saved, self.held = self.held, []
            self.scope_chain.append(
                {a.arg for a in node.args.args + node.args.kwonlyargs}
            )
            self._expr(node.body)
            self.scope_chain.pop()
            self.held = saved
            return
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
        ):
            comp_locals: Set[str] = set()
            for gen in node.generators:
                for sub in ast.walk(gen.target):
                    if isinstance(sub, ast.Name):
                        comp_locals.add(sub.id)
            self.scope_chain.append(comp_locals)
            for gen in node.generators:
                self._expr(gen.iter)
                for cond in gen.ifs:
                    self._expr(cond)
            if isinstance(node, ast.DictComp):
                self._expr(node.key)
                self._expr(node.value)
            else:
                self._expr(node.elt)
            self.scope_chain.pop()
            return
        if isinstance(node, ast.Call):
            self._call(node)
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                state = self._global_target(node.id)
                if state is not None:
                    self._record(state, node.lineno, "read")
            return
        if isinstance(node, ast.Attribute):
            state = self._ref_target(node)
            if state is not None:
                kind = (
                    "read" if isinstance(node.ctx, ast.Load) else "rebind"
                )
                self._record(state, node.lineno, kind)
                return
            self._expr(node.value)
            return
        if isinstance(node, ast.Subscript):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                state = self._ref_target(node.value)
                if state is not None:
                    self._record(state, node.lineno, "mutate")
                else:
                    self._expr(node.value)
            else:
                self._expr(node.value)
            self._expr(node.slice)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _call(self, call: ast.Call) -> None:
        f = call.func
        if isinstance(f, ast.Attribute):
            # lock protocol
            if f.attr in ("acquire", "release"):
                lock = _resolve_lock(self.idx, self.cls, f.value)
                if lock is not None:
                    if f.attr == "acquire":
                        self.held.append(lock)
                    elif lock in self.held:
                        self.held.remove(lock)
                    for a in list(call.args) + [k.value for k in call.keywords]:
                        self._expr(a)
                    return
            # pool boundary: <pool>.submit(fn, …) / <pool>.map(fn, …)
            if f.attr in ("submit", "map") and call.args:
                target = self._resolve_callable(call.args[0])
                if target is not None:
                    self.info.submits.add(target)
            # in-place mutation of shared state
            if f.attr in _MUTATORS:
                state = self._ref_target(f.value)
                if state is not None:
                    self._record(state, call.lineno, "mutate")
        callee = self._resolve_callable(f)
        if callee is not None:
            self.info.calls.add(callee)
            self.info.call_held.append((callee, frozenset(self.held)))
        self._expr(f)
        for a in call.args:
            self._expr(a)
        for k in call.keywords:
            self._expr(k.value)


class _Checker:
    def __init__(self, project: Project):
        self.project = project
        self.pkg_idx = _PkgIndex(project)
        self.infos: Dict[FuncKey, FnInfo] = {}

    def analyze(self) -> None:
        for rel, sf in self.project.files.items():
            if sf.tree is None:
                continue
            for node in sf.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.analyze_function(node, rel, None, node.name, [], {})
                elif isinstance(node, ast.ClassDef):
                    for m in node.body:
                        if isinstance(
                            m, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            self.analyze_function(
                                m, rel, node.name, m.name, [], {}
                            )

    def analyze_function(
        self,
        fn: ast.AST,
        rel: str,
        cls: Optional[str],
        qualname: str,
        outer_scopes: List[Set[str]],
        outer_nested: Dict[str, FuncKey],
    ) -> None:
        key: FuncKey = (rel, cls, qualname)
        sf = self.project.files[rel]
        info = FnInfo(key, rel, sf.rel_path)
        self.infos[key] = info
        locals_, globals_decl = _local_names(fn)
        nested = dict(outer_nested)
        for sub in _direct_nested_defs(fn):
            nested[sub.name] = (rel, cls, f"{qualname}.{sub.name}")
        scope_chain = outer_scopes + [locals_]
        walker = _FnWalker(
            self,
            info,
            self.pkg_idx.locks_idx[rel],
            cls,
            scope_chain,
            globals_decl,
            nested,
        )
        walker.run_body(fn.body)

    # -- reachability -------------------------------------------------------
    def pool_reachable(self) -> Set[FuncKey]:
        roots: Set[FuncKey] = set()
        for info in self.infos.values():
            roots |= info.submits
        seen: Set[FuncKey] = set()
        frontier = [k for k in roots if k in self.infos]
        while frontier:
            k = frontier.pop()
            if k in seen:
                continue
            seen.add(k)
            for callee in self.infos[k].calls:
                if callee in self.infos and callee not in seen:
                    frontier.append(callee)
        return seen

    # -- candidates ---------------------------------------------------------
    def candidate_globals(self) -> Set[StateId]:
        """Module globals that are real cross-thread hazards: assigned at
        module level (non-lock, non-threadlocal) AND written by at least
        one function anywhere in the package. Never-written module dicts
        (KERNEL_TWINS, allowlists, …) are config, not state."""
        written: Set[StateId] = set()
        for info in self.infos.values():
            for a in info.accesses:
                if a.state[0] == "mod" and a.kind in ("rebind", "mutate"):
                    written.add(a.state)
        return written


def _state_name(state: StateId) -> str:
    if state[0] == "mod":
        return f"{state[1]}::{state[2]}"
    return f"{state[1]}::{state[2]}.{state[3]}"


def _locked_credits(checker: "_Checker") -> Dict[FuncKey, frozenset]:
    """Caller-held lock credit for ``*_locked`` functions.

    The codebase convention (``_dispatch_pending_locked``,
    ``_fast_cache_get_locked``, …): a ``_locked`` suffix promises "my
    caller holds the lock". This VERIFIES the promise instead of
    trusting it — the credit is the INTERSECTION of the locks held at
    every resolved call site (inheritance-aware: a call through a
    base-class method key may dispatch to a subclass override), so one
    lock-free call site voids the credit and HS602 fires at the access.
    Pool-submitted ``_locked`` callables get no credit (they run with
    an empty held set by definition), and a ``_locked`` function with
    no resolved call sites gets none either. Fixpoint: a ``_locked``
    caller's own credit counts at its call sites, so helper chains
    (``a_locked`` -> ``b_locked``) resolve; credits only grow, so the
    iteration terminates."""
    submitted: Set[FuncKey] = set()
    for info in checker.infos.values():
        submitted |= info.submits
    locked_keys = [
        k
        for k in checker.infos
        if k[2].endswith("_locked") and k not in submitted
    ]
    if not locked_keys:
        return {}
    # callee key -> every *_locked key it may dispatch to: itself, plus
    # any subclass override of the same method name
    dispatch: Dict[FuncKey, List[FuncKey]] = {}
    for k in locked_keys:
        rel, cls, name = k
        dispatch.setdefault(k, []).append(k)
        if cls is None:
            continue
        ancestors = checker.pkg_idx.locks_idx[rel].resolved_bases.get(
            cls, set()
        )
        for arel, acls in ancestors:
            dispatch.setdefault((arel, acls, name), []).append(k)
    # k -> [(caller, held at call site)]
    edges: Dict[FuncKey, List[Tuple[FuncKey, frozenset]]] = {}
    for caller, info in checker.infos.items():
        for callee, held in info.call_held:
            for k in dispatch.get(callee, ()):
                edges.setdefault(k, []).append((caller, held))
    credits: Dict[FuncKey, frozenset] = {}
    changed = True
    while changed:
        changed = False
        for k in locked_keys:
            sites = edges.get(k)
            if not sites:
                continue
            inter: Optional[frozenset] = None
            for caller, held in sites:
                eff = held | credits.get(caller, frozenset())
                inter = eff if inter is None else inter & eff
            if inter and inter != credits.get(k, frozenset()):
                credits[k] = inter
                changed = True
    return credits


def check(project: Project) -> List[Finding]:
    entries, reg_line = parse_registry(project)
    checker = _Checker(project)
    checker.analyze()
    pkg_idx = checker.pkg_idx
    reg_sf = project.file(REGISTRY_FILE)
    reg_path = reg_sf.rel_path if reg_sf is not None else REGISTRY_FILE
    findings: List[Finding] = []

    # -- HS603: the registry must resolve -----------------------------------
    registered: Dict[StateId, Entry] = {}
    for e in entries:
        ok = True
        e.state = pkg_idx.resolve_state_path(e.path)
        if e.state is None:
            findings.append(
                Finding(
                    "HS603",
                    reg_path,
                    e.line,
                    f"SHARED_STATE entry {e.path!r} names no module global "
                    "or class attribute in the package (stale registry?)",
                )
            )
            ok = False
        policy_ok = e.policy in POLICIES
        if not policy_ok:
            findings.append(
                Finding(
                    "HS603",
                    reg_path,
                    e.line,
                    f"{e.path}: unknown policy {e.policy!r} "
                    f"(have {', '.join(POLICIES)})",
                )
            )
            ok = False
        if not e.why.strip():
            findings.append(
                Finding(
                    "HS603",
                    reg_path,
                    e.line,
                    f"{e.path}: missing justification — every registry "
                    "entry must say why its policy is sound",
                )
            )
            ok = False
        needs_lock = policy_ok and e.policy in ("guarded", "guarded-writes")
        if needs_lock:
            e.lock = pkg_idx.resolve_lock_spec(e.lock_spec, e.state)
            if e.lock is None:
                findings.append(
                    Finding(
                        "HS603",
                        reg_path,
                        e.line,
                        f"{e.path}: declared lock {e.lock_spec!r} does not "
                        "resolve to a threading.Lock/RLock in the package",
                    )
                )
                ok = False
        elif policy_ok and e.lock_spec:
            findings.append(
                Finding(
                    "HS603",
                    reg_path,
                    e.line,
                    f"{e.path}: policy {e.policy!r} takes no lock, got "
                    f"{e.lock_spec!r}",
                )
            )
            ok = False
        if ok and e.state is not None:
            registered[e.state] = e

    # -- HS601: unregistered shared state reachable from a pool -------------
    reachable = checker.pool_reachable()
    candidates = checker.candidate_globals()
    seen_601: Set[Tuple[StateId, str]] = set()
    for key in sorted(reachable, key=str):
        info = checker.infos[key]
        for a in info.accesses:
            if a.state[0] != "mod" or a.state not in candidates:
                continue
            if a.state in registered:
                continue
            dedup = (a.state, info.rel_path)
            if dedup in seen_601:
                continue
            seen_601.add(dedup)
            findings.append(
                Finding(
                    "HS601",
                    info.rel_path,
                    a.line,
                    f"module global {a.state[2]!r} ({a.state[1]}) is "
                    f"{'written' if a.kind != 'read' else 'read'} from "
                    f"thread-pool-reachable {key[2]}() but has no "
                    "SHARED_STATE entry — declare its lock and policy in "
                    f"{REGISTRY_FILE}",
                )
            )

    # -- HS602: registered state must honor its policy ----------------------
    credits = _locked_credits(checker)
    seen_602: Set[Tuple[StateId, str, int]] = set()
    for key, info in sorted(checker.infos.items(), key=lambda kv: str(kv[0])):
        if key[1] is not None and key[2].split(".")[0] == "__init__":
            continue  # construction happens-before sharing
        credit = credits.get(key, frozenset())
        for a in info.accesses:
            e = registered.get(a.state)
            if e is None:
                continue
            held = a.held | credit
            bad: Optional[str] = None
            if e.policy == "guarded":
                if e.lock not in held:
                    bad = (
                        f"accessed without {e.lock_spec} held "
                        "(policy: guarded)"
                    )
            elif e.policy == "guarded-writes":
                if a.kind != "read" and e.lock not in held:
                    bad = (
                        f"written without {e.lock_spec} held "
                        "(policy: guarded-writes)"
                    )
            elif e.policy == "rebind-only":
                if a.kind == "mutate":
                    bad = (
                        "mutated in place (policy: rebind-only — build a "
                        "new object and publish it with one rebind)"
                    )
            elif e.policy == "frozen":
                if a.kind != "read" and key in reachable:
                    bad = (
                        "written from a thread-pool-reachable function "
                        "(policy: frozen — import-time registration only)"
                    )
            if bad is None:
                continue
            dedup = (a.state, info.rel_path, a.line)
            if dedup in seen_602:
                continue
            seen_602.add(dedup)
            findings.append(
                Finding(
                    "HS602",
                    info.rel_path,
                    a.line,
                    f"{_state_name(a.state)} {bad} in {key[2]}()",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# Lock-witness cross-check (``hslint --witness``)
# ---------------------------------------------------------------------------


def load_witness(path: str, doc: Optional[dict] = None) -> dict:
    """Parse a witness artifact; raises ValueError on a malformed one
    (the CLI maps that to a usage error — a corrupt artifact must never
    pass as 'zero model gaps', nor crash with a traceback). Pass a
    pre-parsed ``doc`` to validate it without re-reading the file (the
    CLI already parsed it to sniff the artifact kind)."""
    if doc is None:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    if not isinstance(doc, dict) or "locks" not in doc or "edges" not in doc:
        raise ValueError(f"not a lock-witness artifact: {path}")
    locks = doc["locks"]
    if not isinstance(locks, dict) or not all(
        isinstance(k, str) and isinstance(v, int) for k, v in locks.items()
    ):
        raise ValueError(f"malformed witness 'locks' map: {path}")
    edges = doc["edges"]
    if not isinstance(edges, list) or not all(
        isinstance(e, list)
        and len(e) >= 2
        and isinstance(e[0], str)
        and isinstance(e[1], str)
        for e in edges
    ):
        raise ValueError(f"malformed witness 'edges' list: {path}")
    return doc


def witness_cross_check(
    projects: List[Project], doc: dict, artifact: str
) -> Tuple[List[Finding], List[str]]:
    """(model-gap findings, staleness warnings) of a witness artifact
    against the static lock model — the UNION over ``projects`` when
    several package dirs are analyzed, since one artifact records every
    wrapped lock in the process.

    A WITNESSED acquisition edge (or lock) the static graph does not
    contain is a hard HS604 error: the runtime did something the model
    cannot see, so every ordering/guard verdict is suspect. A STATIC
    edge between two witnessed locks that was never observed is only a
    staleness warning — the stress suite may simply not have driven that
    path this run."""
    static_names: Set[str] = set()
    static_edges: Set[Tuple[str, str]] = set()
    for project in projects:
        all_locks, edges, _sites = _locks.build_lock_graph(project)
        static_names |= {canonical_lock_name(l) for l in all_locks}
        static_edges |= {
            (canonical_lock_name(a), canonical_lock_name(b))
            for a, targets in edges.items()
            for b in targets
        }
    findings: List[Finding] = []
    warnings: List[str] = []

    wit_locks: Dict[str, int] = dict(doc.get("locks", {}))
    for name in sorted(wit_locks):
        if name not in static_names:
            findings.append(
                Finding(
                    "HS604",
                    artifact,
                    1,
                    f"witnessed lock {name!r} is unknown to the static "
                    "model — a lock exists at runtime that the analyzer "
                    "cannot see",
                )
            )
    witnessed_edges: Set[Tuple[str, str]] = set()
    for edge in doc.get("edges", []):
        a, b = edge[0], edge[1]
        witnessed_edges.add((a, b))
        if (a, b) not in static_edges:
            findings.append(
                Finding(
                    "HS604",
                    artifact,
                    1,
                    f"witnessed acquisition edge {a} -> {b} is absent from "
                    "the static lock graph — the model has a gap; HS501's "
                    "cycle verdict cannot be trusted until it is closed",
                )
            )
    for a, b in sorted(static_edges):
        if a in wit_locks and b in wit_locks and (a, b) not in witnessed_edges:
            warnings.append(
                f"static lock edge never witnessed: {a} -> {b} — stale "
                "model or an unexercised path"
            )
    for entry, meta in sorted(doc.get("entries", {}).items()):
        lock = meta.get("lock")
        if lock and wit_locks.get(lock, 0) == 0:
            warnings.append(
                f"SHARED_STATE entry {entry}: declared lock {lock} was "
                "never acquired during the witnessed run — guard coverage "
                "gap"
            )
    return findings, warnings
