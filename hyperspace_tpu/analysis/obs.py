"""HS9xx — observability-site lints.

The obs plane (``hyperspace_tpu/obs/``, docs/observability.md) gives
every query a root span, every breakdown stage a child span and every
telemetry snapshot a registry instrument. Instrumentation has a failure
mode nothing else catches mechanically: it GROWS — a span per row in a
hot loop, a metric registered from a worker thread, a stage name
misspelled so the trace taxonomy silently forks from the breakdown keys
the querylog, bench gates and docs all key on. This checker makes the
instrumentation surface a declared contract, in the house registry
style (KERNEL_TWINS / SHARED_STATE / COLLECTIVE_SITES): every site is
in ``OBS_SITES`` (``obs/sites.py``) with a one-line justification.

* HS901 — a call that creates spans (``trace.root`` / ``trace.span`` /
  ``trace.stage``) or registers metrics (``registry.counter`` /
  ``gauge`` / ``labeled_counter`` / ``stage_timer`` /
  ``register_view`` / ``register_weak_view``) whose outermost
  enclosing function (or module, for import-time registration) has no
  ``OBS_SITES`` entry:
  undeclared instrumentation. Propagation shims (``trace.carry`` /
  ``activate``) and point events (``trace.event``) are exempt — they
  create no spans.
* HS902 — a CONSTANT span/stage name passed to ``trace.span`` /
  ``trace.stage`` that is not in the declared stage vocabulary
  (the ``*_STAGES`` tuples in ``obs/sites.py``), or a constant
  ``trace.root`` name not in ``ROOT_NAMES``: stage spans exist to
  mirror the breakdown keys — a drifted name forks the taxonomy.
* HS903 — a stale ``OBS_SITES`` entry: unresolved path, unknown kind,
  missing justification, or a declared site whose function no longer
  contains any obs primitive call.

The obs package itself (``obs/``) is exempt from HS901/902: it defines
the primitives and the vocabulary. Trees without an ``OBS_SITES``
registry skip the checker entirely (fixture mini-packages opt in by
shipping one).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.analysis.core import (
    Finding,
    Project,
    const_str,
    dotted_name,
)

RULES = {
    "HS901": "obs span/metric call site absent from OBS_SITES",
    "HS902": "span/stage name outside the declared stage vocabulary",
    "HS903": "stale OBS_SITES registry entry",
}

#: candidate homes of the OBS_SITES literal, first hit wins
REGISTRY_FILES = ("obs/sites.py", "sites.py")

KINDS = ("span", "metric", "view")

#: span-creating trace primitives (module alias must look like a trace
#: module) and metric-registering registry primitives
TRACE_PRIMS = frozenset({"root", "span", "stage"})
METRIC_PRIMS = frozenset(
    {
        "counter",
        "gauge",
        "labeled_counter",
        "stage_timer",
        "register_view",
        "register_weak_view",
    }
)
_TRACE_BASES = frozenset({"trace", "obs_trace", "_obs_trace"})
_METRIC_BASES = frozenset(
    {"registry", "metrics", "obs_metrics", "_obs_metrics"}
)


@dataclasses.dataclass
class SiteEntry:
    path: str
    kind: str
    why: str
    line: int


# ---------------------------------------------------------------------------
# Registry parsing
# ---------------------------------------------------------------------------


def registry_file(project: Project) -> Optional[str]:
    for rel in REGISTRY_FILES:
        sf = project.file(rel)
        if sf is None or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            targets: List[str] = []
            if isinstance(node, ast.Assign):
                targets = [
                    t.id for t in node.targets if isinstance(t, ast.Name)
                ]
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                targets = [node.target.id]
            if "OBS_SITES" in targets:
                return rel
    return None


def parse_sites(
    project: Project,
) -> Tuple[List[SiteEntry], Set[str], Set[str], Optional[str]]:
    """(entries, stage vocabulary, root names, registry rel) from the
    OBS_SITES literal + the ``*_STAGES`` / ``ROOT_NAMES`` tuples;
    ([], set(), set(), None) when absent — trees without an obs plane
    skip the checker."""
    rel = registry_file(project)
    if rel is None:
        return [], set(), set(), None
    sf = project.file(rel)
    entries: List[SiteEntry] = []
    stages: Set[str] = set()
    roots: Set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            targets = [node.target.id]
        else:
            continue
        for name in targets:
            if name == "OBS_SITES" and isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    key = const_str(k) if k is not None else None
                    if key is None:
                        continue
                    kind = why = ""
                    if isinstance(v, (ast.Tuple, ast.List)) and len(v.elts) >= 2:
                        kind = const_str(v.elts[0]) or ""
                        why = const_str(v.elts[1]) or ""
                    entries.append(SiteEntry(key, kind, why, v.lineno))
            elif name.endswith("_STAGES") and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                stages.update(
                    s for s in (const_str(e) for e in node.value.elts) if s
                )
            elif name == "ROOT_NAMES" and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                roots.update(
                    s for s in (const_str(e) for e in node.value.elts) if s
                )
    return entries, stages, roots, rel


# ---------------------------------------------------------------------------
# Package function index + primitive-call scan
# ---------------------------------------------------------------------------


def _module_dotted(project: Project, rel: str) -> str:
    import os

    pkg = os.path.basename(project.package_dir)
    mod = rel[: -len(".py")] if rel.endswith(".py") else rel
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    mod = mod.replace("/", ".")
    return pkg if mod in ("__init__", "") else f"{pkg}.{mod}"


@dataclasses.dataclass
class _Call:
    rel: str
    line: int
    prim: str  # primitive name (span/root/stage/counter/...)
    site: str  # dotted site path (function, method, or module)
    const_name: Optional[str]  # constant first arg, when present


def _is_obs_call(node: ast.Call) -> Optional[str]:
    """The primitive name when this call is an obs span/metric
    primitive, else None."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    base = dotted_name(f.value)
    if base is None:
        return None
    last = base.rsplit(".", 1)[-1]
    if f.attr in TRACE_PRIMS and last in _TRACE_BASES:
        return f.attr
    if f.attr in METRIC_PRIMS and last in _METRIC_BASES:
        return f.attr
    return None


def _scan_calls(project: Project) -> List[_Call]:
    """Every obs primitive call in the package (obs/ itself exempt),
    attributed to its outermost enclosing def/method or the module."""
    out: List[_Call] = []
    for rel, sf in sorted(project.files.items()):
        if sf.tree is None or rel.split("/", 1)[0] == "obs":
            continue
        mod = _module_dotted(project, rel)

        def visit(node, site: str, depth: int, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                child_site, child_depth, child_cls = site, depth, cls
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    if depth == 0:
                        child_site = (
                            f"{mod}.{cls}.{child.name}"
                            if cls
                            else f"{mod}.{child.name}"
                        )
                    child_depth = depth + 1
                elif isinstance(child, ast.ClassDef) and depth == 0:
                    child_cls = child.name
                elif isinstance(child, ast.Call):
                    prim = _is_obs_call(child)
                    if prim is not None:
                        cname = (
                            const_str(child.args[0]) if child.args else None
                        )
                        out.append(
                            _Call(rel, child.lineno, prim, site, cname)
                        )
                visit(child, child_site, child_depth, child_cls)

        visit(sf.tree, mod, 0, None)
    return out


def _resolvable_paths(project: Project) -> Set[str]:
    """Every dotted path an OBS_SITES entry may legally name: modules,
    top-level functions, and class methods."""
    paths: Set[str] = set()
    for rel, sf in project.files.items():
        if sf.tree is None:
            continue
        mod = _module_dotted(project, rel)
        paths.add(mod)
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                paths.add(f"{mod}.{node.name}")
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        paths.add(f"{mod}.{node.name}.{sub.name}")
    return paths


# ---------------------------------------------------------------------------
# Checker
# ---------------------------------------------------------------------------


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    entries, stages, roots, reg_rel = parse_sites(project)
    if reg_rel is None:
        return findings
    reg_sf = project.file(reg_rel)
    reg_path = reg_sf.rel_path if reg_sf is not None else reg_rel
    declared: Dict[str, SiteEntry] = {e.path: e for e in entries}
    calls = _scan_calls(project)
    called_sites: Set[str] = {c.site for c in calls}

    # -- HS901: every primitive call site is declared ------------------------
    for c in calls:
        if c.site in declared:
            continue
        sf = project.file(c.rel)
        findings.append(
            Finding(
                "HS901",
                sf.rel_path if sf is not None else c.rel,
                c.line,
                f"obs primitive '{c.prim}' called at {c.site!r} but the "
                "site has no OBS_SITES entry (obs/sites.py) — declare "
                "the span/metric site with a one-line justification",
            )
        )

    # -- HS902: constant names stay inside the vocabulary --------------------
    for c in calls:
        if c.const_name is None:
            continue
        if c.prim in ("span", "stage") and stages and c.const_name not in stages:
            sf = project.file(c.rel)
            findings.append(
                Finding(
                    "HS902",
                    sf.rel_path if sf is not None else c.rel,
                    c.line,
                    f"stage-span name {c.const_name!r} is not in the "
                    "declared stage vocabulary (obs/sites.py *_STAGES) — "
                    "span names must mirror the breakdown keys they "
                    "measure",
                )
            )
        elif c.prim == "root" and roots and c.const_name not in roots:
            sf = project.file(c.rel)
            findings.append(
                Finding(
                    "HS902",
                    sf.rel_path if sf is not None else c.rel,
                    c.line,
                    f"root-span name {c.const_name!r} is not in "
                    "ROOT_NAMES (obs/sites.py) — root names are the "
                    "trace taxonomy's top level",
                )
            )

    # -- HS903: registry entries stay live ------------------------------------
    resolvable = _resolvable_paths(project)
    for e in entries:
        if e.kind not in KINDS:
            findings.append(
                Finding(
                    "HS903",
                    reg_path,
                    e.line,
                    f"OBS_SITES entry {e.path!r} has unknown kind "
                    f"{e.kind!r} (want one of {KINDS})",
                )
            )
            continue
        if not e.why.strip():
            findings.append(
                Finding(
                    "HS903",
                    reg_path,
                    e.line,
                    f"OBS_SITES entry {e.path!r} has no justification — "
                    "every instrumented site says why in one line",
                )
            )
            continue
        if e.path not in resolvable:
            findings.append(
                Finding(
                    "HS903",
                    reg_path,
                    e.line,
                    f"OBS_SITES entry {e.path!r} does not resolve to a "
                    "module, function or method in the package — stale "
                    "registry entry",
                )
            )
            continue
        if e.path not in called_sites:
            findings.append(
                Finding(
                    "HS903",
                    reg_path,
                    e.line,
                    f"OBS_SITES entry {e.path!r} resolves but its site "
                    "issues no obs primitive call — stale entry (remove "
                    "it or restore the instrumentation)",
                )
            )
    return findings
