"""hslint — repo-native static analysis for hyperspace_tpu.

Ten checkers guard the correctness-critical seams nothing else checks
mechanically (see ``docs/static-analysis.md``):

* :mod:`kernel_parity` (HS1xx) — every native C++ export has a
  registered numpy twin and a differential test;
* :mod:`log_state` (HS2xx) — every Action's begin/commit edges are
  legal transitions of the operation-log state machine;
* :mod:`purity` (HS3xx) — no host numpy / host syncs inside traced
  (jit/shard_map) hot-path functions;
* :mod:`except_policy` (HS4xx) — no bare/overbroad excepts that can
  mask the native rc-code or OCC contracts;
* :mod:`locks` (HS5xx) — no lock-order cycles, no I/O under a lock;
* :mod:`shared_state` (HS6xx) — every mutable global a thread pool can
  reach is registered in ``SHARED_STATE`` (``concurrency.py``) and
  accessed per its declared lock/policy; ``--witness`` cross-checks the
  static lock model against a runtime witness artifact;
* :mod:`contracts` (HS7xx) — config keys have constants defaults and
  ``docs/CONFIG.md`` rows, fault points are matrix-tested, dead keys
  are flagged;
* :mod:`spmd` (HS8xx) — every collective call site declares its
  symmetry contract in ``COLLECTIVE_SITES``
  (``parallel/collectives.py``), process-identity branches and
  process-local loop bounds cannot make processes issue diverging
  collective programs, and ``--witness`` cross-checks the per-process
  runtime collective sequences recorded by
  ``testing/collective_witness.py``;
* :mod:`obs` (HS9xx) — every span/metric instrumentation site is
  declared in ``OBS_SITES`` (``obs/sites.py``) with a justification,
  constant span/stage names stay inside the declared breakdown-key
  vocabulary, and stale registry entries are flagged;
* :mod:`residency` (HS10xx) — every row-proportional hot-path
  materialization is declared in ``ALLOC_SITES`` (``memory.py``) with
  a plane and a structurally-enforced bound class, and ``--witness``
  cross-checks the per-site peak bytes recorded by
  ``testing/residency_witness.py`` against the declared bounds.

Run it: ``python -m hyperspace_tpu.analysis [package_dir]`` — exits
nonzero when any unsuppressed finding remains. Suppress a finding with
``# hslint: disable=<RULE>`` on (or directly above) the flagged line,
with a justification comment.

The analyzer is pure stdlib ``ast`` — importing this package never
imports jax/numpy, and the checked code is never executed.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from hyperspace_tpu.analysis import (
    contracts,
    except_policy,
    kernel_parity,
    locks,
    log_state,
    obs,
    purity,
    residency,
    shared_state,
    spmd,
)
from hyperspace_tpu.analysis.core import FINDING_FIELDS, Finding, Project

__all__ = [
    "Finding",
    "Project",
    "ALL_RULES",
    "CHECKERS",
    "FINDING_FIELDS",
    "run_analysis",
]

CHECKERS = (
    kernel_parity,
    log_state,
    purity,
    except_policy,
    locks,
    shared_state,
    contracts,
    spmd,
    obs,
    residency,
)

#: rule id -> one-line description; HS001 is the analyzer's own
#: parse-failure rule.
ALL_RULES: Dict[str, str] = {"HS001": "file does not parse"}
for _mod in CHECKERS:
    ALL_RULES.update(_mod.RULES)


def run_analysis(
    package_dir: str,
    tests_dir: Optional[str] = None,
    project: Optional[Project] = None,
) -> List[Finding]:
    """All findings (suppressed ones included, marked) for the package at
    ``package_dir``, sorted by (path, line, rule). Pass a prebuilt
    ``project`` to share the parsed tree with other passes (the CLI's
    ``--witness`` cross-check reuses it)."""
    if project is None:
        project = Project(package_dir, tests_dir=tests_dir)
    findings: List[Finding] = list(project.findings)
    for checker in CHECKERS:
        findings.extend(checker.check(project))
    by_display = {sf.rel_path: sf for sf in project.files.values()}
    for f in findings:
        sf = by_display.get(f.path)
        if sf is not None and sf.is_suppressed(f.rule, f.line):
            f.suppressed = True
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings
