"""HS3xx — hot-path purity inside traced (jit / shard_map / vmap) code.

A traced JAX function must stay on-device: a stray ``np.*`` array op
silently falls back to host numpy on concrete tracer values (or raises
a TracerArrayConversionError much later), and a host sync
(``block_until_ready``, ``.item()``, ``np.asarray``, ``float()`` on a
tracer, ``jax.device_get``) serializes the pipeline — exactly the class
of perf bug that bit the serve path before the dispatch-policy rework.

Scope: files under ``ops/``, ``execution/``, ``parallel/`` and
``rules/``. A function is *traced* when it is

* decorated with ``jax.jit`` / ``functools.partial(jax.jit, ...)`` /
  ``jax.vmap``, or
* passed by name to ``jax.jit(...)``, ``jax.vmap(...)`` or
  ``shard_map(...)`` anywhere in the same file.

Analysis covers the traced function's body including nested ``def``s
and lambdas (their bodies trace too). It deliberately does NOT follow
calls into helper functions: helpers like ``ops/hash.hash_words`` are
dtype-generic by design (shared between the numpy and device twins),
and flagging them would force a fork of every shared kernel.

Allowlist: ``np.<scalar-type>`` constructors (``np.uint32(4)`` makes a
host constant, which traces fine) and dtype/introspection helpers
(``np.iinfo``, ``np.dtype``, ``np.pi`` …).
"""

from __future__ import annotations

import ast
from typing import List, Set

from hyperspace_tpu.analysis.core import Finding, Project, dotted_name

RULES = {
    "HS301": "numpy call inside a traced (jit/shard_map/vmap) function",
    "HS302": "host synchronization inside a traced function",
}

HOT_DIRS = ("ops", "execution", "parallel", "rules")

#: np.<attr> uses that are pure host constants / introspection — safe
#: under trace.
NP_ALLOWED = {
    "uint8", "uint16", "uint32", "uint64",
    "int8", "int16", "int32", "int64", "intp",
    "float16", "float32", "float64", "bool_",
    "dtype", "iinfo", "finfo", "issubdtype",
    "pi", "e", "inf", "nan", "newaxis", "errstate",
}

#: method names whose call on a traced value forces a host sync
SYNC_METHODS = {"block_until_ready", "item", "tolist"}

#: np.<attr> calls that are host syncs rather than plain numpy ops
NP_SYNC = {"asarray", "array", "save", "savez"}

_TRACERS = ("jit", "vmap", "shard_map", "pmap")


def _is_tracer_expr(node: ast.AST) -> bool:
    """True for ``jax.jit``, ``jax.vmap``, ``shard_map``, and
    ``(functools.)partial(jax.jit, ...)`` expressions."""
    name = dotted_name(node)
    if name:
        leaf = name.split(".")[-1]
        if leaf in _TRACERS:
            return True
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn and fn.split(".")[-1] == "partial" and node.args:
            return _is_tracer_expr(node.args[0])
        return _is_tracer_expr(node.func)
    return False


def _traced_names(tree: ast.AST) -> Set[str]:
    """Names of functions passed (by name) to a tracer call anywhere in
    the file: ``x = jax.jit(f)``, ``shard_map(local, ...)`` …"""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_tracer_expr(node.func):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name):
                    out.add(arg.id)
    return out


def _traced_functions(tree: ast.AST) -> List[ast.FunctionDef]:
    by_call = _traced_names(tree)
    traced = [
        node
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and (
            node.name in by_call
            or any(_is_tracer_expr(d) for d in node.decorator_list)
        )
    ]
    # drop functions nested inside another traced function — the parent's
    # body walk already covers them (avoids duplicate findings)
    nested = set()
    for fn in traced:
        for sub in ast.walk(fn):
            if sub is not fn and isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                nested.add(id(sub))
    return [fn for fn in traced if id(fn) not in nested]


def _annotation_nodes(fn: ast.FunctionDef) -> Set[int]:
    """ids of every node inside a type annotation anywhere under ``fn``
    (parameter/return annotations of fn and nested defs, AnnAssign
    targets): annotations never execute under trace, so ``np.ndarray``
    there must not flag."""
    roots: List[ast.AST] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            for a in (
                list(args.posonlyargs)
                + list(args.args)
                + list(args.kwonlyargs)
                + [args.vararg, args.kwarg]
            ):
                if a is not None and a.annotation is not None:
                    roots.append(a.annotation)
            if node.returns is not None:
                roots.append(node.returns)
        elif isinstance(node, ast.AnnAssign):
            roots.append(node.annotation)
    return {id(n) for root in roots for n in ast.walk(root)}


def _check_body(fn: ast.FunctionDef, sf_path: str) -> List[Finding]:
    findings: List[Finding] = []
    skip = _annotation_nodes(fn)
    # walk only the body: decorators and annotations are def-time (or
    # no-op) constructs, never traced
    for node in [
        n
        for stmt in fn.body
        for n in ast.walk(stmt)
        if id(n) not in skip
    ]:
        # np.<attr> access
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("np", "numpy")
        ):
            if node.attr in NP_SYNC:
                findings.append(
                    Finding(
                        "HS302",
                        sf_path,
                        node.lineno,
                        f"np.{node.attr} in traced function "
                        f"{fn.name!r} forces a host transfer/sync",
                    )
                )
            elif node.attr not in NP_ALLOWED:
                findings.append(
                    Finding(
                        "HS301",
                        sf_path,
                        node.lineno,
                        f"np.{node.attr} in traced function {fn.name!r} — "
                        "use jnp (host numpy silently degrades or fails on "
                        "tracers)",
                    )
                )
        if isinstance(node, ast.Call):
            # .block_until_ready() / .item() / .tolist()
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_METHODS
            ):
                findings.append(
                    Finding(
                        "HS302",
                        sf_path,
                        node.lineno,
                        f".{node.func.attr}() in traced function "
                        f"{fn.name!r} is a host sync",
                    )
                )
            fname = dotted_name(node.func)
            if fname == "jax.device_get":
                findings.append(
                    Finding(
                        "HS302",
                        sf_path,
                        node.lineno,
                        f"jax.device_get in traced function {fn.name!r} "
                        "is a host sync",
                    )
                )
            # float(x)/int(x)/bool(x) on a non-literal concretizes a tracer
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and node.args
                and not isinstance(node.args[0], ast.Constant)
            ):
                findings.append(
                    Finding(
                        "HS302",
                        sf_path,
                        node.lineno,
                        f"{node.func.id}() on a traced value in "
                        f"{fn.name!r} concretizes the tracer (host sync)",
                    )
                )
    return findings


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for _rel, sf in project.files_under(*HOT_DIRS):
        if sf.tree is None:
            continue
        for fn in _traced_functions(sf.tree):
            findings.extend(_check_body(fn, sf.rel_path))
    return findings
