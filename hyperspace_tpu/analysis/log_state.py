"""HS2xx — operation-log state-machine conformance.

The index lifecycle is a state machine defined once, in
``constants.States`` (the states, the stable subset, and the ROLLBACK
map from each transient state to the stable state it recovers to — the
machine ``metadata/entry.py``'s ``LogEntry.state`` field ranges over).
Every Action in ``actions/*`` declares its edges as class attributes:
``begin()`` writes ``transient_state``, commit writes ``final_state``,
and ``required_state`` (where present) is the stable state the action
validates against before beginning.

Legal edges, derived statically from the States class:

* begin:   ROLLBACK[T] -> T   — so T must be a ROLLBACK key, or a crash
  mid-action leaves the index in a state ``cancel()`` cannot recover
  (HS201: unguarded transient);
* commit:  T -> F with F in STABLE_STATES (HS202);
* every state name referenced in actions/ or metadata/ must be a
  member of States (HS203 — catches typos that would otherwise become
  permanently wedged log entries);
* where an action declares ``required_state``, it must equal
  ROLLBACK[transient_state]: validating against any other state makes
  the begin edge illegal (HS204);
* a ROLLBACK key no action uses as its transient state is dead machine
  surface (HS205) — either a missing action or a stale state;
* every ROLLBACK edge must LAND on a stable state (HS206): the rollback
  edges are exactly what crash recovery (``metadata/recovery.py``) and
  ``cancel()`` traverse, and an edge into another transient state would
  make "recover" mean "strand differently".
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.analysis.core import Finding, Project, const_str

RULES = {
    "HS201": "action transient state has no ROLLBACK edge (cancel cannot recover)",
    "HS202": "action final state is not a stable state",
    "HS203": "unknown state name referenced in a transition site",
    "HS204": "required_state does not match the transient state's ROLLBACK source",
    "HS205": "transient state defined in ROLLBACK but used by no action",
    "HS206": "ROLLBACK edge lands on a non-stable state (recovery would strand)",
}


class StateMachine:
    def __init__(self):
        self.states: Dict[str, str] = {}  # attr name -> string value
        self.stable: Set[str] = set()  # attr names
        self.rollback: Dict[str, str] = {}  # transient attr -> stable attr


def _extract_machine(project: Project) -> Optional[Tuple[StateMachine, str]]:
    sf = project.file("constants.py")
    if sf is None or sf.tree is None:
        return None
    cls = next(
        (
            n
            for n in ast.walk(sf.tree)
            if isinstance(n, ast.ClassDef) and n.name == "States"
        ),
        None,
    )
    if cls is None:
        return None
    m = StateMachine()
    for node in cls.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if (val := const_str(node.value)) is not None:
            m.states[target.id] = val
        elif target.id == "STABLE_STATES":
            for n in ast.walk(node.value):
                if isinstance(n, ast.Name) and n.id in m.states:
                    m.stable.add(n.id)
        elif target.id == "ROLLBACK" and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Name) and isinstance(v, ast.Name):
                    m.rollback[k.id] = v.id
    return m, sf.rel_path


def _state_attr(node: ast.AST) -> Optional[Tuple[str, int]]:
    """('CREATING', line) for a ``States.CREATING`` attribute node."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "States"
    ):
        return node.attr, node.lineno
    return None


def check(project: Project) -> List[Finding]:
    got = _extract_machine(project)
    action_files = project.files_under("actions")
    if got is None or not action_files:
        return []
    machine, constants_path = got
    findings: List[Finding] = []
    used_transients: Set[str] = set()

    for _rel, sf in action_files + project.files_under("metadata"):
        if sf.tree is None:
            continue
        # HS203 over every States.X reference in the file
        for node in ast.walk(sf.tree):
            ref = _state_attr(node)
            if ref is None:
                continue
            name, line = ref
            if name not in machine.states and name not in (
                "STABLE_STATES",
                "ROLLBACK",
            ):
                findings.append(
                    Finding(
                        "HS203",
                        sf.rel_path,
                        line,
                        f"States.{name} is not a defined lifecycle state",
                    )
                )
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            attrs: Dict[str, Tuple[Optional[str], int]] = {}
            for node in cls.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if isinstance(t, ast.Name) and t.id in (
                        "transient_state",
                        "final_state",
                        "required_state",
                    ):
                        ref = _state_attr(node.value)
                        if ref is not None:
                            attrs[t.id] = (ref[0], ref[1])
                        elif const_str(node.value) == "":
                            attrs[t.id] = (None, node.lineno)
            transient = attrs.get("transient_state")
            final = attrs.get("final_state")
            required = attrs.get("required_state")
            if transient and transient[0] is not None:
                used_transients.add(transient[0])
                if transient[0] not in machine.rollback:
                    findings.append(
                        Finding(
                            "HS201",
                            sf.rel_path,
                            transient[1],
                            f"{cls.name}: transient state "
                            f"States.{transient[0]} has no ROLLBACK edge — a "
                            "crash mid-action cannot be cancel()ed",
                        )
                    )
            if final and final[0] is not None and final[0] not in machine.stable:
                findings.append(
                    Finding(
                        "HS202",
                        sf.rel_path,
                        final[1],
                        f"{cls.name}: final state States.{final[0]} is not in "
                        "STABLE_STATES — the commit edge leaves the log "
                        "unstable",
                    )
                )
            if (
                required
                and required[0] is not None
                and transient
                and transient[0] is not None
                and machine.rollback.get(transient[0]) is not None
                and machine.rollback[transient[0]] != required[0]
            ):
                findings.append(
                    Finding(
                        "HS204",
                        sf.rel_path,
                        required[1],
                        f"{cls.name}: requires States.{required[0]} but "
                        f"States.{transient[0]} rolls back to "
                        f"States.{machine.rollback[transient[0]]} — begin "
                        "edge and rollback edge disagree",
                    )
                )
    for t in sorted(machine.rollback):
        if t not in used_transients:
            findings.append(
                Finding(
                    "HS205",
                    constants_path,
                    1,
                    f"ROLLBACK defines transient state {t} but no Action "
                    "uses it (unreachable state)",
                )
            )
        if machine.rollback[t] not in machine.stable:
            findings.append(
                Finding(
                    "HS206",
                    constants_path,
                    1,
                    f"ROLLBACK edge {t} -> {machine.rollback[t]} lands on "
                    "a non-stable state — crash recovery and cancel() "
                    "walk these edges and must terminate on a stable "
                    "state",
                )
            )
    return findings
