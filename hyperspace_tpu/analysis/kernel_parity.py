"""HS1xx — native kernel / numpy-twin parity.

The native C++ kernels (``native/hs_native.cpp``) are trusted only
because each one has a numpy twin with bit-identical semantics and a
differential test comparing the two (the Flare doctrine: a native fast
path is only as good as its systematic parity check against the
reference engine). This checker turns that contract into lint:

* every ``extern "C"`` export must appear in the ``KERNEL_TWINS``
  registry in ``native/__init__.py`` (HS101), and every registry entry
  must name a real export (HS102);
* the registered wrapper must be defined in ``native/__init__.py`` and
  the registered numpy twin must resolve — either a ``numpy.*`` function
  or a dotted path into the package whose target function exists
  (HS103);
* at least one file under ``tests/`` must reference the export or its
  wrapper, so the parity claim is actually exercised (HS104);
* a FUSED-PIPELINE export (``hs_fused_*``) must register an in-package
  interpreted twin — the op chain the fused pass replaces — not a
  ``numpy.*`` single op: a single-op twin cannot witness whole-pipeline
  parity (HS105). This is the KERNEL_TWINS doctrine generalized from
  kernels to pipelines (docs/serve-compiler.md).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.analysis.core import Finding, Project, const_str

RULES = {
    "HS101": "native export missing from the KERNEL_TWINS parity registry",
    "HS102": "KERNEL_TWINS entry names a symbol not exported by hs_native.cpp",
    "HS103": "KERNEL_TWINS wrapper or numpy twin does not resolve",
    "HS104": "native kernel has no differential test referencing it",
    "HS105": "fused-pipeline export needs an in-package interpreted twin",
}

# A C export: one or more type tokens, then an hs_-prefixed identifier,
# then an argument list — anchored at line start so call sites inside
# kernel bodies don't match.
_EXPORT_RE = re.compile(
    r"^(?:[A-Za-z_][A-Za-z0-9_]*\s+)+\**(hs_[A-Za-z0-9_]+)\s*\(", re.MULTILINE
)


def cpp_exports(cpp_text: str) -> List[str]:
    """Exported symbol names: line-anchored ``hs_``-prefixed function
    definitions. The ``hs_`` prefix is the export convention (internal
    helpers are unprefixed/static), so no brace tracking of the
    ``extern "C"`` block is needed — and brace counting through comments
    and string literals is exactly the kind of fragile parsing a linter
    should avoid."""
    out: List[str] = []
    for m in _EXPORT_RE.finditer(cpp_text):
        if m.group(1) not in out:
            out.append(m.group(1))
    return out


def _registry(tree: ast.AST) -> Optional[Tuple[int, Dict[str, Tuple[str, str]]]]:
    """(line, {export: (wrapper, twin)}) from the KERNEL_TWINS literal."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "KERNEL_TWINS" not in targets or not isinstance(node.value, ast.Dict):
            continue
        entries: Dict[str, Tuple[str, str]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            key = const_str(k) if k is not None else None
            if key is None:
                continue
            wrapper = twin = ""
            if isinstance(v, (ast.Tuple, ast.List)) and len(v.elts) >= 2:
                wrapper = const_str(v.elts[0]) or ""
                twin = const_str(v.elts[1]) or ""
            entries[key] = (wrapper, twin)
        return node.lineno, entries
    return None


def _twin_resolves(project: Project, twin: str) -> bool:
    if twin.startswith("numpy."):
        return True  # external reference twin; parity proven by the tests
    pkg = os.path.basename(project.package_dir)
    if not twin.startswith(pkg + "."):
        return False
    parts = twin[len(pkg) + 1 :].split(".")
    if len(parts) < 2:
        return False
    mod_rel, func = "/".join(parts[:-1]) + ".py", parts[-1]
    sf = project.file(mod_rel)
    if sf is None or sf.tree is None:
        return False
    return any(
        isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n.name == func
        for n in ast.walk(sf.tree)
    )


def check(project: Project) -> List[Finding]:
    cpp = project.native_cpp_path()
    native_sf = project.file("native/__init__.py")
    if cpp is None or native_sf is None or native_sf.tree is None:
        return []  # no native layer in this tree: nothing to check
    with open(cpp, "r", encoding="utf-8") as f:
        exports = cpp_exports(f.read())
    reg = _registry(native_sf.tree)
    findings: List[Finding] = []
    if reg is None:
        findings.append(
            Finding(
                "HS101",
                native_sf.rel_path,
                1,
                "no KERNEL_TWINS registry found; every native export needs a "
                f"registered numpy twin (exports: {', '.join(exports)})",
            )
        )
        return findings
    reg_line, entries = reg
    wrappers_defined = {
        n.name
        for n in ast.walk(native_sf.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    tests = project.test_files()
    for export in exports:
        if export not in entries:
            findings.append(
                Finding(
                    "HS101",
                    native_sf.rel_path,
                    reg_line,
                    f"native export {export!r} has no KERNEL_TWINS entry "
                    "(wrapper + numpy twin)",
                )
            )
            continue
        wrapper, twin = entries[export]
        if wrapper not in wrappers_defined:
            findings.append(
                Finding(
                    "HS103",
                    native_sf.rel_path,
                    reg_line,
                    f"{export}: registered wrapper {wrapper!r} is not defined "
                    "in native/__init__.py",
                )
            )
        if not twin or not _twin_resolves(project, twin):
            findings.append(
                Finding(
                    "HS103",
                    native_sf.rel_path,
                    reg_line,
                    f"{export}: numpy twin {twin!r} does not resolve "
                    "(expected numpy.<fn> or a dotted in-package function)",
                )
            )
        if export.startswith("hs_fused_") and twin.startswith("numpy."):
            # fused pipelines replace a whole op CHAIN: the registered
            # twin must be the in-package interpreted chain the
            # differential test runs, not a numpy single op
            findings.append(
                Finding(
                    "HS105",
                    native_sf.rel_path,
                    reg_line,
                    f"{export}: fused-pipeline exports must register an "
                    f"in-package interpreted twin, got {twin!r} — a numpy "
                    "single-op twin cannot witness whole-pipeline parity",
                )
            )
        if tests and not any(
            export in text or (wrapper and wrapper in text) for _, text in tests
        ):
            findings.append(
                Finding(
                    "HS104",
                    native_sf.rel_path,
                    reg_line,
                    f"{export}: no test under tests/ references {export!r} or "
                    f"its wrapper {wrapper!r} — the parity contract is "
                    "unverified",
                )
            )
    for name in entries:
        if name not in exports:
            findings.append(
                Finding(
                    "HS102",
                    native_sf.rel_path,
                    reg_line,
                    f"KERNEL_TWINS entry {name!r} matches no extern \"C\" "
                    "export in hs_native.cpp (stale registry?)",
                )
            )
    return findings
