"""CLI driver: ``python -m hyperspace_tpu.analysis [package_dir ...]``.

Exit status: 0 when every finding is suppressed (or there are none),
1 when unsuppressed findings remain, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from hyperspace_tpu.analysis import ALL_RULES, run_analysis


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hyperspace_tpu.analysis",
        description="hslint: repo-native static analysis for hyperspace_tpu",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=None,
        help="package directories to analyze (default: hyperspace_tpu "
        "next to the installed package)",
    )
    parser.add_argument(
        "--tests-dir",
        default=None,
        help="tests directory for the kernel-parity checker "
        "(default: sibling tests/ of the package)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format",
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print suppressed findings",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the ruleset and exit"
    )
    parser.add_argument(
        "--witness",
        metavar="ARTIFACT",
        action="append",
        default=None,
        help="cross-check a runtime witness artifact against the static "
        "model (repeatable). A lock-witness JSON "
        "(testing/lock_witness.py) checks the lock model: witnessed "
        "edges/locks absent from it are hard HS604 errors. A "
        "residency-witness JSON (testing/residency_witness.py) checks "
        "the allocation-bound model: a witnessed site absent from "
        "ALLOC_SITES, or a per-site peak past its declared bound-class "
        "ceiling, is a hard HS1004 error. A "
        "collective-witness prefix (testing/collective_witness.py; "
        "per-process <prefix>.p<i>.json files) merges the per-process "
        "collective sequences: any cross-process divergence or "
        "unregistered witnessed site is a hard HS804 error. Static "
        "edges / registered sites never witnessed print as warnings",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(ALL_RULES):
            print(f"{rule}  {ALL_RULES[rule]}")
        return 0

    paths = args.paths or [
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ]
    for p in paths:
        if not os.path.isdir(p):
            print(f"error: not a directory: {p}", file=sys.stderr)
            return 2

    from hyperspace_tpu.analysis.core import Project

    projects = [Project(p, tests_dir=args.tests_dir) for p in paths]
    all_findings = []
    for p, project in zip(paths, projects):
        all_findings.extend(
            run_analysis(p, tests_dir=args.tests_dir, project=project)
        )

    for witness in args.witness or ():
        # ONE cross-check per artifact against the union of the analyzed
        # packages' models: an artifact records every wrapped lock /
        # registered site in its process, so a per-package comparison
        # would call each package's surface "unknown" to the other.
        # Artifact kind is sniffed from its content: a lock witness is a
        # single JSON file with a "locks" map; a residency witness one
        # with a "sites" map; a collective witness is a per-process
        # <prefix>.p<i>.json family (or one such file).
        from hyperspace_tpu.analysis import residency, shared_state, spmd

        try:
            doc = None
            if os.path.isfile(witness):
                import json as _json

                with open(witness, "r", encoding="utf-8") as f:
                    doc = _json.load(f)
            if isinstance(doc, dict) and "locks" in doc:
                lock_doc = shared_state.load_witness(witness, doc=doc)
                gaps, warnings = shared_state.witness_cross_check(
                    projects, lock_doc, os.path.basename(witness)
                )
            elif isinstance(doc, dict) and "sites" in doc:
                res_doc = residency.load_witness(witness, doc=doc)
                gaps, warnings = residency.witness_cross_check(
                    projects, res_doc, os.path.basename(witness)
                )
            else:
                docs = spmd.load_collective_witness(witness)
                gaps, warnings = spmd.collective_cross_check(
                    projects, docs, os.path.basename(witness)
                )
        except (OSError, ValueError) as exc:
            print(f"error: bad witness artifact: {exc}", file=sys.stderr)
            return 2
        all_findings.extend(gaps)
        for w in warnings:
            print(f"hslint: warning: {w}", file=sys.stderr)

    active = [f for f in all_findings if not f.suppressed]
    suppressed = [f for f in all_findings if f.suppressed]
    if args.format == "json":
        shown = all_findings if args.show_suppressed else active
        print(json.dumps([f.to_dict() for f in shown], indent=2))
    else:
        for f in active:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f.render())
        print(
            f"hslint: {len(active)} finding(s), "
            f"{len(suppressed)} suppressed, "
            f"{len(ALL_RULES)} rules, {len(paths)} package(s)"
        )
    return 1 if active else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # Downstream pager/head closed the pipe mid-print. The gate's
        # verdict is unknown at this point, so exit with the conventional
        # SIGPIPE status (128+13) — never 0, or `hslint.sh | head` under
        # pipefail could wave a failing tree through.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(141)
