"""HS7xx — config/doc/fault-matrix contract lints.

PRs 7–8 grew two operator-facing surfaces faster than anything checks
them: the flat ``hyperspace.*`` config keys (``constants.py`` +
``docs/CONFIG.md``) and the fault-injection points
(``testing/faults.py`` + the ``tests/test_faults.py`` matrix). Each is
a three-way contract — declaration, default, documentation (or test) —
that only stays consistent by diligence. This checker makes it
mechanical:

* HS701 — a ``hyperspace.*`` key that the package reads has no
  ``<NAME>_DEFAULT`` sibling in ``constants.py`` (or is read as a bare
  string literal with no constants entry at all): the one place
  defaults live is the constants module, not scattered call sites.
* HS702 — a key the package reads has no row in ``docs/CONFIG.md``:
  every operator-visible knob is documented or it does not ship.
* HS703 — a fault point armed in ``testing/faults.py`` (``POINTS``)
  never appears in ``tests/test_faults.py``, or a crash point
  (``CRASH_POINTS``) never appears in ``tests/test_crash_recovery.py``:
  the point × mode (and crash point × action) matrices are the tested
  contract, an unexercised point is an untested failure mode. The same
  rule covers the multi-host plane: a ``COLLECTIVE_SITES`` entry
  (``parallel/collectives.py``) that never appears in
  ``scripts/dryrun_multihost.py`` — by dotted path or trailing callable
  name, the prefix-family discipline — is a collective the dryrun's
  witness matrix can never exercise, so a newly added collective cannot
  ship unwitnessed.
* HS704 — a dead key: a ``hyperspace.*`` token documented in
  ``docs/CONFIG.md`` that no constants entry backs (or that nothing
  reads), or a key constant in ``constants.py`` that nothing reads —
  documentation drift in either direction.

Key *prefix families* (constants whose value ends with ``.``, e.g.
``hyperspace.faults.``) are matched by prefix: the doc row
``hyperspace.faults.<point>`` documents the family, and per-point keys
are read through ``Config.prefixed``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.analysis.core import Finding, Project, const_str

RULES = {
    "HS701": "config key read without a constants default",
    "HS702": "config key read but undocumented in docs/CONFIG.md",
    "HS703": "fault point missing from the tests/test_faults.py matrix",
    "HS704": "dead config key (documented or declared but never read)",
}

CONSTANTS_FILE = "constants.py"
FAULTS_FILE = "testing/faults.py"
FAULT_TESTS = "test_faults.py"
CRASH_TESTS = "test_crash_recovery.py"
CONFIG_DOC = "CONFIG.md"
DRYRUN_FILE = "dryrun_multihost.py"

_GETTERS = frozenset(
    {"get", "get_bool", "get_int", "get_float", "get_str", "set", "unset"}
)

#: a documented key token: `hyperspace.` followed by dotted identifiers
_DOC_KEY_RE = re.compile(r"hyperspace\.[A-Za-z0-9_]+(?:\.[A-Za-z0-9_]+)*\.?")


def _constants_keys(
    project: Project,
) -> Tuple[Dict[str, Tuple[str, int]], Set[str], Set[str]]:
    """({key -> (NAME, line)}, default names, prefix-family values) from
    ``constants.py`` — every ``NAME = "hyperspace.…"`` string assign."""
    keys: Dict[str, Tuple[str, int]] = {}
    defaults: Set[str] = set()
    prefixes: Set[str] = set()
    sf = project.file(CONSTANTS_FILE)
    if sf is None or sf.tree is None:
        return keys, defaults, prefixes
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            if t.id.endswith("_DEFAULT"):
                defaults.add(t.id)
                continue
            val = const_str(node.value)
            if val is None or not val.startswith("hyperspace."):
                continue
            if val.endswith("."):
                prefixes.add(val)
            keys[val] = (t.id, node.lineno)
    return keys, defaults, prefixes


def _reads(project: Project, names: Set[str]) -> Tuple[Set[str], List[Tuple[str, int, str]]]:
    """(constant NAMEs referenced outside constants.py, literal
    ``hyperspace.*`` keys passed straight to Config getters). A NAME
    reference is any ``C.NAME`` / imported-``NAME`` use — typed
    accessors in config.py all read through these."""
    used: Set[str] = set()
    literals: List[Tuple[str, int, str]] = []  # (display path, line, key)
    for rel, sf in project.files.items():
        if rel == CONSTANTS_FILE or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Attribute) and node.attr in names:
                used.add(node.attr)
            elif (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in names
            ):
                used.add(node.id)
            elif isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in _GETTERS
                    and node.args
                ):
                    lit = const_str(node.args[0])
                    if lit is not None and lit.startswith("hyperspace."):
                        literals.append((sf.rel_path, node.lineno, lit))
    return used, literals


def _doc_tokens(lines: List[str]) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for i, line in enumerate(lines, start=1):
        for m in _DOC_KEY_RE.finditer(line):
            out.append((m.group(0), i))
    return out


def _fault_points(
    project: Project, var_name: str = "POINTS"
) -> Tuple[List[str], int, Optional[str]]:
    """(``var_name`` tuple entries, line, display path) from
    testing/faults.py — POINTS for the injection registry, CRASH_POINTS
    for the crash registry."""
    sf = project.file(FAULTS_FILE)
    if sf is None or sf.tree is None:
        return [], 0, None
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if var_name not in targets:
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            pts = [const_str(e) for e in node.value.elts]
            return (
                [p for p in pts if p],
                node.lineno,
                sf.rel_path,
            )
    return [], 0, sf.rel_path


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    keys, defaults, prefixes = _constants_keys(project)
    const_sf = project.file(CONSTANTS_FILE)
    const_path = const_sf.rel_path if const_sf is not None else CONSTANTS_FILE
    names = {name for name, _line in keys.values()}
    used, literals = _reads(project, names)
    doc_lines = project.doc_lines(CONFIG_DOC)
    doc_text = "\n".join(doc_lines) if doc_lines is not None else None

    # -- HS701/HS702/HS704(b): per declared key ------------------------------
    for key, (name, line) in sorted(keys.items()):
        is_prefix = key in prefixes
        if name not in used:
            findings.append(
                Finding(
                    "HS704",
                    const_path,
                    line,
                    f"config key {key!r} ({name}) is declared but nothing "
                    "in the package reads it — wire it or delete it",
                )
            )
            continue
        if not is_prefix and f"{name}_DEFAULT" not in defaults:
            findings.append(
                Finding(
                    "HS701",
                    const_path,
                    line,
                    f"config key {key!r} ({name}) is read but has no "
                    f"{name}_DEFAULT in constants.py — defaults live in "
                    "ONE place or they drift",
                )
            )
        if doc_text is not None and key not in doc_text:
            findings.append(
                Finding(
                    "HS702",
                    const_path,
                    line,
                    f"config key {key!r} ({name}) is read but has no row "
                    "in docs/CONFIG.md — undocumented operator surface",
                )
            )

    # -- HS701 for literal-key reads (no constants entry at all) -------------
    for path, line, lit in literals:
        if lit in keys or any(lit.startswith(p) for p in prefixes):
            continue
        findings.append(
            Finding(
                "HS701",
                path,
                line,
                f"config key {lit!r} is read as a bare string literal — "
                "declare it in constants.py with a default",
            )
        )

    # -- HS704(a): documented keys nothing backs -----------------------------
    if doc_lines is not None and keys:
        for token, line in _doc_tokens(doc_lines):
            bare = token.rstrip(".")
            known = (
                token in keys
                or bare in keys
                or (token if token.endswith(".") else token + ".") in prefixes
                or any(token.startswith(p) for p in prefixes)
            )
            if known:
                continue
            if "hslint: disable=HS704" in doc_lines[line - 1]:
                continue
            findings.append(
                Finding(
                    "HS704",
                    f"docs/{CONFIG_DOC}",
                    line,
                    f"documented key {token!r} matches no constants.py "
                    "entry — dead documentation (delete the row or add "
                    "the key)",
                )
            )

    # -- HS703: the fault/crash matrices cover every point -------------------
    for var_name, tests_file, what in (
        ("POINTS", FAULT_TESTS, "point × mode"),
        ("CRASH_POINTS", CRASH_TESTS, "crash point × action"),
    ):
        points, pts_line, faults_path = _fault_points(project, var_name)
        if not points:
            continue
        matrix = None
        for rel, text in project.test_files():
            if rel.endswith(tests_file):
                matrix = text
                break
        if matrix is not None:
            for p in points:
                if p not in matrix:
                    findings.append(
                        Finding(
                            "HS703",
                            faults_path or FAULTS_FILE,
                            pts_line,
                            f"fault point {p!r} is armed in "
                            f"testing/faults.py but never appears in "
                            f"tests/{tests_file} — the {what} "
                            "matrix has a hole",
                        )
                    )

    # -- HS703 (collective plane): every COLLECTIVE_SITES entry must be
    # exercised by the multi-host dryrun's witness matrix — prefix-family
    # match: the full dotted site path or its trailing callable name
    from hyperspace_tpu.analysis import spmd as _spmd

    site_entries, site_rel = _spmd.parse_sites(project)
    if site_entries:
        dryrun = project.aux_lines("scripts", DRYRUN_FILE)
        if dryrun is not None:
            text = "\n".join(dryrun)
            site_sf = project.file(site_rel)
            site_path = (
                site_sf.rel_path if site_sf is not None else site_rel
            )
            for e in site_entries:
                token = e.path.rsplit(".", 1)[-1]
                if e.path in text or token in text:
                    continue
                findings.append(
                    Finding(
                        "HS703",
                        site_path,
                        e.line,
                        f"collective site {e.path!r} is registered in "
                        f"COLLECTIVE_SITES but never appears in "
                        f"scripts/{DRYRUN_FILE} — the dryrun's witness "
                        "matrix has a hole; add it to a WITNESS_* tuple "
                        "and drive (or explicitly exclude) it",
                    )
                )
    return findings
