"""Plan analysis & introspection: explain, whyNot, statistics.

Reference: ``index/plananalysis/`` — ``PlanAnalyzer`` (with/without plan
diff), ``CandidateIndexAnalyzer`` (whyNot reason harvesting),
``FilterReason`` catalog, ``IndexStatistics`` surface.
"""
