"""`hs.explain(df)` — plan diff with vs. without Hyperspace.

Reference: ``plananalysis/PlanAnalyzer.scala:37-418`` — build the plan both
ways, highlight the subtrees that changed (the index scans), and list the
indexes used plus, in verbose mode, all ACTIVE candidate indexes and the
physical-operator-count diff (``PhysicalOperatorAnalyzer.scala``).
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Tuple

from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException

_BAR = "=" * 65


class DisplayMode:
    """Explain rendering mode (reference: ``plananalysis/DisplayMode.scala``
    — PlainText / Console / HTML variants differing in the highlight tags
    wrapped around index scans and in newline/escape handling)."""

    name = "plaintext"
    highlight_open = "<----"
    highlight_close = "---->"
    newline = "\n"

    def escape(self, text: str) -> str:
        return text


class ConsoleMode(DisplayMode):
    name = "console"
    highlight_open = "\x1b[93m"  # bright yellow
    highlight_close = "\x1b[0m"


class HTMLMode(DisplayMode):
    name = "html"
    highlight_open = "<b>"
    highlight_close = "</b>"
    newline = "<br/>"

    def escape(self, text: str) -> str:
        return (
            text.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;")
        )


_MODES = {m.name: m for m in (DisplayMode, ConsoleMode, HTMLMode)}


def get_display_mode(name: str) -> DisplayMode:
    cls = _MODES.get(name.lower())
    if cls is None:
        raise HyperspaceException(
            f"Unknown explain display mode {name!r}; one of {sorted(_MODES)}"
        )
    return cls()


def _highlighted_plan(plan, changed_scans, mode: DisplayMode) -> str:
    """Pretty plan string with changed Scan lines wrapped in the mode's
    highlight tags (the reference's BufferStream highlight tags)."""
    lines = []

    def walk(node, indent):
        text = mode.escape(node._node_string())
        if node in changed_scans:
            text = f"{mode.highlight_open}{text}{mode.highlight_close}"
        lines.append("  " * indent + text)
        for c in node.children:
            walk(c, indent + 1)

    walk(plan, 0)
    return "\n".join(lines)


def _index_scans(plan) -> List:
    return [s for s in plan.collect_leaves() if s.relation.index_info]


def _operator_counts(plan) -> Counter:
    c: Counter = Counter()

    def walk(node):
        c[type(node).__name__] += 1
        for ch in node.children:
            walk(ch)

    walk(plan)
    return c


def _operator_diff_table(with_plan, without_plan) -> str:
    """Operator-count comparison (PhysicalOperatorAnalyzer.scala)."""
    wc, woc = _operator_counts(with_plan), _operator_counts(without_plan)
    names = sorted(set(wc) | set(woc))
    rows = [("Operator", "Hyperspace", "Original")]
    rows += [(n, str(wc.get(n, 0)), str(woc.get(n, 0))) for n in names]
    widths = [max(len(r[i]) for r in rows) for i in range(3)]
    out = []
    for i, r in enumerate(rows):
        out.append(" | ".join(v.ljust(w) for v, w in zip(r, widths)))
        if i == 0:
            out.append("-+-".join("-" * w for w in widths))
    return "\n".join(out)


def explain_string(
    df, session, manager, verbose: bool = False, mode: str = None
) -> str:
    """PlanAnalyzer.explainString: optimize the plan with the rule enabled
    and render the diff against the unoptimized plan. ``mode`` overrides
    the session's ``hyperspace.explain.displayMode`` conf (plaintext /
    console / html)."""
    dm = get_display_mode(mode or session.conf.explain_display_mode)
    original = df.logical_plan
    prev = session.is_hyperspace_enabled()
    try:
        session.enable_hyperspace()
        optimized = session.optimize(original)
    finally:
        if not prev:
            session.disable_hyperspace()

    used_scans = _index_scans(optimized)
    used: Dict[str, Tuple[int, str]] = {}
    for s in used_scans:
        name, ver, abbr = s.relation.index_info
        used[name] = (ver, s.relation.root_paths[0] if s.relation.root_paths else "")

    buf = [
        _BAR,
        "Plan with indexes:",
        _BAR,
        _highlighted_plan(optimized, set(used_scans), dm),
        "",
        _BAR,
        "Plan without indexes:",
        _BAR,
        dm.escape(original.pretty()),
        "",
        _BAR,
        "Indexes used:",
        _BAR,
    ]
    for name in sorted(used):
        ver, root = used[name]
        buf.append(dm.escape(f"{name} (v{ver}): {root}"))
    if not used:
        buf.append("(none)")
    buf.append("")

    if verbose:
        buf += [
            _BAR,
            "Operator diff:",
            _BAR,
            dm.escape(_operator_diff_table(optimized, original)),
            "",
            _BAR,
            "Applicable indexes:",
            _BAR,
        ]
        active = manager.get_indexes([States.ACTIVE])
        for e in sorted(active, key=lambda e: e.name):
            index = e.derived_dataset
            buf.append(
                dm.escape(
                    f"{e.name}: kind={index.kind}, "
                    f"indexed={list(index.indexed_columns)}"
                )
            )
        if not active:
            buf.append("(none)")
        buf.append("")
    # identity when dm.newline == "\n"; re-joins per-line for html's <br/>
    return dm.newline.join(line for chunk in buf for line in chunk.split("\n"))
