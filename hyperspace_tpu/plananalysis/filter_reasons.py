"""FilterReason catalog — why an index was NOT applied.

Reference: ``plananalysis/FilterReason.scala:33-158``. Each reason has a
stable code plus an argument list; ``why_not`` renders them per index.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple


@dataclasses.dataclass(frozen=True)
class FilterReason:
    code: str
    args: Tuple[Tuple[str, str], ...] = ()
    verbose: str = ""

    @property
    def arg_string(self) -> str:
        return ", ".join(f"{k}={v}" for k, v in self.args)

    def to_string(self, extended: bool = False) -> str:
        if extended and self.verbose:
            return f"[{self.code}] {self.verbose}"
        return f"[{self.code}] {self.arg_string}"


def col_schema_mismatch(index_cols: str, relation_cols: str) -> FilterReason:
    return FilterReason(
        "COL_SCHEMA_MISMATCH",
        (("indexCols", index_cols), ("relationCols", relation_cols)),
        "Index columns are not part of the relation's schema.",
    )


def source_data_changed() -> FilterReason:
    return FilterReason(
        "SOURCE_DATA_CHANGED",
        (),
        "Source data changed since the index was built and Hybrid Scan "
        "is disabled or inapplicable.",
    )


def no_delete_support() -> FilterReason:
    return FilterReason(
        "NO_DELETE_SUPPORT",
        (),
        "Source files were deleted but the index has no lineage column.",
    )


def too_much_appended(appended_ratio: float, threshold: float) -> FilterReason:
    return FilterReason(
        "TOO_MUCH_APPENDED",
        (("appendedRatio", f"{appended_ratio:.3f}"), ("threshold", str(threshold))),
        "Appended bytes exceed the Hybrid Scan threshold.",
    )


def too_much_deleted(deleted_ratio: float, threshold: float) -> FilterReason:
    return FilterReason(
        "TOO_MUCH_DELETED",
        (("deletedRatio", f"{deleted_ratio:.3f}"), ("threshold", str(threshold))),
        "Deleted bytes exceed the Hybrid Scan threshold.",
    )


def missing_required_col(required: str, index_cols: str) -> FilterReason:
    return FilterReason(
        "MISSING_REQUIRED_COL",
        (("requiredCols", required), ("indexCols", index_cols)),
        "The query needs columns the index does not cover.",
    )


def no_first_indexed_col_cond(first_indexed: str, condition_cols: str) -> FilterReason:
    return FilterReason(
        "NO_FIRST_INDEXED_COL_COND",
        (("firstIndexedCol", first_indexed), ("conditionCols", condition_cols)),
        "The filter does not constrain the index's first indexed column.",
    )

def no_indexed_col_cond(indexed: str, condition_cols: str) -> FilterReason:
    return FilterReason(
        "NO_INDEXED_COL_COND",
        (("indexedCols", indexed), ("conditionCols", condition_cols)),
        "The filter constrains none of the index's indexed columns.",
    )


def not_eligible_join(reason: str) -> FilterReason:
    return FilterReason(
        "NOT_ELIGIBLE_JOIN",
        (("reason", reason),),
        "The join shape is not eligible for the join-index rewrite.",
    )


def no_avail_join_index_pair(side: str) -> FilterReason:
    return FilterReason(
        "NO_AVAIL_JOIN_INDEX_PAIR",
        (("child", side),),
        "No compatible index pair covers both join sides.",
    )


def not_covering_filter(reason: str) -> FilterReason:
    return FilterReason("NOT_APPLICABLE", (("reason", reason),), reason)


def another_index_applied(applied: str) -> FilterReason:
    return FilterReason(
        "ANOTHER_INDEX_APPLIED",
        (("appliedIndex", applied),),
        "A different index scored higher for this subtree.",
    )


def ineligible_predicate(reason: str) -> FilterReason:
    return FilterReason(
        "INELIGIBLE_FILTER_CONDITION",
        (("reason", reason),),
        "The filter condition cannot be translated for this index.",
    )
