"""`hs.why_not(df)` — why each index was (not) applied to a plan.

Reference: ``plananalysis/CandidateIndexAnalyzer.scala:30-43`` — set the
``INDEX_PLAN_ANALYSIS_ENABLED`` tag on every ACTIVE index, re-run the
candidate collector and the score-based optimizer, then harvest the
``FILTER_REASONS`` tags the rule filters recorded
(``IndexFilter.withFilterReasonTag``, rules/IndexFilter.scala:26-110).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.rules import tags
from hyperspace_tpu.rules.candidate import collect_candidates
from hyperspace_tpu.rules.score import ScoreBasedIndexPlanOptimizer

_BAR = "=" * 65


def _analyze(df, session, entries):
    """Re-run collection + optimization with analysis tagging enabled;
    returns (applied index names, entries with FILTER_REASONS tags)."""
    from hyperspace_tpu.plan.nodes import prune_join_columns

    for e in entries:
        # drop reasons accumulated by earlier analyses of other plans
        for key, _ in e.collect_tag(tags.FILTER_REASONS):
            e.unset_tag(key, tags.FILTER_REASONS)
        e.set_tag(None, tags.INDEX_PLAN_ANALYSIS_ENABLED, True)
    try:
        plan = prune_join_columns(df.logical_plan)
        candidates = collect_candidates(session, plan, entries)
        optimized = ScoreBasedIndexPlanOptimizer(session).apply(plan, candidates)
        applied = {
            s.relation.index_info[0]
            for s in optimized.collect_leaves()
            if s.relation.index_info
        }
        return applied, entries
    finally:
        for e in entries:
            e.unset_tag(None, tags.INDEX_PLAN_ANALYSIS_ENABLED)


def why_not_string(
    df,
    session,
    manager,
    index_name: Optional[str] = None,
    extended: bool = False,
) -> str:
    entries = manager.get_indexes([States.ACTIVE])
    if index_name is not None:
        entries = [e for e in entries if e.name == index_name]
        if not entries:
            raise HyperspaceException(
                f"No ACTIVE index named {index_name!r} to analyze"
            )
    if not entries:
        return "No ACTIVE indexes to analyze."

    applied, entries = _analyze(df, session, entries)

    buf = [
        _BAR,
        "Plan:",
        _BAR,
        df.logical_plan.pretty(),
        "",
        _BAR,
        "Applicable indexes:",
        _BAR,
    ]
    applicable = sorted(n for n in applied)
    for n in applicable:
        buf.append(f"{n}: applied by the optimizer for this plan")
    if not applicable:
        buf.append("(none)")
    buf += ["", _BAR, "Non-applicable indexes:", _BAR]
    any_reason = False
    for e in sorted(entries, key=lambda e: e.name):
        if e.name in applied:
            continue
        any_reason = True
        reasons = [r for _, rs in e.collect_tag(tags.FILTER_REASONS) for r in rs]
        buf.append(f"{e.name} ({e.derived_dataset.kind}):")
        if reasons:
            seen = set()
            for r in reasons:
                line = "  - " + r.to_string(extended)
                if line not in seen:
                    seen.add(line)
                    buf.append(line)
        else:
            buf.append(
                "  - [NO_CANDIDATE_SCAN] the plan has no scan this index's "
                "source files match"
            )
    if not any_reason:
        buf.append("(none)")
    buf.append("")
    return "\n".join(buf)
