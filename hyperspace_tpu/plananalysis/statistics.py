"""IndexStatistics — the ``hs.indexes()`` / ``hs.index(name)`` surface.

Reference: ``index/IndexStatistics.scala:41-60`` (summary row per index;
extended stats for a single index) and
``IndexCollectionManager.scala:119-128,139-149``.
"""

from __future__ import annotations

from typing import List

import pyarrow as pa

from hyperspace_tpu.metadata.entry import IndexLogEntry

INDEX_SUMMARY_COLUMNS = [
    "name",
    "indexedColumns",
    "includedColumns",
    "numBuckets",
    "schema",
    "indexLocation",
    "state",
]


def _summary_row(entry: IndexLogEntry) -> dict:
    index = entry.derived_dataset
    stats = index.statistics(extended=False)
    files = entry.content.files
    location = files[0].rsplit("/", 2)[0] if files else ""
    return {
        "name": entry.name,
        "indexedColumns": ",".join(index.indexed_columns),
        "includedColumns": ",".join(index.included_columns),
        "numBuckets": int(stats.get("numBuckets", 0) or 0),
        "schema": index.schema_json if hasattr(index, "schema_json") else "",
        "indexLocation": location,
        "state": entry.state,
    }


def indexes_summary_table(entries: List[IndexLogEntry]) -> pa.Table:
    rows = [_summary_row(e) for e in entries]
    return pa.table(
        {c: [r[c] for r in rows] for c in INDEX_SUMMARY_COLUMNS}
    )


def index_stats_table(entry: IndexLogEntry) -> pa.Table:
    """Extended stats for one index (IndexStatistics extended mode)."""
    row = _summary_row(entry)
    extended = entry.derived_dataset.statistics(extended=True)
    row["logVersion"] = entry.id
    row["indexContentFileCount"] = len(entry.content.files)
    row["indexContentSizeInBytes"] = entry.content.size_in_bytes
    row["sourceFileCount"] = len(entry.relation.content.files)
    row["sourceSizeInBytes"] = entry.source_files_size_in_bytes
    row["additionalStats"] = str(extended)
    return pa.table({k: [v] for k, v in row.items()})
