"""Min/max layout-quality analysis.

TPU-native port of the reference's ``util/MinMaxAnalysisUtil.scala:30-780``:
for each requested column, collect per-FILE min/max, then measure how many
files a point lookup on that column would have to touch — the figure of
merit for physical layout quality (z-ordering, clustering, partitioning).
A perfectly clustered column touches 1 file per point lookup; a randomly
laid-out column touches all of them.

The reference line-sweeps start/end markers with Catalyst orderings and
renders an ASCII histogram; here the sweep is vectorized numpy over the
per-file [min, max] intervals (closed-interval overlap, ties inclusive —
matching the reference's start-before-end tie sort). Non-numeric columns
are skipped with a note, like the reference.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io import parquet as pio
from hyperspace_tpu.plan.nodes import Scan


@dataclasses.dataclass
class MinMaxColumnResult:
    column: str
    min_val: Optional[float]
    max_val: Optional[float]
    total_files: int
    total_bytes: int
    # per value-bin: number of files whose [min,max] intersects the bin
    bin_file_counts: List[int]
    max_files_per_lookup: int  # exact (computed at interval endpoints)
    avg_files_per_lookup: float
    max_bytes_per_lookup: int

    def to_text(self) -> str:
        lines = [f"Column: {self.column}"]
        if self.min_val is None:
            lines += [
                "  all values null",
                f"  Total num of files: {self.total_files}",
                f"  Total byte size of files: {self.total_bytes}",
            ]
            return "\n".join(lines)
        pct_max = 100.0 * self.max_files_per_lookup / max(self.total_files, 1)
        pct_avg = 100.0 * self.avg_files_per_lookup / max(self.total_files, 1)
        pct_bytes = 100.0 * self.max_bytes_per_lookup / max(self.total_bytes, 1)
        lines += [
            f"  min: {self.min_val}  max: {self.max_val}",
            f"  Total num of files: {self.total_files}",
            f"  Total byte size of files: {self.total_bytes}",
            f"  Max files for a point lookup: {self.max_files_per_lookup}"
            f" ({pct_max:.2f}%)",
            f"  Avg files for a point lookup: {self.avg_files_per_lookup:.2f}"
            f" ({pct_avg:.2f}%)",
            f"  Max bytes for a point lookup: {self.max_bytes_per_lookup}"
            f" ({pct_bytes:.2f}%)",
        ]
        if self.bin_file_counts:
            peak = max(self.bin_file_counts) or 1
            width = 40
            lines.append("  files touched per value range:")
            for i, c in enumerate(self.bin_file_counts):
                bar = "#" * max(1 if c else 0, round(width * c / peak))
                lines.append(f"  [{i:3d}] {c:6d} |{bar}")
        return "\n".join(lines)


def _stat_to_float(v) -> float:
    """Float image of a parquet-statistics value (logical types arrive as
    python date/datetime objects). Scale only needs to be consistent
    WITHIN a column: footer and data paths are never mixed per column."""
    import datetime as _dt

    if isinstance(v, _dt.datetime):
        return float(np.datetime64(v, "us").view("int64"))
    if isinstance(v, _dt.date):
        return float(np.datetime64(v, "D").view("int64"))
    if isinstance(v, _dt.time):
        return float(
            ((v.hour * 60 + v.minute) * 60 + v.second) * 10**6 + v.microsecond
        )
    return _norm(v)


def _footer_ranges(files, column: str, metadata_cache: Dict[str, object]):
    """Per-file (lo, hi) from parquet row-group statistics, or None when
    any file lacks min/max stats for the column (caller falls back to a
    data read for the whole column — scales must not mix). Entries are
    None for all-null files. ``metadata_cache`` holds each file's parsed
    footer so N analyzed columns cost one footer parse per file, not N."""
    import pyarrow.parquet as pq

    out = []
    for f in files:
        md = metadata_cache.get(f)
        if md is None:
            md = pq.ParquetFile(f).metadata
            metadata_cache[f] = md
        lo = hi = None
        for rg in range(md.num_row_groups):
            row_group = md.row_group(rg)
            cc = None
            for ci in range(row_group.num_columns):
                c = row_group.column(ci)
                if c.path_in_schema == column:
                    cc = c
                    break
            if cc is None:
                return None
            st = cc.statistics
            if st is None or not st.has_min_max:
                if cc.num_values == 0 or (
                    st is not None and st.null_count == row_group.num_rows
                ):
                    continue  # empty / all-null row group
                return None
            mn, mx = _stat_to_float(st.min), _stat_to_float(st.max)
            lo = mn if lo is None else min(lo, mn)
            hi = mx if hi is None else max(hi, mx)
        out.append(None if lo is None else (lo, hi))
    return out


def _norm(x) -> float:
    """Finite float image of a column value (NaN never reaches here —
    column_value_range excludes NaN rows, matching engine comparison
    semantics)."""
    f = float(x)
    if np.isposinf(f):
        return float(np.finfo(np.float64).max)
    if np.isneginf(f):
        return float(np.finfo(np.float64).min)
    return 0.0 if f == 0.0 else f


def _is_numeric_like(t: pa.DataType) -> bool:
    return (
        pa.types.is_integer(t)
        or pa.types.is_floating(t)
        or pa.types.is_boolean(t)
        or pa.types.is_temporal(t)
    )


def analyze_column(
    column: str,
    intervals: List[Tuple[float, float]],
    sizes: List[int],
    total_files: int,
    total_bytes: int,
    num_bins: int = 50,
) -> MinMaxColumnResult:
    """Overlap analysis over per-file [min,max] intervals (all-null files
    excluded by the caller)."""
    if not intervals:
        return MinMaxColumnResult(
            column, None, None, total_files, total_bytes, [], 0, 0.0, 0
        )
    lo = np.array([a for a, _ in intervals])
    hi = np.array([b for _, b in intervals])
    sz = np.array(sizes, dtype=np.int64)
    vmin, vmax = float(lo.min()), float(hi.max())
    # exact max overlap via an O(F log F) line sweep (the reference's
    # start/end marker sort): +1 at each min, -1 after each max; at equal
    # coordinates starts process first so closed intervals sharing an
    # endpoint both count (reference tie order: start before end).
    coords = np.concatenate([lo, hi])
    kinds = np.concatenate(
        [np.zeros(len(lo), np.int8), np.ones(len(hi), np.int8)]
    )
    deltas = np.concatenate([np.ones(len(lo), np.int64), -np.ones(len(hi), np.int64)])
    byte_deltas = np.concatenate([sz, -sz])
    order = np.lexsort((kinds, coords))
    max_files = int(np.cumsum(deltas[order]).max())
    max_bytes = int(np.cumsum(byte_deltas[order]).max())
    # value-range histogram: bin overlap counts (display + avg)
    if vmax > vmin:
        edges = np.linspace(vmin, vmax, num_bins + 1)
        starts, ends = edges[:-1], edges[1:]
        overlap = (lo[None, :] <= ends[:, None]) & (starts[:, None] <= hi[None, :])
        counts = overlap.sum(axis=1).astype(int).tolist()
    else:
        counts = [len(intervals)]
    nonzero = [c for c in counts if c > 0]
    avg = float(sum(nonzero) / len(nonzero)) if nonzero else 0.0
    return MinMaxColumnResult(
        column,
        vmin,
        vmax,
        total_files,
        total_bytes,
        counts,
        max_files,
        avg,
        max_bytes,
    )


def analyze_min_max(
    df, columns: Sequence[str], num_bins: int = 50
) -> List[MinMaxColumnResult]:
    """Per-column layout analysis of a DataFrame's underlying files
    (reference: ``MinMaxAnalysisUtil.analyze(df, cols)``)."""
    leaves = [p for p in df.logical_plan.collect_leaves() if isinstance(p, Scan)]
    if len(leaves) != 1:
        raise HyperspaceException(
            "min/max analysis needs a single-relation DataFrame"
        )
    from hyperspace_tpu.io.columnar import Column, column_value_range

    rel = leaves[0].relation
    schema = rel.schema
    file_sizes = {f: os.path.getsize(f) for f in rel.files}
    total_bytes = sum(file_sizes.values())
    for c in columns:
        if c not in rel.column_names:
            raise HyperspaceException(f"No such column {c!r}")
    numeric_cols = [c for c in columns if _is_numeric_like(schema[c])]
    ranges: Dict[str, List[Tuple[float, float]]] = {c: [] for c in numeric_cols}
    sizes: Dict[str, List[int]] = {c: [] for c in numeric_cols}
    # footer-statistics fast path (no data read) for non-float columns of
    # parquet-like sources; floats need the NaN-aware data read (parquet
    # float stats are writer-dependent around NaN)
    data_cols = []
    footer_md_cache: Dict[str, object] = {}
    for c in numeric_cols:
        footer = None
        if rel.fmt in ("parquet", "delta", "iceberg") and not (
            pa.types.is_floating(schema[c])
        ):
            footer = _footer_ranges(rel.files, c, footer_md_cache)
        if footer is None:
            data_cols.append(c)
            continue
        for f, rng in zip(rel.files, footer):
            if rng is None:
                continue  # all-null file
            ranges[c].append(rng)
            sizes[c].append(file_sizes[f])
    # one read per file for the remaining columns (not one per column)
    if data_cols:
        for f in rel.files:
            t = pio.read_table([f], data_cols, rel.fmt)
            for c in data_cols:
                lo, hi = column_value_range(Column.from_arrow(t.column(c)))
                if lo is None:
                    continue  # all null/NaN in this file
                ranges[c].append((_norm(lo), _norm(hi)))
                sizes[c].append(file_sizes[f])
    results = []
    for c in columns:
        if c not in ranges:
            results.append(
                MinMaxColumnResult(
                    c + " (skipped: non-numeric)",
                    None,
                    None,
                    len(rel.files),
                    total_bytes,
                    [],
                    0,
                    0.0,
                    0,
                )
            )
            continue
        results.append(
            analyze_column(
                c, ranges[c], sizes[c], len(rel.files), total_bytes, num_bins
            )
        )
    return results


def analyze_min_max_string(df, columns: Sequence[str], num_bins: int = 50) -> str:
    return "\n\n".join(r.to_text() for r in analyze_min_max(df, columns, num_bins))
