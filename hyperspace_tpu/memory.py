"""ALLOC_SITES — the registry of row-proportional allocation sites.

The KERNEL_TWINS / SHARED_STATE doctrine applied to resident bytes:
every hot-path function that materializes memory proportional to
relation size (a full parquet read, an arrow->numpy decode, a
concatenated prepared side, an ``np.empty(n_rows, ...)``) is declared
HERE, together with the *plane* it runs on and the *bound class* that
keeps its resident set finite — so "what stops this allocation from
growing past RAM?" is a mechanical question (``hslint`` HS10xx,
``analysis/residency.py``), not an archaeology project. The runtime
residency witness (``testing/residency_witness.py``) wraps the sites
named here, records per-site peak bytes + process RSS high-water, and
``hslint --witness`` cross-checks what actually happened against this
model. The out-of-core arc (ROADMAP item 1: budgeted streaming, spill)
changes DECLARED bounds in this file instead of hunting for hidden
materializations.

Entry shape::

    "<dotted path of the allocating function/method>": (
        "<plane: build | serve | maintenance>",
        "<bound class>",
        "<one-line justification — why this bound holds>",
    )

Site paths name a module-level function
(``hyperspace_tpu.io.parquet.read_table``), a class method
(``hyperspace_tpu.execution.join_exec.PreparedJoinSide.subset``) or a
module (import-time allocation). Bound classes:

``cache-governed``
    The materialized value flows into the ``ServeCache`` byte governor
    (``execution/serve_cache.py``): residency is bounded by the cache
    budget, eviction frees it. HS1002 flags a declared site whose value
    never flows through a ``.put(...)`` (in the site or a direct
    caller).
``wave-budget``
    Bounded by the in-flight wave of a pooled fan-out (the scan pool's
    bounded worker count times per-unit size). HS1002 requires the
    site to reference the wave/budget/pool machinery.
``chunk-bounded``
    Allocated per chunk inside an explicit chunk loop; peak residency
    is one chunk plus the reduced accumulator. HS1002 flags a declared
    site with no loop.
``row-group-bounded``
    Proportional to one parquet row group (``io/parquet.py``
    INDEX_ROW_GROUP_SIZE rows), not the relation. HS1002 requires the
    site to touch the row-group read path.
``const-bounded``
    O(1) or O(schema) — statistics, offsets, per-file footers summary;
    grows with column/file *count* ceilings that config caps, never
    with row count. No structural check; the justification carries it.
``spill-bounded``
    Bounded by the on-disk spill tier budget
    (``hyperspace.serve.spill.maxBytes``): the materialized value is a
    zero-copy view of a memory-mapped spill file whose resident charge
    is the O(1) mmap token, with real residency governed by the page
    cache. HS1002 requires the site to reference the spill machinery.

The witness gates each class against ``BOUND_CLASS_CEILINGS`` below:
an observed per-site peak past its class ceiling is a hard HS1004
error, the same doctrine as a witnessed lock edge the static model
lacks.

Keep this module stdlib-only and import-cheap: the analyzer parses it
(never imports it) and the residency witness imports it inside test
processes before any session exists.
"""

from __future__ import annotations

from typing import Dict, Tuple

#: planes an allocation site may run on
PLANES = ("build", "serve", "maintenance")

#: the six declared bound classes (see module doc)
BOUND_CLASSES = (
    "cache-governed",
    "wave-budget",
    "chunk-bounded",
    "row-group-bounded",
    "const-bounded",
    "spill-bounded",
)

#: per-class byte ceilings the runtime witness gates on (HS1004): an
#: observed per-site peak past its declared class ceiling hard-errors.
#: cache-governed mirrors the serve-cache default budget
#: (constants.SERVE_CACHE_MAX_BYTES_DEFAULT); the rest are the
#: engineering envelopes the out-of-core arc will tighten.
BOUND_CLASS_CEILINGS: Dict[str, int] = {
    "cache-governed": 4 << 30,
    "wave-budget": 1 << 30,
    "chunk-bounded": 512 << 20,
    "row-group-bounded": 256 << 20,
    "const-bounded": 64 << 20,
    "spill-bounded": 512 << 20,
}

ALLOC_SITES: Dict[str, Tuple[str, str, str]] = {
    # -- io: the read primitives ---------------------------------------------
    "hyperspace_tpu.io.parquet.read_table": (
        "serve",
        "cache-governed",
        "materializes exactly the pruned file selection the planner "
        "chose; every serve-path caller publishes the decoded result "
        "into the ServeCache byte governor or holds a per-chunk slice",
    ),
    "hyperspace_tpu.io.parquet.read_table_row_groups": (
        "serve",
        "row-group-bounded",
        "reads only the selected row groups, fanned per file through "
        "the bounded scan pool; residency is selection-, not "
        "relation-proportional",
    ),
    "hyperspace_tpu.io.columnar.Column.from_arrow": (
        "serve",
        "cache-governed",
        "arrow->numpy decode of one column of whatever table the "
        "caller read; decoded columns live in ServeCache entries "
        "(ScanCacheEntry) whose budget_nbytes pre-charges them",
    ),
    "hyperspace_tpu.io.columnar.Column.concat": (
        "serve",
        "cache-governed",
        "concatenates per-file column pieces into the one decoded copy "
        "the scan/joinside cache entries charge against the governor",
    ),
    "hyperspace_tpu.io.columnar.ColumnarBatch.from_arrow": (
        "serve",
        "cache-governed",
        "per-column decode of a read table; the batch is what the "
        "serve cache charges (batch_nbytes/estimate_nbytes)",
    ),
    # -- serve-plane prepared state ------------------------------------------
    "hyperspace_tpu.execution.join_exec.prepare_join_side": (
        "serve",
        "cache-governed",
        "the prepared side (concat batch, combined keys, offsets, "
        "memoized sort permutations) is pre-charged via "
        "PreparedJoinSide.nbytes and put into ServeCache",
    ),
    "hyperspace_tpu.execution.join_exec.prepare_join_side_pipelined": (
        "serve",
        "cache-governed",
        "streaming twin of prepare_join_side (bit-identical output): "
        "the concatenated prepared side flows into the joinside "
        "ServeCache entry via the caller's put "
        "(executor._joinside_for_child), pre-charged with .nbytes",
    ),
    "hyperspace_tpu.execution.join_exec.PreparedJoinSide.subset": (
        "serve",
        "cache-governed",
        "column-subset view rebuilt from a cached side; the subset is "
        "re-put with its own nbytes charge",
    ),
    # -- zonemap / aggregate metadata planes ---------------------------------
    "hyperspace_tpu.indexes.zonemaps.assemble_zone_data": (
        "serve",
        "chunk-bounded",
        "footers are decoded in fixed-size file chunks; only the "
        "per-row-group stat cells survive a chunk, so transient "
        "residency is one chunk of footers + the O(row-group) zones",
    ),
    "hyperspace_tpu.indexes.zonemaps.zone_data_for": (
        "serve",
        "cache-governed",
        "assembled ZoneData is put into ServeCache with zd.nbytes (and "
        "mirrored in the byte-bounded module LRU fallback)",
    ),
    "hyperspace_tpu.indexes.aggindex.agg_data_for": (
        "serve",
        "cache-governed",
        "assembled AggData is put into ServeCache with its decoded "
        "nbytes (and mirrored in the byte-bounded module LRU fallback)",
    ),
    "hyperspace_tpu.indexes.aggindex.install_fanout_payload": (
        "serve",
        "cache-governed",
        "peer-pushed aggregate payload is decoded then put into "
        "ServeCache under the same key/charge as agg_data_for",
    ),
    # -- executor serve hot paths --------------------------------------------
    "hyperspace_tpu.execution.executor._scan_cache_entry": (
        "serve",
        "cache-governed",
        "decodes the missing columns of the pruned selection and puts "
        "the ScanCacheEntry with budget_nbytes pre-charged against the "
        "governor",
    ),
    "hyperspace_tpu.execution.executor._exec_bucketed": (
        "serve",
        "cache-governed",
        "materializes one bucket's file subset and publishes the "
        "decoded batch under a ('bucketed', fp, cols) cache key",
    ),
    "hyperspace_tpu.execution.executor._bucket_stream": (
        "serve",
        "wave-budget",
        "per-bucket reads fan out on the bounded scan pool; residency "
        "is the in-flight worker wave times one bucket, the stream "
        "consumer drops each bucket after merging",
    ),
    "hyperspace_tpu.execution.executor._exec_scan": (
        "serve",
        "cache-governed",
        "reads the planner's pruned selection (row-group-narrowed when "
        "zone maps supply file_row_groups); the decoded batch becomes "
        "the scan cache entry the governor charges",
    ),
    # -- out-of-core streaming serve (hyperspace.serve.stream.*) -------------
    "hyperspace_tpu.execution.executor._stream_wave_side": (
        "serve",
        "wave-budget",
        "reads exactly one wave's bucket files — waves are packed by "
        "_exec_join_streaming so both sides' estimated decoded bytes "
        "fit hyperspace.serve.stream.maxBytes — and the prepared wave "
        "is released as soon as its join output is assembled",
    ),
    "hyperspace_tpu.execution.join_exec.prepare_join_side_contiguous": (
        "serve",
        "wave-budget",
        "zero-concat prepared side over one already-contiguous wave "
        "batch: allocates only the O(wave) key/offset arrays beside "
        "the batch the wave reader materialized under the budget",
    ),
    # -- spill tier (hyperspace.serve.spill.*) -------------------------------
    "hyperspace_tpu.execution.serve_cache.ServeCache._restore_from_spill": (
        "serve",
        "spill-bounded",
        "restored values are zero-copy read-only views of the mmap'd "
        "spill file (resident charge = the O(1) mmap token); real "
        "pages belong to the kernel page cache, and the tier's total "
        "bytes are capped by hyperspace.serve.spill.maxBytes",
    ),
    "hyperspace_tpu.io.columnar.open_mmap_table": (
        "serve",
        "spill-bounded",
        "memory-maps an arrow IPC file and registers the region so "
        "estimate_nbytes charges views of it as file-backed tokens; "
        "residency is governed by the page cache, not the heap",
    ),
    # -- aggregate / sample plane (approximate answers) ----------------------
    "hyperspace_tpu.indexes.aggindex.prune_missing": (
        "maintenance",
        "const-bounded",
        "vacuum reads one sample sidecar to re-point lineage; sidecars "
        "are capped at sample_rows per row group by construction",
    ),
    "hyperspace_tpu.indexes.aggindex._sample_table_cached": (
        "serve",
        "const-bounded",
        "one directory's sample sidecar (sample_rows-capped per row "
        "group) behind a small lru_cache; bounded by maxsize x sidecar "
        "cap, never by relation rows",
    ),
    "hyperspace_tpu.indexes.aggindex.sample_data_for": (
        "serve",
        "const-bounded",
        "assembles the per-file sample strata: sample_rows per row "
        "group, a 2**16x reduction of the relation — the approximate "
        "plane's contract, config-capped by INDEX_AGG_SAMPLE_ROWS",
    ),
    # -- build plane: wave loops and per-file passes -------------------------
    "hyperspace_tpu.indexes.covering_build._scan_with_lineage": (
        "build",
        "chunk-bounded",
        "per-file read loop whose concat accumulator is exactly the "
        "file subset the caller passed — wave-planned stripes from the "
        "streaming writers, never the whole relation on the build path",
    ),
    "hyperspace_tpu.indexes.covering_build._write_bucketed_streaming": (
        "build",
        "wave-budget",
        "materializes one planned wave within build_memory_budget plus "
        "one bucket at merge time; spill files carry the rest",
    ),
    "hyperspace_tpu.indexes.zorder._write_zordered_streaming": (
        "build",
        "wave-budget",
        "wave-planned z-order rewrite: one build_memory_budget wave "
        "resident at a time, sorted runs spill to disk between waves",
    ),
    "hyperspace_tpu.indexes.dataskipping.DataSkippingIndex.build_sketch_rows": (
        "build",
        "chunk-bounded",
        "reads one source file per iteration and keeps only its O(1) "
        "sketch row; peak residency is the largest single file",
    ),
    "hyperspace_tpu.indexes.zonemaps._capture_zspans": (
        "build",
        "chunk-bounded",
        "two per-file passes that read one file at a time and retain "
        "only per-file span cells; bounded by the largest single file",
    ),
    # -- maintenance plane: optimize / refresh subsets -----------------------
    "hyperspace_tpu.indexes.covering_build.rewrite_files": (
        "maintenance",
        "const-bounded",
        "optimize reads only this host's stripe of the operator-chosen "
        "small-file victim set (config-thresholded), not the relation",
    ),
    "hyperspace_tpu.indexes.zorder.ZOrderCoveringIndex.optimize": (
        "maintenance",
        "const-bounded",
        "optimize rewrites the config-selected small-file subset in "
        "one pass; victim-set size is thresholded, not row-proportional",
    ),
    "hyperspace_tpu.indexes.dataskipping.DataSkippingIndex.optimize": (
        "maintenance",
        "const-bounded",
        "re-sketches the operator-chosen optimize subset; the index "
        "itself stays one row per source file",
    ),
    "hyperspace_tpu.indexes.dataskipping.DataSkippingIndex.refresh_incremental": (
        "maintenance",
        "const-bounded",
        "re-reads the previous sketch table — one O(1) row per source "
        "file, file-count- not row-proportional",
    ),
    # -- workload advisor (advisor/) -----------------------------------------
    # pure-Python dict/list growth, invisible to the checker's
    # numpy/pyarrow allocation model; declared anyway so the residency
    # witness measures it  # hslint: disable=HS1003
    "hyperspace_tpu.advisor.profile.build_profile": (
        "maintenance",
        "const-bounded",
        "folds a query-log stream into at most advisor.profile."
        "maxShapes shape groups (overflow counted, not stored), each "
        "capped at _DURATION_SAMPLES duration samples — O(maxShapes), "
        "never O(records)",
    ),
    # -- io: generic scan plumbing -------------------------------------------
    "hyperspace_tpu.io.scan.read_relation_files": (
        "serve",
        "chunk-bounded",
        "decodes one file per iteration on the partition-value branch; "
        "the accumulator is the caller's pruned selection, and every "
        "in-package caller passes planner-bounded subsets",
    ),
}
