"""Quickstart: the reference's hello-world flow, TPU-native.

Mirrors the reference's ``notebooks``/``examples`` entry flow
(``Hyperspace Quick-Start``): read a dataset, create a covering index,
enable the rewrite, watch a filter get index-served, inspect with
``explain``/``why_not``. Runs on whatever ``jax.devices()`` offers (one
TPU chip, or CPU).

    python examples/quickstart.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
from hyperspace_tpu import constants as C


def main():
    workdir = tempfile.mkdtemp(prefix="hs_quickstart_")
    data_dir = os.path.join(workdir, "sales")
    os.makedirs(data_dir)
    rng = np.random.default_rng(0)
    n = 100_000
    pq.write_table(
        pa.table(
            {
                "order_id": pa.array(rng.integers(0, 10_000, n), pa.int64()),
                "amount": pa.array(np.round(rng.uniform(1, 500, n), 2)),
                "region": pa.array(
                    [["NA", "EU", "APAC"][i % 3] for i in range(n)]
                ),
            }
        ),
        os.path.join(data_dir, "part-0.parquet"),
    )

    session = HyperspaceSession()
    session.conf.set(C.INDEX_SYSTEM_PATH, os.path.join(workdir, "indexes"))
    hs = Hyperspace(session)

    df = session.read.parquet(data_dir)
    hs.create_index(
        df, CoveringIndexConfig("sales_by_order", ["order_id"], ["amount", "region"])
    )
    print(hs.indexes().to_pandas() if hasattr(hs.indexes(), "to_pandas") else hs.indexes())

    session.enable_hyperspace()
    query = df.filter(df["order_id"] == 42).select("order_id", "amount")
    print(hs.explain(query))
    print(query.collect())

    # SQL goes through the same optimizer
    df.create_or_replace_temp_view("sales")
    print(
        session.sql(
            "SELECT region, COUNT(*) AS n, SUM(amount) AS total "
            "FROM sales WHERE order_id = 42 GROUP BY region"
        ).collect()
    )

    # why was (or wasn't) an index used?
    other = df.filter(df["amount"] > 400).select("amount")
    print(hs.why_not(other, "sales_by_order"))


if __name__ == "__main__":
    main()
