"""Serve-server mode: a long-lived process answering indexed queries
from RAM (``hyperspace.serve.cache.enabled`` — see docs/CONFIG.md).

The reference cannot do this (Spark executors are stateless); here a
query's FIRST touch of an index bucket decodes it into the serve cache
(with bucket pruning on, each distinct key prunes to one bucket, so each
new key's first lookup is that bucket's populating miss) and every later
query over a resident bucket answers from memory: point filters by
binary search on the RAM-resident sorted bucket (sub-millisecond on the
bench chip), joins from prepared sides.

    python examples/serve_server.py
"""
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
from hyperspace_tpu import constants as C


def main():
    workdir = tempfile.mkdtemp(prefix="hs_serve_")
    data_dir = os.path.join(workdir, "events")
    os.makedirs(data_dir)
    rng = np.random.default_rng(1)
    n = 1_000_000
    pq.write_table(
        pa.table(
            {
                "user_id": pa.array(rng.integers(0, 50_000, n), pa.int64()),
                "ts": pa.array(
                    (
                        np.datetime64("2026-01-01")
                        + rng.integers(0, 180, n).astype("timedelta64[D]")
                    ).astype("datetime64[D]")
                ),
                "value": pa.array(rng.normal(0, 1, n)),
            }
        ),
        os.path.join(data_dir, "part-0.parquet"),
    )

    session = HyperspaceSession()
    session.conf.set(C.INDEX_SYSTEM_PATH, os.path.join(workdir, "indexes"))
    session.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
    hs = Hyperspace(session)
    df = session.read.parquet(data_dir)
    hs.create_index(
        df, CoveringIndexConfig("events_by_user", ["user_id"], ["ts", "value"])
    )
    session.enable_hyperspace()
    session.conf.set(C.SERVE_CACHE_ENABLED, True)

    def lookup(uid):
        t0 = time.perf_counter()
        out = df.filter(df["user_id"] == uid).select("ts", "value").collect()
        return out.num_rows, (time.perf_counter() - t0) * 1e3

    for uid in (7, 99, 4242):
        rows, cold = lookup(uid)
        print(
            f"cold lookup user {uid} (populates its bucket): "
            f"{rows} rows in {cold:.2f}ms"
        )
    for uid in (7, 99, 4242):
        rows, warm = lookup(uid)
        print(f"warm lookup user {uid}: {rows} rows in {warm:.3f}ms")
    cache = session.serve_cache
    print(
        f"cache: {cache.hits} hits / {cache.misses} misses, "
        f"{cache.resident_bytes / 1e6:.1f}MB resident"
    )

    # --- concurrent serving (docs/serve-server.md): 16 client threads
    # through the admission-controlled frontend — snapshot pinning,
    # single-flight dedup of identical plans, retry/degrade on faults
    import threading

    fe = session.serve_frontend

    def client(cid, uids):
        for uid in uids:
            fe.serve(
                df.filter(df["user_id"] == int(uid)).select("ts", "value")
            )

    rng2 = np.random.default_rng(2)
    t0 = time.perf_counter()
    threads = [
        threading.Thread(
            target=client, args=(i, rng2.integers(0, 50_000, 8))
        )
        for i in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    st = fe.stats()
    print(
        f"16 concurrent clients x 8 lookups: {st['completed']} served "
        f"({st['deduped']} deduped) in {wall * 1e3:.0f}ms "
        f"(p50 {st.get('p50_s', 0) * 1e3:.2f}ms, "
        f"p99 {st.get('p99_s', 0) * 1e3:.2f}ms); "
        f"cache high-water {cache.high_water_bytes / 1e6:.1f}MB "
        f"of {cache.max_bytes / 1e9:.0f}GB budget"
    )
    fe.close()


if __name__ == "__main__":
    main()
