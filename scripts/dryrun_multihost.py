"""Multi-host dryrun: 2 REAL processes x 4 CPU devices each.

Exercises the multi-host plane end-to-end (docs/MULTIHOST.md):

  * ``initialize_distributed`` joins both processes into one jax job
    (gloo CPU collectives — the simulation stand-in for DCN);
  * the flat data-plane mesh (``default_mesh``) spans all 8 devices and
    runs the shuffle's collective shape (shard_map all_to_all + psum)
    ACROSS the process boundary;
  * the hierarchical (dcn, ici) mesh runs the two-stage reduction
    (ici-first, then dcn) and both stages agree with the flat psum;
  * the process-local bucket shuffle (per-host feed -> twostage DCN
    exchange -> per-host owned rows) matches the canonical order;
  * a CREATE runs end to end across both processes: each host scans its
    file stripe, the exchange routes rows to their owner host, and the
    metadata plane stays single-writer (``is_coordinator`` gates the
    begin/commit log writes + latestStable publish) — ONE log entry
    pair, identical global content on both processes, zero stranded
    state.

When ``HS_COLLECTIVE_WITNESS=<prefix>`` is set, every worker wraps the
``COLLECTIVE_SITES`` registry (``testing/collective_witness.py``)
before the bootstrap and dumps its ordered collective sequence to
``<prefix>.p<i>.json``; ``hslint --witness <prefix>`` then merges the
artifacts and gates on zero cross-process divergence (the HS804 loop;
``scripts/bench_smoke.sh`` runs exactly that). The witness-coverage
matrix below is the contract the HS703 lint checks the registry
against: every registered site is either exercised here multi-process,
proven coordinator-only, or asserted to be a single-controller program
a multi-process job must never route through.

Run directly (spawns its own workers):   python scripts/dryrun_multihost.py
Run as one worker (used by the parent):  python scripts/dryrun_multihost.py --worker <pid> <port>
"""
import hashlib
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# COLLECTIVE_SITES coverage matrix (checked statically by hslint HS703
# and at runtime against the recorded witness artifact):
#: sites every process must witness during this dryrun
WITNESS_MULTIHOST_SITES = (
    "hyperspace_tpu.parallel.mesh.initialize_distributed",
    "hyperspace_tpu.parallel.shuffle._twostage_program",
    "hyperspace_tpu.parallel.shuffle._twostage_exchange_mp",
    "hyperspace_tpu.indexes.covering_build._global_written",
    "hyperspace_tpu.actions.base._action_rendezvous",
)
#: coordinator-gated sites: witnessed on process 0, NEVER elsewhere
WITNESS_COORDINATOR_SITES = (
    "hyperspace_tpu.actions.base._publish_log",
    "hyperspace_tpu.actions.base._publish_latest_stable",
)
#: single-controller device programs a multi-process job must never
#: route through (resolve_strategy coerces to twostage)
WITNESS_SINGLE_HOST_SITES = (
    "hyperspace_tpu.parallel.shuffle._flat_program",
    "hyperspace_tpu.parallel.shuffle._compact_program",
)

N_GLOBAL_CREATE = 4000
CREATE_FILES = 4


def worker(pid: int, port: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, REPO)
    import jax

    jax.config.update("jax_platforms", "cpu")

    witness_prefix = os.environ.get("HS_COLLECTIVE_WITNESS")
    if witness_prefix:
        # wrap the registered sites BEFORE the bootstrap so even
        # initialize_distributed lands in the recorded sequence
        from hyperspace_tpu.testing import collective_witness

        collective_witness.install()

    # module-attribute access (not from-imports) so the witness wrappers
    # are seen by every call below
    from hyperspace_tpu.parallel import mesh as hs_mesh

    hs_mesh.initialize_distributed(
        coordinator_address=f"localhost:{port}",
        num_processes=2,
        process_id=pid,
        cpu_local_devices=4,
    )
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:  # jax >= 0.6 exposes shard_map at top level
        shard_map = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()

    DCN_AXIS, ICI_AXIS, SHARD_AXIS = (
        hs_mesh.DCN_AXIS,
        hs_mesh.ICI_AXIS,
        hs_mesh.SHARD_AXIS,
    )

    # --- flat mesh: the data-plane collective shape used by the shuffle
    mesh = hs_mesh.default_mesh()
    D = mesh.devices.size

    def exchange(a):
        # one all_to_all over the flat shard axis (the bucket shuffle's
        # collective) + a psum checksum
        b = jax.lax.all_to_all(
            a.reshape(D, -1), SHARD_AXIS, 0, 0, tiled=False
        )
        return jax.lax.psum(b.sum(), SHARD_AXIS)

    x = jax.device_put(
        jnp.arange(float(D * D)).reshape(D, D),
        NamedSharding(mesh, P(SHARD_AXIS)),
    )
    flat_total = jax.jit(
        shard_map(
            exchange, mesh=mesh, in_specs=P(SHARD_AXIS), out_specs=P()
        )
    )(x)
    flat_total = float(np.asarray(jax.device_get(flat_total)).ravel()[0])
    expect = float(np.arange(D * D).sum())
    assert flat_total == expect, (flat_total, expect)

    # --- hierarchical mesh: two-stage reduction (ici first, then dcn)
    hmesh = hs_mesh.hierarchical_mesh()

    def two_stage(a):
        local = jax.lax.psum(a.sum(), ICI_AXIS)  # within-host (ICI)
        return jax.lax.psum(local, DCN_AXIS)  # once across hosts (DCN)

    y = jax.device_put(
        jnp.arange(float(D * 4)).reshape(D, 4),
        NamedSharding(hmesh, P((DCN_AXIS, ICI_AXIS))),
    )
    hier_total = jax.jit(
        shard_map(
            two_stage,
            mesh=hmesh,
            in_specs=P((DCN_AXIS, ICI_AXIS)),
            out_specs=P(),
        )
    )(y)
    hier_total = float(np.asarray(jax.device_get(hier_total)).ravel()[0])
    assert hier_total == float(np.arange(D * 4).sum()), hier_total

    # --- process-local bucket shuffle: the exchange-strategy plane's
    # real multi-host leg (per-host feed -> twostage DCN exchange with
    # per-peer round caps -> per-host owned rows). Both workers hold the
    # same deterministic GLOBAL dataset, feed only their process-major
    # slice, and check their received rows against the host-computed
    # canonical order restricted to the buckets their devices own.
    from hyperspace_tpu.ops.hash import bucket_ids_np
    from hyperspace_tpu.parallel import shuffle as hs_shuffle

    rng = np.random.default_rng(7)
    n_global, nb = 4000, 16
    keys_g = rng.integers(0, 500, (1, n_global)).astype(np.int64)
    pay_g = rng.integers(0, 10**9, n_global).astype(np.int64)
    half = n_global // 2
    lo, hi = pid * half, (pid + 1) * half
    got_b, got_cols, got_offs = hs_shuffle.bucket_shuffle(
        mesh,
        keys_g[:, lo:hi],
        [keys_g[0, lo:hi], pay_g[lo:hi]],
        nb,
        with_shard_offsets=True,
    )
    stats = hs_shuffle.last_shuffle_stats
    assert stats["strategy"] == "twostage", stats
    assert stats.get("process_local") == 1.0, stats
    ids = bucket_ids_np(keys_g, nb)
    L = jax.local_device_count()
    order = np.lexsort((np.arange(n_global), ids, ids % D))
    mine = (ids[order] % D) // L == pid
    exp_rows = order[mine]
    np.testing.assert_array_equal(got_b, ids[exp_rows])
    np.testing.assert_array_equal(got_cols[0], keys_g[0, exp_rows])
    np.testing.assert_array_equal(got_cols[1], pay_g[exp_rows])
    per_shard = np.zeros(D, dtype=np.int64)
    counts = np.bincount(ids % D, minlength=D)
    per_shard[pid * L : (pid + 1) * L] = counts[pid * L : (pid + 1) * L]
    np.testing.assert_array_equal(
        got_offs, np.concatenate([[0], np.cumsum(per_shard)])
    )

    # --- 2-process CREATE end to end: per-host scan stripes, twostage
    # exchange, coordinator-gated metadata plane (ROADMAP item 4's
    # multi-writer gap). The parent wrote the shared dataset.
    content_hash = create_rows = ""
    root = os.environ.get("HS_DRYRUN_ROOT")
    if root:
        content_hash, create_rows = _create_end_to_end(root)

    if witness_prefix:
        from hyperspace_tpu.testing import collective_witness

        doc = collective_witness.dump(witness_prefix)
        witnessed = {r["site"] for r in doc["sequence"]}
        missing = [s for s in WITNESS_MULTIHOST_SITES if s not in witnessed]
        assert not missing, f"unwitnessed multi-host sites: {missing}"
        for site in WITNESS_COORDINATOR_SITES:
            if root:  # the CREATE drives the metadata plane
                assert (site in witnessed) == (pid == 0), (
                    site,
                    pid,
                    site in witnessed,
                )
        routed = [s for s in WITNESS_SINGLE_HOST_SITES if s in witnessed]
        assert not routed, (
            f"multi-process job routed through single-controller "
            f"programs: {routed}"
        )

    print(
        f"DRYRUN-OK proc={pid} procs={jax.process_count()} "
        f"devices={jax.device_count()} flat_psum={flat_total} "
        f"two_stage={hier_total} "
        f"exchange_rows={len(got_b)}/{n_global} "
        f"round_caps=[{stats['round_cap_min']:.0f},"
        f"{stats['round_cap_max']:.0f}] "
        f"create_content={content_hash} create_rows={create_rows}",
        flush=True,
    )


def _create_end_to_end(root: str) -> tuple:
    """Run the CREATE on both processes, assert the single-writer log
    and the global content, return (content hash, row count) for the
    parent's cross-process identity check."""
    import pyarrow.parquet as pq
    from jax.experimental import multihost_utils as mhu

    from hyperspace_tpu import (
        CoveringIndexConfig,
        Hyperspace,
        HyperspaceSession,
    )
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.constants import States

    session = HyperspaceSession()
    session.conf.set(C.INDEX_SYSTEM_PATH, os.path.join(root, "indexes"))
    session.conf.set(C.INDEX_NUM_BUCKETS, 16)
    hs = Hyperspace(session)
    df = session.read.parquet(os.path.join(root, "data"))
    hs.create_index(df, CoveringIndexConfig("mh_create", ["k"], ["v"]))
    # the worker returns from op() before the coordinator publishes the
    # final entry — rendezvous before asserting the metadata plane
    mhu.sync_global_devices("dryrun_create_done")

    index_root = os.path.join(root, "indexes", "mh_create")
    log_dir = os.path.join(index_root, C.HYPERSPACE_LOG_DIR)
    ids = sorted(int(n) for n in os.listdir(log_dir) if n.isdigit())
    assert ids == [1, 2], f"expected ONE begin/commit pair, got ids {ids}"
    from hyperspace_tpu.metadata.log_manager import IndexLogManager

    log_mgr = IndexLogManager(index_root)
    assert log_mgr.get_log(1).state == States.CREATING
    final = log_mgr.get_log(2)
    assert final.state == States.ACTIVE, final.state
    assert log_mgr.get_latest_stable_pointer_id() == 2
    # zero stranded state: no spill dirs, every data file accounted for
    # in the committed content and vice versa
    strays = [n for n in os.listdir(index_root) if n.startswith("_spill_")]
    assert not strays, strays
    content_files = sorted(final.content.files)
    data_dirs = [
        os.path.join(index_root, n)
        for n in os.listdir(index_root)
        if n.startswith("v__=")
    ]
    assert len(data_dirs) == 1, data_dirs
    on_disk = sorted(
        os.path.join(data_dirs[0], n)
        for n in os.listdir(data_dirs[0])
        # hidden-path filter: sidecars (_aggsample.parquet) are not data
        if n.endswith(".parquet") and not n.startswith(("_", "."))
    )
    assert [os.path.basename(f) for f in content_files] == [
        os.path.basename(f) for f in on_disk
    ], (content_files, on_disk)
    rows = 0
    digest = hashlib.md5()
    for f in on_disk:
        meta = pq.read_metadata(f)
        rows += meta.num_rows
        digest.update(f"{os.path.basename(f)}:{meta.num_rows}\n".encode())
    assert rows == N_GLOBAL_CREATE, rows

    # a failing action must abort SYMMETRICALLY (the abort-aware
    # rendezvous), never hang: the duplicate CREATE fails validate on
    # every process with the same typed error, and leaves no new state
    from hyperspace_tpu.exceptions import HyperspaceException

    try:
        hs.create_index(df, CoveringIndexConfig("mh_create", ["k"], ["v"]))
        raise AssertionError("duplicate CREATE unexpectedly succeeded")
    except HyperspaceException:
        pass
    mhu.sync_global_devices("dryrun_dup_create_done")
    ids_after = sorted(int(n) for n in os.listdir(log_dir) if n.isdigit())
    assert ids_after == [1, 2], ids_after
    return digest.hexdigest()[:12], str(rows)


def _write_create_dataset(root: str) -> None:
    """The shared CREATE input: numeric key/payload (the supported
    multi-process build shape, docs/MULTIHOST.md), several files so each
    process scans a real stripe (``files[p::P]``)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    data_dir = os.path.join(root, "data")
    os.makedirs(data_dir)
    rng = np.random.default_rng(11)
    per = N_GLOBAL_CREATE // CREATE_FILES
    for i in range(CREATE_FILES):
        pq.write_table(
            pa.table(
                {
                    "k": pa.array(
                        rng.integers(0, 300, per), type=pa.int64()
                    ),
                    "v": pa.array(
                        rng.integers(0, 10**9, per), type=pa.int64()
                    ),
                }
            ),
            os.path.join(data_dir, f"part-{i}.parquet"),
        )


def main() -> int:
    import re
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    root = tempfile.mkdtemp(prefix="hs_dryrun_")
    try:
        _write_create_dataset(root)
        env = dict(os.environ, HS_DRYRUN_ROOT=root)
        procs = [
            subprocess.Popen(
                [
                    sys.executable,
                    os.path.abspath(__file__),
                    "--worker",
                    str(i),
                    str(port),
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
            for i in range(2)
        ]
        ok = 0
        contents = []
        for p in procs:
            out, _ = p.communicate(timeout=300)
            if p.returncode == 0 and "DRYRUN-OK" in out:
                ok += 1
            contents += re.findall(r"create_content=(\w+)", out)
            sys.stdout.write(out)
        # "identical global content": both processes listed the same
        # committed file set with the same per-file row counts
        if len(set(contents)) != 1:
            print(f"multihost dryrun: content hashes diverge: {contents}")
            return 1
        print(f"multihost dryrun: {ok}/2 workers ok")
        return 0 if ok == 2 else 1
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--worker":
        worker(int(sys.argv[2]), int(sys.argv[3]))
    else:
        raise SystemExit(main())
