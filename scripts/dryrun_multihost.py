"""Multi-host dryrun: 2 REAL processes x 4 CPU devices each.

Exercises the multi-host bootstrap end-to-end (docs/MULTIHOST.md):

  * ``initialize_distributed`` joins both processes into one jax job
    (gloo CPU collectives — the simulation stand-in for DCN);
  * the flat data-plane mesh (``default_mesh``) spans all 8 devices and
    runs the shuffle's collective shape (shard_map all_to_all + psum)
    ACROSS the process boundary;
  * the hierarchical (dcn, ici) mesh runs the two-stage reduction
    (ici-first, then dcn) and both stages agree with the flat psum.

Run directly (spawns its own workers):   python scripts/dryrun_multihost.py
Run as one worker (used by the parent):  python scripts/dryrun_multihost.py --worker <pid> <port>
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def worker(pid: int, port: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, REPO)
    import jax

    jax.config.update("jax_platforms", "cpu")

    from hyperspace_tpu.parallel.mesh import (
        DCN_AXIS,
        ICI_AXIS,
        SHARD_AXIS,
        default_mesh,
        hierarchical_mesh,
        initialize_distributed,
    )

    initialize_distributed(
        coordinator_address=f"localhost:{port}",
        num_processes=2,
        process_id=pid,
        cpu_local_devices=4,
    )
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:  # jax >= 0.6 exposes shard_map at top level
        shard_map = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()

    # --- flat mesh: the data-plane collective shape used by the shuffle
    mesh = default_mesh()
    D = mesh.devices.size

    def exchange(a):
        # one all_to_all over the flat shard axis (the bucket shuffle's
        # collective) + a psum checksum
        b = jax.lax.all_to_all(
            a.reshape(D, -1), SHARD_AXIS, 0, 0, tiled=False
        )
        return jax.lax.psum(b.sum(), SHARD_AXIS)

    x = jax.device_put(
        jnp.arange(float(D * D)).reshape(D, D),
        NamedSharding(mesh, P(SHARD_AXIS)),
    )
    flat_total = jax.jit(
        shard_map(
            exchange, mesh=mesh, in_specs=P(SHARD_AXIS), out_specs=P()
        )
    )(x)
    flat_total = float(np.asarray(jax.device_get(flat_total)).ravel()[0])
    expect = float(np.arange(D * D).sum())
    assert flat_total == expect, (flat_total, expect)

    # --- hierarchical mesh: two-stage reduction (ici first, then dcn)
    hmesh = hierarchical_mesh()

    def two_stage(a):
        local = jax.lax.psum(a.sum(), ICI_AXIS)  # within-host (ICI)
        return jax.lax.psum(local, DCN_AXIS)  # once across hosts (DCN)

    y = jax.device_put(
        jnp.arange(float(D * 4)).reshape(D, 4),
        NamedSharding(hmesh, P((DCN_AXIS, ICI_AXIS))),
    )
    hier_total = jax.jit(
        shard_map(
            two_stage,
            mesh=hmesh,
            in_specs=P((DCN_AXIS, ICI_AXIS)),
            out_specs=P(),
        )
    )(y)
    hier_total = float(np.asarray(jax.device_get(hier_total)).ravel()[0])
    assert hier_total == float(np.arange(D * 4).sum()), hier_total

    # --- process-local bucket shuffle: the exchange-strategy plane's
    # real multi-host leg (per-host feed -> twostage DCN exchange with
    # per-peer round caps -> per-host owned rows). Both workers hold the
    # same deterministic GLOBAL dataset, feed only their process-major
    # slice, and check their received rows against the host-computed
    # canonical order restricted to the buckets their devices own.
    from hyperspace_tpu.ops.hash import bucket_ids_np
    from hyperspace_tpu.parallel import shuffle as hs_shuffle

    rng = np.random.default_rng(7)
    n_global, nb = 4000, 16
    keys_g = rng.integers(0, 500, (1, n_global)).astype(np.int64)
    pay_g = rng.integers(0, 10**9, n_global).astype(np.int64)
    half = n_global // 2
    lo, hi = pid * half, (pid + 1) * half
    got_b, got_cols, got_offs = hs_shuffle.bucket_shuffle(
        mesh,
        keys_g[:, lo:hi],
        [keys_g[0, lo:hi], pay_g[lo:hi]],
        nb,
        with_shard_offsets=True,
    )
    stats = hs_shuffle.last_shuffle_stats
    assert stats["strategy"] == "twostage", stats
    assert stats.get("process_local") == 1.0, stats
    ids = bucket_ids_np(keys_g, nb)
    L = jax.local_device_count()
    order = np.lexsort((np.arange(n_global), ids, ids % D))
    mine = (ids[order] % D) // L == pid
    exp_rows = order[mine]
    np.testing.assert_array_equal(got_b, ids[exp_rows])
    np.testing.assert_array_equal(got_cols[0], keys_g[0, exp_rows])
    np.testing.assert_array_equal(got_cols[1], pay_g[exp_rows])
    per_shard = np.zeros(D, dtype=np.int64)
    counts = np.bincount(ids % D, minlength=D)
    per_shard[pid * L : (pid + 1) * L] = counts[pid * L : (pid + 1) * L]
    np.testing.assert_array_equal(
        got_offs, np.concatenate([[0], np.cumsum(per_shard)])
    )

    print(
        f"DRYRUN-OK proc={pid} procs={jax.process_count()} "
        f"devices={jax.device_count()} flat_psum={flat_total} "
        f"two_stage={hier_total} "
        f"exchange_rows={len(got_b)}/{n_global} "
        f"round_caps=[{stats['round_cap_min']:.0f},"
        f"{stats['round_cap_max']:.0f}]",
        flush=True,
    )


def main() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker", str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    ok = 0
    for p in procs:
        out, _ = p.communicate(timeout=300)
        if p.returncode == 0 and "DRYRUN-OK" in out:
            ok += 1
        sys.stdout.write(out)
    print(f"multihost dryrun: {ok}/2 workers ok")
    return 0 if ok == 2 else 1


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--worker":
        worker(int(sys.argv[2]), int(sys.argv[3]))
    else:
        raise SystemExit(main())
