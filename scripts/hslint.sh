#!/usr/bin/env bash
# hslint — repo-native static analysis gate.
#
# Runs the analyzer over the package; exits nonzero when any unsuppressed
# finding remains (the same check tier-1 enforces via
# tests/test_hslint.py::TestPackageClean). Extra arguments are passed
# through, e.g.:
#
#   scripts/hslint.sh                      # the gate
#   scripts/hslint.sh --show-suppressed    # also list justified suppressions
#   scripts/hslint.sh --format json        # machine-readable findings
#   scripts/hslint.sh --list-rules         # the ruleset
#   scripts/hslint.sh --witness wit.json   # + cross-check a runtime lock
#                                          #   witness artifact (recorded by
#                                          #   HS_LOCK_WITNESS=wit.json pytest
#                                          #   runs) against the static model
#   scripts/hslint.sh --witness cw         # + merge + cross-check per-process
#                                          #   COLLECTIVE witness artifacts
#                                          #   (cw.p<i>.json, recorded by
#                                          #   HS_COLLECTIVE_WITNESS=cw
#                                          #   scripts/dryrun_multihost.py):
#                                          #   any cross-process sequence
#                                          #   divergence is a hard HS804 error
#   scripts/hslint.sh --witness res.json   # + cross-check a runtime RESIDENCY
#                                          #   witness artifact (recorded by
#                                          #   HS_RESIDENCY_WITNESS=res.json
#                                          #   pytest/bench runs): a witnessed
#                                          #   allocation site absent from
#                                          #   ALLOC_SITES, or a per-site peak
#                                          #   past its declared bound-class
#                                          #   ceiling, is a hard HS1004 error
#                                          #   (artifact kind is sniffed from
#                                          #   content)
#
# Rule docs: docs/static-analysis.md
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m hyperspace_tpu.analysis hyperspace_tpu/ "$@"
